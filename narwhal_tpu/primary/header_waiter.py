"""The HeaderWaiter: executes SyncBatches / SyncParents repair commands.

Reference: /root/reference/primary/src/header_waiter.rs:44-406 — for each
suspended header it registers store waiters (`notify_read`) on the missing
dependencies, optimistically asks one node (own workers for batches, the
header author's primary for parent certificates), retries on a timer by
asking `sync_retry_nodes` random peers (the lucky-broadcast policy), and
loops the header back to the core once everything is local. Waiters are
cancelled by garbage collection.
"""

from __future__ import annotations

import asyncio
import logging
import random

from ..channels import Channel, Subscriber, Watch
from ..config import Committee, Parameters, WorkerCache
from ..messages import CertificatesBatchRequest, SynchronizeMsg
from ..network import NetworkClient, RpcError
from ..stores import CertificateStore, PayloadStore
from ..types import Digest, Header, PublicKey, Round
from .synchronizer import SyncBatches, SyncParents

logger = logging.getLogger("narwhal.primary")


class HeaderWaiter:
    def __init__(
        self,
        name: PublicKey,
        committee: Committee,
        worker_cache: WorkerCache,
        certificate_store: CertificateStore,
        payload_store: PayloadStore,
        parameters: Parameters,
        network: NetworkClient,
        rx_synchronizer: Channel,  # SyncBatches | SyncParents
        tx_core: Channel,  # replayed headers
        tx_primary_messages: Channel,  # fetched certificates -> core input
        rx_consensus_round_updates: Watch,
        rx_reconfigure: Watch,
        metrics=None,
    ):
        self.name = name
        self.committee = committee
        self.worker_cache = worker_cache
        self.certificate_store = certificate_store
        self.payload_store = payload_store
        self.parameters = parameters
        self.network = network
        self.rx_synchronizer = rx_synchronizer
        self.tx_core = tx_core
        self.tx_primary_messages = tx_primary_messages
        self.rx_consensus_round_updates = Subscriber(rx_consensus_round_updates)
        self.rx_reconfigure = Subscriber(rx_reconfigure)
        self.metrics = metrics

        self.gc_round: Round = 0
        # header digest -> (round, waiter task)
        self.pending: dict[Digest, tuple[Round, asyncio.Task]] = {}
        self._task: asyncio.Task | None = None

    def spawn(self) -> asyncio.Task:
        self._task = asyncio.ensure_future(self.run())
        return self._task

    # ------------------------------------------------------------------
    async def _sync_batches_once(self, missing: dict[Digest, int], author: PublicKey) -> None:
        """Group missing batch digests by worker id and send Synchronize to
        our own workers (header_waiter.rs:163-236). The worker synchronizer
        has its own retry loop, so one send per tick is enough."""
        by_worker: dict[int, list[Digest]] = {}
        for digest, worker_id in missing.items():
            by_worker.setdefault(worker_id, []).append(digest)
        sends = []
        for worker_id, digests in by_worker.items():
            try:
                address = self.worker_cache.worker(self.name, worker_id).worker_address
            except KeyError:
                logger.debug(
                    "no local worker %d to sync %d batches", worker_id, len(digests)
                )
                continue
            sends.append(
                self.network.unreliable_send(
                    address, SynchronizeMsg(tuple(digests), author)
                )
            )
            if self.metrics is not None:
                self.metrics.sync_batch_requests.inc()
        if sends:
            # Concurrent fan-out: one coalesced Synchronize per worker, all
            # workers in flight together.
            await asyncio.gather(*sends)

    async def _fetch_certificates(self, digests: list[Digest], address: str) -> None:
        """Request parent certificates and feed replies into the core's
        message stream (so they pass the usual sanitize path)."""
        try:
            response = await self.network.request(
                address,
                CertificatesBatchRequest(tuple(digests), self.name),
                timeout=self.parameters.block_synchronizer_certs_timeout,
            )
        except (RpcError, OSError):
            return
        for _, certificate in response.certificates:
            if certificate is not None:
                await self.tx_primary_messages.send(certificate)

    async def _wait_batches(self, msg: SyncBatches) -> None:
        header = msg.header
        waiters = [
            self.payload_store.notify_contains(digest, worker_id)
            for digest, worker_id in msg.missing.items()
        ]
        gathered = asyncio.gather(*waiters)
        try:
            while True:
                # Trim per tick: batches that arrived since the last tick
                # must not ride the next Synchronize — the worker would
                # re-fetch (and peers re-ship) payload we already hold.
                still_missing = {
                    digest: worker_id
                    for digest, worker_id in msg.missing.items()
                    if not self.payload_store.contains(digest, worker_id)
                }
                if still_missing:
                    await self._sync_batches_once(still_missing, header.author)
                try:
                    await asyncio.wait_for(
                        asyncio.shield(gathered), self.parameters.sync_retry_delay
                    )
                    break
                except asyncio.TimeoutError:  # lint: allow(no-silent-except)
                    continue  # retry tick: re-send sync requests by design
        except asyncio.CancelledError:
            gathered.cancel()
            raise
        await self.tx_core.send(header)

    async def _wait_parents(self, msg: SyncParents) -> None:
        header = msg.header
        waiters = [self.certificate_store.notify_read(d) for d in msg.missing]
        gathered = asyncio.gather(*waiters)
        author_address = self.committee.primary_address(header.author)
        others = [
            addr for _, addr, _ in self.committee.others_primaries(self.name)
        ]
        first = True
        try:
            while True:
                if first:
                    await self._fetch_certificates(msg.missing, author_address)
                    first = False
                else:
                    # Timer retry: ask sync_retry_nodes random peers
                    # (header_waiter.rs:292-321).
                    # Deliberate draw from the scenario-seeded global
                    # stream: retry-peer choice replays under the same seed.
                    for addr in random.sample(  # lint: allow(unseeded-random)
                        others, min(self.parameters.sync_retry_nodes, len(others))
                    ):
                        await self._fetch_certificates(msg.missing, addr)
                if self.metrics is not None:
                    self.metrics.sync_parent_requests.inc()
                try:
                    await asyncio.wait_for(
                        asyncio.shield(gathered), self.parameters.sync_retry_delay
                    )
                    break
                except asyncio.TimeoutError:  # lint: allow(no-silent-except)
                    continue  # retry tick: re-send sync requests by design
        except asyncio.CancelledError:
            gathered.cancel()
            raise
        await self.tx_core.send(header)

    # ------------------------------------------------------------------
    def _spawn_waiter(self, header: Header, coro) -> None:
        if header.digest in self.pending:
            coro.close()  # already being repaired; drop the duplicate quietly
            return
        task = asyncio.ensure_future(coro)
        self.pending[header.digest] = (header.round, task)

        def _done(t: asyncio.Task, digest=header.digest) -> None:
            self.pending.pop(digest, None)
            if self.metrics is not None:
                self.metrics.pending_header_waits.set(len(self.pending))
            if not t.cancelled() and t.exception() is not None:
                logger.warning("Header waiter failed: %r", t.exception())

        task.add_done_callback(_done)
        if self.metrics is not None:
            self.metrics.pending_header_waits.set(len(self.pending))

    async def run(self) -> None:
        cmd_task = asyncio.ensure_future(self.rx_synchronizer.recv())
        recon_task = asyncio.ensure_future(self.rx_reconfigure.changed())
        round_task = asyncio.ensure_future(self.rx_consensus_round_updates.changed())
        try:
            while True:
                done, _ = await asyncio.wait(
                    {cmd_task, recon_task, round_task},
                    return_when=asyncio.FIRST_COMPLETED,
                )
                if cmd_task in done:
                    msg = cmd_task.result()
                    cmd_task = asyncio.ensure_future(self.rx_synchronizer.recv())
                    if msg.header.round > self.gc_round:
                        if isinstance(msg, SyncBatches):
                            self._spawn_waiter(msg.header, self._wait_batches(msg))
                        elif isinstance(msg, SyncParents):
                            self._spawn_waiter(msg.header, self._wait_parents(msg))
                if round_task in done:
                    committed_round = round_task.result()
                    round_task = asyncio.ensure_future(
                        self.rx_consensus_round_updates.changed()
                    )
                    self._gc(committed_round)
                if recon_task in done:
                    note = recon_task.result()
                    if note.kind == "shutdown":
                        return
                    if note.committee is not None:
                        self.committee = note.committee
                        self.gc_round = 0
                        self._cancel_all()
                    recon_task = asyncio.ensure_future(self.rx_reconfigure.changed())
        finally:
            cmd_task.cancel()
            recon_task.cancel()
            round_task.cancel()
            self._cancel_all()

    def _gc(self, committed_round: Round) -> None:
        if committed_round <= self.parameters.gc_depth:
            return
        gc_round = committed_round - self.parameters.gc_depth
        if gc_round <= self.gc_round:
            return
        self.gc_round = gc_round
        for digest, (round_, task) in list(self.pending.items()):
            if round_ <= gc_round:
                task.cancel()
                self.pending.pop(digest, None)

    def _cancel_all(self) -> None:
        for _, task in self.pending.values():
            task.cancel()
        self.pending.clear()
