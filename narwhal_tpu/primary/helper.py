"""The Helper: serves peers' certificate / payload-availability queries.

Reference: /root/reference/primary/src/helper.rs:32-261. In the reference the
helper is an actor replying with loose messages; our RPC layer supports typed
request/response, so these are direct handlers mounted by the primary's RPC
server — same capability, one less hop.
"""

from __future__ import annotations

import logging

from ..config import Committee
from ..messages import (
    CertificatesBatchRequest,
    CertificatesBatchResponse,
    CertificatesRangeRequest,
    CertificatesRangeResponse,
    PayloadAvailabilityRequest,
    PayloadAvailabilityResponse,
)
from ..stores import CertificateStore, PayloadStore

logger = logging.getLogger("narwhal.primary")


class Helper:
    def __init__(
        self,
        committee: Committee,
        certificate_store: CertificateStore,
        payload_store: PayloadStore,
    ):
        self.committee = committee
        self.certificate_store = certificate_store
        self.payload_store = payload_store

    async def on_certificates_batch(
        self, msg: CertificatesBatchRequest, peer: str
    ) -> CertificatesBatchResponse:
        """(helper.rs:117-163): return each requested certificate or None."""
        pairs = tuple(
            (digest, self.certificate_store.read(digest)) for digest in msg.digests
        )
        return CertificatesBatchResponse(pairs)

    async def on_certificates_range(
        self, msg: CertificatesRangeRequest, peer: str
    ) -> CertificatesRangeResponse:
        """Catch-up support (block_synchronizer SynchronizeRange): digests of
        all stored certificates with from_round < round <= to_round."""
        digests = tuple(
            cert.digest
            for cert in self.certificate_store.after_round(msg.from_round + 1)
            if cert.round <= msg.to_round
        )
        return CertificatesRangeResponse(digests)

    async def on_payload_availability(
        self, msg: PayloadAvailabilityRequest, peer: str
    ) -> PayloadAvailabilityResponse:
        """(helper.rs:165-213): for each certificate digest, do we hold its
        entire payload locally?"""
        result = []
        for digest in msg.digests:
            certificate = self.certificate_store.read(digest)
            if certificate is None:
                result.append((digest, False))
                continue
            available = all(
                self.payload_store.contains(batch_digest, worker_id)
                for batch_digest, worker_id in certificate.header.payload.items()
            )
            result.append((digest, available))
        return PayloadAvailabilityResponse(tuple(result))
