"""BlockSynchronizer: fetch missing certificates and payloads from peers.

Reference: /root/reference/primary/src/block_synchronizer/{mod,handler,
peers}.rs — three flows:

- `synchronize_block_headers(digests)`: certificates we lack are requested
  from peer primaries (`CertificatesBatchRequest`); responses are verified
  and re-injected into the Core (loopback channel) for causal completion,
  exactly like handler.rs:200-260.
- `synchronize_block_payloads(certs)`: ask peers who holds each payload
  (`PayloadAvailabilityRequest`), then instruct our workers to `Synchronize`
  the batches from the matching peer workers; completion is awaited on the
  payload store's notify primitive.
- `synchronize_range(from_round)`: restart catch-up — collect certificate
  digests above our last round from peers (`CertificatesRangeRequest`) and
  pull the certificates (mod.rs:75-83).

Peer selection mirrors peers.rs: every peer carries a weight that successful
answers raise and failures halve, selection is weight-biased with jitter (so
a recovered peer can regain standing), and payload sync rotates through the
peers that declared availability instead of hammering the first one.
"""

from __future__ import annotations

import asyncio
import logging
import random
from collections import defaultdict

from ..clock import now
from ..config import Committee, Parameters, WorkerCache
from ..messages import (
    CertificatesBatchRequest,
    CertificatesBatchResponse,
    CertificatesRangeRequest,
    CertificatesRangeResponse,
    PayloadAvailabilityRequest,
    PayloadAvailabilityResponse,
    SynchronizeMsg,
)
from ..network import NetworkClient, RpcError
from ..stores import CertificateStore, PayloadStore
from ..types import Certificate, Digest, InvalidSignatureError, PublicKey

logger = logging.getLogger("narwhal.primary")

CERTIFICATE_RESPONSES_RATIO_THRESHOLD = 0.5  # mod.rs:58


class PeerScores:
    """Weighted peer standing (/root/reference/primary/src/block_synchronizer/
    peers.rs): successes add, failures halve, and selection multiplies the
    score by a random jitter so low-scored peers are still probed
    occasionally and can recover after an outage."""

    INITIAL = 10.0
    MIN = 0.5
    MAX = 100.0

    def __init__(self, rng: random.Random | None = None):
        self._scores: dict[PublicKey, float] = {}
        # Falling back to the module means the scenario-seeded global
        # stream under simnet (scenario.py seeds it per plan) — replayable;
        # tests inject a dedicated random.Random for isolation.
        self._rng = rng or random  # lint: allow(unseeded-random)

    def score(self, peer: PublicKey) -> float:
        return self._scores.get(peer, self.INITIAL)

    def reward(self, peer: PublicKey) -> None:
        self._scores[peer] = min(self.MAX, self.score(peer) + 1.0)

    def penalize(self, peer: PublicKey) -> None:
        self._scores[peer] = max(self.MIN, self.score(peer) / 2.0)

    def select(
        self, candidates: list[tuple[PublicKey, str]], count: int
    ) -> list[tuple[PublicKey, str]]:
        return sorted(
            candidates,
            key=lambda pa: -self.score(pa[0]) * self._rng.uniform(0.5, 1.0),
        )[:count]


class BlockSynchronizer:
    def __init__(
        self,
        name: PublicKey,
        committee: Committee,
        worker_cache: WorkerCache,
        certificate_store: CertificateStore,
        payload_store: PayloadStore,
        network: NetworkClient,
        parameters: Parameters,
        tx_loopback=None,  # re-inject fetched certificates into the Core
        crypto_pool=None,  # AsyncVerifierPool/VerifyService: batched verify
    ):
        self.name = name
        self.committee = committee
        self.worker_cache = worker_cache
        self.certificate_store = certificate_store
        self.payload_store = payload_store
        self.network = network
        self.parameters = parameters
        self.tx_loopback = tx_loopback
        self.crypto_pool = crypto_pool
        self.peers = PeerScores()  # peers.rs standing

    # -- peer selection ---------------------------------------------------

    def _peers(self, count: int) -> list[tuple[PublicKey, str]]:
        others = [
            (pk, address)
            for pk, address, _net in self.committee.others_primaries(self.name)
        ]
        return self.peers.select(others, count)

    # -- certificates -----------------------------------------------------

    async def synchronize_block_headers(
        self, digests: list[Digest], timeout: float | None = None
    ) -> list[Certificate]:
        """Return certificates for `digests`, fetching missing ones from
        peers; fetched certificates are verified, stored via the Core
        loopback, and returned."""
        found: dict[Digest, Certificate] = {}
        missing: list[Digest] = []
        for digest in digests:
            cert = self.certificate_store.read(digest)
            if cert is not None:
                found[digest] = cert
            else:
                missing.append(digest)
        if missing:
            fetched = await self._fetch_certificates(
                missing, timeout or self.parameters.sync_retry_delay * 4
            )
            for cert in fetched:
                found[cert.digest] = cert
        return [found[d] for d in digests if d in found]

    async def _verify_certificate(self, cert: Certificate) -> None:
        """Certificate.verify with the signature work routed through the
        node's crypto pool when one is configured (advisor r4: catch-up
        sync of compact certificates through the pure-Python
        host_verify_aggregate costs ~one scalar-mul per signer per cert —
        minutes for a long N=50 round range — while the pool's aggregate
        lane fuses whole batches into one device dispatch). Semantics match
        the VerifierStage: structural checks inline, signatures batched."""
        if self.crypto_pool is None:
            # Documented no-pool fallback (full-format cpu committees);
            # compact proofs inside still take the cached single-group MSM.
            # lint: allow(no-per-item-cert-verify)
            cert.verify(self.committee, self.worker_cache)
            return
        if cert.is_compact:
            group = cert.aggregate_group(self.committee)
            if group is None:  # genesis
                return
            cert.header.verify(
                self.committee, self.worker_cache, check_signature=False
            )
            results = await asyncio.gather(
                self.crypto_pool.verify(*cert.header.signature_item()),
                self.crypto_pool.verify_aggregate(*group),
            )
        else:
            items = cert.verify_items(self.committee)
            if not items:  # genesis
                return
            cert.header.verify(
                self.committee, self.worker_cache, check_signature=False
            )
            items.append(cert.header.signature_item())
            results = await asyncio.gather(
                *(self.crypto_pool.verify(*item) for item in items)
            )
        if not all(results):
            raise InvalidSignatureError("fetched certificate failed verification")

    async def _fetch_certificates(
        self, digests: list[Digest], timeout: float
    ) -> list[Certificate]:
        peers = self._peers(self.parameters.sync_retry_nodes)
        if not peers:
            return []

        async def ask(peer: PublicKey, address: str) -> list[Certificate]:
            try:
                resp: CertificatesBatchResponse = await self.network.request(
                    address, CertificatesBatchRequest(tuple(digests)), timeout=timeout
                )
            except (RpcError, OSError, asyncio.TimeoutError):
                self.peers.penalize(peer)
                raise
            got = [c for _, c in resp.certificates if c is not None]
            self.peers.reward(peer)
            return got

        tasks = [asyncio.ensure_future(ask(p, a)) for p, a in peers]
        wanted = set(digests)
        collected: dict[Digest, Certificate] = {}
        try:
            for fut in asyncio.as_completed(tasks, timeout=timeout):
                try:
                    certs = await fut
                except (RpcError, OSError, asyncio.TimeoutError) as e:
                    # Individual peer failure: ask() already penalized its
                    # score; other peers may still satisfy the want-list.
                    logger.debug("certificate fetch peer failed: %r", e)
                    continue
                for cert in certs:
                    if cert.digest in wanted and cert.digest not in collected:
                        try:
                            await self._verify_certificate(cert)
                        except Exception as e:
                            logger.warning("peer sent invalid certificate: %s", e)
                            continue
                        collected[cert.digest] = cert
                if len(collected) == len(wanted):
                    break
        except asyncio.TimeoutError:
            logger.debug(
                "certificate fetch deadline: %d/%d collected",
                len(collected),
                len(wanted),
            )
        finally:
            for t in tasks:
                t.cancel()
        # Hand fetched certificates to the Core for causal completion +
        # storage (handler.rs:233-249).
        if self.tx_loopback is not None:
            for cert in collected.values():
                await self.tx_loopback.send(cert)
        return list(collected.values())

    # -- payloads ---------------------------------------------------------

    async def synchronize_block_payloads(
        self, certificates: list[Certificate], timeout: float | None = None
    ) -> list[Certificate]:
        """Ensure the payload of each certificate is available in our
        workers' stores; returns the certificates whose payload arrived.

        Retry loop with availability rotation (peers.rs + mod.rs:900-1050):
        each attempt targets the NEXT peer that declared availability for a
        still-missing payload, so one unresponsive provider cannot stall the
        sync until the outer timeout."""
        timeout = timeout or self.parameters.sync_retry_delay * 4
        deadline = now() + timeout

        def missing(cert: Certificate) -> bool:
            return any(
                not self.payload_store.contains(bd, wid)
                for bd, wid in cert.header.payload.items()
            )

        pending = [c for c in certificates if missing(c)]
        providers: dict[Digest, list[PublicKey]] = {}
        if pending:
            providers = await self._payload_providers(
                pending, min(timeout, self.parameters.sync_retry_delay * 2)
            )

        attempt = 0
        while pending and now() < deadline:
            await self._request_worker_sync(pending, providers, attempt)
            # Wait for arrivals until the retry tick, then rotate targets.
            waiters = [
                self.payload_store.notify_contains(bd, wid)
                for c in pending
                for bd, wid in c.header.payload.items()
                if not self.payload_store.contains(bd, wid)
            ]
            interval = min(
                self.parameters.sync_retry_delay, max(0.0, deadline - now())
            )
            if waiters:
                try:
                    await asyncio.wait_for(asyncio.gather(*waiters), interval)
                except asyncio.TimeoutError:  # lint: allow(no-silent-except)
                    pass  # retry tick by design; wait_for cancelled the gather
            pending = [c for c in pending if missing(c)]
            attempt += 1
        return [c for c in certificates if not missing(c)]

    async def _payload_providers(
        self, certificates: list[Certificate], timeout: float
    ) -> dict[Digest, list[PublicKey]]:
        """Which peers can serve each certificate's payload?"""
        digests = tuple(c.digest for c in certificates)
        peers = self._peers(self.parameters.sync_retry_nodes)
        providers: dict[Digest, list[PublicKey]] = defaultdict(list)

        async def ask(peer: PublicKey, address: str) -> None:
            try:
                resp: PayloadAvailabilityResponse = await self.network.request(
                    address, PayloadAvailabilityRequest(digests), timeout=timeout
                )
            except (RpcError, OSError, asyncio.TimeoutError):
                self.peers.penalize(peer)
                return
            for digest, available in resp.available:
                if available:
                    providers[digest].append(peer)
            self.peers.reward(peer)

        await asyncio.gather(*(ask(p, a) for p, a in peers))
        return providers

    async def _request_worker_sync(
        self,
        certificates: list[Certificate],
        providers: dict[Digest, list[PublicKey]],
        attempt: int = 0,
    ) -> None:
        """Tell our workers which batches to pull and from whom; `attempt`
        rotates through each payload's available providers (falling back to
        the certificate author) so retries fail over to a different peer."""
        by_worker: dict[int, dict[PublicKey, list[Digest]]] = defaultdict(
            lambda: defaultdict(list)
        )
        for cert in certificates:
            # The certificate author is always a last-resort provider: a
            # peer that declares availability but never serves (a liar or a
            # dead worker) must not monopolize the rotation.
            targets = list(providers.get(cert.digest) or [])
            if cert.origin not in targets:
                targets.append(cert.origin)
            target = targets[attempt % len(targets)]
            for batch_digest, worker_id in cert.header.payload.items():
                if not self.payload_store.contains(batch_digest, worker_id):
                    by_worker[worker_id][target].append(batch_digest)
        # One coalesced Synchronize per (worker, target) group, all groups
        # fanned out concurrently — never one awaited RTT per group.
        sends = [
            (
                self.worker_cache.worker(self.name, worker_id).worker_address,
                SynchronizeMsg(tuple(batch_digests), target),
            )
            for worker_id, per_target in by_worker.items()
            for target, batch_digests in per_target.items()
        ]
        if sends:
            await asyncio.gather(
                *(self.network.unreliable_send(a, m) for a, m in sends)
            )

    # -- range catch-up ---------------------------------------------------

    async def synchronize_range(
        self, from_round: int, to_round: int | None = None, timeout: float = 5.0
    ) -> list[Digest]:
        """Collect certificate digests in (from_round, to_round] known to a
        quorum-ish of peers (mod.rs SynchronizeRange), then fetch them."""
        peers = self._peers(max(self.parameters.sync_retry_nodes, 3))
        if not peers:
            return []
        req = CertificatesRangeRequest(from_round, to_round or (1 << 62))
        counts: dict[Digest, int] = defaultdict(int)
        answers = 0

        async def ask(peer: PublicKey, address: str) -> None:
            nonlocal answers
            try:
                resp: CertificatesRangeResponse = await self.network.request(
                    address, req, timeout=timeout
                )
            except (RpcError, OSError, asyncio.TimeoutError):
                self.peers.penalize(peer)
                return
            answers += 1
            for digest in resp.digests:
                counts[digest] += 1
            self.peers.reward(peer)

        await asyncio.gather(*(ask(p, a) for p, a in peers))
        if answers == 0:
            return []
        # Ceiling, not truncation: with 3 answers a digest needs 2 backers —
        # int() would let a single (possibly lying) peer's digest through.
        threshold = max(1, -(-answers * CERTIFICATE_RESPONSES_RATIO_THRESHOLD // 1))
        wanted = [
            d
            for d, n in counts.items()
            if n >= threshold and not self.certificate_store.contains(d)
        ]
        if wanted:
            await self._fetch_certificates(wanted, timeout)
        return wanted
