"""BlockSynchronizer: fetch missing certificates and payloads from peers.

Reference: /root/reference/primary/src/block_synchronizer/{mod,handler,
peers}.rs — three flows:

- `synchronize_block_headers(digests)`: certificates we lack are requested
  from peer primaries (`CertificatesBatchRequest`); responses are verified
  and re-injected into the Core (loopback channel) for causal completion,
  exactly like handler.rs:200-260.
- `synchronize_block_payloads(certs)`: ask peers who holds each payload
  (`PayloadAvailabilityRequest`), then instruct our workers to `Synchronize`
  the batches from the matching peer workers; completion is awaited on the
  payload store's notify primitive.
- `synchronize_range(from_round)`: restart catch-up — collect certificate
  digests above our last round from peers (`CertificatesRangeRequest`) and
  pull the certificates (mod.rs:75-83).

Peer selection keeps a simple success score per peer (peers.rs weights) and
asks the best `ask_nodes` peers concurrently, first sufficient answer wins.
"""

from __future__ import annotations

import asyncio
import logging
import random
from collections import defaultdict

from ..config import Committee, Parameters, WorkerCache
from ..messages import (
    CertificatesBatchRequest,
    CertificatesBatchResponse,
    CertificatesRangeRequest,
    CertificatesRangeResponse,
    PayloadAvailabilityRequest,
    PayloadAvailabilityResponse,
    SynchronizeMsg,
)
from ..network import NetworkClient, RpcError
from ..stores import CertificateStore, PayloadStore
from ..types import Certificate, Digest, PublicKey

logger = logging.getLogger("narwhal.primary")

CERTIFICATE_RESPONSES_RATIO_THRESHOLD = 0.5  # mod.rs:58


class BlockSynchronizer:
    def __init__(
        self,
        name: PublicKey,
        committee: Committee,
        worker_cache: WorkerCache,
        certificate_store: CertificateStore,
        payload_store: PayloadStore,
        network: NetworkClient,
        parameters: Parameters,
        tx_loopback=None,  # re-inject fetched certificates into the Core
    ):
        self.name = name
        self.committee = committee
        self.worker_cache = worker_cache
        self.certificate_store = certificate_store
        self.payload_store = payload_store
        self.network = network
        self.parameters = parameters
        self.tx_loopback = tx_loopback
        self._scores: dict[PublicKey, int] = defaultdict(int)  # peers.rs

    # -- peer selection ---------------------------------------------------

    def _peers(self, count: int) -> list[tuple[PublicKey, str]]:
        others = [
            (pk, address)
            for pk, address, _net in self.committee.others_primaries(self.name)
        ]
        random.shuffle(others)
        others.sort(key=lambda pa: -self._scores[pa[0]])
        return others[:count]

    # -- certificates -----------------------------------------------------

    async def synchronize_block_headers(
        self, digests: list[Digest], timeout: float | None = None
    ) -> list[Certificate]:
        """Return certificates for `digests`, fetching missing ones from
        peers; fetched certificates are verified, stored via the Core
        loopback, and returned."""
        found: dict[Digest, Certificate] = {}
        missing: list[Digest] = []
        for digest in digests:
            cert = self.certificate_store.read(digest)
            if cert is not None:
                found[digest] = cert
            else:
                missing.append(digest)
        if missing:
            fetched = await self._fetch_certificates(
                missing, timeout or self.parameters.sync_retry_delay * 4
            )
            for cert in fetched:
                found[cert.digest] = cert
        return [found[d] for d in digests if d in found]

    async def _fetch_certificates(
        self, digests: list[Digest], timeout: float
    ) -> list[Certificate]:
        peers = self._peers(self.parameters.sync_retry_nodes)
        if not peers:
            return []

        async def ask(peer: PublicKey, address: str) -> list[Certificate]:
            resp: CertificatesBatchResponse = await self.network.request(
                address, CertificatesBatchRequest(tuple(digests)), timeout=timeout
            )
            got = [c for _, c in resp.certificates if c is not None]
            self._scores[peer] += 1
            return got

        tasks = [asyncio.ensure_future(ask(p, a)) for p, a in peers]
        wanted = set(digests)
        collected: dict[Digest, Certificate] = {}
        try:
            for fut in asyncio.as_completed(tasks, timeout=timeout):
                try:
                    certs = await fut
                except (RpcError, OSError, asyncio.TimeoutError):
                    continue
                for cert in certs:
                    if cert.digest in wanted and cert.digest not in collected:
                        try:
                            cert.verify(self.committee, self.worker_cache)
                        except Exception as e:
                            logger.warning("peer sent invalid certificate: %s", e)
                            continue
                        collected[cert.digest] = cert
                if len(collected) == len(wanted):
                    break
        except asyncio.TimeoutError:
            pass
        finally:
            for t in tasks:
                t.cancel()
        # Hand fetched certificates to the Core for causal completion +
        # storage (handler.rs:233-249).
        if self.tx_loopback is not None:
            for cert in collected.values():
                await self.tx_loopback.send(cert)
        return list(collected.values())

    # -- payloads ---------------------------------------------------------

    async def synchronize_block_payloads(
        self, certificates: list[Certificate], timeout: float | None = None
    ) -> list[Certificate]:
        """Ensure the payload of each certificate is available in our
        workers' stores; returns the certificates whose payload arrived."""
        timeout = timeout or self.parameters.sync_retry_delay * 4
        pending = [
            c
            for c in certificates
            if any(
                not self.payload_store.contains(bd, wid)
                for bd, wid in c.header.payload.items()
            )
        ]
        if pending:
            providers = await self._payload_providers(pending, timeout)
            await self._request_worker_sync(pending, providers)

        async def wait_for(cert: Certificate) -> Certificate | None:
            try:
                await asyncio.wait_for(
                    asyncio.gather(
                        *(
                            self.payload_store.notify_contains(bd, wid)
                            for bd, wid in cert.header.payload.items()
                        )
                    ),
                    timeout,
                )
                return cert
            except asyncio.TimeoutError:
                return None

        results = await asyncio.gather(*(wait_for(c) for c in certificates))
        return [c for c in results if c is not None]

    async def _payload_providers(
        self, certificates: list[Certificate], timeout: float
    ) -> dict[Digest, list[PublicKey]]:
        """Which peers can serve each certificate's payload?"""
        digests = tuple(c.digest for c in certificates)
        peers = self._peers(self.parameters.sync_retry_nodes)
        providers: dict[Digest, list[PublicKey]] = defaultdict(list)

        async def ask(peer: PublicKey, address: str) -> None:
            resp: PayloadAvailabilityResponse = await self.network.request(
                address, PayloadAvailabilityRequest(digests), timeout=timeout
            )
            for digest, available in resp.available:
                if available:
                    providers[digest].append(peer)
            self._scores[peer] += 1

        await asyncio.gather(
            *(ask(p, a) for p, a in peers), return_exceptions=True
        )
        return providers

    async def _request_worker_sync(
        self,
        certificates: list[Certificate],
        providers: dict[Digest, list[PublicKey]],
    ) -> None:
        """Tell our workers which batches to pull and from whom."""
        by_worker: dict[int, dict[PublicKey, list[Digest]]] = defaultdict(
            lambda: defaultdict(list)
        )
        for cert in certificates:
            targets = providers.get(cert.digest) or [cert.origin]
            target = targets[0]
            for batch_digest, worker_id in cert.header.payload.items():
                if not self.payload_store.contains(batch_digest, worker_id):
                    by_worker[worker_id][target].append(batch_digest)
        for worker_id, per_target in by_worker.items():
            info = self.worker_cache.worker(self.name, worker_id)
            for target, batch_digests in per_target.items():
                await self.network.unreliable_send(
                    info.worker_address,
                    SynchronizeMsg(tuple(batch_digests), target),
                )

    # -- range catch-up ---------------------------------------------------

    async def synchronize_range(
        self, from_round: int, to_round: int | None = None, timeout: float = 5.0
    ) -> list[Digest]:
        """Collect certificate digests in (from_round, to_round] known to a
        quorum-ish of peers (mod.rs SynchronizeRange), then fetch them."""
        peers = self._peers(max(self.parameters.sync_retry_nodes, 3))
        if not peers:
            return []
        req = CertificatesRangeRequest(from_round, to_round or (1 << 62))
        counts: dict[Digest, int] = defaultdict(int)
        answers = 0

        async def ask(peer: PublicKey, address: str) -> None:
            nonlocal answers
            resp: CertificatesRangeResponse = await self.network.request(
                address, req, timeout=timeout
            )
            answers += 1
            for digest in resp.digests:
                counts[digest] += 1
            self._scores[peer] += 1

        await asyncio.gather(*(ask(p, a) for p, a in peers), return_exceptions=True)
        if answers == 0:
            return []
        threshold = max(1, int(answers * CERTIFICATE_RESPONSES_RATIO_THRESHOLD))
        wanted = [
            d
            for d, n in counts.items()
            if n >= threshold and not self.certificate_store.contains(d)
        ]
        if wanted:
            await self._fetch_certificates(wanted, timeout)
        return wanted
