"""The Core: header/vote/certificate protocol state machine.

Reference: /root/reference/primary/src/core.rs:36-715 — processes our own
headers (store, broadcast, self-vote), peers' headers (sanitize → parents &
payload availability → equivocation-protected vote), votes (stake aggregation
→ certificate assembly → broadcast), and certificates (causal-completeness
check → store → per-round quorum aggregation feeding the proposer → feed to
consensus). Garbage collection follows consensus round updates.
"""

from __future__ import annotations

import asyncio
import logging

from ..channels import Channel, Subscriber, Watch
from ..config import Committee, WorkerCache
from ..crypto import SignatureService
from ..network import NetworkClient
from ..stores import CertificateStore, HeaderStore, VoteDigestStore
from ..types import (
    Certificate,
    DagError,
    Digest,
    Header,
    InvalidEpoch,
    PublicKey,
    Round,
    TooOld,
    Vote,
)
from .aggregators import CertificatesAggregator, VotesAggregator
from .delta import (
    HeaderDeltaCodec,
    encode_announcement,
    encode_certificate_announcement,
)
from .synchronizer import Synchronizer
from .verifier_stage import PreVerified

logger = logging.getLogger("narwhal.primary")


class Core:
    def __init__(
        self,
        name: PublicKey,
        committee: Committee,
        worker_cache: WorkerCache,
        header_store: HeaderStore,
        certificate_store: CertificateStore,
        vote_digest_store: VoteDigestStore,
        synchronizer: Synchronizer,
        signature_service: SignatureService,
        network: NetworkClient,
        rx_primaries: Channel,  # Header | Vote | Certificate from peers
        rx_header_waiter: Channel,  # replayed headers whose deps arrived
        rx_certificate_waiter: Channel,  # replayed certificates
        rx_proposer: Channel,  # our own freshly built headers
        tx_consensus: Channel,
        tx_proposer: Channel,  # (parent certs, round, epoch)
        rx_consensus_round_updates: Watch,  # committed round for GC
        gc_depth: Round,
        rx_reconfigure: Watch,
        metrics=None,
        cert_format: str = "full",  # full | compact (Parameters.cert_format)
        fanout=None,  # fanout.FanoutBroadcaster: tree dissemination
        header_wire: str = "full",  # full | delta (Parameters.header_wire)
        wire_counters=None,  # network.WireCounters: per-round egress gauge
    ):
        self.name = name
        self.committee = committee
        self.worker_cache = worker_cache
        self.header_store = header_store
        self.certificate_store = certificate_store
        self.vote_digest_store = vote_digest_store
        self.synchronizer = synchronizer
        self.signature_service = signature_service
        self.network = network
        self.rx_primaries = rx_primaries
        self.rx_header_waiter = rx_header_waiter
        self.rx_certificate_waiter = rx_certificate_waiter
        self.rx_proposer = rx_proposer
        self.tx_consensus = tx_consensus
        self.tx_proposer = tx_proposer
        self.rx_consensus_round_updates = Subscriber(rx_consensus_round_updates)
        self.gc_depth = gc_depth
        self.rx_reconfigure = Subscriber(rx_reconfigure)
        self.metrics = metrics

        self.gc_round: Round = 0
        self.highest_received_round: Round = 0
        self.current_header: Header | None = None
        self.cert_format = cert_format
        # Wire diet: fanout-tree dissemination + delta-encoded
        # header/certificate announcements (primary/fanout.py, delta.py).
        # The codec always runs (decoding must work whatever WE send);
        # header_wire only selects the form we broadcast.
        self.fanout = fanout
        self.header_wire = header_wire
        self.delta_codec = HeaderDeltaCodec(committee)
        self.wire_counters = wire_counters
        self._egress_at_last_header = (
            wire_counters.bytes_sent if wire_counters is not None else 0
        )
        self.votes_aggregator = VotesAggregator(cert_format)
        self.certificates_aggregators: dict[Round, CertificatesAggregator] = {}
        self.processing: dict[Round, set[Digest]] = {}
        # Reliable-send handles by round, dropped (cancelled) at GC so a dead
        # peer can't accumulate retry-forever tasks (core.rs cancel_handlers).
        self.cancel_handlers: dict[Round, list] = {}
        # Channel the certificate waiter listens on; set by the assembly.
        self.tx_certificate_waiter: Channel | None = None
        # Committee-wide payload sighting hook (set by the assembly to the
        # proposer's note_payload): a peer's payload-bearing header keeps
        # OUR round cadence on the pacing floor so the quorum commits it
        # promptly even when our own worker is idle.
        self.on_payload_header = None
        # Messages from a FUTURE epoch: our reconfigure notification races
        # the first new-epoch header over different channels, and dropping
        # the loser can deadlock the epoch change (every peer drops every
        # other peer's round-1 header and nobody re-requests it). Hold a
        # bounded buffer and replay it the moment we adopt the new epoch.
        self.pending_future_epoch: list[tuple[object, bool]] = []
        # Deferred group-commit futures: header/certificate store writes
        # enqueue onto the engine's commit group (immediately visible via
        # the memtable) and are awaited ONCE per run-loop iteration, so a
        # burst of K messages costs one fused WAL flush, not K.
        self._pending_commits: list = []
        # Bounded greedy drain of each input channel per loop iteration: a
        # burst of K queued certificates becomes one grouped store commit
        # and one batched consensus/proposer hand-off instead of K
        # interleaved awaits.
        self.max_burst = 64
        self._task: asyncio.Task | None = None

    def spawn(self) -> asyncio.Task:
        self._task = asyncio.ensure_future(self.run())
        return self._task

    # ------------------------------------------------------------------
    # Own-header path (core.rs:149-179)
    # ------------------------------------------------------------------
    async def process_own_header(self, header: Header) -> None:
        self.current_header = header
        self.votes_aggregator = VotesAggregator(self.cert_format)
        if self.wire_counters is not None and self.metrics is not None:
            # Per-round egress: everything this primary wrote to the wire
            # since its previous header (the quantity the fanout tree +
            # delta encodings exist to shrink; MB/round from metrics, not
            # log scraping).
            # WireCounters are monotonic add-only tallies bumped by every
            # sender task; a read interleaving with an add is off by one
            # frame's bytes at worst — metrics-grade, not protocol state.
            total = self.wire_counters.bytes_sent  # lint: allow(multi-task-mutation)
            self.metrics.round_egress_bytes.set(total - self._egress_at_last_header)
            self._egress_at_last_header = total
        self.delta_codec.note_own_header(header)
        msg = encode_announcement(self.delta_codec, header, self.header_wire)
        self._broadcast(header.round, msg)
        await self.process_header(header)

    def _broadcast(self, round: Round, msg) -> None:
        """Disseminate an announcement: through the fanout tree when one is
        wired (it owns + GCs the handles), else the reference's all-to-all
        reliable broadcast with round-keyed cancel handles."""
        if self.fanout is not None:
            self.fanout.broadcast(round, msg)
            return
        addresses = [
            addr for _, addr, _ in self.committee.others_primaries(self.name)
        ]
        handlers = self.network.broadcast(addresses, msg)
        self.cancel_handlers.setdefault(round, []).extend(handlers)

    # ------------------------------------------------------------------
    # Header path (core.rs:183-355)
    # ------------------------------------------------------------------
    async def process_header(self, header: Header) -> None:
        self.processing.setdefault(header.round, set()).add(header.digest)
        # Headers reach us a full round before their certificates: index
        # the DERIVED certificate digest now so peers' next-round delta
        # headers reconstruct without waiting on the certificate broadcast.
        self.delta_codec.note_header(header)
        if header.payload and self.on_payload_header is not None:
            self.on_payload_header()

        # Causal completeness: parents must be certified and local
        # (core.rs:200-231). The synchronizer queues repair + loopback.
        parents = await self.synchronizer.get_parents(header)
        if parents is None:
            logger.debug("Header %s suspended: missing parents", header.digest.hex()[:16])
            if self.metrics is not None:
                self.metrics.headers_suspended.inc()
            return
        # Always run the round-match and stake-quorum checks — genesis
        # certificates count toward the quorum like any parent
        # (synchronizer.rs:119-125, core.rs:214-231). An empty parent set
        # yields zero stake and is rejected here, never voted for.
        stake = sum(self.committee.stake(p.origin) for p in parents)
        if any(p.round + 1 != header.round for p in parents):
            raise DagError(f"header {header.digest.hex()[:16]} has malformed parents")
        if stake < self.committee.quorum_threshold():
            raise DagError(
                f"header {header.digest.hex()[:16]} lacks parent quorum"
            )

        # Payload availability (core.rs:233-246).
        if await self.synchronizer.missing_payload(header):
            logger.debug("Header %s suspended: missing payload", header.digest.hex()[:16])
            if self.metrics is not None:
                self.metrics.headers_suspended.inc()
            return

        # Group commit: the header is readable (and notify_read fires)
        # immediately via the memtable; durability is awaited once per
        # run-loop burst rather than per header.
        self._pending_commits.append(self.header_store.write_async(header))
        if self.metrics is not None:
            self.metrics.headers_processed.inc()

        # Equivocation-protected voting (core.rs:281-308): vote at most once
        # per (origin, round), persistently.
        last = self.vote_digest_store.read(header.author)
        if last is not None:
            last_round, last_digest = last
            if header.round < last_round:
                return
            if header.round == last_round and last_digest != header.digest:
                logger.warning(
                    "Authority %s equivocated at round %s",
                    header.author.hex()[:16],
                    header.round,
                )
                return
            if header.round == last_round and last_digest == header.digest and header.author != self.name:
                pass  # re-vote the same header is safe (vote may have been lost)
        # The equivocation guard must be durable BEFORE the vote leaves this
        # node (a crash in between could re-vote differently on restart), so
        # this one write awaits its commit group — concurrent writers across
        # the process share the flush.
        await self.vote_digest_store.write_async(
            header.author, header.round, header.digest
        )

        vote = Vote.for_header(header, self.name, self.signature_service)
        if header.author == self.name:
            await self.process_vote(vote)
        else:
            from ..messages import Vote2Msg

            # Slim wire form (the author reconstructs round/epoch/origin
            # from its own header); generous per-attempt deadline — a
            # deadline miss on a loaded committee means the author is slow,
            # and the resent 200-byte frames were measurable at N=50.
            address = self.committee.primary_address(header.author)
            handler = self.network.send(
                address, Vote2Msg.from_vote(vote), timeout=30.0
            )
            self.cancel_handlers.setdefault(header.round, []).append(handler)
            if self.metrics is not None:
                self.metrics.votes_sent.inc()

    # ------------------------------------------------------------------
    # Vote path (core.rs:359-396)
    # ------------------------------------------------------------------
    async def process_vote(self, vote: Vote) -> None:
        if self.fanout is not None:
            # A vote proves the voter received our header broadcast — the
            # implicit receipt that replaces explicit relay acks on the
            # slim header lane (fanout.note_vote).
            self.fanout.note_vote(vote.round, vote.author)
        if self.current_header is None or vote.header_digest != self.current_header.digest:
            return  # vote for an old header of ours
        certificate = self.votes_aggregator.append(
            vote, self.committee, self.current_header
        )
        if self.metrics is not None:
            self.metrics.votes_processed.inc()
        if certificate is not None:
            logger.debug(
                "Assembled certificate %s round %s",
                certificate.digest.hex()[:16],
                certificate.round,
            )
            if self.metrics is not None:
                self.metrics.certificates_created.inc()
                # Stage tracing: the proposer started this clock when it
                # proposed the header this certificate certifies. The causal
                # key hops header -> certificate here, so record the link
                # edge the waterfall joins on.
                self.metrics.certify_timer.stop(certificate.header.digest)
                tracer = self.metrics.tracer
                if (
                    tracer is not None
                    and tracer.enabled
                    and tracer.sampled(certificate.header.digest)
                ):
                    tracer.link(
                        "certify", certificate.header.digest, certificate.digest
                    )
            # Compact certificates broadcast by reference (peers hold the
            # header already — they voted on it); full-format ones shed the
            # embedded header body the same way under header_wire="delta".
            msg = encode_certificate_announcement(certificate, self.header_wire)
            self._broadcast(certificate.round, msg)
            await self.process_certificate(certificate)

    # ------------------------------------------------------------------
    # Certificate path (core.rs:400-494)
    # ------------------------------------------------------------------
    async def process_certificate(self, certificate: Certificate) -> None:
        # Process the embedded header if we haven't seen it: its quorum of
        # signers proves the data exists, but we still want our local copy of
        # payload/parents fetched (core.rs:404-417).
        if certificate.header.digest not in self.processing.get(
            certificate.header.round, set()
        ):
            await self.process_header(certificate.header)

        # Ancestry must be locally complete before the DAG accepts it; the
        # certificate waiter replays it once parents arrive (core.rs:419-431).
        if not certificate.is_genesis() and not self.synchronizer.deliver_certificate(
            certificate
        ):
            logger.debug(
                "Certificate %s suspended: missing ancestors",
                certificate.digest.hex()[:16],
            )
            if self.metrics is not None:
                self.metrics.certificates_suspended.inc()
            if self.tx_certificate_waiter is not None:
                await self.tx_certificate_waiter.send(certificate)
            return

        self._pending_commits.append(
            self.certificate_store.write_async(certificate)
        )
        # Accepted certificates feed the delta codec's recent index: the
        # encoder resolves its own parents from here, the decoder any delta
        # header the core drains after this certificate.
        self.delta_codec.note_certificate(certificate)
        if self.metrics is not None:
            self.metrics.certificates_processed.inc()

        # Enough certificates at this round => next-round parents for the
        # proposer (core.rs:445-461).
        aggregator = self.certificates_aggregators.setdefault(
            certificate.round, CertificatesAggregator()
        )
        parents = aggregator.append(certificate, self.committee)
        if parents is not None:
            # Wait-cycle with the proposer (core -> tx_parents -> proposer
            # -> tx_headers -> core), justified: the protocol itself bounds
            # the in-flight count far below either capacity — the
            # aggregator emits at most ONE parent set per round, the
            # proposer at most one header per round, and neither side can
            # advance a round until the other consumed the previous item
            # (round advance is parent-quorum-gated). narwhal-topo flags
            # the shape; this argument is why it cannot fill.
            # lint: allow(bounded-channel-cycle)
            await self.tx_proposer.send(
                (parents, certificate.round, certificate.epoch)
            )

        await self.tx_consensus.send(certificate)

    # ------------------------------------------------------------------
    # Sanitization (core.rs:497-573)
    # ------------------------------------------------------------------
    def sanitize_header(self, header: Header, preverified: bool = False) -> None:
        if header.epoch != self.committee.epoch:
            raise InvalidEpoch(f"header from epoch {header.epoch}")
        if header.round <= self.gc_round:
            raise TooOld(f"header round {header.round} <= gc {self.gc_round}")
        header.verify(self.committee, self.worker_cache, check_signature=not preverified)

    def sanitize_vote(self, vote: Vote, preverified: bool = False) -> None:
        if vote.epoch != self.committee.epoch:
            raise InvalidEpoch(f"vote from epoch {vote.epoch}")
        if self.current_header is None or vote.round < self.current_header.round:
            raise TooOld(f"vote for stale round {vote.round}")
        vote.verify(self.committee, check_signature=not preverified)

    def sanitize_certificate(
        self, certificate: Certificate, preverified: bool = False
    ) -> None:
        if certificate.epoch != self.committee.epoch:
            raise InvalidEpoch(f"certificate from epoch {certificate.epoch}")
        if certificate.round < self.gc_round:
            raise TooOld(
                f"certificate round {certificate.round} < gc {self.gc_round}"
            )
        if preverified:
            # Signatures checked by the verifier stage; re-run only the
            # structural/stake checks (no message/weight recomputation).
            certificate.structural_verify(self.committee)
        else:
            # Terminal no-pool fallback (full-format cpu committees and the
            # block-synchronizer loopback): Certificate.verify itself rides
            # the cached single-group MSM for compact proofs, so the
            # loopback re-check of an already-pool-verified fetch is a
            # process-wide cache hit.
            # lint: allow(no-per-item-cert-verify)
            certificate.verify(self.committee, self.worker_cache)

    def _observe_round(self, round: Round) -> None:
        """Track the highest round seen for metrics (core.rs:434-443)."""
        if round > self.highest_received_round:
            self.highest_received_round = round

    # ------------------------------------------------------------------
    # Main loop (core.rs:615-715)
    # ------------------------------------------------------------------
    async def _handle_message(self, msg) -> None:
        preverified = isinstance(msg, PreVerified)
        if preverified:
            msg = msg.inner
        try:
            if isinstance(msg, Header):
                self.sanitize_header(msg, preverified)
                self._observe_round(msg.round)
                await self.process_header(msg)
            elif isinstance(msg, Vote):
                self.sanitize_vote(msg, preverified)
                await self.process_vote(msg)
            elif isinstance(msg, Certificate):
                self.sanitize_certificate(msg, preverified)
                self._observe_round(msg.round)
                await self.process_certificate(msg)
            else:
                logger.warning("Core received unexpected %r", type(msg))
        except (InvalidEpoch, TooOld) as e:
            if (
                isinstance(e, InvalidEpoch)
                and getattr(msg, "epoch", 0) == self.committee.epoch + 1
            ):
                # Exactly one epoch ahead: our own reconfigure notification
                # is in flight, not a byzantine replay (anything further
                # ahead IS dropped — a peer cannot legitimately outrun our
                # reconfigure by more than one epoch, and a bigger horizon
                # would let an adversary squat the buffer).
                if len(self.pending_future_epoch) < 128:
                    self.pending_future_epoch.append((msg, preverified))
                logger.debug("Buffered next-epoch message: %s", e)
            else:
                logger.debug("Dropped stale message: %s", e)
        except DagError as e:
            logger.warning("Rejected message: %s", e)

    async def _gc(self, committed_round: Round) -> None:
        if committed_round <= self.gc_depth:
            return
        gc_round = committed_round - self.gc_depth
        if gc_round <= self.gc_round:
            return
        self.gc_round = gc_round
        for r in [r for r in self.processing if r <= gc_round]:
            del self.processing[r]
        for r in [r for r in self.certificates_aggregators if r <= gc_round]:
            del self.certificates_aggregators[r]
        for r in [r for r in self.cancel_handlers if r <= gc_round]:
            for handler in self.cancel_handlers.pop(r):
                handler.cancel()
        self.delta_codec.gc(gc_round)
        if self.fanout is not None:
            self.fanout.gc(gc_round)
        if self.metrics is not None:
            self.metrics.gc_round.set(gc_round)

    async def run(self) -> None:
        channels = {
            "primaries": self.rx_primaries,
            "header_waiter": self.rx_header_waiter,
            "certificate_waiter": self.rx_certificate_waiter,
            "proposer": self.rx_proposer,
        }
        tasks = {
            key: asyncio.ensure_future(ch.recv()) for key, ch in channels.items()
        }
        recon_task = asyncio.ensure_future(self.rx_reconfigure.changed())
        round_task = asyncio.ensure_future(self.rx_consensus_round_updates.changed())
        try:
            while True:
                done, _ = await asyncio.wait(
                    set(tasks.values()) | {recon_task, round_task},
                    return_when=asyncio.FIRST_COMPLETED,
                )
                if recon_task in done:
                    note = recon_task.result()
                    if note.kind == "shutdown":
                        return
                    if note.committee is not None:
                        self.change_epoch(note.committee)
                        # Replay messages that arrived from this epoch before
                        # we adopted it (full re-sanitization: anything still
                        # ahead or now stale re-buffers or drops).
                        replay, self.pending_future_epoch = (
                            self.pending_future_epoch, []
                        )
                        for m, pv in replay:
                            await self._handle_message(
                                PreVerified(m) if pv else m
                            )
                    recon_task = asyncio.ensure_future(self.rx_reconfigure.changed())
                if round_task in done:
                    committed_round = round_task.result()
                    round_task = asyncio.ensure_future(
                        self.rx_consensus_round_updates.changed()
                    )
                    await self._gc(committed_round)
                for key, ch in channels.items():
                    task = tasks[key]
                    if task not in done:
                        continue
                    # Done asyncio task from the select set — result() is a
                    # completed-task read.  # lint: allow(no-blocking-in-async)
                    msgs = [task.result()]
                    # Greedy bounded drain: everything already queued (up
                    # to max_burst) is handled in this iteration, sharing
                    # one grouped store commit below instead of one select
                    # round-trip + flush each.
                    while len(msgs) < self.max_burst:
                        extra = ch.try_recv()
                        if extra is None:
                            break
                        msgs.append(extra)
                    tasks[key] = asyncio.ensure_future(ch.recv())
                    if self.metrics is not None:
                        self.metrics.core_burst.observe(len(msgs))
                    for msg in msgs:
                        if key == "proposer":
                            await self.process_own_header(msg)
                        elif key in ("header_waiter",):
                            # Replayed headers were sanitized on first
                            # receipt.
                            try:
                                await self.process_header(msg)
                            except DagError as e:
                                logger.warning("Replayed header rejected: %s", e)
                        elif key == "certificate_waiter":
                            try:
                                await self.process_certificate(msg)
                            except DagError as e:
                                logger.warning(
                                    "Replayed certificate rejected: %s", e
                                )
                        else:
                            await self._handle_message(msg)
                # One durability barrier per iteration: every store write
                # deferred above rides a shared fused WAL flush.
                if self._pending_commits:
                    commits, self._pending_commits = self._pending_commits, []
                    await asyncio.gather(*commits)
        finally:
            for t in tasks.values():
                t.cancel()
            recon_task.cancel()
            round_task.cancel()

    def change_epoch(self, committee: Committee) -> None:
        """(core.rs:592-611): fresh per-epoch volatile state."""
        self.committee = committee
        self.gc_round = 0
        self.highest_received_round = 0
        self.current_header = None
        self.votes_aggregator = VotesAggregator(self.cert_format)
        self.certificates_aggregators.clear()
        self.processing.clear()
        # Rounds restart at 0: the persistent per-author vote guard must be
        # wiped or no new-epoch header ever gets a vote (core.rs:598-601).
        self.vote_digest_store.clear()
        for handlers in self.cancel_handlers.values():
            for handler in handlers:
                handler.cancel()
        self.cancel_handlers.clear()
        self.delta_codec.change_epoch(committee)
        if self.fanout is not None:
            self.fanout.change_epoch(committee)
        self.synchronizer.update_genesis(self.committee)
