"""Primary assembly: channels, RPC routing, and actor spawning.

Reference: /root/reference/primary/src/primary.rs:71-470 — creates the metered
channels, binds the primary network address with the PrimaryToPrimary and
WorkerToPrimary services, and spawns Core, Proposer, HeaderWaiter,
CertificateWaiter, PayloadReceiver, Helper (mounted as RPC handlers here) and
StateHandler. Consensus channels (tx_new_certificates in, rx_committed
certificates back) are handed in by the node assembly, like the reference's
spawn signature.
"""

from __future__ import annotations

import asyncio
import logging

from ..channels import Channel, Watch, drain_cancelled, metered_channel
from ..config import (
    Committee,
    Parameters,
    WorkerCache,
    connection_pool_effective,
    env_float,
    header_wire_effective,
    pacing_enabled,
    relay_fanout_effective,
)
from ..crypto import SignatureService
from ..messages import (
    CertificateDeltaMsg,
    CertificatesBatchRequest,
    CertificatesRangeRequest,
    CertificateMsg,
    DeltaHeaderMsg,
    HeaderMsg,
    HeaderResyncRequest,
    HeaderResyncResponse,
    OthersBatchMsg,
    OurBatchMsg,
    PayloadAvailabilityRequest,
    ReconfigureMsg,
    RelayAckMsg,
    RelayMsg,
    VoteMsg,
)
from ..metrics import Registry
from ..network import NetworkClient, RpcServer, WireCounters, cached_allow_sets
from ..stores import NodeStorage
from ..types import Certificate, PublicKey, ReconfigureNotification
from .certificate_waiter import CertificateWaiter
from .core import Core
from .fanout import FanoutBroadcaster
from .header_waiter import HeaderWaiter
from .helper import Helper
from .metrics import PrimaryMetrics
from .payload_receiver import PayloadReceiver
from .proposer import NetworkModel, Proposer
from .state_handler import StateHandler
from .synchronizer import Synchronizer

logger = logging.getLogger("narwhal.primary")


class Primary:
    def __init__(
        self,
        name: PublicKey,
        signature_service: SignatureService,
        committee: Committee,
        worker_cache: WorkerCache,
        parameters: Parameters,
        storage: NodeStorage,
        tx_new_certificates: Channel,  # -> consensus
        rx_committed_certificates: Channel,  # <- consensus
        network_model: NetworkModel = NetworkModel.PARTIALLY_SYNCHRONOUS,
        registry: Registry | None = None,
        crypto_pool=None,  # AsyncVerifierPool: enables the pre-verify stage
        network_keypair=None,
        tracer=None,  # tracing.Tracer: the node's span/flight recorder
    ):
        self.name = name
        self.committee = committee
        self.worker_cache = worker_cache
        self.parameters = parameters
        self.storage = storage
        self.registry = registry or Registry()
        if tracer is None:
            from ..tracing import Tracer

            tracer = Tracer(node=f"primary-{name.hex()[:8]}")
        self.tracer = tracer
        self.metrics = PrimaryMetrics(self.registry, tracer=tracer)

        # Transport identity (the anemo PeerId model, p2p.rs:26-158): with a
        # network keypair the primary mesh requires the mutual handshake;
        # without one (bare component tests) it runs open.
        self.network_keypair = network_keypair
        credentials = None
        if network_keypair is not None:
            from ..network import Credentials, committee_resolver

            credentials = Credentials(
                network_keypair,
                committee_resolver(lambda: self.committee, lambda: self.worker_cache),
            )
        # Per-link wire accounting: every frame this primary writes/reads,
        # by message type and lane (wire_bytes_{sent,received}_total
        # {msg_type=,lane=}) — the measurement plane for the fanout/delta
        # wire diet and the pool's lane interleaving.
        self.wire_counters = WireCounters(self.registry)
        # Connection pool: ONE multiplexed authenticated stream per peer
        # node pair, shared by every co-hosted lane (network/pool.py). The
        # primary — holder of the node's network keypair — owns the pool
        # and registers it for the node's workers to join at spawn.
        # Pooling needs the authenticated handshake (the link identity IS
        # the verified network key), so bare unauthenticated assemblies run
        # legacy dedicated connections.
        self.pool = None
        if credentials is not None and connection_pool_effective(parameters):
            from ..network import LanePool, register_node_pool

            self.pool = LanePool(
                network_keypair.public,
                credentials,
                lambda: self.committee,
                lambda: self.worker_cache,
                counters=self.wire_counters,
                passive_dial_delay=parameters.pool_passive_dial_delay,
                linger=parameters.pool_linger,
            )
            register_node_pool(self.name, self.pool)
        self.network = NetworkClient(
            credentials=credentials, counters=self.wire_counters, pool=self.pool
        )
        self.server = RpcServer(
            parameters.max_concurrent_requests,
            auth_keypair=network_keypair,
            counters=self.wire_counters,
            pool=self.pool,
            dedup_cache_bytes=parameters.relay_dedup_cache_bytes,
        )
        if self.pool is not None:
            from ..network import LANE_PRIMARY

            self.pool.register_lane(LANE_PRIMARY, self.server)
        self._tasks: list[asyncio.Task] = []

        # Channels (primary.rs:104-151), each with a depth gauge — SURVEY
        # §5.6 "every inter-task channel is a gauge"
        # (types/src/metered_channel.rs:15-259, PrimaryChannelMetrics).
        def chan(name: str, capacity: int) -> Channel:
            return metered_channel(self.registry, "primary", name, capacity)

        self.tx_primary_messages = chan("primary_messages", 1_000)
        self.tx_headers_loopback = chan("headers_loopback", 1_000)
        self.tx_certificates_loopback = chan("certificates_loopback", 1_000)
        self.tx_sync_headers = chan("sync_headers", 1_000)  # SyncBatches|Parents
        self.tx_sync_certificates = chan("sync_certificates", 1_000)  # suspended
        self.tx_headers = chan("headers", 1_000)  # proposer -> core
        self.tx_parents = chan("parents", 1_000)  # core -> proposer
        self.tx_our_digests = chan("our_digests", 10_000)  # workers -> proposer
        self.tx_others_digests = chan("others_digests", 10_000)  # -> payload recv
        self.tx_state_handler = chan("state_handler", 100)
        self.tx_new_certificates = tx_new_certificates
        self.rx_committed_certificates = rx_committed_certificates

        # Watches.
        self.tx_reconfigure: Watch = Watch(ReconfigureNotification("boot"))
        self.tx_consensus_round_updates: Watch = Watch(0)

        self.header_store = storage.header_store
        self._ref_tasks: set[asyncio.Task] = set()  # certificate-ref resolvers

        genesis = {c.digest: c for c in Certificate.genesis(committee)}
        genesis_digests = frozenset(genesis)
        self.synchronizer = Synchronizer(
            name,
            storage.certificate_store,
            storage.payload_store,
            self.tx_sync_headers,
            genesis,
        )
        self.helper = Helper(
            committee, storage.certificate_store, storage.payload_store
        )
        # Fanout-tree dissemination (degenerates to direct broadcast when
        # the committee is too small for the tree to have depth >= 2, and
        # under the NARWHAL_RELAY=0 kill-switch).
        self.fanout = FanoutBroadcaster(
            name,
            committee,
            self.network,
            fanout=relay_fanout_effective(parameters),
            fallback_timeout=parameters.relay_fallback_timeout,
            metrics=self.metrics,
        )
        self.core = Core(
            name,
            committee,
            worker_cache,
            storage.header_store,
            storage.certificate_store,
            storage.vote_digest_store,
            self.synchronizer,
            signature_service,
            self.network,
            self.tx_primary_messages,
            self.tx_headers_loopback,
            self.tx_certificates_loopback,
            self.tx_headers,
            self.tx_new_certificates,
            self.tx_parents,
            self.tx_consensus_round_updates,
            parameters.gc_depth,
            self.tx_reconfigure,
            self.metrics,
            cert_format=getattr(parameters, "cert_format", "full"),
            fanout=self.fanout,
            header_wire=header_wire_effective(parameters),
            wire_counters=self.wire_counters,
        )
        self.core.tx_certificate_waiter = self.tx_sync_certificates
        # Adaptive header pacing: the proposer's effective delay tracks the
        # EWMA occupancy of the digest/ingest/consensus channels between
        # header_delay_floor and max_header_delay — short rounds when the
        # pipeline is shallow, full-sized headers at the configured cadence
        # under load. NARWHAL_PACING=0 pins the ceiling (seed behavior).
        proposer_pacing = None
        if pacing_enabled():
            from ..pacing import PacingController

            proposer_pacing = PacingController(
                ceiling=parameters.max_header_delay,
                floor=env_float(
                    "NARWHAL_HEADER_DELAY_FLOOR", parameters.header_delay_floor
                ),
                low_occupancy=parameters.pacing_low_occupancy,
                high_occupancy=parameters.pacing_high_occupancy,
                ewma_alpha=parameters.pacing_ewma_alpha,
                sources=[
                    self.tx_our_digests.occupancy,
                    self.tx_primary_messages.occupancy,
                    self.tx_new_certificates.occupancy,
                ],
                gauge=self.metrics.pacing_occupancy,
            )
        self.proposer = Proposer(
            name,
            committee,
            signature_service,
            parameters.header_size,
            parameters.max_header_delay,
            network_model,
            self.tx_parents,
            self.tx_our_digests,
            self.tx_headers,
            self.tx_reconfigure,
            self.metrics,
            pacing=proposer_pacing,
        )
        # A peer's payload-bearing header keeps our proposer on the pacing
        # floor: round advance is quorum-gated, so the whole committee must
        # hurry for anyone's payload to commit fast.
        self.core.on_payload_header = self.proposer.note_payload
        self.header_waiter = HeaderWaiter(
            name,
            committee,
            worker_cache,
            storage.certificate_store,
            storage.payload_store,
            parameters,
            self.network,
            self.tx_sync_headers,
            self.tx_headers_loopback,
            self.tx_primary_messages,
            self.tx_consensus_round_updates,
            self.tx_reconfigure,
            self.metrics,
        )
        self.certificate_waiter = CertificateWaiter(
            storage.certificate_store,
            genesis_digests,
            self.tx_sync_certificates,
            self.tx_certificates_loopback,
            self.tx_consensus_round_updates,
            self.tx_reconfigure,
            parameters.gc_depth,
            self.metrics,
        )
        self.payload_receiver = PayloadReceiver(
            storage.payload_store, self.tx_others_digests
        )
        if crypto_pool is not None:
            from .verifier_stage import VerifierStage

            self.verifier_stage = VerifierStage(
                committee,
                worker_cache,
                crypto_pool,
                self.tx_primary_messages,
                rx_reconfigure=self.tx_reconfigure,
            )
        else:
            self.verifier_stage = None
        self.state_handler = StateHandler(
            name,
            committee,
            worker_cache,
            self.network,
            self.rx_committed_certificates,
            self.tx_state_handler,
            self.tx_consensus_round_updates,
            self.tx_reconfigure,
            self.metrics,
        )

    async def spawn(self) -> None:
        address = self.committee.primary_address(self.name)
        host, port = address.rsplit(":", 1)
        bound = await self.server.start(host, int(port))
        self.address = f"{host}:{bound}"

        # PrimaryToPrimary plane: any committee primary's network identity.
        # WorkerToPrimary plane (digests + reconfigure): ONLY our own workers
        # (worker/src/primary_connector.rs; state path state_handler.rs).
        allow_peer_primary = self._allow_peer_primary if self.network_keypair else None
        allow_own_worker = self._allow_own_worker if self.network_keypair else None
        self.server.route(HeaderMsg, self._on_header, allow=allow_peer_primary)
        self.server.route(VoteMsg, self._on_vote, allow=allow_peer_primary)
        self.server.route(CertificateMsg, self._on_certificate, allow=allow_peer_primary)
        from ..messages import CertificateRefMsg

        self.server.route(
            CertificateRefMsg, self._on_certificate_ref, allow=allow_peer_primary
        )
        # Wire-diet plane: relay envelopes + delta announcements + resync.
        # Relay envelopes are forwarded UNCHANGED hop to hop, so duplicate
        # copies arriving from different relayers are byte-identical: the
        # dedup= shortcut answers all but the first from the server's
        # digest cache — ack/forward bookkeeping still runs, but the codec
        # decode and the core's sanitize path are paid once per payload,
        # not once per copy (the N=200 per-copy decode tax).
        self.server.route(
            RelayMsg, self._on_relay, allow=allow_peer_primary,
            dedup=self._on_relay_dup,
        )
        self.server.route(RelayAckMsg, self._on_relay_ack, allow=allow_peer_primary)
        from ..messages import Relay2Msg, RelayAck2Msg, Vote2Msg

        self.server.route(
            Relay2Msg, self._on_relay2, allow=allow_peer_primary,
            dedup=self._on_relay2_dup,
        )
        self.server.route(
            RelayAck2Msg, self._on_relay_ack2, allow=allow_peer_primary
        )
        self.server.route(Vote2Msg, self._on_vote2, allow=allow_peer_primary)
        self.server.route(
            DeltaHeaderMsg, self._on_delta_header, allow=allow_peer_primary
        )
        # CertificateDeltaMsg shares CertificateRefMsg's resolution path:
        # identical field names + rebuild(header) signature.
        self.server.route(
            CertificateDeltaMsg, self._on_certificate_ref, allow=allow_peer_primary
        )
        self.server.route(
            HeaderResyncRequest, self._on_header_resync, allow=allow_peer_primary
        )
        self.server.route(
            CertificatesBatchRequest,
            self.helper.on_certificates_batch,
            allow=allow_peer_primary,
        )
        self.server.route(
            CertificatesRangeRequest,
            self.helper.on_certificates_range,
            allow=allow_peer_primary,
        )
        self.server.route(
            PayloadAvailabilityRequest,
            self.helper.on_payload_availability,
            allow=allow_peer_primary,
        )
        self.server.route(OurBatchMsg, self._on_our_batch, allow=allow_own_worker)
        self.server.route(OthersBatchMsg, self._on_others_batch, allow=allow_own_worker)
        self.server.route(ReconfigureMsg, self._on_reconfigure, allow=allow_own_worker)

        self._tasks = [
            self.core.spawn(),
            self.proposer.spawn(),
            self.header_waiter.spawn(),
            self.certificate_waiter.spawn(),
            self.payload_receiver.spawn(),
            self.state_handler.spawn(),
        ]
        # Benchmark-parsed boot line (primary.rs:442-450).
        logger.info(
            "Primary %s successfully booted on %s", self.name.hex()[:16], self.address
        )

    # -- authorization predicates ------------------------------------------
    def _auth_sets(self) -> tuple[frozenset, frozenset]:
        def build():
            primaries = frozenset(
                a.network_key for a in self.committee.authorities.values()
            )
            workers = frozenset(
                info.name
                for info in self.worker_cache.our_workers(self.name).values()
            )
            # Pooled links authenticate with the NODE identity (the
            # authority network key) rather than per-worker keys, so our
            # own workers' traffic over the self-link presents our own
            # network key — the anemo node-granularity trust model.
            own = self.committee.authorities.get(self.name)
            if own is not None:
                workers = workers | {own.network_key}
            return primaries, workers

        return cached_allow_sets(self, self.committee, self.worker_cache, build)

    def _allow_peer_primary(self, peer) -> bool:
        """Any committee authority's primary network identity."""
        return peer.key is not None and peer.key in self._auth_sets()[0]

    def _allow_own_worker(self, peer) -> bool:
        """Only our own authority's workers."""
        return peer.key is not None and peer.key in self._auth_sets()[1]

    # -- handlers ----------------------------------------------------------
    async def _ingest(self, msg) -> None:
        """Protocol messages go through the async verification stage when a
        crypto pool is configured (signatures batched off the Core's loop),
        else straight to the Core."""
        if self.verifier_stage is not None:
            await self.verifier_stage.submit(msg)
        else:
            await self.tx_primary_messages.send(msg)

    async def _on_header(self, msg: HeaderMsg, peer: str):
        await self._ingest(msg.header)
        return None

    async def _on_vote(self, msg: VoteMsg, peer: str):
        await self._ingest(msg.vote)
        return None

    async def _on_certificate(self, msg: CertificateMsg, peer: str):
        await self._ingest(msg.certificate)
        return None

    async def _on_vote2(self, msg, peer: str):
        """Slim vote: reconstruct the full Vote from the header it
        endorses — our current header in the common case, the header store
        for a late one. A vote can OUTRUN our own proposal processing (the
        broadcast leaves before the core stores the header; on a loaded
        1-core host the voter's round trip can win that race), so a miss
        WAITS on the store instead of dropping: the RPC ack tells the
        voter's reliable send the vote landed, so a silent drop here would
        lose the vote forever — fatal in a committee whose quorum needs
        every survivor. The reconstructed fields are covered by the vote
        signature, so a forged rebuild can only fail verification."""
        # Atomic read of Core's latest proposed header (Core.run replaces
        # the whole reference between awaits, never mutates in place); a
        # mismatch just falls through to the store/waiter path below.
        header = self.core.current_header  # lint: allow(multi-task-mutation)
        if header is None or header.digest != msg.header_digest:
            header = self.header_store.read(msg.header_digest)
        if header is None:
            try:
                header = await asyncio.wait_for(
                    self.header_store.notify_read(msg.header_digest), timeout=3.0
                )
            except asyncio.TimeoutError:
                return None  # genuinely unknown header: stale/forged vote
        if header.author != self.name:
            return None
        await self._ingest(msg.rebuild(header))
        return None

    async def _on_relay(self, msg: RelayMsg, peer: str):
        """Fanout-tree envelope: forward to our children in the origin's
        tree + ack the origin (both non-blocking), then deliver the inner
        announcement through the same ingest path a direct send takes."""
        try:
            inner = msg.inner()
        except ValueError as e:
            logger.warning("relay with undecodable inner message: %s", e)
            return None
        self.fanout.on_relay(msg)
        await self._deliver_announcement(inner, peer)
        return None

    async def _deliver_announcement(self, inner, peer) -> None:
        if isinstance(inner, HeaderMsg):
            await self._ingest(inner.header)
        elif isinstance(inner, DeltaHeaderMsg):
            await self._on_delta_header(inner, peer)
        elif isinstance(inner, CertificateMsg):
            await self._ingest(inner.certificate)
        elif hasattr(inner, "rebuild"):  # CertificateDeltaMsg | CertificateRefMsg
            await self._on_certificate_ref(inner, peer)
        else:
            logger.warning("relay carried unexpected %r", type(inner))

    async def _on_relay_dup(self, msg: RelayMsg, peer: str):
        """Duplicate copy of a relay envelope already decoded (the server's
        digest cache hit before the codec ran): only the bookkeeping —
        forward to our tree children if we have not yet, ack the origin so
        its fallback timer stands down. The inner announcement was already
        delivered by the first copy; re-ingesting it would just re-pay
        sanitize/verify for a no-op."""
        self.fanout.on_relay(msg)
        return None

    async def _on_relay2_dup(self, msg, peer: str):
        """Slim-envelope duplicate: ack/forward bookkeeping without the
        decode_relay2 reconstruction or re-delivery (see _on_relay_dup)."""
        if msg.epoch != self.committee.epoch:
            return None
        origin = self.committee.key_of(msg.origin_index)
        self.fanout.on_relay2(msg, origin)
        return None

    async def _on_relay_ack(self, msg: RelayAckMsg, peer):
        self.fanout.on_ack(msg, getattr(peer, "key", None))
        return None

    async def _on_relay2(self, msg, peer: str):
        """Slim fanout-tree envelope: reconstitute the fat announcement
        (purpose-built compact body -> DeltaHeaderMsg/CertificateRefMsg),
        forward + ack one-way, then deliver through the identical ingest
        path the fat forms take."""
        from .fanout import decode_relay2

        if msg.epoch != self.committee.epoch:
            # Slim bodies are keyed to the SENDER's committee (origin and
            # bitmap positions are dense indices): across an epoch boundary
            # our index->key mapping may differ, so decoding would
            # reconstitute the announcement under the WRONG authorities.
            # Drop it — the origin's fallback delivers the fat form, which
            # the Core's next-epoch buffer then handles (the epoch-change
            # deadlock fix stays intact, one fallback deadline later).
            logger.debug(
                "dropping cross-epoch relay2 (epoch %s != %s); origin "
                "fallback covers delivery",
                msg.epoch,
                self.committee.epoch,
            )
            return None
        try:
            inner = decode_relay2(self.committee, msg)
        except Exception as e:
            logger.warning("relay2 with undecodable body: %s", e)
            return None
        origin = self.committee.key_of(msg.origin_index)
        self.fanout.on_relay2(msg, origin)
        await self._deliver_announcement(inner, peer)
        return None

    async def _on_relay_ack2(self, msg, peer):
        self.fanout.on_ack2(msg, getattr(peer, "key", None))
        return None

    async def _on_delta_header(self, msg: DeltaHeaderMsg, peer: str):
        """Delta header announcement: reconstruct from the recent-certificate
        index (self-verifying against the carried digest), else retry once
        shortly — the missing parent certificate is usually in flight on
        another link — and finally resync the full header from the author."""
        header = self.core.delta_codec.decode_header(msg)
        if header is not None:
            self.metrics.delta_headers_rebuilt.inc()
            await self._ingest(header)
            return None
        task = asyncio.ensure_future(self._resync_header(msg))
        self._ref_tasks.add(task)
        task.add_done_callback(self._ref_tasks.discard)
        return None

    async def _resync_header(self, msg: DeltaHeaderMsg) -> None:
        # Grace for in-flight parent certificates: the core drains its
        # queue in arrival order, so one short beat usually resolves the
        # reconstruction without paying the resync round trip.
        await asyncio.sleep(0.15)
        header = self.core.delta_codec.decode_header(msg)
        if header is not None:
            self.metrics.delta_headers_rebuilt.inc()
            await self._ingest(header)
            return
        self.metrics.delta_resyncs.inc()
        try:
            address = self.committee.primary_address(msg.author)
            resp: HeaderResyncResponse = await self.network.request(
                address,
                HeaderResyncRequest(
                    msg.header_digest,
                    msg.author,
                    self.core.delta_codec.last_seen_round(msg.author),
                    self.name,
                ),
                timeout=5.0,
            )
        except Exception as e:
            logger.debug("header resync from author failed: %s", e)
            return
        for header in getattr(resp, "headers", ()) or ():
            # Full sanitize path: a byzantine responder can only send
            # headers that fail verification.
            await self._ingest(header)

    async def _on_header_resync(self, msg: HeaderResyncRequest, peer: str):
        headers = []
        wanted = self.header_store.read(msg.header_digest)
        if wanted is not None:
            headers.append(wanted)
        if msg.author == self.name:
            headers.extend(
                self.core.delta_codec.own_headers_since(
                    msg.since_round, exclude=msg.header_digest
                )
            )
        return HeaderResyncResponse(tuple(headers))

    async def _on_certificate_ref(self, msg, peer: str):
        """Compact-certificate announcement: rebuild from our header store
        (we voted on the header, so the common case is a local hit), or
        fetch the full certificate from the origin on miss via the Helper's
        batch route. Runs as a task so a fetch RTT never blocks the
        connection's dispatch loop."""
        header = self.header_store.read(msg.header_digest)
        if header is None:
            task = asyncio.ensure_future(self._resolve_certificate_ref(msg))
            self._ref_tasks.add(task)
            task.add_done_callback(self._ref_tasks.discard)
            return None
        if (
            header.round == msg.round
            and header.epoch == msg.epoch
            and header.author == msg.origin
        ):
            await self._ingest(msg.rebuild(header))
        return None

    async def _resolve_certificate_ref(self, msg) -> None:
        from ..crypto import digest256
        from ..messages import CertificatesBatchRequest

        # Brief grace for the in-flight HeaderMsg to land before paying a
        # fetch round trip.
        try:
            header = await asyncio.wait_for(
                self.header_store.notify_read(msg.header_digest), timeout=0.5
            )
        except asyncio.TimeoutError:
            header = None
        if header is not None:
            if (
                header.round == msg.round
                and header.epoch == msg.epoch
                and header.author == msg.origin
            ):
                await self._ingest(msg.rebuild(header))
            return
        # The certificate digest is derived from the header digest alone
        # (types.Certificate.digest), so the fetch key is computable.
        cert_digest = digest256(b"CERT" + msg.header_digest)
        try:
            address = self.committee.primary_address(msg.origin)
            resp = await self.network.request(
                address,
                CertificatesBatchRequest((cert_digest,), self.name),
                timeout=5.0,
            )
        except Exception as e:
            logger.debug("certificate-ref fetch from origin failed: %s", e)
            return
        for _, cert in getattr(resp, "certificates", ()) or ():
            if cert is not None:
                await self._ingest(cert)

    async def _on_our_batch(self, msg: OurBatchMsg, peer: str):
        await self.tx_our_digests.send((msg.digest, msg.worker_id))
        return None

    async def _on_others_batch(self, msg: OthersBatchMsg, peer: str):
        await self.tx_others_digests.send((msg.digest, msg.worker_id))
        return None

    async def _on_reconfigure(self, msg: ReconfigureMsg, peer: str):
        await self.tx_state_handler.send(
            ReconfigureNotification(msg.kind, msg.committee())
        )
        return None

    # -- lifecycle ---------------------------------------------------------
    async def shutdown(self) -> None:
        self.tx_reconfigure.send(ReconfigureNotification("shutdown"))
        self.fanout.shutdown()
        if self.verifier_stage is not None:
            self.verifier_stage.shutdown()
        for t in list(self._ref_tasks):
            t.cancel()
        for t in self._tasks:
            t.cancel()
        await drain_cancelled(self._tasks, who="primary")
        await self.server.stop()
        if self.pool is not None:
            from ..network import unregister_node_pool

            unregister_node_pool(self.name, self.pool)
            self.pool.close()
        self.network.close()
