"""Delta-encoded header wire forms: the header/certificate wire diet.

At committee scale the control plane's per-link bytes are dominated by the
O(N) parts of every header announcement — the parent set (one 32-byte
certificate digest per committee member) — and by the certificate broadcast
re-shipping the same header body every voter already stores. This module
ref-encodes both against state the receiver provably holds:

- `DeltaHeaderMsg` (messages.py) carries the payload pairs *added since the
  sender's last header* (in this codebase a header's payload map is already
  the per-round delta: the proposer clears its digest buffer at every seal)
  and each parent as a 2-byte committee index into the receiver's
  recent-certificate index (parents of a round-r header are round r-1
  certificates, and at most one certificate per (round, origin) can gather a
  vote quorum, so (round-1, origin) names a parent unambiguously).
- Reconstruction is self-verifying: the rebuilt Header must hash to the
  carried header_digest (collision resistance makes a verified match
  byte-exact), after which the normal signature/sanitize path runs. Any
  unresolvable parent or digest mismatch falls back to the full-map resync
  path: `HeaderResyncRequest(digest, author, since_round)` to the author,
  answered with the full header plus the author's own intervening headers
  after the receiver's last-seen round.
- `CertificateDeltaMsg` rebuilds full-format certificates from the header
  store exactly like the compact form's CertificateRefMsg (primary.py shares
  one resolution path between them).

The codec is owned by the Core (one per primary): certificates are noted as
the core accepts them, which is also the order-correct place to decode —
a delta header queued behind its parent certificates resolves once the core
drains the queue in arrival order.
"""

from __future__ import annotations

import logging

from ..config import Committee
from ..crypto import digest256
from ..messages import CertificateDeltaMsg, DeltaHeaderMsg, HeaderMsg
from ..types import Certificate, Digest, Header, PublicKey, Round

logger = logging.getLogger("narwhal.primary")

# Own headers retained for resync service / since_round catch-up. Far above
# any plausible resync horizon (a receiver more than gc_depth behind repairs
# through the block synchronizer, not this path).
OWN_HEADER_WINDOW = 128
# Cap on intervening own headers piggybacked on one resync response.
RESYNC_CATCHUP_CAP = 32


class HeaderDeltaCodec:
    """Encode/decode delta headers against the recent-certificate index.

    All state is per-epoch volatile: rounds restart at 0 on epoch change and
    the index reseeds from the new committee's genesis certificates.
    """

    def __init__(self, committee: Committee):
        # round -> committee dense index -> certificate digest, and the
        # reverse map used by the encoder (parent digests -> indices).
        self._by_round: dict[Round, dict[int, Digest]] = {}
        self._index_of: dict[Digest, tuple[Round, int]] = {}
        # Our own recent headers, served to resyncing peers.
        self._own_headers: dict[Round, Header] = {}
        self.change_epoch(committee)

    # -- state maintenance -------------------------------------------------
    def note_certificate(self, certificate: Certificate) -> None:
        """Called by the core for every ACCEPTED certificate (the same spot
        that feeds the parent aggregator), so the encoder can always resolve
        its own parents and the decoder resolves anything the core already
        processed."""
        self._note(certificate.origin, certificate.round, certificate.digest)

    def note_header(self, header: Header) -> None:
        """Called by the core for every header it processes: a certificate's
        digest is a pure function of its header's digest (types.Certificate
        — digest256(b"CERT" || header_digest)), and receivers see a round's
        headers a FULL ROUND before the matching certificates arrive. Under
        load the certificate broadcast lags in-flight, so without this the
        decoder would miss parents it could already name — every miss costs
        a grace sleep or a resync round trip on the vote path. A wrong guess
        (equivocating origin) is harmless: the reconstruction digest check
        catches it and the resync path recovers."""
        self._note(
            header.author, header.round, digest256(b"CERT" + header.digest)
        )

    def _note(self, origin: PublicKey, round: Round, cert_digest: Digest) -> None:
        try:
            idx = self.committee.index_of(origin)
        except KeyError:
            return  # not in this epoch's committee; sanitize already rejects
        self._by_round.setdefault(round, {})[idx] = cert_digest
        self._index_of[cert_digest] = (round, idx)

    def note_own_header(self, header: Header) -> None:
        self._own_headers[header.round] = header
        while len(self._own_headers) > OWN_HEADER_WINDOW:
            del self._own_headers[min(self._own_headers)]

    def last_seen_round(self, origin: PublicKey) -> Round:
        """The highest round with an indexed certificate from `origin` — the
        since_round key a resync request carries."""
        try:
            idx = self.committee.index_of(origin)
        except KeyError:
            return 0
        seen = [r for r, certs in self._by_round.items() if idx in certs]
        return max(seen) if seen else 0

    def own_headers_since(self, since_round: Round, exclude: Digest) -> list[Header]:
        """Our own headers after since_round (ascending, capped) for the
        resync response's catch-up piggyback."""
        rounds = sorted(r for r in self._own_headers if r > since_round)
        out = [
            self._own_headers[r]
            for r in rounds[:RESYNC_CATCHUP_CAP]
            if self._own_headers[r].digest != exclude
        ]
        return out

    # -- encode ------------------------------------------------------------
    def encode_header(self, header: Header) -> DeltaHeaderMsg | None:
        """The wire-diet form of our own header, or None when any parent is
        not in the index (the caller then broadcasts the full HeaderMsg —
        correctness never depends on the delta form being available)."""
        indices = []
        for parent in header.parents:
            entry = self._index_of.get(parent)
            if entry is None or entry[0] + 1 != header.round:
                return None
            indices.append(entry[1])
        return DeltaHeaderMsg(
            header.author,
            header.round,
            header.epoch,
            header.digest,
            tuple(header.payload.items()),
            tuple(sorted(indices)),
            header.signature,
        )

    # -- decode ------------------------------------------------------------
    def decode_header(self, msg: DeltaHeaderMsg) -> Header | None:
        """Reconstruct the full Header, or None when a parent is missing or
        the reconstruction does not hash to the carried digest (the caller
        resyncs). A successful decode is byte-exact: header.digest ==
        msg.header_digest pins every reconstructed field."""
        round_certs = self._by_round.get(msg.round - 1, {})
        parents = []
        for idx in msg.parent_indices:
            digest = round_certs.get(idx)
            if digest is None:
                return None
            parents.append(digest)
        header = Header(
            msg.author,
            msg.round,
            msg.epoch,
            dict(msg.payload),
            frozenset(parents),
            msg.signature,
        )
        if header.digest != msg.header_digest:
            logger.debug(
                "delta header %s reconstruction mismatch (stale index or "
                "bad sender); resyncing",
                msg.header_digest.hex()[:16],
            )
            return None
        return header

    # -- lifecycle ---------------------------------------------------------
    def gc(self, gc_round: Round) -> None:
        for r in [r for r in self._by_round if r <= gc_round]:
            for digest in self._by_round.pop(r).values():
                self._index_of.pop(digest, None)
        for r in [r for r in self._own_headers if r <= gc_round]:
            del self._own_headers[r]

    def change_epoch(self, committee: Committee) -> None:
        self.committee = committee
        self._by_round.clear()
        self._index_of.clear()
        self._own_headers.clear()
        # Round-1 headers parent the genesis certificates; seed them so the
        # first delta headers of the epoch encode/decode without resync.
        for cert in Certificate.genesis(committee):
            self.note_certificate(cert)


def encode_announcement(codec: HeaderDeltaCodec, header: Header, wire: str):
    """The header announcement in the configured wire form, falling back to
    the self-describing full form whenever the delta is unavailable."""
    if wire == "delta":
        msg = codec.encode_header(header)
        if msg is not None:
            return msg
    return HeaderMsg(header)


def encode_certificate_announcement(certificate: Certificate, wire: str):
    """The certificate announcement: compact certificates already broadcast
    by reference (CertificateRefMsg); full-format ones shed the embedded
    header body under the delta wire form."""
    from ..messages import CertificateMsg, CertificateRefMsg

    if certificate.is_compact:
        return CertificateRefMsg.from_certificate(certificate)
    if wire == "delta":
        return CertificateDeltaMsg.from_certificate(certificate)
    return CertificateMsg(certificate)
