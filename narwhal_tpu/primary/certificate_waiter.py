"""The CertificateWaiter: parks certificates until their parents are local.

Reference: /root/reference/primary/src/certificate_waiter.rs:35-228 — each
parked certificate registers `notify_read` waiters on its missing parents in
the certificate store; once they all land (fetched by the header waiter's
repair of the embedded header, or broadcast by peers) the certificate is
looped back to the core for re-processing. GC cancels waiters below the
collection round.
"""

from __future__ import annotations

import asyncio
import logging

from ..channels import Channel, Subscriber, Watch
from ..stores import CertificateStore
from ..types import Certificate, Digest, Round

logger = logging.getLogger("narwhal.primary")


class CertificateWaiter:
    def __init__(
        self,
        certificate_store: CertificateStore,
        genesis_digests: frozenset[Digest],
        rx_synchronizer: Channel,  # suspended certificates from the core
        tx_core: Channel,  # replayed certificates
        rx_consensus_round_updates: Watch,
        rx_reconfigure: Watch,
        gc_depth: Round,
        metrics=None,
    ):
        self.certificate_store = certificate_store
        self.genesis_digests = genesis_digests
        self.rx_synchronizer = rx_synchronizer
        self.tx_core = tx_core
        self.rx_consensus_round_updates = Subscriber(rx_consensus_round_updates)
        self.rx_reconfigure = Subscriber(rx_reconfigure)
        self.gc_depth = gc_depth
        self.metrics = metrics

        self.gc_round: Round = 0
        self.pending: dict[Digest, tuple[Round, asyncio.Task]] = {}
        self._task: asyncio.Task | None = None

    def spawn(self) -> asyncio.Task:
        self._task = asyncio.ensure_future(self.run())
        return self._task

    async def _wait(self, certificate: Certificate) -> None:
        waiters = [
            self.certificate_store.notify_read(d)
            for d in certificate.header.parents
            if d not in self.genesis_digests and not self.certificate_store.contains(d)
        ]
        try:
            await asyncio.gather(*waiters)
        except asyncio.CancelledError:
            raise
        await self.tx_core.send(certificate)

    def _park(self, certificate: Certificate) -> None:
        if certificate.digest in self.pending:
            return
        task = asyncio.ensure_future(self._wait(certificate))
        self.pending[certificate.digest] = (certificate.round, task)

        def _done(t: asyncio.Task, digest=certificate.digest) -> None:
            self.pending.pop(digest, None)
            if self.metrics is not None:
                self.metrics.pending_certificate_waits.set(len(self.pending))
            if not t.cancelled() and t.exception() is not None:
                logger.warning("Certificate waiter failed: %r", t.exception())

        task.add_done_callback(_done)
        if self.metrics is not None:
            self.metrics.pending_certificate_waits.set(len(self.pending))

    async def run(self) -> None:
        cert_task = asyncio.ensure_future(self.rx_synchronizer.recv())
        recon_task = asyncio.ensure_future(self.rx_reconfigure.changed())
        round_task = asyncio.ensure_future(self.rx_consensus_round_updates.changed())
        try:
            while True:
                done, _ = await asyncio.wait(
                    {cert_task, recon_task, round_task},
                    return_when=asyncio.FIRST_COMPLETED,
                )
                if cert_task in done:
                    certificate = cert_task.result()
                    cert_task = asyncio.ensure_future(self.rx_synchronizer.recv())
                    if certificate.round > self.gc_round:
                        self._park(certificate)
                if round_task in done:
                    committed_round = round_task.result()
                    round_task = asyncio.ensure_future(
                        self.rx_consensus_round_updates.changed()
                    )
                    self._gc(committed_round)
                if recon_task in done:
                    note = recon_task.result()
                    if note.kind == "shutdown":
                        return
                    if note.committee is not None:
                        self._cancel_all()
                        self.genesis_digests = frozenset(
                            c.digest for c in Certificate.genesis(note.committee)
                        )
                        self.gc_round = 0
                    recon_task = asyncio.ensure_future(self.rx_reconfigure.changed())
        finally:
            cert_task.cancel()
            recon_task.cancel()
            round_task.cancel()
            self._cancel_all()

    def _gc(self, committed_round: Round) -> None:
        if committed_round <= self.gc_depth:
            return
        gc_round = committed_round - self.gc_depth
        if gc_round <= self.gc_round:
            return
        self.gc_round = gc_round
        for digest, (round_, task) in list(self.pending.items()):
            if round_ <= gc_round:
                task.cancel()
                self.pending.pop(digest, None)

    def _cancel_all(self) -> None:
        for _, task in self.pending.values():
            task.cancel()
        self.pending.clear()
