"""Stake-weighted vote and certificate accumulation.

Reference: /root/reference/primary/src/aggregators.rs:16-99 — VotesAggregator
turns a quorum of votes over our header into a Certificate; one
CertificatesAggregator per round turns a quorum of certificates into the next
round's parent set.
"""

from __future__ import annotations

from ..config import Committee
from ..types import Certificate, Digest, Header, Vote


class VotesAggregator:
    """Collects votes for one of our headers; yields the certificate once the
    accumulated stake (author's own stake included, counted at append of the
    author's implicit self-vote) reaches quorum
    (/root/reference/primary/src/aggregators.rs:16-57)."""

    def __init__(self, cert_format: str = "full") -> None:
        self.weight = 0
        self.votes: list[tuple[int, bytes]] = []  # (committee index, signature)
        self.seen: set[bytes] = set()  # voter public keys
        self.done = False
        self.cert_format = cert_format

    def append(
        self, vote: Vote, committee: Committee, header: Header
    ) -> Certificate | None:
        if self.done or vote.author in self.seen:
            return None
        self.seen.add(vote.author)
        self.votes.append((committee.index_of(vote.author), vote.signature))
        self.weight += committee.stake(vote.author)
        if self.weight >= committee.quorum_threshold():
            self.done = True
            signers, sigs = zip(*sorted(self.votes))
            if self.cert_format == "compact":
                # Half-aggregate: ~32 bytes/signer instead of 64, and the
                # proof verifies as one msm-kernel equation (types.py
                # Certificate docstring; Parameters.cert_format). Passing
                # the committee lets assembly pre-seed the aggregate
                # verdict cache when every vote is already known-valid.
                return Certificate.compact_from_votes(
                    header, tuple(signers), tuple(sigs), committee=committee
                )
            return Certificate(header, tuple(signers), tuple(sigs))
        return None


class CertificatesAggregator:
    """Collects certificates of one round; yields the parent digest set once
    their combined stake reaches quorum
    (/root/reference/primary/src/aggregators.rs:59-99)."""

    def __init__(self) -> None:
        self.weight = 0
        self.certificates: list[Certificate] = []
        self.seen: set[bytes] = set()  # origins

    def append(
        self, certificate: Certificate, committee: Committee
    ) -> list[Certificate] | None:
        if certificate.origin in self.seen:
            return None
        self.seen.add(certificate.origin)
        self.certificates.append(certificate)
        self.weight += committee.stake(certificate.origin)
        if self.weight >= committee.quorum_threshold():
            # Deliberately keep the accumulated weight: certificates arriving
            # after the quorum (e.g. the leader's) are each drained and
            # forwarded too — Bullshark's leader linkage depends on late
            # parents reaching the proposer (aggregators.rs:83-97).
            drained = self.certificates
            self.certificates = []
            return drained
        return None
