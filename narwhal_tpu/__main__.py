"""Role binaries: the CLI entry point.

Reference: /root/reference/node/src/main.rs:39-153 — subcommands
`generate_keys`, `run primary [--consensus-disabled]`, `run worker --id N`,
plus `benchmark_client`; telemetry goes to stdout in the RFC-3339-ish format
the benchmark harness parses (:155-200); a prometheus endpoint serves each
role's registry (:279-285).

Usage:
  python -m narwhal_tpu generate_keys --filename key.json
  python -m narwhal_tpu run --keys key.json --committee committee.json \
      --workers workers.json --parameters parameters.json --store db primary
  python -m narwhal_tpu run ... worker --id 0
  python -m narwhal_tpu benchmark_client --target host:port --rate 1000 --size 512
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import sys

from .benchmark_client import BenchmarkClient
from .config import Committee, Parameters, WorkerCache
from .crypto import KeyPair
from .metrics import serve_metrics
from .node import PrimaryNode, WorkerNode
from .stores import NodeStorage


def _setup_logging(verbosity: int) -> None:
    level = [logging.WARNING, logging.INFO, logging.DEBUG][min(verbosity, 2)]
    # The benchmark harness parses "<RFC3339 UTC> <LEVEL> <msg>" lines.
    logging.basicConfig(
        stream=sys.stdout,
        level=level,
        format="%(asctime)s.%(msecs)03dZ %(levelname)s %(message)s",
        datefmt="%Y-%m-%dT%H:%M:%S",
    )


def _load_keys(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def cmd_generate_keys(args) -> None:
    """Emit the authority's protocol keypair plus its transport identities:
    the primary network key and one network key per worker lane (the
    reference's generate_keys + generate_network_keys,
    node/src/main.rs:40-76)."""
    import secrets

    # Boot-time key material for a PRODUCTION node: generate-keys runs once
    # on an operator's machine, never inside a seeded replay — real entropy
    # is the requirement here, not a divergence.
    seed = secrets.token_bytes(32)  # lint: allow(raw-entropy)
    network_seed = secrets.token_bytes(32)  # lint: allow(raw-entropy)
    worker_seeds = {str(w): secrets.token_bytes(32) for w in range(args.workers)}  # lint: allow(raw-entropy)
    kp = KeyPair.from_seed(seed)
    doc = {
        "name": kp.public.hex(),
        "seed": seed.hex(),
        "network_seed": network_seed.hex(),
        "network_key": KeyPair.from_seed(network_seed).public.hex(),
        "worker_network_seeds": {w: s.hex() for w, s in worker_seeds.items()},
        "worker_network_keys": {
            w: KeyPair.from_seed(s).public.hex() for w, s in worker_seeds.items()
        },
    }
    with open(args.filename, "w") as f:
        json.dump(doc, f, indent=2)
    print(kp.public.hex())


async def _run_node(args) -> None:
    keys = _load_keys(args.keys)
    keypair = KeyPair.from_seed(bytes.fromhex(keys["seed"]))
    committee = Committee.import_(args.committee)
    worker_cache = WorkerCache.import_(args.workers)
    parameters = (
        Parameters.import_(args.parameters) if args.parameters else Parameters()
    )

    if args.role == "primary":
        if "network_seed" not in keys and not args.insecure:
            raise SystemExit(
                "key file has no 'network_seed': transport authentication would "
                "be silently disabled. Regenerate with `generate_keys` or pass "
                "--insecure to run an open mesh deliberately."
            )
        network_keypair = (
            KeyPair.from_seed(bytes.fromhex(keys["network_seed"]))
            if "network_seed" in keys
            else None
        )
        storage = NodeStorage(f"{args.store}-primary" if args.store else None)
        node = PrimaryNode(
            keypair,
            committee,
            worker_cache,
            parameters,
            storage,
            internal_consensus=not args.consensus_disabled,
            consensus_protocol=getattr(args, "consensus_protocol", "bullshark"),
            crypto_backend=getattr(args, "crypto_backend", "cpu"),
            dag_backend=getattr(args, "dag_backend", "cpu"),
            dag_shards=getattr(args, "dag_shards", 1),
            verify_shards=getattr(args, "verify_shards", 1),
            network_keypair=network_keypair,
        )
        await node.spawn()
        registry = node.registry

        # A standalone primary has no embedding draining the execution
        # output channel: without a consumer it fills after ~10k applied
        # transactions, wedging the executor's output flush and pinning the
        # backpressure level at 1.0 forever. Drain and drop — the default
        # no-op execution state has no application consumer by definition.
        async def _drain_execution_output() -> None:
            ch = node.tx_execution_output
            while True:
                await ch.recv()

        _exec_drain = asyncio.ensure_future(_drain_execution_output())

        # Machine-readable boot line: the primary's gRPC telemetry
        # endpoint, for harnesses that scrape-then-kill (benchmark/
        # local.py). Parsing the human "gRPC public API listening on ..."
        # log line tied those harnesses to the log format; this line is
        # the contract. Empty when the gRPC plane is not mounted.
        print(f"TELEMETRY_ADDR={node.grpc_api_address}", flush=True)
    else:
        worker_seed = keys.get("worker_network_seeds", {}).get(str(args.id))
        if worker_seed is None and not args.insecure:
            raise SystemExit(
                f"key file has no worker_network_seeds entry for worker {args.id}: "
                "transport authentication would be silently disabled. Regenerate "
                "with `generate_keys --workers N` or pass --insecure."
            )
        network_keypair = (
            KeyPair.from_seed(bytes.fromhex(worker_seed)) if worker_seed else None
        )
        storage = NodeStorage(
            f"{args.store}-worker-{args.id}" if args.store else None
        )
        node = WorkerNode(
            keypair.public,
            args.id,
            committee,
            worker_cache,
            parameters,
            storage,
            benchmark=True,
            network_keypair=network_keypair,
        )
        await node.spawn()
        registry = node.registry

    host, port = parameters.prometheus_address.rsplit(":", 1)
    await serve_metrics(registry, host, int(port))
    await asyncio.Event().wait()  # run forever


async def _run_benchmark_client(args) -> None:
    client = BenchmarkClient(
        args.target, size=args.size, rate=args.rate, nodes=tuple(args.nodes)
    )
    await client.wait_for_nodes()
    client.spawn()
    await asyncio.Event().wait()


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(prog="narwhal_tpu")
    parser.add_argument("-v", "--verbose", action="count", default=1)
    sub = parser.add_subparsers(dest="command", required=True)

    g = sub.add_parser("generate_keys")
    g.add_argument("--filename", required=True)
    g.add_argument(
        "--workers", type=int, default=4,
        help="worker lanes to generate transport identities for",
    )

    r = sub.add_parser("run")
    r.add_argument("--keys", required=True)
    r.add_argument(
        "--insecure", action="store_true",
        help="run without transport authentication when the key file lacks "
        "network seeds (testing only)",
    )
    r.add_argument("--committee", required=True)
    r.add_argument("--workers", required=True)
    r.add_argument("--parameters", default=None)
    r.add_argument("--store", default=None)
    r.add_argument(
        "--mem-profiling", action="store_true",
        help="tracemalloc heap profiling (dhat analog): dumps "
        "memprofile-<role>-<pid>.txt to the store dir on exit",
    )
    rsub = r.add_subparsers(dest="role", required=True)
    p = rsub.add_parser("primary")
    p.add_argument(
        "--consensus-disabled", action="store_true",
        help="external consensus: expose the Dag API instead of Bullshark",
    )
    p.add_argument(
        "--crypto-backend", choices=("cpu", "pool", "tpu"), default="cpu",
        help="signature verification: inline host (cpu), coalescing host "
        "pool, or the TPU batch kernel",
    )
    p.add_argument(
        "--dag-backend", choices=("cpu", "tpu"), default="cpu",
        help="consensus commit walk: host order_dag (cpu) or the on-device "
        "adjacency-tensor kernels (tpu)",
    )
    p.add_argument(
        "--dag-shards", type=int, default=1,
        help="with --dag-backend tpu: shard the committee axis of the DAG "
        "window over this many devices (an 'auth' mesh; 1 = single device)",
    )
    p.add_argument(
        "--verify-shards", type=int, default=1,
        help="with --crypto-backend tpu: shard every verify flush over this "
        "many devices (a 'data' mesh; must divide the service's dispatch "
        "bucket — validated at startup)",
    )
    p.add_argument(
        "--consensus-protocol", choices=("bullshark", "tusk"), default="bullshark",
        help="ordering engine (the reference's default is bullshark; tusk is "
        "the asynchronous-network variant)",
    )
    w = rsub.add_parser("worker")
    w.add_argument("--id", type=int, required=True)

    b = sub.add_parser("benchmark_client")
    b.add_argument(
        "--target", required=True, action="append",
        help="worker transactions address; repeat for a validator's W "
        "worker lanes (bursts round-robin across them)",
    )
    b.add_argument("--size", type=int, default=512)
    b.add_argument("--rate", type=int, default=1_000)
    b.add_argument("--nodes", nargs="*", default=[])

    args = parser.parse_args(argv)
    _setup_logging(args.verbose)
    # NARWHAL_PROFILE=<dir>: dump cProfile stats per process on exit — the
    # profiling plane (the reference's dhat/pprof analog, node/src/lib.rs:224).
    import os

    profile_dir = os.environ.get("NARWHAL_PROFILE")
    profiler = None
    if profile_dir:
        import atexit
        import cProfile

        profiler = cProfile.Profile()
        profiler.enable()
        role = getattr(args, "role", args.command)
        out = os.path.join(profile_dir, f"{role}-{os.getpid()}.pstats")

        def _dump():
            profiler.disable()
            profiler.dump_stats(out)

        atexit.register(_dump)
        # atexit only runs on clean exit; the bench harness stops nodes with
        # SIGTERM, so convert it into a normal interpreter exit.
        import signal as _signal

        _signal.signal(_signal.SIGTERM, lambda *_: sys.exit(0))
    # NARWHAL_MEM_PROFILE=<dir> (or --mem-profiling on `run`): tracemalloc
    # sampling — the reference's dhat heap profiling analog
    # (node/src/lib.rs:224-238, `mem_profiling` bench param). Periodic
    # top-allocation log lines plus a final per-process dump file.
    mem_dir = os.environ.get("NARWHAL_MEM_PROFILE") or (
        getattr(args, "mem_profiling", None) and (args.store or ".")
    )
    if mem_dir:
        import atexit
        import signal as _signal
        import tracemalloc

        tracemalloc.start(10)
        role = getattr(args, "role", args.command)
        mem_out = os.path.join(mem_dir, f"memprofile-{role}-{os.getpid()}.txt")

        def _dump_mem():
            snap = tracemalloc.take_snapshot()
            os.makedirs(os.path.dirname(mem_out) or ".", exist_ok=True)
            with open(mem_out, "w") as fh:
                current, peak = tracemalloc.get_traced_memory()
                fh.write(f"current={current} peak={peak}\n")
                for stat in snap.statistics("lineno")[:40]:
                    fh.write(f"{stat}\n")

        atexit.register(_dump_mem)
        _signal.signal(_signal.SIGTERM, lambda *_: sys.exit(0))
    if args.command == "generate_keys":
        cmd_generate_keys(args)
    elif args.command == "run":
        asyncio.run(_run_node(args))
    elif args.command == "benchmark_client":
        asyncio.run(_run_benchmark_client(args))


if __name__ == "__main__":
    main()
