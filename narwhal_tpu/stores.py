"""Typed stores over the storage engine — the 9 column families.

Reference: NodeStorage opens 9 RocksDB CFs (/root/reference/node/src/lib.rs:53-123):
votes, headers, certificates, certificate_id_by_round, payload, batches,
last_committed, sequence, temp_batches. CertificateStore adds a round
secondary index and notify_read (/root/reference/storage/src/certificate_store.rs:28-331).
"""

from __future__ import annotations

import struct
from typing import Iterable

from .bounded_cache import BoundedCache
from .codec import Reader, Writer
from .storage import ColumnFamily, StorageEngine
from .types import (
    Certificate,
    Digest,
    Header,
    PublicKey,
    Round,
    SequenceNumber,
    Vote,
    WorkerId,
)

_RK = struct.Struct(">Q")  # big-endian round for ordered iteration


# Digest -> decoded object caches (BoundedCache: thread-safe FIFO, shared
# implementation with the decode/verify caches). The stores are
# CONTENT-ADDRESSED (the key is the value's digest), so a digest can only
# ever map to one object and the cache needs no invalidation for
# correctness; presence/absence still comes from the engine on every
# read, so deletions behave exactly as before — only the re-decode is
# skipped. The N=50 profile measured repeated certificate decode at 48%
# of the host's CPU (1.58M decodes for ~2.5k distinct live certs).


class CertificateStore:
    """Certificates by digest + (round, digest) secondary index + notify_read
    (/root/reference/storage/src/certificate_store.rs)."""

    def __init__(self, engine: StorageEngine):
        self._main: ColumnFamily = engine.column_family("certificates")
        self._by_round: ColumnFamily = engine.column_family("certificate_id_by_round")
        self._engine = engine
        self._decoded = BoundedCache(max_entries=4096)

    @staticmethod
    def _round_key(round: Round, origin: PublicKey, digest: Digest) -> bytes:
        return _RK.pack(round) + origin + digest

    def _puts(self, certs: Iterable[Certificate]) -> list:
        puts = []
        for c in certs:
            puts.append((self._main, c.digest, c.to_bytes()))
            puts.append((self._by_round, self._round_key(c.round, c.origin, c.digest), b"\0"))
        return puts

    def write(self, cert: Certificate) -> None:
        """Atomic main+index write (certificate_store.rs:55-90)."""
        self._engine.write_batch(self._puts([cert]))

    def write_all(self, certs: Iterable[Certificate]) -> None:
        self._engine.write_batch(self._puts(certs))

    def write_async(self, cert: Certificate):
        """Group-commit write: returns the shared commit future (the
        memtable — and notify_read waiters — see the certificate without
        awaiting it)."""
        return self._engine.write_batch_async(self._puts([cert]))

    def write_all_async(self, certs: Iterable[Certificate]):
        return self._engine.write_batch_async(self._puts(certs))

    def read(self, digest: Digest) -> Certificate | None:
        raw = self._main.get(digest)
        if raw is None:
            return None
        cert = self._decoded.get(digest)
        if cert is None:
            cert = Certificate.from_bytes(raw)
            self._decoded.put(digest, cert)
        return cert

    def read_all(self, digests: Iterable[Digest]) -> list[Certificate | None]:
        return [self.read(d) for d in digests]

    def contains(self, digest: Digest) -> bool:
        return self._main.contains(digest)

    async def notify_read(self, digest: Digest) -> Certificate:
        raw = await self._main.notify_read(digest)
        cert = self._decoded.get(digest)
        if cert is None:
            cert = Certificate.from_bytes(raw)
            self._decoded.put(digest, cert)
        return cert

    def delete(self, digest: Digest) -> None:
        cert = self.read(digest)
        if cert is None:
            return
        self._engine.write_batch(
            [],
            [
                (self._main, digest),
                (self._by_round, self._round_key(cert.round, cert.origin, digest)),
            ],
        )

    def delete_all(self, digests: Iterable[Digest]) -> None:
        for d in digests:
            self.delete(d)

    def after_round(self, round: Round) -> list[Certificate]:
        """All certificates with round >= round, ascending
        (certificate_store.rs:216-242) — consensus crash recovery reads this."""
        out = []
        for key, _ in sorted(self._by_round.iter()):
            (r,) = _RK.unpack(key[:8])
            if r >= round:
                digest = key[8 + 32 :]
                cert = self.read(digest)
                if cert is not None:
                    out.append(cert)
        return out

    def last_round(self, origin: PublicKey | None = None) -> Round:
        """Highest round (optionally of one origin) with a stored certificate
        (certificate_store.rs:244-331); 0 when empty."""
        best = 0
        for key, _ in self._by_round.iter():
            (r,) = _RK.unpack(key[:8])
            if origin is not None and key[8 : 8 + 32] != origin:
                continue
            best = max(best, r)
        return best

    def __len__(self) -> int:
        return len(self._main)


class HeaderStore:
    def __init__(self, engine: StorageEngine):
        self._cf = engine.column_family("headers")
        self._decoded = BoundedCache(max_entries=2048)

    def write(self, header: Header) -> None:
        self._cf.put(header.digest, header.to_bytes())

    def write_async(self, header: Header):
        return self._cf.put_async(header.digest, header.to_bytes())

    def read(self, digest: Digest) -> Header | None:
        raw = self._cf.get(digest)
        if raw is None:
            return None
        header = self._decoded.get(digest)
        if header is None:
            header = Header.from_bytes(raw)
            self._decoded.put(digest, header)
        return header

    async def notify_read(self, digest: Digest) -> Header:
        raw = await self._cf.notify_read(digest)
        header = self._decoded.get(digest)
        if header is None:
            header = Header.from_bytes(raw)
            self._decoded.put(digest, header)
        return header

    def delete_all(self, digests: Iterable[Digest]) -> None:
        self._cf.delete_all(digests)


class PayloadStore:
    """(BatchDigest, WorkerId) -> available token
    (node/src/lib.rs payload_store)."""

    def __init__(self, engine: StorageEngine):
        self._cf = engine.column_family("payload")

    @staticmethod
    def _key(digest: Digest, worker_id: WorkerId) -> bytes:
        return digest + struct.pack("<I", worker_id)

    def write(self, digest: Digest, worker_id: WorkerId) -> None:
        self._cf.put(self._key(digest, worker_id), b"\1")

    def write_async(self, digest: Digest, worker_id: WorkerId):
        return self._cf.put_async(self._key(digest, worker_id), b"\1")

    def write_all_async(self, pairs: Iterable[tuple[Digest, WorkerId]]):
        """One grouped availability commit for a burst of worker reports."""
        return self._cf.put_all_async(
            (self._key(d, w), b"\1") for d, w in pairs
        )

    def contains(self, digest: Digest, worker_id: WorkerId) -> bool:
        return self._cf.contains(self._key(digest, worker_id))

    async def notify_contains(self, digest: Digest, worker_id: WorkerId) -> None:
        await self._cf.notify_read(self._key(digest, worker_id))

    def delete_all(self, pairs: Iterable[tuple[Digest, WorkerId]]) -> None:
        self._cf.delete_all(self._key(d, w) for d, w in pairs)


class BatchStore:
    """BatchDigest -> serialized batch bytes (the worker's bulk store)."""

    def __init__(self, engine: StorageEngine, name: str = "batches"):
        self._cf = engine.column_family(name)

    def write(self, digest: Digest, serialized: bytes) -> None:
        self._cf.put(digest, serialized)

    def read(self, digest: Digest) -> bytes | None:
        return self._cf.get(digest)

    def read_all(self, digests: Iterable[Digest]) -> list[bytes | None]:
        """One coalesced engine read for a whole fetch group (the server
        side of RequestBatchesMsg): per-digest presence, request order."""
        return self._cf.get_all(digests)

    async def notify_read(self, digest: Digest) -> bytes:
        return await self._cf.notify_read(digest)

    def contains(self, digest: Digest) -> bool:
        return self._cf.contains(digest)

    def delete_all(self, digests: Iterable[Digest]) -> None:
        self._cf.delete_all(digests)

    def __len__(self) -> int:
        return len(self._cf)


class VoteDigestStore:
    """origin -> last vote info (round, header_digest) — the equivocation
    guard that must survive restart (primary/src/core.rs:281-308)."""

    def __init__(self, engine: StorageEngine):
        self._cf = engine.column_family("votes")

    def write(self, origin: PublicKey, round: Round, header_digest: Digest) -> None:
        self._cf.put(origin, struct.pack("<Q", round) + header_digest)

    def write_async(self, origin: PublicKey, round: Round, header_digest: Digest):
        return self._cf.put_async(
            origin, struct.pack("<Q", round) + header_digest
        )

    def read(self, origin: PublicKey) -> tuple[Round, Digest] | None:
        raw = self._cf.get(origin)
        if raw is None:
            return None
        (r,) = struct.unpack("<Q", raw[:8])
        return r, raw[8:]

    def clear(self) -> None:
        """Epoch change: rounds restart at 0, so per-epoch vote guards must
        reset with them (core.rs change_epoch clears this store)."""
        self._cf.delete_all(self._cf.keys())


class ConsensusStore:
    """last_committed per authority + global sequence
    (/root/reference/types/src/consensus.rs:24-95)."""

    def __init__(self, engine: StorageEngine):
        self._last = engine.column_family("last_committed")
        self._seq = engine.column_family("sequence")
        self._engine = engine

    def write_consensus_state(
        self,
        last_committed: dict[PublicKey, Round],
        consensus_index: SequenceNumber,
        cert_digest: Digest,
    ) -> None:
        """Atomic per-commit persistence (types/src/consensus.rs:50-65)."""
        puts = [
            (self._last, pk, struct.pack("<Q", r)) for pk, r in last_committed.items()
        ]
        puts.append((self._seq, _RK.pack(consensus_index), cert_digest))
        self._engine.write_batch(puts)

    def read_last_committed(self) -> dict[PublicKey, Round]:
        return {
            pk: struct.unpack("<Q", raw)[0] for pk, raw in self._last.iter()
        }

    def last_consensus_index(self) -> SequenceNumber:
        idx = -1
        for key, _ in self._seq.iter():
            (i,) = _RK.unpack(key)
            idx = max(idx, i)
        return idx + 1

    def read_sequenced_digests_after(self, index: SequenceNumber) -> list[tuple[SequenceNumber, Digest]]:
        out = []
        for key, val in sorted(self._seq.iter()):
            (i,) = _RK.unpack(key)
            if i >= index:
                out.append((i, val))
        return out


class NodeStorage:
    """All stores of one node, the NodeStorage::reopen analog
    (/root/reference/node/src/lib.rs:43-124)."""

    def __init__(self, path: str | None):
        self.engine = StorageEngine(path)
        self.vote_digest_store = VoteDigestStore(self.engine)
        self.header_store = HeaderStore(self.engine)
        self.certificate_store = CertificateStore(self.engine)
        self.payload_store = PayloadStore(self.engine)
        self.batch_store = BatchStore(self.engine, "batches")
        self.temp_batch_store = BatchStore(self.engine, "temp_batches")
        self.consensus_store = ConsensusStore(self.engine)

    def close(self) -> None:
        self.engine.close()
