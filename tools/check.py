"""One command for all three static-analysis planes.

`python -m tools.check` runs narwhal-lint (per-function invariants),
narwhal-topo (whole-program actor/channel topology + stale-artifact
check) and narwhal-sched (interleaving races + replay determinism) in a
single process with ONE combined exit code — and one whole-program
extraction: topo and sched share the same interpreted wiring instead of
walking the program twice.

    python -m tools.check              # the pre-commit / tier-1 gate
    python -m tools.check --json       # machine output, per plane
    python -m tools.check -v           # per-plane timings

Exit 0 when every plane is clean (all findings suppressed or baselined,
topology artifact current), 1 otherwise.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from dataclasses import dataclass, field
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parents[1]
DEFAULT_PATHS = ("narwhal_tpu", "tests")


@dataclass
class CheckReport:
    """Per-plane results plus the combined verdict."""

    results: dict = field(default_factory=dict)  # plane -> lint.Result
    timings: dict = field(default_factory=dict)  # plane -> seconds
    artifact_stale: bool = False
    elapsed: float = 0.0
    topology: object = None  # the shared extraction (topo + sched)
    extractor: object = None

    @property
    def ok(self) -> bool:
        return (
            all(r.ok for r in self.results.values())
            and not self.artifact_stale
        )


def run_check(
    root: Path = REPO_ROOT, paths: tuple = DEFAULT_PATHS
) -> CheckReport:
    from tools.analysis.__main__ import (
        ARTIFACT_JSON,
        DEFAULT_BASELINE as TOPO_BASELINE,
        topology_doc,
    )
    from tools.analysis.detectors import Context, run_detectors
    from tools.analysis.extractor import DEFAULT_PACKAGE, DEFAULT_ROOTS, extract
    from tools.lint.__main__ import DEFAULT_BASELINE as LINT_BASELINE
    from tools.lint.engine import Baseline, run_lint
    from tools.sched.__main__ import DEFAULT_BASELINE as SCHED_BASELINE
    from tools.sched.engine import run_sched

    root = Path(root)
    scan = [root / p for p in paths]
    report = CheckReport()
    t_all = time.perf_counter()

    t0 = time.perf_counter()
    report.results["lint"] = run_lint(
        scan, baseline=Baseline.load(LINT_BASELINE), root=root
    )
    report.timings["lint"] = time.perf_counter() - t0

    # ONE extraction feeds both whole-program planes.
    t0 = time.perf_counter()
    extraction = extract(root, package=DEFAULT_PACKAGE, roots=DEFAULT_ROOTS)
    topo, extractor = extraction
    report.topology, report.extractor = topo, extractor
    ctx = Context(topo, extractor.program, root)
    report.results["topo"] = run_detectors(
        ctx, baseline=Baseline.load(TOPO_BASELINE)
    )
    doc = topology_doc(topo, DEFAULT_ROOTS)
    try:
        current = json.loads(ARTIFACT_JSON.read_text(encoding="utf-8"))
    except (OSError, ValueError):
        current = None
    report.artifact_stale = current != doc
    report.timings["topo"] = time.perf_counter() - t0

    t0 = time.perf_counter()
    report.results["sched"] = run_sched(
        scan,
        root=root,
        baseline=Baseline.load(SCHED_BASELINE),
        extraction=extraction,
    )
    report.timings["sched"] = time.perf_counter() - t0

    report.elapsed = time.perf_counter() - t_all
    return report


def main(argv: list[str] | None = None) -> int:
    from tools.lint.report import render_json, render_text

    ap = argparse.ArgumentParser(
        prog="python -m tools.check",
        description=(
            "run narwhal-lint + narwhal-topo + narwhal-sched with one "
            "combined exit code (topo and sched share one extraction)"
        ),
    )
    ap.add_argument(
        "paths",
        nargs="*",
        default=list(DEFAULT_PATHS),
        help=f"scan paths for the per-file planes (default: {DEFAULT_PATHS})",
    )
    ap.add_argument("--root", type=Path, default=REPO_ROOT)
    ap.add_argument(
        "--json", action="store_true", help="machine output, one key per plane"
    )
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)

    report = run_check(root=args.root, paths=tuple(args.paths))

    if args.json:
        payload = {
            plane: json.loads(render_json(res))
            for plane, res in report.results.items()
        }
        payload["artifact_stale"] = report.artifact_stale
        payload["ok"] = report.ok
        payload["elapsed"] = round(report.elapsed, 3)
        print(json.dumps(payload, indent=2))
    else:
        for plane, res in report.results.items():
            status = "ok" if res.ok else "FAIL"
            line = f"[{plane}] {status}"
            if args.verbose:
                line += f" ({report.timings[plane]:.2f}s)"
            print(line)
            if not res.ok:
                print(render_text(res, verbose=args.verbose))
        if report.artifact_stale:
            print(
                "[topo] STALE ARTIFACT: tools/analysis/topology.json no "
                "longer matches the wiring — regenerate with "
                "`python -m tools.analysis --write-artifact`"
            )
        verdict = "clean" if report.ok else "FINDINGS"
        print(f"static analysis: {verdict} in {report.elapsed:.2f}s")
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
