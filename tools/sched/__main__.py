"""CLI: `python -m tools.sched [paths...]`.

Exit status: 0 when every finding is suppressed or baselined, 1 when new
findings exist (or a listed path contains a syntax error), 2 on usage
errors. Typical invocations:

    python -m tools.sched narwhal_tpu/ tests/          # the tier-1 gate
    python -m tools.sched --format json narwhal_tpu/   # machine output
    python -m tools.sched --diff origin/main narwhal_tpu/  # pre-commit
    python -m tools.sched --root . --package "" \\
        --roots tests/sched_fixtures/foo.py::Node tests/sched_fixtures/foo.py
    python -m tools.sched --list-rules
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

from tools.analysis.extractor import DEFAULT_PACKAGE, DEFAULT_ROOTS
from tools.lint.engine import DEFAULT_EXCLUDES, Baseline
from tools.lint.report import render_json, render_text
from tools.sched.engine import RULES, run_sched

DEFAULT_BASELINE = Path(__file__).with_name("baseline.json")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.sched",
        description=(
            "narwhal-sched: interleaving-race and replay-determinism "
            "analysis over the task/state graph"
        ),
    )
    ap.add_argument("paths", nargs="*", default=[], help="files or directories")
    ap.add_argument(
        "--format", choices=("text", "json"), default="text", dest="fmt"
    )
    ap.add_argument(
        "--baseline",
        type=Path,
        default=DEFAULT_BASELINE,
        help=f"baseline file (default {DEFAULT_BASELINE})",
    )
    ap.add_argument(
        "--no-baseline",
        action="store_true",
        help="ignore the baseline: report every finding as new",
    )
    ap.add_argument(
        "--write-baseline",
        action="store_true",
        help="grandfather all current non-suppressed findings and exit 0",
    )
    ap.add_argument(
        "--rule",
        action="append",
        default=None,
        metavar="NAME",
        help="run only this rule (repeatable)",
    )
    ap.add_argument(
        "--exclude",
        action="append",
        default=list(DEFAULT_EXCLUDES),
        metavar="GLOB",
        help="extra fnmatch pattern excluded from directory walks",
    )
    ap.add_argument(
        "--root",
        type=Path,
        default=Path(__file__).resolve().parents[2],
        help="analysis root (defaults to the repo root)",
    )
    ap.add_argument(
        "--package",
        default=DEFAULT_PACKAGE,
        help="package interpreted for task/state attribution "
        "('' to skip whole-program extraction)",
    )
    ap.add_argument(
        "--roots",
        nargs="*",
        default=list(DEFAULT_ROOTS),
        metavar="FILE.py::Symbol",
        help="extraction roots (empty to skip extraction and run only "
        "the syntactic determinism rules)",
    )
    ap.add_argument(
        "--diff",
        metavar="REV",
        default=None,
        help="analyze only files changed versus this git rev "
        "(fast pre-commit mode; whole-program findings are filtered "
        "to changed files)",
    )
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)

    # Import for the registration side effect before --list-rules.
    from tools.sched import determinism, races  # noqa: F401

    if args.list_rules:
        for name, rule in sorted(RULES.items()):
            print(f"{name}\n    {rule.summary}")
        return 0
    if not args.paths:
        ap.error("no paths given (try: python -m tools.sched narwhal_tpu/ tests/)")

    rules = RULES
    if args.rule:
        unknown = set(args.rule) - set(RULES)
        if unknown:
            ap.error(f"unknown rule(s): {', '.join(sorted(unknown))}")
        rules = {n: RULES[n] for n in args.rule}

    baseline = Baseline() if args.no_baseline else Baseline.load(args.baseline)
    t0 = time.perf_counter()
    result = run_sched(
        args.paths,
        root=args.root,
        package=args.package,
        roots=tuple(args.roots),
        rules=rules,
        baseline=baseline,
        excludes=args.exclude,
        diff_base=args.diff,
    )
    elapsed = time.perf_counter() - t0

    if args.write_baseline:
        Baseline.dump(result.new + result.baselined, args.baseline)
        print(
            f"baseline: {len(result.new) + len(result.baselined)} finding(s) "
            f"written to {args.baseline}"
        )
        return 0

    if args.fmt == "json":
        print(render_json(result))
    else:
        print(render_text(result, verbose=args.verbose))
        if args.verbose:
            print(f"({elapsed:.2f}s)")
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
