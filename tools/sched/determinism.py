"""Replay-determinism detectors: protocol code must behave identically
on every run of the same seeded scenario.

The whole consensus argument rests on every validator deterministically
interpreting the same DAG, and simnet's oracle testing rests on two runs
of the same seed producing bit-identical logs. Both PR-9 divergences —
iterating a `set` of connections in `set_partition` (hash order differs
per process) and `os.urandom` handshake nonces — were found by hand A/B
log diffing. These rules make the class machine-checked:

* `raw-entropy` — ambient entropy (`os.urandom`, `uuid.uuid1/uuid4`,
  `secrets.*`, `random.SystemRandom`) called in protocol code. Seeded
  scenarios route entropy through the `auth.set_entropy` /
  `types.set_weight_entropy` seams; drawing beside the seam diverges
  replays. The seam *installations* (`_entropy = os.urandom`) are name
  references, not calls, and stay quiet.
* `unseeded-random` — the process-global `random` module used as an RNG:
  module-level draw calls, `random.Random()` with no seed, or the module
  object itself bound as an RNG value (`rng or random`). Under simnet the
  global stream IS seeded (`scenario.py` pins it per plan) — sites that
  deliberately draw from that seeded stream carry an inline allow saying
  so. `random.seed`/`getstate`/`setstate` (the seam installers) are
  exempt.
* `id-keyed-ordering` — `id()` used as a key/ordering input: CPython
  allocation addresses differ run to run, so any ordering derived from
  them diverges replays.
* `unordered-iteration` — a `for` loop over a `set` whose body sends,
  signs, resets or awaits: set iteration is hash order, so effect order
  differs between runs. `sorted(...)` the set first (the PR-9 fix).

Scope: `narwhal_tpu/` plus explicitly-analyzed fixtures; the test suite
and tooling may use ambient entropy legitimately and are skipped.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.lint.engine import Finding, Module
from tools.lint.rules import import_aliases, resolve
from tools.sched.engine import Detector, SchedContext, protocol_scope, register


class _SyntacticDetector(Detector):
    """Shared per-module iteration for the determinism family."""

    def check(self, ctx: SchedContext) -> Iterator[Finding]:
        for mod in ctx.modules:
            if not protocol_scope(mod.rel):
                continue
            yield from self.check_module(mod)

    def check_module(self, mod: Module) -> Iterator[Finding]:
        raise NotImplementedError


_ENTROPY_CALLS = frozenset({
    "os.urandom",
    "uuid.uuid1",
    "uuid.uuid4",
    "random.SystemRandom",
})


@register
class RawEntropy(_SyntacticDetector):
    name = "raw-entropy"
    summary = (
        "ambient entropy (os.urandom/uuid/secrets) outside the "
        "auth.set_entropy seam — diverges seeded replays"
    )

    def check_module(self, mod: Module) -> Iterator[Finding]:
        aliases = import_aliases(mod.tree)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            target = resolve(node.func, aliases)
            if target is None:
                continue
            if target in _ENTROPY_CALLS or target.startswith("secrets."):
                yield mod.finding(
                    self.name,
                    node,
                    f"`{target}` draws ambient entropy; protocol code must "
                    "draw through the seeded entropy seam "
                    "(auth.set_entropy / types.set_weight_entropy) so "
                    "replays of the same scenario seed are bit-identical",
                )


_GLOBAL_DRAWS = frozenset({
    "random", "uniform", "randint", "randrange", "choice", "choices",
    "shuffle", "sample", "gauss", "betavariate", "expovariate",
    "normalvariate", "triangular", "randbytes", "getrandbits",
})


@register
class UnseededRandom(_SyntacticDetector):
    name = "unseeded-random"
    summary = (
        "the process-global random module used as an RNG (unseeded "
        "outside simnet); inject a seeded random.Random instead"
    )

    def check_module(self, mod: Module) -> Iterator[Finding]:
        aliases = import_aliases(mod.tree)
        parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(mod.tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                target = resolve(node.func, aliases)
                if target == "random.Random" and not node.args:
                    yield mod.finding(
                        self.name,
                        node,
                        "`random.Random()` with no seed draws from OS "
                        "entropy at construction; pass an explicit seed "
                        "or the scenario's rng",
                    )
                elif (
                    target is not None
                    and target.startswith("random.")
                    and target.split(".", 1)[1] in _GLOBAL_DRAWS
                ):
                    yield mod.finding(
                        self.name,
                        node,
                        f"`{target}` draws from the process-global random "
                        "stream; outside a seeded simnet scenario this is "
                        "unseeded — deliberate draws from the "
                        "scenario-seeded global stream carry an inline "
                        "allow saying so",
                    )
            elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                if resolve(node, aliases) != "random":
                    continue
                parent = parents.get(node)
                # Qualified uses (`random.x`) are the call rules' business;
                # what this arm catches is the module OBJECT bound as an
                # RNG value: `self._rng = rng or random`.
                if isinstance(parent, ast.Attribute):
                    continue
                yield mod.finding(
                    self.name,
                    node,
                    "the `random` module object is bound as an RNG value; "
                    "its draws are process-global and unseeded outside "
                    "simnet — inject a seeded random.Random",
                )


@register
class IdKeyedOrdering(_SyntacticDetector):
    name = "id-keyed-ordering"
    summary = (
        "id() used as a key or ordering input — allocation addresses "
        "differ run to run"
    )

    def check_module(self, mod: Module) -> Iterator[Finding]:
        aliases = import_aliases(mod.tree)
        for node in ast.walk(mod.tree):
            if (
                isinstance(node, ast.Call)
                and resolve(node.func, aliases) == "id"
                and len(node.args) == 1
            ):
                yield mod.finding(
                    self.name,
                    node,
                    "`id()` yields a CPython allocation address: any "
                    "ordering, dict key or dedup derived from it differs "
                    "between runs — key on a stable protocol identity "
                    "(digest, name, connection id) instead",
                )


# Calls whose invocation order is an observable effect: wire sends,
# signatures, connection-state transitions, task scheduling.
_EFFECT_CALLS = frozenset({
    "send", "send_many", "try_send", "unreliable_send", "request",
    "write", "writelines", "reset", "sign", "ensure_future",
    "create_task", "call_soon", "call_later", "call_at", "put",
    "put_nowait", "set_result", "set_exception", "feed_data", "feed_eof",
    "broadcast", "spawn",
})


class _SetCollector(ast.NodeVisitor):
    """Names/attributes syntactically bound to set values."""

    def __init__(self, aliases: dict):
        self.aliases = aliases
        self.local_sets: set[str] = set()  # bare names
        self.attr_sets: set[str] = set()  # `self.X` within a class

    def _is_set_expr(self, node) -> bool:
        if isinstance(node, (ast.Set, ast.SetComp)):
            return True
        if isinstance(node, ast.Call):
            return resolve(node.func, self.aliases) in ("set", "frozenset")
        return False

    def _is_set_annotation(self, node) -> bool:
        if isinstance(node, ast.Subscript):
            node = node.value
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value.split("[")[0].strip() in ("set", "frozenset")
        return resolve(node, self.aliases) in ("set", "frozenset") or (
            isinstance(node, ast.Attribute) and node.attr in ("Set", "FrozenSet")
        )

    def visit_Assign(self, node):
        if self._is_set_expr(node.value):
            for t in node.targets:
                self._bind(t)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        if (node.value is not None and self._is_set_expr(node.value)) or (
            self._is_set_annotation(node.annotation)
        ):
            self._bind(node.target)
        self.generic_visit(node)

    def _bind(self, target):
        if isinstance(target, ast.Name):
            self.local_sets.add(target.id)
        elif isinstance(target, ast.Attribute) and isinstance(
            target.value, ast.Name
        ):
            self.attr_sets.add(target.attr)


@register
class UnorderedIteration(_SyntacticDetector):
    name = "unordered-iteration"
    summary = (
        "effectful iteration over a set — hash order reorders sends/"
        "signatures/scheduling between runs; sort it first"
    )

    def check_module(self, mod: Module) -> Iterator[Finding]:
        aliases = import_aliases(mod.tree)
        # One collector per lexical region: module level, plus each class
        # (self-attr sets are class-scoped).
        module_sets = _SetCollector(aliases)
        module_sets.visit(mod.tree)
        class_ranges: list[tuple[int, int, _SetCollector]] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                c = _SetCollector(aliases)
                c.visit(node)
                class_ranges.append(
                    (node.lineno, node.end_lineno or node.lineno, c)
                )

        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.For, ast.AsyncFor)):
                continue
            if not self._iterates_set(
                node.iter, aliases, module_sets, class_ranges, node.lineno
            ):
                continue
            if not self._body_effectful(node.body):
                continue
            yield mod.finding(
                self.name,
                node,
                "iterating a set whose body has observable effects "
                "(sends/signatures/scheduling): set iteration is hash "
                "order and differs between runs — iterate "
                "`sorted(...)` over a stable key instead",
            )

    def _iterates_set(self, it, aliases, module_sets, class_ranges, line):
        # `list(X)`/`tuple(X)` materialize but keep the unordered order.
        if isinstance(it, ast.Call) and resolve(it.func, aliases) in (
            "list", "tuple",
        ) and len(it.args) == 1:
            it = it.args[0]
        if isinstance(it, (ast.Set, ast.SetComp)):
            return True
        if isinstance(it, ast.Call):
            return resolve(it.func, aliases) in ("set", "frozenset")
        if isinstance(it, ast.Name):
            if it.id in module_sets.local_sets:
                return True
            return any(
                lo <= line <= hi and it.id in c.local_sets
                for lo, hi, c in class_ranges
            )
        if isinstance(it, ast.Attribute) and isinstance(it.value, ast.Name):
            for lo, hi, c in class_ranges:
                if lo <= line <= hi and it.attr in c.attr_sets:
                    return True
        return False

    def _body_effectful(self, body) -> bool:
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Await):
                    return True
                if isinstance(node, ast.Call):
                    f = node.func
                    name = (
                        f.attr if isinstance(f, ast.Attribute)
                        else f.id if isinstance(f, ast.Name) else ""
                    )
                    if name in _EFFECT_CALLS:
                        return True
        return False
