"""Interleaving-race detectors: shared mutable state across asyncio tasks.

Both rules consume the extractor's read/write-site attribution. The model
of danger is cooperative scheduling: code between two awaits is atomic, so
multi-task access to an instance attribute is safe *while it stays behind
one encapsulation boundary whose methods don't yield mid-mutation*. What
breaks is (a) state mutated from multiple tasks with no single owning
discipline — a process-wide module global, or an attribute poked from
outside its class — and (b) a read-modify-write of shared state that spans
an `await` inside one function (check-then-act across a yield point: the
value checked is stale by the time the write lands).
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.lint.engine import Finding
from tools.sched.engine import Detector, SchedContext, register


def _writer_tasks(kinds: dict) -> set[str]:
    return {t for t in kinds["write"] if not t.startswith("init:")}


def _runtime_sites(kinds: dict) -> list:
    return [
        s
        for k in kinds.values()
        for sites in k.values()
        for s in sites
        if not s.task.startswith("init:")
    ]


def _fmt_tasks(tasks: set[str], cap: int = 4) -> str:
    ordered = sorted(tasks)
    shown = ", ".join(ordered[:cap])
    if len(ordered) > cap:
        shown += f", +{len(ordered) - cap} more"
    return shown


@register
class MultiTaskMutation(Detector):
    name = "multi-task-mutation"
    summary = (
        "shared mutable state written by multiple tasks with no "
        "single-writer discipline (process-wide global, or instance "
        "state accessed from outside its owning class)"
    )

    def check(self, ctx: SchedContext) -> Iterator[Finding]:
        for state, kinds in sorted(ctx.shared_states().items()):
            writers = _writer_tasks(kinds)
            if not writers:
                continue
            sites = _runtime_sites(kinds)
            if ":" in state:
                # Module global: process-wide, shared across every
                # co-hosted simnet node regardless of yield discipline.
                if len(writers) < 2:
                    continue
                anchor = min(
                    (s for s in sites if s.is_write),
                    key=lambda s: (s.path, s.line),
                )
                yield ctx.finding(
                    self.name,
                    anchor.path,
                    anchor.line,
                    f"module global `{state}` is written by "
                    f"{len(writers)} tasks ({_fmt_tasks(writers)}); "
                    "process-wide state crosses co-hosted node boundaries "
                    "— deliberately-shared caches need a documented "
                    "`# lint: allow(multi-task-mutation)` at this site",
                )
            else:
                # Instance attribute: flag only unencapsulated sharing —
                # access sites spanning more than one class body. State
                # touched solely through its owner's methods keeps a
                # single mutation discipline (and rule
                # await-interleaved-rmw covers yields inside it).
                owner = state.split(".")[0]
                containers = {
                    ctx.container_of(s.path, s.line) for s in sites
                }
                if len(containers) < 2:
                    continue
                foreign = sorted(
                    (s for s in sites if ctx.container_of(s.path, s.line) != owner),
                    key=lambda s: (s.path, s.line),
                )
                anchor = next(
                    (s for s in foreign if s.is_write), foreign[0]
                )
                tasks = {s.task for s in sites}
                yield ctx.finding(
                    self.name,
                    anchor.path,
                    anchor.line,
                    f"`{state}` is accessed by {len(tasks)} tasks "
                    f"({_fmt_tasks(tasks)}) across class boundaries "
                    f"({', '.join(sorted(containers))}) with writes from "
                    f"{_fmt_tasks(writers)}; shared mutable state needs a "
                    "single owning writer or a documented discipline",
                )


class _AttrAccessScan(ast.NodeVisitor):
    """Linear scan of one function body: ordered (line, event) stream of
    awaits plus reads/writes of `self.<attr>` and of given global names.
    Does not descend into nested function definitions — they run on
    their own schedule."""

    _MUTATORS = frozenset({
        "append", "appendleft", "add", "update", "pop", "popleft",
        "popitem", "setdefault", "extend", "remove", "discard", "clear",
        "insert", "sort", "rotate",
    })

    def __init__(self, self_name: str, globals_of_interest: set[str]):
        self.self_name = self_name
        self.globals_of_interest = globals_of_interest
        self.awaits: list[int] = []
        self.reads: dict[str, list[int]] = {}
        self.writes: dict[str, list[int]] = {}
        self._local_names: set[str] = set()
        self._global_decls: set[str] = set()

    # -- structure ------------------------------------------------------
    def visit_FunctionDef(self, node):  # do not descend
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def visit_Global(self, node):
        self._global_decls.update(node.names)

    def visit_Await(self, node):
        self.awaits.append(node.lineno)
        self.generic_visit(node)

    # -- self.<attr> ----------------------------------------------------
    def _is_self_attr(self, node) -> bool:
        return (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == self.self_name
        )

    def visit_Attribute(self, node):
        if self._is_self_attr(node):
            if isinstance(node.ctx, (ast.Store, ast.Del)):
                self.writes.setdefault(node.attr, []).append(node.lineno)
            else:
                self.reads.setdefault(node.attr, []).append(node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        if self._is_self_attr(node.target):
            self.reads.setdefault(node.target.attr, []).append(node.lineno)
        self.generic_visit(node)

    def visit_Call(self, node):
        # `self.pending.pop(k)` / `_CACHE.setdefault(...)`: container write.
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in self._MUTATORS:
            if self._is_self_attr(f.value):
                self.writes.setdefault(f.value.attr, []).append(node.lineno)
            elif (
                isinstance(f.value, ast.Name)
                and f.value.id in self.globals_of_interest
            ):
                self.writes.setdefault(
                    f"::{f.value.id}", []
                ).append(node.lineno)
        self.generic_visit(node)

    def visit_Subscript(self, node):
        # `self.pending[k] = v` / `_CACHE[k] = v`
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            if self._is_self_attr(node.value):
                self.writes.setdefault(node.value.attr, []).append(node.lineno)
            elif (
                isinstance(node.value, ast.Name)
                and node.value.id in self.globals_of_interest
            ):
                self.writes.setdefault(
                    f"::{node.value.id}", []
                ).append(node.lineno)
        self.generic_visit(node)

    # -- module globals -------------------------------------------------
    def visit_Name(self, node):
        if node.id in self.globals_of_interest:
            if isinstance(node.ctx, ast.Load):
                if node.id not in self._local_names:
                    self.reads.setdefault(f"::{node.id}", []).append(node.lineno)
            elif node.id in self._global_decls:
                self.writes.setdefault(f"::{node.id}", []).append(node.lineno)
            else:
                self._local_names.add(node.id)  # local shadow, not the global
        self.generic_visit(node)


def _rmw_spans_await(scan: _AttrAccessScan, key: str) -> int | None:
    """Line of the first write that lands after an await which itself
    follows a read — the check-then-act shape — else None."""
    reads = scan.reads.get(key, ())
    writes = scan.writes.get(key, ())
    for a in scan.awaits:
        if any(r < a for r in reads):
            later = [w for w in writes if w > a]
            if later:
                return min(later)
    return None


@register
class AwaitInterleavedRMW(Detector):
    name = "await-interleaved-rmw"
    summary = (
        "read-modify-write of task-shared state spanning an await inside "
        "one function (check-then-act across a yield point)"
    )

    def check(self, ctx: SchedContext) -> Iterator[Finding]:
        if ctx.extractor is None:
            return
        shared = ctx.shared_states()
        # Only states with >=2 *writer* tasks can lose an update: a lone
        # writer's RMW over an await is stale-read-tolerant by design.
        attrs_by_class: dict[str, set[str]] = {}
        globals_by_module: dict[str, set[str]] = {}
        for state, kinds in shared.items():
            if len(_writer_tasks(kinds)) < 2:
                continue
            if ":" in state:
                mod, name = state.split(":", 1)
                globals_by_module.setdefault(mod, set()).add(name)
            else:
                owner, _, attr = state.partition(".")
                attrs_by_class.setdefault(owner, set()).add(attr)

        program = ctx.extractor.program
        seen: set[tuple[str, int, str]] = set()
        for mod in sorted(program.modules.values(), key=lambda m: m.rel):
            globals_here = globals_by_module.get(mod.dotted, set())
            for cls_name, ci in sorted(mod.classes.items()):
                attrs = attrs_by_class.get(cls_name, set())
                if not attrs and not globals_here:
                    continue
                for mname, fn in sorted(ci.methods.items()):
                    if not isinstance(fn, ast.AsyncFunctionDef):
                        continue
                    yield from self._scan_function(
                        ctx, mod, f"{cls_name}.{mname}", fn, attrs,
                        globals_here, seen,
                    )
            for fname, fi in sorted(mod.functions.items()):
                if globals_here and isinstance(fi.node, ast.AsyncFunctionDef):
                    yield from self._scan_function(
                        ctx, mod, fname, fi.node, set(), globals_here, seen
                    )

    def _scan_function(
        self, ctx, mod, qual, fn, attrs, globals_here, seen
    ) -> Iterator[Finding]:
        args = fn.args.args
        self_name = args[0].arg if args else "self"
        scan = _AttrAccessScan(self_name, globals_here)
        for stmt in fn.body:
            scan.visit(stmt)
        if not scan.awaits:
            return
        for attr in sorted(attrs):
            line = _rmw_spans_await(scan, attr)
            if line is not None and (mod.rel, line, attr) not in seen:
                seen.add((mod.rel, line, attr))
                yield ctx.finding(
                    self.name,
                    mod.rel,
                    line,
                    f"`self.{attr}` is read before an await and written "
                    f"after it in `{qual}`; another task can mutate it at "
                    "the yield point, making this a stale check-then-act",
                )
        for g in sorted(globals_here):
            line = _rmw_spans_await(scan, f"::{g}")
            if line is not None and (mod.rel, line, g) not in seen:
                seen.add((mod.rel, line, g))
                yield ctx.finding(
                    self.name,
                    mod.rel,
                    line,
                    f"module global `{g}` is read before an await and "
                    f"written after it in `{qual}`; concurrent tasks "
                    "interleave at the yield point",
                )
