"""narwhal-sched engine: shared-state attribution + scheduling-determinism
scanning over the whole program.

The third analysis plane. narwhal-lint gates per-function invariants and
narwhal-topo gates the actor/channel wiring; what neither sees are the two
bug classes that cost this repo the most wall-clock to diagnose:

* **interleaving races** — asyncio tasks sharing mutable state across
  `await` yield points (the certify/commit span race chased across PRs
  13/14/16, the PR-1 epoch-change deadlock). The race detectors consume
  the topology extractor's read/write-site attribution
  (`tools/analysis/extractor.py::StateSite`): every access to an instance
  attribute or mutable module global, keyed to the task that performs it.

* **replay nondeterminism** — protocol code whose behavior differs
  between two runs of the same seeded scenario (the PR-9 set-iteration
  and os.urandom divergences, found by hand A/B log diffing). These
  detectors are syntactic, per-module, and scoped to protocol code
  (`narwhal_tpu/` and explicitly-analyzed fixtures — not tests, which
  may legitimately use ambient entropy).

Machinery (Finding identity, `# lint: allow(...)` suppressions, baseline
multiset, reporters) is shared verbatim with narwhal-lint: a sched rule
is allowed the same way a lint rule is, and the checked-in baseline is
empty by policy — the tree stays clean, deliberate idioms carry inline
allows at the finding's anchor line.
"""

from __future__ import annotations

import ast
import re
import subprocess
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Iterator, Sequence

from tools.analysis.extractor import (
    DEFAULT_PACKAGE,
    DEFAULT_ROOTS,
    Extractor,
    StateSite,
    Topology,
    extract,
    state_table,
)
from tools.lint.engine import (
    DEFAULT_EXCLUDES,
    Baseline,
    Finding,
    Module,
    Result,
    discover,
    parse_module,
)

__all__ = [
    "RULES",
    "SchedContext",
    "Detector",
    "register",
    "run_sched",
    "changed_files",
]


@dataclass
class SchedContext:
    """Everything a detector may consult for one run."""

    root: Path
    modules: list[Module]  # the syntactic scan set, allows pre-scanned
    extractor: Extractor | None = None
    topology: Topology | None = None
    diff_files: set[str] | None = None  # repo-relative; None = unrestricted

    _by_rel: dict = field(default_factory=dict)
    _containers: dict = field(default_factory=dict)  # rel -> [(lo, hi, name)]

    def __post_init__(self):
        self._by_rel = {m.rel: m for m in self.modules}
        if self.extractor is not None:
            for mod in self.extractor.program.modules.values():
                spans = [
                    (ci.node.lineno, ci.node.end_lineno or ci.node.lineno, name)
                    for name, ci in mod.classes.items()
                ]
                spans.sort(key=lambda s: (s[0], -s[1]))
                self._containers[mod.rel] = spans

    # -- source access --------------------------------------------------
    def module(self, rel: str) -> Module | None:
        """Scan-set module for `rel`, parsing on demand when a finding
        anchors outside the scan set (whole-program detectors can)."""
        mod = self._by_rel.get(rel)
        if mod is None:
            path = self.root / rel
            if path.is_file():
                parsed = parse_module(path, self.root)
                if isinstance(parsed, Module):
                    mod = parsed
            self._by_rel[rel] = mod
        return mod

    def snippet(self, rel: str, line: int) -> str:
        mod = self.module(rel)
        return mod.snippet(line) if mod is not None else ""

    def finding(self, rule: str, rel: str, line: int, message: str) -> Finding:
        return Finding(rule, rel, line, 0, message, self.snippet(rel, line))

    def allowed(self, f: Finding) -> bool:
        mod = self.module(f.path)
        return mod is not None and mod.allowed(f)

    # -- structural queries ---------------------------------------------
    def container_of(self, rel: str, line: int) -> str:
        """Innermost class whose body contains (rel, line), else the
        module itself — the encapsulation unit owning that code."""
        best = None
        for lo, hi, name in self._containers.get(rel, ()):
            if lo <= line <= hi and (best is None or lo > best[0]):
                best = (lo, name)
        return best[1] if best is not None else f"module:{rel}"

    def shared_states(self, min_tasks: int = 2) -> dict[str, dict]:
        """State-table entries accessed by >= `min_tasks` distinct
        non-init tasks, with `#n` instance suffixes normalized away so
        every instance of a class aggregates into one logical state."""
        if self.extractor is None:
            return {}
        merged: dict[str, dict[str, dict[str, list[StateSite]]]] = {}
        for state, kinds in state_table(self.extractor.state_sites).items():
            norm = re.sub(r"#\d+", "", state)
            slot = merged.setdefault(norm, {"read": {}, "write": {}})
            for kind, tasks in kinds.items():
                for task, sites in tasks.items():
                    slot[kind].setdefault(task, []).extend(sites)
        out = {}
        for state, kinds in merged.items():
            tasks = {
                t
                for k in kinds.values()
                for t in k
                if not t.startswith("init:")
            }
            if len(tasks) >= min_tasks:
                out[state] = kinds
        return out


class Detector:
    """One sched rule; subclasses set name/summary and yield Findings."""

    name = "base"
    summary = ""

    def check(self, ctx: SchedContext) -> Iterator[Finding]:
        raise NotImplementedError


RULES: dict[str, Detector] = {}


def register(cls):
    RULES[cls.name] = cls()
    return cls


def protocol_scope(rel: str) -> bool:
    """Determinism rules apply to protocol/simnet-reachable code: the
    package, explicitly-analyzed sched fixtures, and out-of-repo trees
    (the --diff unit tests run against synthetic repos) — but not the
    test suite or tooling, which may use ambient entropy legitimately."""
    parts = Path(rel).parts
    if "sched_fixtures" in parts:
        return True
    return "tests" not in parts and "tools" not in parts


def changed_files(root: Path, base: str) -> set[str]:
    """Repo-relative .py paths changed between `base` and the working
    tree (deleted files excluded — nothing to analyze)."""
    proc = subprocess.run(
        [
            "git", "-C", str(root), "diff", "--name-only",
            "--diff-filter=d", base, "--", "*.py",
        ],
        capture_output=True,
        text=True,
        check=True,
    )
    return {line.strip() for line in proc.stdout.splitlines() if line.strip()}


def run_sched(
    paths: Iterable[str | Path],
    *,
    root: Path,
    package: str = DEFAULT_PACKAGE,
    roots: Sequence[str] = DEFAULT_ROOTS,
    rules: dict | None = None,
    baseline: Baseline | None = None,
    excludes: Sequence[str] = DEFAULT_EXCLUDES,
    diff_base: str | None = None,
    extraction: tuple[Topology, Extractor] | None = None,
) -> Result:
    """Run every registered detector; same Result contract as run_lint.

    `extraction` lets an embedder (tools.check) share one whole-program
    extraction between topo and sched instead of interpreting twice.
    `diff_base` restricts the syntactic scan AND the reported findings to
    files changed since that rev — whole-program extraction still sees
    the full package (races are whole-program properties)."""
    # Import for the registration side effect; rules live in RULES.
    from tools.sched import determinism, races  # noqa: F401

    rules = RULES if rules is None else rules
    baseline = baseline or Baseline()
    root = Path(root)

    diff_files: set[str] | None = None
    if diff_base is not None:
        diff_files = changed_files(root, diff_base)

    new: list[Finding] = []
    baselined: list[Finding] = []
    suppressed: list[Finding] = []
    modules: list[Module] = []
    files = discover(paths, excludes)
    for path in files:
        mod = parse_module(path, root)
        if isinstance(mod, Finding):
            if diff_files is None or mod.path in diff_files:
                new.append(mod)
            continue
        if diff_files is not None and mod.rel not in diff_files:
            continue
        modules.append(mod)

    if extraction is None and roots:
        extraction = extract(root, package=package, roots=roots)
    topology, extractor = extraction if extraction is not None else (None, None)

    ctx = SchedContext(
        root=root,
        modules=modules,
        extractor=extractor,
        topology=topology,
        diff_files=diff_files,
    )
    for rule in rules.values():
        for f in rule.check(ctx):
            if diff_files is not None and f.path not in diff_files:
                continue
            if ctx.allowed(f):
                suppressed.append(f)
            elif baseline.claim(f):
                baselined.append(f)
            else:
                new.append(f)
    new.sort(key=lambda f: (f.path, f.line, f.rule))
    return Result(new, baselined, suppressed, baseline.stale(), len(files))
