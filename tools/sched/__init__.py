"""narwhal-sched: the interleaving-race + replay-determinism plane.

Third static-analysis gate alongside narwhal-lint (tools/lint) and
narwhal-topo (tools/analysis). Shares lint's Finding/allow/baseline
machinery and consumes topo's extractor for task-attributed read/write
sites. See tools/sched/engine.py for the model and README.md for the
detector catalog.
"""

from tools.sched.engine import (  # noqa: F401
    RULES,
    Detector,
    SchedContext,
    register,
    run_sched,
)

# Importing the rule modules registers the detectors.
from tools.sched import determinism, races  # noqa: F401, E402
