"""Metrics catalog extractor: the checked-in contract for the scrape surface.

Constructs one PrimaryNode and one WorkerNode from the deterministic
CommitteeFixture WITHOUT spawning them — every metric in the repo is
registered at assembly time (constructors create channels, role metrics
objects, and the backpressure gauge), so construction alone materialises the
full per-role registry. The extracted {name, type, labels, help} rows are
diffed against tools/metrics_catalog.json by tests/test_telemetry.py: adding,
renaming, or dropping a metric without updating the catalog fails the gate,
which is how dashboards and scrapers learn about surface changes in review
instead of in production.

Regenerate after an intentional change:

    JAX_PLATFORMS=cpu python -m tools.metrics_catalog --write
"""

from __future__ import annotations

import json
import os

CATALOG_PATH = os.path.join(os.path.dirname(__file__), "metrics_catalog.json")

# Span stages are catalog rows too: the flight-recorder stage vocabulary
# is a scrape-surface contract exactly like metric names — waterfall
# stitching, the stage-percentile tables, and the perf attributors
# (tools/perf/epilogue.py) all key on these strings, so a renamed or
# drive-by stage must show up in review as catalog drift. Rows are
# `span:<stage>` with type "span_stage"; `roles` names the recording
# component.
SPAN_STAGES: tuple[tuple[str, str, str], ...] = (
    ("seal", "worker", "batch sealed by a worker's BatchMaker"),
    ("propose", "primary", "header proposed for the batch digests"),
    ("certify", "primary", "votes aggregated into a certificate"),
    ("commit", "consensus", "certificate committed by the commit rule"),
    ("execute", "executor", "committed payload applied to execution state"),
    ("device_pack", "device", "host staging of one verify batch "
     "(verify_items / aggregate_group)"),
    ("pack_items", "device", "device_pack sub-span: full-format per-vote "
     "signature item staging"),
    ("pack_groups", "device", "device_pack sub-span: compact-format "
     "aggregate-group decompress staging"),
    ("device_dispatch", "device", "async submit of the verify kernels"),
    ("device_mask_readback", "device", "blocking device->host verdict copies"),
    ("host_epilogue", "device", "post-readback host work for one batch"),
    ("epilogue_unpack", "device", "host_epilogue sub-span: verdict unpack "
     "+ accept/reject routing"),
    ("epilogue_commit", "device", "host_epilogue sub-span: process_batch "
     "DAG insert + commit walk + output bookkeeping"),
)


def span_stage_rows() -> list[dict]:
    return [
        {
            "name": f"span:{stage}",
            "type": "span_stage",
            "labels": [],
            "help": help_,
            "roles": [role],
        }
        for stage, role, help_ in SPAN_STAGES
    ]


def extract_catalog() -> list[dict]:
    """Build both role registries and return sorted catalog rows."""
    # cpu + full cert format keeps assembly free of the async verifier pool
    # (and of any accelerator imports): registration is identical across
    # backends — backends change metric VALUES, never the surface.
    from narwhal_tpu.config import Parameters
    from narwhal_tpu.fixtures import CommitteeFixture
    from narwhal_tpu.node import PrimaryNode, WorkerNode
    from narwhal_tpu.stores import NodeStorage

    fixture = CommitteeFixture(size=4, workers=1, seed=0)
    parameters = Parameters()
    parameters.cert_format = "full"
    auth = fixture.authority(0)

    primary = PrimaryNode(
        auth.keypair,
        fixture.committee,
        fixture.worker_cache,
        parameters,
        NodeStorage(None),
        network_keypair=auth.network_keypair,
    )
    worker = WorkerNode(
        auth.public,
        0,
        fixture.committee,
        fixture.worker_cache,
        parameters,
        NodeStorage(None),
        network_keypair=auth.worker_keypairs[0],
    )

    rows: dict[str, dict] = {}
    for role, registry in (("primary", primary.registry), ("worker", worker.registry)):
        for name, metric in registry._metrics.items():
            row = rows.get(name)
            if row is None:
                rows[name] = {
                    "name": name,
                    "type": metric.kind,
                    "labels": list(metric.label_names),
                    "help": metric.help,
                    "roles": [role],
                }
            elif role not in row["roles"]:
                row["roles"].append(role)
    primary.storage.close()
    worker.storage.close()
    return sorted(
        list(rows.values()) + span_stage_rows(), key=lambda r: r["name"]
    )


def load_catalog() -> list[dict]:
    with open(CATALOG_PATH) as f:
        return json.load(f)


def main() -> int:
    import argparse

    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "--write", action="store_true",
        help="regenerate tools/metrics_catalog.json from the live registries",
    )
    args = parser.parse_args()
    catalog = extract_catalog()
    if args.write:
        with open(CATALOG_PATH, "w") as f:
            json.dump(catalog, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"wrote {len(catalog)} metrics to {CATALOG_PATH}")
        return 0
    checked = {r["name"]: r for r in load_catalog()}
    live = {r["name"]: r for r in catalog}
    missing = sorted(set(live) - set(checked))
    stale = sorted(set(checked) - set(live))
    changed = sorted(
        n for n in set(live) & set(checked) if live[n] != checked[n]
    )
    for kind, names in (("undocumented", missing), ("stale", stale), ("changed", changed)):
        for n in names:
            print(f"{kind}: {n}")
    if missing or stale or changed:
        print("catalog drift — rerun with --write and review the diff")
        return 1
    print(f"catalog clean ({len(catalog)} metrics)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
