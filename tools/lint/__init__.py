"""narwhal-lint: in-repo AST analyzer for the actor/JAX invariants.

Usage: `python -m tools.lint [paths...]` — see tools/lint/__main__.py for
flags and README.md § "Static analysis" for the rule catalog, suppression
syntax, and the baseline workflow.
"""

from .engine import (  # noqa: F401
    DEFAULT_EXCLUDES,
    Baseline,
    Finding,
    Module,
    Result,
    discover,
    parse_module,
    run_lint,
)
from .rules import RULES  # noqa: F401
