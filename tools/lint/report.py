"""Reporters: human text (default) and machine JSON (--format json)."""

from __future__ import annotations

import json

from .engine import Result


def render_text(result: Result, verbose: bool = False) -> str:
    out: list[str] = []
    for f in result.new:
        out.append(f"{f.path}:{f.line}:{f.col}: [{f.rule}] {f.message}")
        if f.snippet:
            out.append(f"    {f.snippet}")
    if result.stale_baseline:
        out.append("")
        out.append(
            f"{len(result.stale_baseline)} stale baseline entr"
            f"{'y' if len(result.stale_baseline) == 1 else 'ies'} "
            "(fixed findings still grandfathered — regenerate with "
            "--write-baseline):"
        )
        for rule, path, snippet in result.stale_baseline:
            out.append(f"    {path} [{rule}] {snippet}")
    summary = (
        f"{result.files_scanned} files scanned: "
        f"{len(result.new)} new finding{'s' if len(result.new) != 1 else ''}, "
        f"{len(result.baselined)} baselined, "
        f"{len(result.suppressed)} suppressed"
    )
    out.append(summary if not out else "\n" + summary)
    if verbose and result.suppressed:
        out.append("suppressed:")
        for f in result.suppressed:
            out.append(f"    {f.path}:{f.line}: [{f.rule}]")
    return "\n".join(out)


def render_json(result: Result) -> str:
    return json.dumps(
        {
            "files_scanned": result.files_scanned,
            "new": [f.to_json() for f in result.new],
            "baselined": [f.to_json() for f in result.baselined],
            "suppressed": [f.to_json() for f in result.suppressed],
            "stale_baseline": [
                {"rule": r, "path": p, "snippet": s}
                for r, p, s in result.stale_baseline
            ],
            "ok": result.ok,
        },
        indent=2,
    )
