"""narwhal-lint engine: file discovery, suppressions, baseline matching.

The analyzer exists because Narwhal's reliability invariants live *between*
the lines the interpreter checks: every inter-actor edge must be a metered
bounded channel, nothing may block the event loop, spawned tasks must stay
drainable, jitted kernels must be pure, and decoded (cached, shared)
messages must never be mutated. Each of those was violated at least once
in rounds 4-5 (shutdown wedge, epoch deadlock, shared decode-cache
finding); this module makes the whole class machine-checked in tier-1.

Vocabulary:

- **Finding** — one rule violation at one source location. Identity for
  baseline purposes is (rule, path, stripped source line), NOT the line
  number, so unrelated edits above a grandfathered finding don't
  invalidate the baseline.
- **Suppression** — `# lint: allow(rule-a, rule-b)` on the violating line
  or on a comment-only line directly above it. Suppressions are the
  "explicitly intended" channel; the baseline is the "grandfathered,
  pay down later" channel.
- **Baseline** — a checked-in JSON multiset of findings that are
  tolerated. New findings (not suppressed, not in the baseline) fail the
  run; stale baseline entries are reported so the file can be shrunk.
"""

from __future__ import annotations

import ast
import fnmatch
import json
import re
from collections import Counter
from dataclasses import dataclass
from pathlib import Path
from typing import Iterable, Sequence

# Directories/files never scanned when *walking* a directory argument.
# Explicitly listed files are always scanned (so fixture tests can point
# the engine straight at a tripping snippet).
DEFAULT_EXCLUDES: tuple[str, ...] = (
    "lint_fixtures",  # the analyzer's own tripping/clean test snippets
    "topo_fixtures",  # narwhal-topo's tripping/clean wiring fixtures
    "sched_fixtures",  # narwhal-sched's race/determinism regression fixtures
    "__pycache__",
    "*_pb2.py",  # generated protobuf modules
    ".*",
)

_ALLOW_RE = re.compile(r"#\s*lint:\s*allow\(([A-Za-z0-9_\-*,\s]+)\)")


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # posix-relative to the lint root (repo root in practice)
    line: int
    col: int
    message: str
    snippet: str  # stripped source line — baseline identity

    @property
    def key(self) -> tuple[str, str, str]:
        return (self.rule, self.path, self.snippet)

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "snippet": self.snippet,
        }


@dataclass
class Module:
    """One parsed source file plus the pre-scanned suppression map."""

    path: Path
    rel: str
    source: str
    lines: list[str]
    tree: ast.Module
    allows: dict[int, set[str]]  # 1-based line -> allowed rule names

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(rule, self.rel, line, col, message, self.snippet(line))

    def allowed(self, finding: Finding) -> bool:
        rules = self.allows.get(finding.line, ())
        return finding.rule in rules or "*" in rules


def _scan_allows(lines: list[str]) -> dict[int, set[str]]:
    allows: dict[int, set[str]] = {}
    for i, text in enumerate(lines, start=1):
        m = _ALLOW_RE.search(text)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        allows.setdefault(i, set()).update(rules)
        if text.lstrip().startswith("#"):
            # Comment-only line: the suppression covers the next line too,
            # for statements too long to carry a trailing comment.
            allows.setdefault(i + 1, set()).update(rules)
    return allows


def parse_module(path: Path, root: Path) -> Module | Finding:
    """Parse one file; a syntax error comes back as a `syntax-error`
    finding (never baselinable by accident: the snippet is the message)."""
    try:
        rel = path.resolve().relative_to(root.resolve()).as_posix()
    except ValueError:
        rel = path.as_posix()
    source = path.read_text(encoding="utf-8")
    lines = source.splitlines()
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError as e:
        return Finding(
            "syntax-error", rel, e.lineno or 1, e.offset or 0, str(e), ""
        )
    return Module(path, rel, source, lines, tree, _scan_allows(lines))


def _excluded(part: str, excludes: Sequence[str]) -> bool:
    return any(fnmatch.fnmatch(part, pat) for pat in excludes)


def discover(paths: Iterable[str | Path], excludes: Sequence[str] = DEFAULT_EXCLUDES) -> list[Path]:
    """Expand path arguments into the ordered list of files to scan.
    Directory walks honor `excludes`; explicit file arguments do not."""
    out: list[Path] = []
    seen: set[Path] = set()
    for p in paths:
        p = Path(p)
        if p.is_dir():
            for f in sorted(p.rglob("*.py")):
                rel_parts = f.relative_to(p).parts
                if any(_excluded(part, excludes) for part in rel_parts):
                    continue
                r = f.resolve()
                if r not in seen:
                    seen.add(r)
                    out.append(f)
        elif p.suffix == ".py":
            r = p.resolve()
            if r not in seen:
                seen.add(r)
                out.append(p)
    return out


class Baseline:
    """Multiset of grandfathered findings keyed by (rule, path, snippet)."""

    def __init__(self, entries: Iterable[dict] | None = None):
        self.entries = list(entries or [])
        self._budget: Counter = Counter(
            (e["rule"], e["path"], e["snippet"]) for e in self.entries
        )

    @classmethod
    def load(cls, path: Path) -> "Baseline":
        if not path.exists():
            return cls()
        data = json.loads(path.read_text(encoding="utf-8"))
        return cls(data.get("findings", []))

    @staticmethod
    def dump(findings: Iterable[Finding], path: Path) -> None:
        entries = sorted(
            (
                {"rule": f.rule, "path": f.path, "snippet": f.snippet}
                for f in findings
            ),
            key=lambda e: (e["path"], e["rule"], e["snippet"]),
        )
        path.write_text(
            json.dumps({"version": 1, "findings": entries}, indent=2) + "\n",
            encoding="utf-8",
        )

    def claim(self, finding: Finding) -> bool:
        """Consume one budget slot for a matching entry, if any remains."""
        if self._budget[finding.key] > 0:
            self._budget[finding.key] -= 1
            return True
        return False

    def stale(self) -> list[tuple[str, str, str]]:
        """Entries whose budget was never (fully) consumed."""
        return sorted(k for k, n in self._budget.items() if n > 0)


@dataclass
class Result:
    new: list[Finding]  # fail the run
    baselined: list[Finding]
    suppressed: list[Finding]
    stale_baseline: list[tuple[str, str, str]]
    files_scanned: int

    @property
    def ok(self) -> bool:
        return not self.new


def run_lint(
    paths: Iterable[str | Path],
    rules: dict | None = None,
    baseline: Baseline | None = None,
    excludes: Sequence[str] = DEFAULT_EXCLUDES,
    root: Path | None = None,
) -> Result:
    from .rules import RULES

    rules = RULES if rules is None else rules
    baseline = baseline or Baseline()
    root = root or Path.cwd()
    new: list[Finding] = []
    baselined: list[Finding] = []
    suppressed: list[Finding] = []
    files = discover(paths, excludes)
    for path in files:
        mod = parse_module(path, root)
        if isinstance(mod, Finding):  # syntax error
            new.append(mod)
            continue
        for rule in rules.values():
            for finding in rule.check(mod):
                if mod.allowed(finding):
                    suppressed.append(finding)
                elif baseline.claim(finding):
                    baselined.append(finding)
                else:
                    new.append(finding)
    new.sort(key=lambda f: (f.path, f.line, f.rule))
    return Result(new, baselined, suppressed, baseline.stale(), len(files))
