"""narwhal-lint rules — each grounded in a failure this repo actually paid for.

| rule                      | incident it guards against                        |
|---------------------------|---------------------------------------------------|
| no-blocking-in-async      | event-loop stalls starving every co-hosted actor  |
| no-raw-queue              | unmetered actor edges (no depth gauge, no bound)  |
| tracked-task-spawn        | the PR-1 shutdown wedge: dropped task handles     |
| jit-purity                | host side effects baked into a traced TPU kernel  |
| no-shared-decode-mutation | the ADVICE r5 medium: decode-cache corruption     |
| no-silent-except          | swallowed failures in the consensus-critical dirs |
| no-per-item-rpc-in-loop   | RTT x items serialization on the commit data plane|
| no-unbounded-channel      | default-capacity edges defeating admission control|
| no-wall-clock-in-actors   | wall time leaking past the simnet virtual clock   |
| no-untracked-jit          | duplicate multi-minute kernel compiles (rc=124)   |
| metric-naming             | scrape-surface drift: unparseable/unitless names  |

Rules are pure `ast` visitors over one `Module` at a time; registration is
import-time via the `@register` decorator so `RULES` is the single catalog
the CLI, the baseline, and the tests all share. Adding a rule = subclass
`Rule`, decorate, ship a tripping + clean fixture (see
tests/lint_fixtures/) — the catalog test enforces the fixture pairing.
"""

from __future__ import annotations

import ast
import re
from pathlib import PurePath
from typing import Iterable, Iterator

from .engine import Finding, Module

RULES: dict[str, "Rule"] = {}


def register(cls: type["Rule"]) -> type["Rule"]:
    rule = cls()
    assert rule.name not in RULES, f"duplicate rule {rule.name}"
    RULES[rule.name] = rule
    return cls


class Rule:
    name: str = ""
    summary: str = ""

    def check(self, mod: Module) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, mod: Module, node: ast.AST, message: str) -> Finding:
        return mod.finding(self.name, node, message)


# ---------------------------------------------------------------------------
# Shared AST helpers
# ---------------------------------------------------------------------------


def dotted(node: ast.AST) -> str | None:
    """Render a Name/Attribute chain as 'a.b.c'; None for anything else."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def import_aliases(tree: ast.Module) -> dict[str, str]:
    """Local name -> canonical dotted origin, for both import forms:
    `import numpy as np` -> {'np': 'numpy'};
    `from time import sleep as zzz` -> {'zzz': 'time.sleep'}."""
    aliases: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def resolve(node: ast.AST, aliases: dict[str, str]) -> str | None:
    """dotted() with the leading segment mapped through the import table,
    so `sp.run` resolves to `subprocess.run` under `import subprocess as sp`."""
    d = dotted(node)
    if d is None:
        return None
    head, _, rest = d.partition(".")
    origin = aliases.get(head)
    if origin is None:
        return d
    return f"{origin}.{rest}" if rest else origin


def own_nodes(func: ast.AST) -> Iterator[ast.AST]:
    """Walk a function's body WITHOUT descending into nested function
    definitions (those run on their own schedule, often in executors)."""
    stack = list(getattr(func, "body", []))
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            stack.append(child)


def in_dirs(mod: Module, names: frozenset[str]) -> bool:
    return bool(names.intersection(PurePath(mod.rel).parts))


# ---------------------------------------------------------------------------
# no-blocking-in-async
# ---------------------------------------------------------------------------


@register
class NoBlockingInAsync(Rule):
    name = "no-blocking-in-async"
    summary = (
        "async def bodies must not call blocking primitives (time.sleep, "
        "sync file/socket I/O, subprocess, bare future .result()); one "
        "stalled coroutine starves every actor sharing the loop"
    )

    BLOCKING = {
        "time.sleep": "use `await asyncio.sleep(...)`",
        "os.system": "use `await asyncio.create_subprocess_shell(...)`",
        "os.popen": "use `await asyncio.create_subprocess_shell(...)`",
        "subprocess.run": "use asyncio.create_subprocess_exec",
        "subprocess.call": "use asyncio.create_subprocess_exec",
        "subprocess.check_call": "use asyncio.create_subprocess_exec",
        "subprocess.check_output": "use asyncio.create_subprocess_exec",
        "subprocess.Popen": "use asyncio.create_subprocess_exec",
        "socket.socket": "use asyncio.open_connection / loop.sock_* APIs",
        "socket.create_connection": "use asyncio.open_connection",
        "open": "read/write off the loop (asyncio.to_thread) or pre-open",
        "input": "never prompt inside an event loop",
    }
    _SPAWNERS = {"ensure_future", "create_task"}

    def check(self, mod: Module) -> Iterator[Finding]:
        aliases = import_aliases(mod.tree)
        for func in ast.walk(mod.tree):
            if not isinstance(func, ast.AsyncFunctionDef):
                continue
            # Names bound from asyncio.ensure_future/create_task in THIS
            # function: .result() on those is an asyncio.Task read (raises
            # if pending, never blocks) — the done-task select-loop idiom.
            safe_tasks: set[str] = set()
            for node in own_nodes(func):
                if (
                    isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and isinstance(node.value.func, ast.Attribute)
                    and node.value.func.attr in self._SPAWNERS
                ):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            safe_tasks.add(t.id)
            for node in own_nodes(func):
                if not isinstance(node, ast.Call):
                    continue
                target = resolve(node.func, aliases)
                if target in self.BLOCKING:
                    yield self.finding(
                        mod,
                        node,
                        f"`{target}(...)` blocks the event loop inside "
                        f"`async def {func.name}`; {self.BLOCKING[target]}",
                    )
                    continue
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "result"
                    and not node.args
                    and not node.keywords
                ):
                    if (
                        isinstance(node.func.value, ast.Name)
                        and node.func.value.id in safe_tasks
                    ):
                        continue  # provably an asyncio task handle
                    yield self.finding(
                        mod,
                        node,
                        "`.result()` on a future of unknown origin inside "
                        f"`async def {func.name}`: a concurrent.futures "
                        "future blocks the loop. Await it instead; if this "
                        "is a known-done asyncio task, suppress with "
                        "`# lint: allow(no-blocking-in-async)`",
                    )


# ---------------------------------------------------------------------------
# no-raw-queue
# ---------------------------------------------------------------------------


@register
class NoRawQueue(Rule):
    name = "no-raw-queue"
    summary = (
        "inter-actor edges must be metered bounded Channels (channels.py), "
        "never bare asyncio queues — the metered_channel.rs discipline: "
        "every edge has a capacity and a depth gauge"
    )

    _QUEUES = {"asyncio.Queue", "asyncio.LifoQueue", "asyncio.PriorityQueue"}

    def check(self, mod: Module) -> Iterator[Finding]:
        if mod.path.name == "channels.py":  # the one sanctioned wrapper
            return
        aliases = import_aliases(mod.tree)
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                target = resolve(node.func, aliases)
                if target in self._QUEUES:
                    yield self.finding(
                        mod,
                        node,
                        f"raw `{target}` constructed outside channels.py — "
                        "actor edges must be metered bounded Channels "
                        "(channels.Channel / metered_channel) so depth is "
                        "gauged and backpressure is bounded",
                    )


# ---------------------------------------------------------------------------
# tracked-task-spawn
# ---------------------------------------------------------------------------


@register
class TrackedTaskSpawn(Rule):
    name = "tracked-task-spawn"
    summary = (
        "a spawned task whose handle is dropped can neither be cancelled "
        "nor drained at shutdown (the PR-1 shutdown-wedge class); keep the "
        "handle in an owner that cancels it"
    )

    _SPAWNERS = {"create_task", "ensure_future"}

    def check(self, mod: Module) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            if (
                isinstance(node, ast.Expr)
                and isinstance(node.value, ast.Call)
                and (
                    (
                        isinstance(node.value.func, ast.Attribute)
                        and node.value.func.attr in self._SPAWNERS
                    )
                    or (
                        isinstance(node.value.func, ast.Name)
                        and node.value.func.id in self._SPAWNERS
                    )
                )
            ):
                yield self.finding(
                    mod,
                    node,
                    f"`{dotted(node.value.func) or node.value.func.attr}"
                    "(...)` drops the task handle — register it with a "
                    "drainable owner (BoundedExecutor, CancelOnDrop, or an "
                    "owner task set cancelled on shutdown)",
                )


# ---------------------------------------------------------------------------
# jit-purity
# ---------------------------------------------------------------------------


@register
class JitPurity(Rule):
    name = "jit-purity"
    summary = (
        "functions reachable from a @jax.jit root in tpu/ must be pure — "
        "across module boundaries (tools/analysis/purity call graph): "
        "no print/time/random/global mutation — side effects run once at "
        "trace time then silently vanish from the compiled kernel"
    )

    _IMPURE_MODULES = {"time", "random"}
    _IMPURE_CALLS = {"print", "input"}

    def check(self, mod: Module) -> Iterator[Finding]:
        if "tpu" not in PurePath(mod.rel).parts:
            return
        yield from self._check_same_module(mod)
        yield from self._check_cross_module(mod)

    def _check_same_module(self, mod: Module) -> Iterator[Finding]:
        aliases = import_aliases(mod.tree)
        funcs: dict[str, ast.AST] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                funcs.setdefault(node.name, node)
        module_globals = {
            t.id
            for stmt in mod.tree.body
            if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign))
            for t in (stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target])
            if isinstance(t, ast.Name)
        }

        roots = self._jit_roots(mod.tree, aliases, funcs)
        # Same-module call-graph BFS from the jitted roots; `via` remembers
        # which root makes each function traced, for the diagnostic.
        via: dict[str, str] = {r: r for r in roots}
        queue = list(roots)
        while queue:
            fname = queue.pop()
            for node in ast.walk(funcs[fname]):
                if isinstance(node, ast.Call):
                    callee = None
                    if isinstance(node.func, ast.Name):
                        callee = node.func.id
                    elif isinstance(node.func, ast.Attribute):
                        callee = node.func.attr  # self.helper(...) style
                    if callee in funcs and callee not in via:
                        via[callee] = via[fname]
                        queue.append(callee)

        for fname, root in via.items():
            yield from self._check_func(mod, funcs[fname], root, aliases, module_globals)

    def _check_cross_module(self, mod: Module) -> Iterator[Finding]:
        """The retired same-module caveat: BFS now continues into sibling
        modules (tools/analysis/purity). Impurities whose site lies in a
        DIFFERENT module than the jit root's declaration are reported
        while scanning the declaring module, anchored at their real site
        (an inline `# lint: allow(jit-purity)` at that site suppresses)."""
        try:
            from tools.analysis.purity import module_purity
        except ImportError:  # running outside the repo checkout
            return
        rel_dir = PurePath(mod.rel).parent
        for imp in module_purity(mod.path, mod.path.parent.parent):
            if not imp.cross_module:
                continue  # same-module findings come from _check_same_module
            if "jit-purity" in imp.allowed_rules or "*" in imp.allowed_rules:
                continue
            rel = (rel_dir / PurePath(imp.path).name).as_posix()
            yield Finding(
                self.name, rel, imp.line, imp.col, imp.message, imp.snippet
            )

    def _jit_roots(
        self, tree: ast.Module, aliases: dict[str, str], funcs: dict[str, ast.AST]
    ) -> set[str]:
        # kernel_registry.tracked_jit is the sanctioned jit wrapper in tpu/
        # (no-untracked-jit); its decoratees are jit roots exactly like raw
        # @jax.jit ones, and registry.sharded(fn, ...) wraps are the
        # sharded-kernel analog of `name = jax.jit(fn)`.
        jit_names = {
            "jax.jit",
            "jit",
            "tracked_jit",
            "kernel_registry.tracked_jit",
            "narwhal_tpu.tpu.kernel_registry.tracked_jit",
            "kernel_registry.sharded",
            "narwhal_tpu.tpu.kernel_registry.sharded",
        }
        roots: set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for deco in node.decorator_list:
                    d = resolve(deco, aliases)
                    if d in jit_names:
                        roots.add(node.name)
                    elif isinstance(deco, ast.Call):
                        f = resolve(deco.func, aliases)
                        if f in jit_names:
                            roots.add(node.name)
                        elif f in ("partial", "functools.partial") and deco.args:
                            if resolve(deco.args[0], aliases) in jit_names:
                                roots.add(node.name)
            elif isinstance(node, ast.Call):
                # name = jax.jit(fn) — wrapping a module-level function
                if resolve(node.func, aliases) in jit_names and node.args:
                    arg = node.args[0]
                    if isinstance(arg, ast.Name) and arg.id in funcs:
                        roots.add(arg.id)
        return roots

    def _check_func(
        self,
        mod: Module,
        func: ast.AST,
        root: str,
        aliases: dict[str, str],
        module_globals: set[str],
    ) -> Iterator[Finding]:
        local_names = {a.arg for a in getattr(func, "args", ast.arguments(args=[])).args}
        for node in ast.walk(func):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) else [node.target]
                for t in targets:
                    if isinstance(t, ast.Name):
                        local_names.add(t.id)
        for node in ast.walk(func):
            if isinstance(node, ast.Global):
                yield self.finding(
                    mod,
                    node,
                    f"`global {', '.join(node.names)}` inside `{func.name}` "
                    f"(reachable from jitted `{root}`): global mutation is "
                    "invisible to the traced kernel after compilation",
                )
            elif isinstance(node, ast.Call):
                target = resolve(node.func, aliases)
                if target is None:
                    continue
                head = target.split(".")[0]
                if target in self._IMPURE_CALLS or (
                    head in self._IMPURE_MODULES and head not in local_names
                ):
                    yield self.finding(
                        mod,
                        node,
                        f"impure call `{target}(...)` in `{func.name}` "
                        f"(reachable from jitted `{root}`): runs once at "
                        "trace time, then is baked into / elided from the "
                        "compiled kernel",
                    )
                elif target.startswith(("numpy.random", "np.random")):
                    yield self.finding(
                        mod,
                        node,
                        f"`{target}(...)` in `{func.name}` (reachable from "
                        f"jitted `{root}`): host RNG is trace-time constant "
                        "under jit; thread a jax.random key instead",
                    )
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) else [node.target]
                for t in targets:
                    base = t
                    hops = 0
                    while isinstance(base, (ast.Attribute, ast.Subscript)):
                        base = base.value
                        hops += 1
                    if (
                        hops
                        and isinstance(base, ast.Name)
                        and base.id in module_globals
                        and base.id not in local_names
                    ):
                        yield self.finding(
                            mod,
                            node,
                            f"mutation of module-level `{base.id}` in "
                            f"`{func.name}` (reachable from jitted "
                            f"`{root}`): happens at trace time only, not "
                            "per kernel invocation",
                        )


# ---------------------------------------------------------------------------
# no-shared-decode-mutation
# ---------------------------------------------------------------------------


@register
class NoSharedDecodeMutation(Rule):
    name = "no-shared-decode-mutation"
    summary = (
        "decoded messages are shared process-wide by the decode cache "
        "(messages._DECODE_CACHE): writing a field of one corrupts every "
        "hosted node's view (the ADVICE r5 medium)"
    )

    # Core wire types whose decoded instances flow through the caches.
    _CORE_TYPES = {"Header", "Certificate", "Vote", "Batch"}
    # The encode memo is the one sanctioned write (messages.encode_message).
    _EXEMPT_ATTRS = {"_encoded"}
    _MUTATORS = {
        "append", "extend", "insert", "remove", "add", "discard",
        "update", "setdefault", "pop", "popitem", "clear",
    }

    def check(self, mod: Module) -> Iterator[Finding]:
        msg_classes = self._message_classes(mod)
        scopes: list[ast.AST] = [mod.tree] + [
            n
            for n in ast.walk(mod.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for scope in scopes:
            tracked = self._tracked_names(scope, msg_classes)
            for node in self._scope_nodes(scope):
                yield from self._check_node(mod, node, tracked, msg_classes)

    def _scope_nodes(self, scope: ast.AST) -> Iterator[ast.AST]:
        if isinstance(scope, ast.Module):
            # Module scope: top-level statements only; functions are their
            # own scopes so tracked-name sets don't leak across.
            for stmt in scope.body:
                if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield from ast.walk(stmt)
        else:
            yield from own_nodes(scope)

    def _message_classes(self, mod: Module) -> set[str]:
        names: set[str] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                if node.module.endswith("messages"):
                    for a in node.names:
                        local = a.asname or a.name
                        if local[:1].isupper():
                            names.add(local)
                elif node.module.endswith("types"):
                    for a in node.names:
                        local = a.asname or a.name
                        if local in self._CORE_TYPES:
                            names.add(local)
            elif isinstance(node, ast.ClassDef):
                for deco in node.decorator_list:
                    if (
                        isinstance(deco, ast.Call)
                        and isinstance(deco.func, ast.Name)
                        and deco.func.id == "message"
                    ):
                        names.add(node.name)
        if mod.path.name in ("types.py", "messages.py"):
            names.update(self._CORE_TYPES)
        return names

    def _is_decode_call(self, node: ast.AST, msg_classes: set[str]) -> bool:
        if not isinstance(node, ast.Call):
            return False
        f = node.func
        if isinstance(f, ast.Name) and f.id == "decode_message":
            return True
        if isinstance(f, ast.Attribute):
            if f.attr == "decode_message":
                return True
            if f.attr in ("decode", "from_bytes") and isinstance(f.value, ast.Name):
                return f.value.id in msg_classes
        return False

    def _tracked_names(self, scope: ast.AST, msg_classes: set[str]) -> set[str]:
        tracked: set[str] = set()
        args = getattr(scope, "args", None)
        if args is not None:
            for a in list(args.args) + list(args.kwonlyargs):
                ann = a.annotation
                ann_name = None
                if isinstance(ann, ast.Name):
                    ann_name = ann.id
                elif isinstance(ann, ast.Constant) and isinstance(ann.value, str):
                    ann_name = ann.value.strip("'\"")
                if ann_name in msg_classes:
                    tracked.add(a.arg)
        for node in self._scope_nodes(scope):
            if isinstance(node, ast.Assign) and self._is_decode_call(node.value, msg_classes):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        tracked.add(t.id)
            elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
                ann = node.target
                if isinstance(node.annotation, ast.Name) and node.annotation.id in msg_classes:
                    tracked.add(ann.id)
                elif node.value is not None and self._is_decode_call(node.value, msg_classes):
                    tracked.add(ann.id)
        return tracked

    def _root_is_tracked(
        self, node: ast.AST, tracked: set[str], msg_classes: set[str]
    ) -> bool:
        """True if an Attribute/Subscript chain bottoms out at a tracked
        name or directly at a decode call result."""
        saw_attr = isinstance(node, ast.Attribute)
        base = node
        while isinstance(base, (ast.Attribute, ast.Subscript)):
            base = base.value
            if isinstance(base, ast.Attribute):
                saw_attr = True
        if not saw_attr:
            return False
        if isinstance(base, ast.Name):
            return base.id in tracked
        return self._is_decode_call(base, msg_classes)

    def _check_node(
        self, mod: Module, node: ast.AST, tracked: set[str], msg_classes: set[str]
    ) -> Iterator[Finding]:
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.Delete)):
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AugAssign):
                targets = [node.target]
            else:
                targets = node.targets
            for t in targets:
                if (
                    isinstance(t, ast.Attribute)
                    and t.attr in self._EXEMPT_ATTRS
                ):
                    continue
                if isinstance(t, (ast.Attribute, ast.Subscript)) and self._root_is_tracked(
                    t, tracked, msg_classes
                ):
                    yield self.finding(
                        mod,
                        node,
                        "write to a field of a decoded message: decoded "
                        "objects are shared by the process-wide decode "
                        "cache across every hosted node — copy before "
                        "mutating",
                    )
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in self._MUTATORS
            and isinstance(node.func.value, (ast.Attribute, ast.Subscript))
            and self._root_is_tracked(node.func.value, tracked, msg_classes)
        ):
            yield self.finding(
                mod,
                node,
                f"`.{node.func.attr}(...)` mutates a container inside a "
                "decoded message shared by the decode cache — copy before "
                "mutating",
            )


# ---------------------------------------------------------------------------
# no-sync-store-write-in-async
# ---------------------------------------------------------------------------


@register
class NoSyncStoreWriteInAsync(Rule):
    name = "no-sync-store-write-in-async"
    summary = (
        "in primary/ and consensus/, async def bodies must use the "
        "group-commit store API (put_async/write_async/write_batch_async): "
        "a sync put/write runs its own WAL append + flush() on the event "
        "loop, paying per-message I/O the batching layer exists to remove"
    )

    _SCOPED_DIRS = frozenset({"primary", "consensus"})
    _WRITE_METHODS = {
        "put",
        "put_all",
        "write",
        "write_all",
        "write_batch",
        "write_consensus_state",
    }
    # Receiver-name heuristics for store-shaped objects: the typed stores
    # (x.header_store, certificate_store, ...), the engine, and the raw
    # column-family handles. Plain `writer.write(...)` (StreamWriter) and
    # non-store receivers never match.
    _STORE_SEGMENTS = frozenset(
        {"engine", "_engine", "_cf", "_main", "_by_round", "_last", "_seq"}
    )

    def check(self, mod: Module) -> Iterator[Finding]:
        if not in_dirs(mod, self._SCOPED_DIRS):
            return
        for func in ast.walk(mod.tree):
            if not isinstance(func, ast.AsyncFunctionDef):
                continue
            for node in own_nodes(func):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in self._WRITE_METHODS
                ):
                    continue
                recv = dotted(node.func.value)
                if recv is None:
                    continue
                segments = recv.split(".")
                if not any(
                    "store" in seg.lower() or seg in self._STORE_SEGMENTS
                    for seg in segments
                ):
                    continue
                yield self.finding(
                    mod,
                    node,
                    f"sync store write `{recv}.{node.func.attr}(...)` "
                    f"inside `async def {func.name}`: each call is its own "
                    "WAL append + flush() on the event loop — use the "
                    f"async variant (`{node.func.attr}_async`/"
                    "`write_batch_async`) so the write rides a fused "
                    "group commit",
                )


# ---------------------------------------------------------------------------
# no-per-item-rpc-in-loop
# ---------------------------------------------------------------------------


@register
class NoPerItemRpcInLoop(Rule):
    name = "no-per-item-rpc-in-loop"
    summary = (
        "in executor/ and primary/, an awaited network RPC inside a for-loop "
        "pays one round trip per item (RTT x batches on the commit path); "
        "coalesce the digests into one batched request (RequestBatchesMsg, "
        "CertificatesBatchRequest) or fan out with asyncio.gather — bounded "
        "retry loops over ONE coalesced request carry a justified "
        "`# lint: allow(no-per-item-rpc-in-loop)`"
    )

    _SCOPED_DIRS = frozenset({"executor", "primary"})
    _RPC_METHODS = {"request", "unreliable_send"}
    # Receiver-name heuristic for RPC-client-shaped objects; plain
    # `queue.request(...)` on unrelated receivers never matches.
    _NET_SEGMENTS = frozenset(
        {"network", "_network", "net", "_net", "client", "_client", "peer"}
    )

    def check(self, mod: Module) -> Iterator[Finding]:
        if not in_dirs(mod, self._SCOPED_DIRS):
            return
        seen: set[tuple[int, int]] = set()
        for loop_node in ast.walk(mod.tree):
            if not isinstance(loop_node, (ast.For, ast.AsyncFor)):
                continue
            for node in self._loop_nodes(loop_node):
                if not (
                    isinstance(node, ast.Await)
                    and isinstance(node.value, ast.Call)
                    and isinstance(node.value.func, ast.Attribute)
                    and node.value.func.attr in self._RPC_METHODS
                ):
                    continue
                recv = dotted(node.value.func.value)
                if recv is None or not self._is_network_receiver(recv):
                    continue
                key = (node.lineno, node.col_offset)
                if key in seen:  # nested loops: report once
                    continue
                seen.add(key)
                yield self.finding(
                    mod,
                    node,
                    f"`await {recv}.{node.value.func.attr}(...)` inside a "
                    "for-loop serializes one RPC round trip per item — "
                    "coalesce the loop's items into one batched request, or "
                    "justify a bounded retry loop with "
                    "`# lint: allow(no-per-item-rpc-in-loop)`",
                )

    def _is_network_receiver(self, recv: str) -> bool:
        return any(
            seg in self._NET_SEGMENTS or "network" in seg.lower()
            for seg in recv.split(".")
        )

    def _loop_nodes(self, loop_node: ast.AST) -> Iterator[ast.AST]:
        """Walk a loop's body (and else) without descending into nested
        function definitions — a helper defined inside the loop runs on its
        own schedule (often gathered), not once per iteration."""
        stack = list(loop_node.body) + list(loop_node.orelse)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))


# ---------------------------------------------------------------------------
# no-unbounded-channel
# ---------------------------------------------------------------------------


@register
class NoUnboundedChannel(Rule):
    name = "no-unbounded-channel"
    summary = (
        "in worker/, primary/ and executor/ hot paths, a Channel "
        "constructed without an explicit capacity silently takes the "
        "1000-item default — an edge nobody sized, invisible to the "
        "occupancy watermarks the pacing controller and admission gate "
        "read; pass a deliberate capacity (or use metered_channel)"
    )

    _SCOPED_DIRS = frozenset({"worker", "primary", "executor"})

    def check(self, mod: Module) -> Iterator[Finding]:
        if not in_dirs(mod, self._SCOPED_DIRS):
            return
        aliases = import_aliases(mod.tree)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            target = resolve(node.func, aliases)
            if target is None or not (
                target == "Channel" or target.endswith(".Channel")
            ):
                continue
            # The first positional argument is the capacity; a capacity=
            # keyword also counts. Anything else (bare Channel(), or only
            # gauge=/other keywords) ships the unexamined default.
            if node.args:
                continue
            if any(kw.arg == "capacity" for kw in node.keywords):
                continue
            yield self.finding(
                mod,
                node,
                f"`{target}(...)` without an explicit capacity takes the "
                "default bound on a hot-path actor edge — size it "
                "deliberately so channel occupancy means something to the "
                "pacing/backpressure watermarks",
            )


# ---------------------------------------------------------------------------
# no-silent-except
# ---------------------------------------------------------------------------


@register
class NoSilentExcept(Rule):
    name = "no-silent-except"
    summary = (
        "in primary/, worker/, consensus/, network/: an except that "
        "swallows without logging hides the exact failures (wedges, "
        "deadlocks) rounds 4-5 spent days reconstructing from timeouts"
    )

    _SCOPED_DIRS = frozenset({"primary", "worker", "consensus", "network"})
    _BROAD = {"Exception", "BaseException"}
    _LOG_METHODS = {
        "debug", "info", "warning", "warn", "error", "exception", "critical", "log",
    }

    def check(self, mod: Module) -> Iterator[Finding]:
        if not in_dirs(mod, self._SCOPED_DIRS):
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            body = [
                s
                for s in node.body
                if not (
                    isinstance(s, ast.Expr)
                    and isinstance(s.value, ast.Constant)
                    and isinstance(s.value.value, str)
                )
            ]
            handled = self._handles(body)
            caught = self._caught_names(node)
            if all(
                isinstance(s, (ast.Pass, ast.Continue))
                or (
                    isinstance(s, ast.Expr)
                    and isinstance(s.value, ast.Constant)
                    and s.value.value is Ellipsis
                )
                for s in body
            ):
                yield self.finding(
                    mod,
                    node,
                    f"except {caught or '<all>'} silently swallows the "
                    "error — log it (logger.debug at minimum), re-raise, "
                    "or suppress with a one-line justification",
                )
            elif (
                not handled
                and (node.type is None or self._BROAD.intersection(self._caught_set(node)))
            ):
                yield self.finding(
                    mod,
                    node,
                    f"broad `except {caught or ''}` without logging or "
                    "re-raise: narrow the exception types, or log what was "
                    "swallowed",
                )

    def _caught_set(self, node: ast.ExceptHandler) -> set[str]:
        t = node.type
        out: set[str] = set()
        if isinstance(t, ast.Name):
            out.add(t.id)
        elif isinstance(t, ast.Tuple):
            for e in t.elts:
                if isinstance(e, ast.Name):
                    out.add(e.id)
        return out

    def _caught_names(self, node: ast.ExceptHandler) -> str:
        if node.type is None:
            return ""
        return ast.unparse(node.type) if hasattr(ast, "unparse") else "..."

    def _handles(self, body: list[ast.stmt]) -> bool:
        """True if the handler visibly deals with the error: re-raises,
        logs, or forwards it into a future."""
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Raise):
                    return True
                if isinstance(node, ast.Call):
                    f = node.func
                    # Any logger-shaped method call counts (logger.warning,
                    # self._log.error, logging.getLogger(...).exception).
                    if isinstance(f, ast.Attribute) and f.attr in self._LOG_METHODS:
                        return True
                    # Forwarding the error into a future propagates it.
                    if isinstance(f, ast.Attribute) and f.attr == "set_exception":
                        return True
                    if dotted(f) in ("warnings.warn", "traceback.print_exc"):
                        return True
        return False


# ---------------------------------------------------------------------------
# no-wall-clock-in-actors
# ---------------------------------------------------------------------------


@register
class NoWallClockInActors(Rule):
    name = "no-wall-clock-in-actors"
    summary = (
        "in primary/, worker/, consensus/, executor/ and network/: direct "
        "wall-clock reads (time.time / time.monotonic / time.perf_counter "
        "/ loop.time()) bypass the injected clock (narwhal_tpu/clock.now) "
        "— under the simnet virtual-clock harness a single stray read "
        "mixes wall time into pacing deadlines and retry backoffs, "
        "breaking both determinism and the zero-wall-clock-wait property"
    )

    _SCOPED_DIRS = frozenset(
        {"primary", "worker", "consensus", "executor", "network"}
    )
    _TIME_FUNCS = frozenset(
        {
            "time.time",
            "time.monotonic",
            "time.perf_counter",
            "time.time_ns",
            "time.monotonic_ns",
            "time.perf_counter_ns",
        }
    )
    _LOOP_GETTERS = frozenset(
        {"asyncio.get_event_loop", "asyncio.get_running_loop"}
    )

    def check(self, mod: Module) -> Iterator[Finding]:
        if not in_dirs(mod, self._SCOPED_DIRS):
            return
        aliases = import_aliases(mod.tree)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            target = resolve(node.func, aliases)
            if target in self._TIME_FUNCS:
                yield self.finding(
                    mod,
                    node,
                    f"`{target}()` reads the wall clock directly — go "
                    "through the injected clock (narwhal_tpu.clock.now) so "
                    "simnet's virtual time stays sound",
                )
                continue
            # loop.time(): any `<x>.time()` where <x> is a loop-ish name or
            # a direct get_event_loop()/get_running_loop() call.
            if not (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "time"
            ):
                continue
            base = node.func.value
            loopish = (
                isinstance(base, ast.Name) and "loop" in base.id.lower()
            ) or (
                isinstance(base, ast.Call)
                and resolve(base.func, aliases) in self._LOOP_GETTERS
            )
            if loopish:
                yield self.finding(
                    mod,
                    node,
                    "`loop.time()` in an actor bypasses the injected clock "
                    "(narwhal_tpu.clock.now); the two agree at runtime but "
                    "only the clock seam keeps the discipline greppable "
                    "and simnet-sound",
                )


# ---------------------------------------------------------------------------
# no-untracked-jit
# ---------------------------------------------------------------------------


@register
class NoUntrackedJit(Rule):
    name = "no-untracked-jit"
    summary = (
        "in tpu/, every jit entry point must route through the shared "
        "kernel registry (kernel_registry.tracked_jit / .sharded): a raw "
        "jax.jit owns its own private compile cache, so two wrappers over "
        "the same kernel+mesh each pay the full multi-minute XLA compile "
        "— the MULTICHIP rc=124 failure class — and its compile wall is "
        "invisible to the registry's per-(kernel, mesh shape) accounting"
    )

    _JIT = {"jax.jit", "jit"}

    def check(self, mod: Module) -> Iterator[Finding]:
        if "tpu" not in PurePath(mod.rel).parts:
            return
        if mod.path.name == "kernel_registry.py":  # the sanctioned wrapper
            return
        aliases = import_aliases(mod.tree)
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for deco in node.decorator_list:
                    site = deco
                    d = resolve(deco, aliases)
                    if isinstance(deco, ast.Call):
                        d = resolve(deco.func, aliases)
                        if (
                            d in ("partial", "functools.partial")
                            and deco.args
                            and resolve(deco.args[0], aliases) in self._JIT
                        ):
                            d = resolve(deco.args[0], aliases)
                    if d in self._JIT:
                        yield self.finding(
                            mod,
                            site,
                            f"`@{ast.unparse(deco)}` on `{node.name}` "
                            "bypasses the shared kernel registry — use "
                            "`@kernel_registry.tracked_jit` so the compile "
                            "is deduped and its wall is accounted per "
                            "(kernel, mesh shape)",
                        )
            elif isinstance(node, ast.Call):
                if resolve(node.func, aliases) in self._JIT:
                    yield self.finding(
                        mod,
                        node,
                        "`jax.jit(...)` called outside the kernel registry "
                        "— sharded/mesh variants must come from "
                        "`kernel_registry.sharded(...)` (one compile per "
                        "(kernel, mesh shape) per process), module-level "
                        "kernels from `@kernel_registry.tracked_jit`",
                    )


# ---------------------------------------------------------------------------
# no-per-item-cert-verify
# ---------------------------------------------------------------------------


@register
class NoPerItemCertVerify(Rule):
    name = "no-per-item-cert-verify"
    summary = (
        "in primary/ and consensus/, a Certificate.verify (or raw "
        "host_verify_aggregate) call site runs per-certificate host crypto "
        "inline; certificates must ride the batched verifier API — the "
        "crypto pool's verify/verify_aggregate lanes or "
        "types.host_batch_verify_aggregates — so signature work amortizes "
        "one device dispatch / one bucket-method MSM per flush. The "
        "documented terminal fallbacks (no pool configured) carry a "
        "justified `# lint: allow(no-per-item-cert-verify)`"
    )

    _SCOPED_DIRS = frozenset({"primary", "consensus"})
    # Receiver-name heuristic for certificate-shaped objects; header.verify
    # and vote.verify never match (their per-item checks ARE the batched
    # stage's structural half).
    _CERT_METHODS = {"verify"}

    def check(self, mod: Module) -> Iterator[Finding]:
        if not in_dirs(mod, self._SCOPED_DIRS):
            return
        aliases = import_aliases(mod.tree)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            target = resolve(node.func, aliases)
            if target is not None and (
                target == "host_verify_aggregate"
                or target.endswith(".host_verify_aggregate")
            ):
                yield self.finding(
                    mod,
                    node,
                    "`host_verify_aggregate(...)` is the per-certificate "
                    "naive reference — dispatch proof groups through "
                    "`host_batch_verify_aggregates` (or the crypto pool's "
                    "verify_aggregate lane) so one MSM serves the flush",
                )
                continue
            if not (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in self._CERT_METHODS
            ):
                continue
            recv = dotted(node.func.value)
            # Only the FINAL segment names the receiver: `cert.verify` is a
            # certificate check, `cert.header.verify` is the header's.
            if recv is None or "cert" not in recv.split(".")[-1].lower():
                continue
            yield self.finding(
                mod,
                node,
                f"`{recv}.{node.func.attr}(...)` verifies one certificate "
                "inline on the host — route it through the batched "
                "verifier API (verifier stage / crypto pool "
                "verify_aggregate), or justify a documented no-pool "
                "fallback with `# lint: allow(no-per-item-cert-verify)`",
            )


# ---------------------------------------------------------------------------
# metric-naming
# ---------------------------------------------------------------------------


@register
class MetricNaming(Rule):
    name = "metric-naming"
    summary = (
        "registry.counter/gauge/histogram names must follow "
        "<subsystem>_<name>[_<unit>]: snake_case, a known subsystem prefix, "
        "and a unit suffix on histograms — the checked-in metrics catalog "
        "(tools/metrics_catalog.json) and every dashboard key on this "
        "grammar, so a drive-by name invents a subsystem or loses its unit "
        "silently"
    )

    _METHODS = frozenset({"counter", "gauge", "histogram"})
    # "perf" is the observatory's namespace (tools/perf, benchmark.ab):
    # perf_* metrics describe the MEASUREMENT plane (calibration capacity,
    # leg timings), never protocol behaviour.
    _SUBSYSTEMS = frozenset(
        {"consensus", "executor", "node", "perf", "primary", "storage",
         "telemetry", "wire", "worker"}
    )
    # Histogram units in use; 'size'/'certificate' are count-like units
    # (created_batch_size, fetch_rpcs_per_certificate).
    _UNITS = frozenset({"seconds", "bytes", "size", "certificate"})
    _NAME_RE = re.compile(r"^[a-z][a-z0-9]*(_[a-z0-9]+)*$")

    def check(self, mod: Module) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in self._METHODS
                and node.args
            ):
                continue
            first = node.args[0]
            # Computed names (the f-string channel-depth gauges built by
            # metered_channel) are covered by their own construction seam.
            if not (isinstance(first, ast.Constant) and isinstance(first.value, str)):
                continue
            name = first.value
            if not self._NAME_RE.match(name):
                yield self.finding(
                    mod,
                    node,
                    f"metric name {name!r} is not snake_case "
                    "(lowercase segments joined by single underscores)",
                )
                continue
            subsystem = name.split("_", 1)[0]
            if subsystem not in self._SUBSYSTEMS:
                yield self.finding(
                    mod,
                    node,
                    f"metric name {name!r} starts with unknown subsystem "
                    f"{subsystem!r}; use one of "
                    f"{'/'.join(sorted(self._SUBSYSTEMS))} (or extend the "
                    "lint's subsystem set deliberately)",
                )
                continue
            if (
                node.func.attr == "histogram"
                and name.rsplit("_", 1)[-1] not in self._UNITS
            ):
                yield self.finding(
                    mod,
                    node,
                    f"histogram {name!r} must end in a unit suffix "
                    f"({'/'.join(sorted(self._UNITS))}) so readers know "
                    "what the buckets measure",
                )


# ---------------------------------------------------------------------------
# no-direct-peer-connection
# ---------------------------------------------------------------------------


@register
class NoDirectPeerConnection(Rule):
    name = "no-direct-peer-connection"
    summary = (
        "in primary/, worker/ and executor/, peer connections must go "
        "through the node's LanePool (NetworkClient.peer routes committee "
        "addresses onto the one pooled link per peer pair): a direct "
        "transport.open_connection / asyncio.open_connection or a "
        "hand-built PeerClient(...) opens a dedicated socket per call "
        "site, quietly re-growing the O(N^2*(1+W)) mesh the pool "
        "collapsed — the socket wall n100_liveness.json died on"
    )

    _SCOPED_DIRS = frozenset({"primary", "worker", "executor"})

    def check(self, mod: Module) -> Iterator[Finding]:
        if not in_dirs(mod, self._SCOPED_DIRS):
            return
        aliases = import_aliases(mod.tree)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            resolved = resolve(node.func, aliases)
            if resolved is None:
                continue
            leaf = resolved.rsplit(".", 1)[-1]
            if leaf == "open_connection":
                yield self.finding(
                    mod,
                    node,
                    f"direct socket dial `{dotted(node.func)}(...)`: peer "
                    "connections belong to the LanePool (one multiplexed "
                    "link per peer pair) — use NetworkClient.peer / "
                    "pool.link_for instead of opening a dedicated stream",
                )
            elif leaf == "PeerClient":
                yield self.finding(
                    mod,
                    node,
                    f"hand-built `{dotted(node.func)}(...)`: construct "
                    "peers via NetworkClient.peer so committee addresses "
                    "ride the pooled lane (PeerClient is the pool's "
                    "internal legacy fallback, not an application API)",
                )
