"""Host-capacity calibration: the pinned probe behind every perf record.

The bench host is a 1-core container whose effective capacity swings
10-20x day to day (and hour to hour, when a sibling build lands on the
same machine). A throughput number without the capacity it was measured
under is therefore uninterpretable — so every ledger record and every
A/B leg carries a `calibration_probe()` snapshot: a fixed CPU workload
(a sha256 hash chain, pinned at module level so the work never drifts
across revisions) timed for a short wall-clock window, plus the load
average and a scan for concurrently-running pytest/bench processes (the
usual source of "mystery" 2x swings mid-suite).

The probe is intentionally cheap (~100 ms at default budget): it brackets
every bench leg without perturbing it, and `drift(a, b)` quantifies how
much the host moved between two probes — benchmark.ab refuses to issue a
verdict when that drift exceeds its gate.
"""

from __future__ import annotations

import hashlib
import os
import time

# The pinned workload: one "op" is _CHAIN_STRIDE chained sha256 digests.
# Chaining defeats any constant-folding and keeps the working set in L1,
# so ops/s tracks available CPU cycles and nothing else.
_CHAIN_STRIDE = 256
_SEED = b"\x5anarwhal-perf-calibration\x5a" * 2


def calibration_probe(budget_s: float = 0.1) -> dict:
    """Time the pinned hash chain for ~budget_s and report capacity.

    Returns a JSON-ready snapshot: `ops_per_s` (the capacity figure —
    higher is a faster host), the measured window, loadavg, cpu count,
    and the probe's unix timestamp.
    """
    h = hashlib.sha256
    digest = _SEED
    ops = 0
    t0 = time.perf_counter()
    deadline = t0 + budget_s
    while time.perf_counter() < deadline:
        for _ in range(_CHAIN_STRIDE):
            digest = h(digest).digest()
        ops += 1
    elapsed = time.perf_counter() - t0
    try:
        load1, load5, load15 = os.getloadavg()
    except OSError:  # pragma: no cover - getloadavg absent on some hosts
        load1 = load5 = load15 = -1.0
    return {
        "unix_time": time.time(),
        "probe_s": elapsed,
        "chain_ops": ops,
        "ops_per_s": ops / elapsed if elapsed > 0 else 0.0,
        "loadavg_1m": load1,
        "loadavg_5m": load5,
        "loadavg_15m": load15,
        "cpu_count": os.cpu_count() or 1,
    }


def drift(a: dict, b: dict) -> float:
    """Relative capacity swing between two probes: 0.0 = identical host,
    1.0 = one probe saw double (or half) the other's ops/s."""
    x, y = a.get("ops_per_s", 0.0), b.get("ops_per_s", 0.0)
    if x <= 0 or y <= 0:
        return float("inf")
    hi, lo = max(x, y), min(x, y)
    return hi / lo - 1.0


def concurrent_processes(patterns: tuple[str, ...] = ("pytest", "benchmark")) -> list[dict]:
    """Scan /proc for OTHER live processes whose cmdline mentions any of
    `patterns` — the self-diagnosis hook for contention flakes (a second
    pytest run on this 1-core host reliably trips liveness timeouts).

    Best-effort: on hosts without /proc (or with restricted permissions)
    it returns what it could see, never raises.
    """
    me = os.getpid()
    found: list[dict] = []
    try:
        pids = [int(p) for p in os.listdir("/proc") if p.isdigit()]
    except OSError:  # pragma: no cover - no /proc
        return found
    for pid in pids:
        if pid == me:
            continue
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as fh:
                cmdline = fh.read().replace(b"\x00", b" ").decode(errors="replace").strip()
        except OSError:
            continue
        if any(pat in cmdline for pat in patterns):
            found.append({"pid": pid, "cmdline": cmdline[:300]})
    return found


def host_context(probe_budget_s: float = 0.05) -> dict:
    """The full host snapshot conftest attaches to failing cluster tests:
    a (short-budget) calibration probe plus the concurrent-process scan."""
    ctx = {"calibration": calibration_probe(budget_s=probe_budget_s)}
    ctx["concurrent"] = concurrent_processes()
    ctx["concurrent_pytest"] = any(
        "pytest" in p["cmdline"] for p in ctx["concurrent"]
    )
    return ctx
