"""Simnet fabric profiler: per-component self-time on the virtual-clock
hot path.

ROADMAP item 3 (the N=200 scenario burns ~1300 s wall for 1.92 M fabric
events; target 10x) is blocked on attribution, not ideas: nobody knows
whether the budget goes to fabric delivery, timer churn in the virtual
selector, the per-frame AEAD, or the hash-chained event log. This module
answers that by running a seeded scenario under cProfile and folding
every function's SELF time into a small set of named components:

  fabric_deliver  simnet/fabric.py transmit/deliver machinery
  event_log       the hash-chained EventLog (append + digest)
  sim_clock       simnet/clock.py — the virtual-time selector + timers
  auth_aead       network/auth.py + the blake2b/hmac primitives it drives
  signing         narwhal_tpu/crypto.py (ed25519 sign/verify)
  wire_rpc        framing, transport seam, channels
  codec           message encode/decode
  protocol        primary/worker/consensus/dag/executor logic
  asyncio_loop    stdlib asyncio + selectors dispatch
  other           everything unmatched (the attribution residual)

Self time (cProfile `tottime`) sums to the profiled wall time, so the
component shares are a true decomposition: the ranked table names where
the 10x must come from, and `attributed_share` (everything but `other`)
is the acceptance figure — below 0.8 the bucket table has drifted from
the code and needs new patterns, which is exactly what the gate in
tests/test_perf_observatory.py would catch.

Run:  JAX_PLATFORMS=cpu python -m tools.perf.simnet_profile \
          --nodes 6 --duration 3 --load-rate 120 --out <artifact.json>
"""

from __future__ import annotations

import cProfile
import json
import pstats
import re

# Ordered: first match wins. Patterns run against "filename:funcname"
# with the filename reduced to its repo-relative (or basename) form.
_COMPONENTS: tuple[tuple[str, re.Pattern], ...] = (
    ("event_log", re.compile(r"simnet/fabric\.py:(append|digest|_chain)")),
    ("fabric_deliver", re.compile(r"simnet/fabric\.py:")),
    ("sim_clock", re.compile(r"simnet/(clock|scenario)\.py:")),
    (
        "auth_aead",
        re.compile(
            r"network/auth\.py:|~:<built-in method _blake2|"
            r"~:.*(blake2b|hmac|compare_digest)|hmac\.py:"
        ),
    ),
    (
        "signing",
        # ed25519_ref is the pure-python group law behind sign/verify; the
        # pow builtin is its field inversion/exponentiation — in a simnet
        # scenario nothing else drives pow at depth, so it bills here.
        re.compile(
            r"narwhal_tpu/crypto\.py:|narwhal_tpu/tpu/ed25519_ref\.py:|"
            r"~:.*(sha512|ed25519|scalarmult)|~:<built-in method builtins\.pow"
        ),
    ),
    (
        "wire_rpc",
        re.compile(
            r"network/(rpc|transport)\.py:|narwhal_tpu/channels\.py:|"
            r"narwhal_tpu/grpc_api\.py:"
        ),
    ),
    ("codec", re.compile(r"narwhal_tpu/(codec|messages)\.py:|~:.*sha256")),
    (
        "protocol",
        re.compile(
            r"narwhal_tpu/(primary|worker|consensus|executor)/|"
            r"narwhal_tpu/(dag|node|native|pacing|storage|stores|types|tracing|"
            r"metrics|config|clock|bounded_cache|cluster|fixtures)\.py:"
        ),
    ),
    (
        "asyncio_loop",
        re.compile(
            r"asyncio/|selectors\.py:|~:<built-in method select|queue\.py:|"
            r"_weakrefset\.py:|~:<method 'run' of '_contextvars|"
            r"~:.*_asyncio"
        ),
    ),
)


def _label(filename: str, funcname: str) -> str:
    # Normalise absolute paths down to a stable repo-relative-ish suffix
    # so the patterns match regardless of checkout location.
    name = filename.replace("\\", "/")
    for anchor in ("narwhal_tpu/", "asyncio/", "tools/"):
        idx = name.rfind(anchor)
        if idx >= 0:
            name = name[idx:]
            break
    else:
        name = name.rsplit("/", 1)[-1]
    return f"{name}:{funcname}"


def classify(filename: str, funcname: str) -> str:
    label = _label(filename, funcname)
    for component, pattern in _COMPONENTS:
        if pattern.search(label):
            return component
    return "other"


def attribute_stats(stats: pstats.Stats) -> dict:
    """Fold a pstats tree into the component decomposition."""
    buckets: dict[str, dict] = {}
    total = 0.0
    for (filename, _lineno, funcname), row in stats.stats.items():  # type: ignore[attr-defined]
        _cc, ncalls, tottime, _cumtime = row[0], row[1], row[2], row[3]
        total += tottime
        component = classify(filename, funcname)
        bucket = buckets.setdefault(
            component, {"self_s": 0.0, "calls": 0, "top": []}
        )
        bucket["self_s"] += tottime
        bucket["calls"] += ncalls
        bucket["top"].append((tottime, _label(filename, funcname)))
    ranked = []
    for component, bucket in buckets.items():
        bucket["top"].sort(reverse=True)
        ranked.append(
            {
                "component": component,
                "self_s": round(bucket["self_s"], 4),
                "share": round(bucket["self_s"] / total, 4) if total else 0.0,
                "calls": bucket["calls"],
                "top_functions": [
                    {"self_s": round(t, 4), "function": name}
                    for t, name in bucket["top"][:5]
                ],
            }
        )
    ranked.sort(key=lambda r: -r["self_s"])
    attributed = sum(r["self_s"] for r in ranked if r["component"] != "other")
    return {
        "total_self_s": round(total, 4),
        "attributed_share": round(attributed / total, 4) if total else 0.0,
        "components": ranked,
    }


def profile_scenario(
    nodes: int = 6,
    duration: float = 3.0,
    load_rate: int = 120,
    seed: int = 7,
    workers: int = 1,
) -> dict:
    """Run one seeded scenario under cProfile and return the component
    attribution plus the scenario's own summary figures."""
    from narwhal_tpu.simnet import FaultPlan, LinkSpec, run_scenario
    from narwhal_tpu.simnet.fabric import SimFabric

    profiler = cProfile.Profile()
    profiler.enable()
    try:
        result = run_scenario(
            nodes=nodes,
            workers=workers,
            duration=duration,
            load_rate=load_rate,
            plan=FaultPlan(seed=seed, default_link=LinkSpec(latency=0.002)),
        )
    finally:
        profiler.disable()
    stats = pstats.Stats(profiler)
    report = attribute_stats(stats)
    report["scenario"] = {
        "nodes": nodes,
        "workers": workers,
        "duration_virtual_s": duration,
        "load_rate": load_rate,
        "seed": seed,
        "wall_s": round(result.wall_s, 3),
        "event_log_len": result.event_log_len,
        "committed_rounds": max(result.rounds) if result.rounds else 0,
        "fabric_counters": dict(SimFabric.last_counters),
    }
    return report


def render_table(report: dict) -> str:
    """The ranked table: where the virtual-clock wall time actually goes."""
    lines = [
        f"simnet fabric profile — {report['total_self_s']:.2f}s self time, "
        f"{report['attributed_share']:.0%} attributed to named components",
        f"{'component':<16} {'self_s':>8} {'share':>7} {'calls':>10}  hottest function",
    ]
    for row in report["components"]:
        hottest = row["top_functions"][0]["function"] if row["top_functions"] else "-"
        lines.append(
            f"{row['component']:<16} {row['self_s']:>8.3f} "
            f"{row['share']:>6.1%} {row['calls']:>10}  {hottest}"
        )
    return "\n".join(lines)


def main() -> int:
    import argparse

    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--nodes", type=int, default=6)
    parser.add_argument("--workers", type=int, default=1)
    parser.add_argument("--duration", type=float, default=3.0)
    parser.add_argument("--load-rate", type=int, default=120)
    parser.add_argument("--seed", type=int, default=7)
    parser.add_argument("--out", default=None, help="write the report JSON here")
    args = parser.parse_args()

    report = profile_scenario(
        nodes=args.nodes,
        workers=args.workers,
        duration=args.duration,
        load_rate=args.load_rate,
        seed=args.seed,
    )
    print(render_table(report))
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(report, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"wrote {args.out}")

    from . import ledger

    ledger.append(
        "simnet_profile",
        report,
        argv=["tools.perf.simnet_profile"]
        + [f"--nodes={args.nodes}", f"--duration={args.duration}"],
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
