"""The commit-keyed perf ledger: benchmark/results/ledger.jsonl.

Before this module, the repo's perf trajectory was reconstructable only
from CHANGES.md prose: every bench run wrote an ad-hoc JSON file with its
own shape (pacing_ab_r8.json, worker_shard_ab_r9.json, trace_ab_r13.json
all differ). The ledger replaces that with ONE append-only JSONL file
where every bench/A/B entry point appends a schema-validated record
keyed by the git revision it measured, carrying the host calibration it
measured UNDER, and (for A/B runs) the canonical verdict.

The schema is deliberately small and closed: unknown top-level keys are
hard errors, so a drive-by bench that invents a field fails the tier-1
schema gate (tests/test_perf_observatory.py) instead of silently forking
the record shape — the exact failure mode the ad-hoc files had.

Environment:
  NARWHAL_PERF_LEDGER=0        disable appends entirely (tests default
                               to this via conftest so suite runs never
                               dirty the checked-in ledger);
  NARWHAL_PERF_LEDGER_PATH=... append somewhere else (ab.py uses this to
                               keep base-leg subprocesses out of the
                               head ledger).

Pre-ledger artifacts in benchmark/results/*.json remain valid history:
`classify_results_dir` tags anything without a `schema` stamp as
`legacy` and only flags unparseable files — the tolerance contract the
legacy-results test pins.
"""

from __future__ import annotations

import json
import os
import subprocess
import time
from pathlib import Path

SCHEMA = "narwhal-perf-ledger/1"

# Every entry point that may append. A record with a kind outside this
# set is an unregistered shape: extend the set (and the test) on purpose.
KINDS = frozenset(
    {
        "inprocess",
        "liveness",
        "sweep",
        "microbench",
        "multichip",
        "ab",
        "simnet_profile",
        "epilogue_profile",
        "fuzz",
    }
)

_REPO_ROOT = Path(__file__).resolve().parents[2]
DEFAULT_PATH = _REPO_ROOT / "benchmark" / "results" / "ledger.jsonl"

# The closed top-level surface: name -> (required, type check).
_FIELDS: dict[str, tuple[bool, object]] = {
    "schema": (True, str),
    "kind": (True, str),
    "git_rev": (True, str),
    "recorded_unix": (True, (int, float)),
    "host": (True, dict),
    "payload": (True, (dict, list)),
    "verdict": (False, dict),
    "scrape": (False, dict),
    "argv": (False, list),
    "note": (False, str),
}


def validate_record(record: object) -> list[str]:
    """Return every schema violation (empty list == valid)."""
    if not isinstance(record, dict):
        return [f"record must be an object, got {type(record).__name__}"]
    errors: list[str] = []
    for name, (required, typ) in _FIELDS.items():
        if name not in record:
            if required:
                errors.append(f"missing required field {name!r}")
            continue
        if not isinstance(record[name], typ):
            errors.append(
                f"field {name!r} must be {typ}, got {type(record[name]).__name__}"
            )
    for name in record:
        if name not in _FIELDS:
            errors.append(f"unregistered field {name!r} (the schema is closed)")
    if record.get("schema") not in (None, SCHEMA):
        errors.append(f"unknown schema {record.get('schema')!r}, want {SCHEMA!r}")
    kind = record.get("kind")
    if isinstance(kind, str) and kind not in KINDS:
        errors.append(f"unregistered kind {kind!r}, want one of {sorted(KINDS)}")
    host = record.get("host")
    if isinstance(host, dict) and "calibration" not in host:
        errors.append("host snapshot missing 'calibration' probe")
    if isinstance(record.get("verdict"), dict):
        v = record["verdict"]
        if v.get("verdict") not in {"win", "null", "regression", "no-verdict"}:
            errors.append(
                f"verdict.verdict must be win/null/regression/no-verdict, "
                f"got {v.get('verdict')!r}"
            )
    return errors


def git_rev(cwd: str | os.PathLike | None = None) -> str:
    """The commit key. Appends '-dirty' when the working tree differs, so
    a record measured on uncommitted code never masquerades as the rev."""
    try:
        rev = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            cwd=cwd or _REPO_ROOT, capture_output=True, text=True, timeout=10,
        ).stdout.strip()
        if not rev:
            return "unknown"
        dirty = subprocess.run(
            ["git", "status", "--porcelain", "--untracked-files=no"],
            cwd=cwd or _REPO_ROOT, capture_output=True, text=True, timeout=10,
        ).stdout.strip()
        return rev + ("-dirty" if dirty else "")
    except Exception:
        return "unknown"


def ledger_path() -> Path:
    override = os.environ.get("NARWHAL_PERF_LEDGER_PATH")
    return Path(override) if override else DEFAULT_PATH


def enabled() -> bool:
    return os.environ.get("NARWHAL_PERF_LEDGER", "1") not in {"0", "false", "no"}


def build_record(
    kind: str,
    payload: dict | list,
    *,
    verdict: dict | None = None,
    scrape: dict | None = None,
    argv: list | None = None,
    note: str | None = None,
    host: dict | None = None,
    rev: str | None = None,
) -> dict:
    """Assemble (and validate) one ledger record. Runs the calibration
    probe unless a host snapshot is supplied (A/B legs probe themselves
    so the record reflects the leg's bracket, not append time)."""
    from . import calibrate

    record: dict = {
        "schema": SCHEMA,
        "kind": kind,
        "git_rev": rev if rev is not None else git_rev(),
        "recorded_unix": time.time(),
        "host": host
        if host is not None
        else {"calibration": calibrate.calibration_probe()},
        "payload": payload,
    }
    if verdict is not None:
        record["verdict"] = verdict
    if scrape is not None:
        record["scrape"] = scrape
    if argv is not None:
        record["argv"] = [str(a) for a in argv]
    if note is not None:
        record["note"] = note
    errors = validate_record(record)
    if errors:
        raise ValueError(f"refusing to build invalid ledger record: {errors}")
    return record


def append(kind: str, payload: dict | list, **kwargs) -> dict | None:
    """Append one validated record; returns it, or None when the ledger
    is disabled. Bench entry points call this exactly once per run, after
    their own --out artifact is written — the ledger is additive, never a
    replacement for the detailed per-bench record."""
    if not enabled():
        return None
    record = build_record(kind, payload, **kwargs)
    path = ledger_path()
    path.parent.mkdir(parents=True, exist_ok=True)
    with open(path, "a") as fh:
        fh.write(json.dumps(record, sort_keys=True) + "\n")
    return record


def read_ledger(path: str | os.PathLike | None = None) -> list[dict]:
    """Parse every line; raises on a malformed line (the ledger is a
    gated artifact — a bad line is a bug, not data)."""
    p = Path(path) if path is not None else ledger_path()
    records: list[dict] = []
    if not p.exists():
        return records
    with open(p) as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                record = json.loads(line)
            except json.JSONDecodeError as exc:
                raise ValueError(f"{p}:{lineno}: malformed ledger line: {exc}")
            errors = validate_record(record)
            if errors:
                raise ValueError(f"{p}:{lineno}: invalid record: {errors}")
            records.append(record)
    return records


def classify_results_dir(results_dir: str | os.PathLike | None = None) -> list[dict]:
    """Walk benchmark/results/ and classify every artifact:

      ledger  — a JSONL/JSON record carrying the `schema` stamp (validated);
      legacy  — pre-ledger JSON without a `schema` stamp (accepted as-is);
      error   — unreadable/unparseable, or a stamped record that fails
                validation (the only hard failures).
    """
    root = (
        Path(results_dir)
        if results_dir is not None
        else _REPO_ROOT / "benchmark" / "results"
    )
    report: list[dict] = []
    for path in sorted(root.iterdir()):
        if path.suffix == ".jsonl":
            try:
                n = len(read_ledger(path))
                report.append({"file": path.name, "status": "ledger", "records": n})
            except ValueError as exc:
                report.append({"file": path.name, "status": "error", "detail": str(exc)})
            continue
        if path.suffix != ".json":
            continue
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except (OSError, json.JSONDecodeError) as exc:
            report.append({"file": path.name, "status": "error", "detail": str(exc)})
            continue
        if isinstance(doc, dict) and "schema" in doc:
            errors = validate_record(doc)
            if errors:
                report.append(
                    {"file": path.name, "status": "error", "detail": str(errors)}
                )
            else:
                report.append({"file": path.name, "status": "ledger", "records": 1})
        else:
            report.append({"file": path.name, "status": "legacy"})
    return report


def main() -> int:
    import argparse

    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--path", default=None, help="ledger file (default: checked-in)")
    parser.add_argument(
        "--classify", action="store_true",
        help="classify every benchmark/results artifact instead",
    )
    args = parser.parse_args()
    if args.classify:
        report = classify_results_dir()
        for row in report:
            print(f"{row['status']:7s} {row['file']}" + (
                f"  ({row['detail']})" if "detail" in row else ""))
        errors = [r for r in report if r["status"] == "error"]
        return 1 if errors else 0
    records = read_ledger(args.path)
    for r in records:
        v = r.get("verdict", {}).get("verdict", "-")
        print(
            f"{r['git_rev'][:12]:12s} {r['kind']:16s} {v:10s} "
            f"ops/s={r['host']['calibration'].get('ops_per_s', 0):.0f}"
        )
    print(f"{len(records)} record(s)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
