"""Device-plane epilogue attributor: where host_epilogue actually goes.

ROADMAP item 5 caps multi-chip scaling on the HOST epilogue (~12.5x over
the device verify), and dieting it needs a denominator: a per-batch
breakdown of the epilogue into its constituents. tpu/pipeline.py records
one span tree per sampled batch, keyed by the batch's first certificate
digest:

  device_pack            host pack: verify_items/aggregate_group staging
    pack_items             - full-format per-vote signature item staging
    pack_groups            - compact-format aggregate-group decompress
  device_dispatch        async submit of the verify kernels
  device_mask_readback   blocking device->host verdict copies
  host_epilogue          everything after the readback lands
    epilogue_unpack        - verdict unpack + accept/reject routing
    epilogue_commit        - engine.process_batch: DAG insert + commit walk

`attribute(dumps)` folds the flight-recorder dumps into per-batch rows
and checks the books: the epilogue sub-spans must sum to within 10% of
the measured host_epilogue span (the acceptance gate), with the
remainder reported as `epilogue_unattributed_s` so a future stage added
to the pipeline without a sub-span shows up as drift here instead of
vanishing.

benchmark/multichip.py runs this over its dryrun leg; it also works on
any flight dump from a traced device-backed run.
"""

from __future__ import annotations

# The epilogue constituents: sub-spans recorded INSIDE host_epilogue.
EPILOGUE_PARTS = ("epilogue_unpack", "epilogue_commit")
# The pack constituents: sub-spans recorded inside device_pack.
PACK_PARTS = ("pack_items", "pack_groups")
STAGES = (
    "device_pack",
    "device_dispatch",
    "device_mask_readback",
    "host_epilogue",
) + EPILOGUE_PARTS + PACK_PARTS


def attribute(dumps: list[dict]) -> dict:
    """Fold flight dumps into the per-batch epilogue breakdown.

    Returns {"batches": [row...], "totals": {...}} where each row carries
    the batch key, n (certificates in the batch), every stage width, the
    epilogue sub-span sum, and its relative error vs the measured
    host_epilogue span.
    """
    # key -> stage -> [width_s, ...] (a key can only host one batch, but
    # stay defensive: sum repeated spans).
    per_key: dict[str, dict[str, float]] = {}
    n_by_key: dict[str, int] = {}
    for dump in dumps:
        for event in dump.get("events", ()):
            if not event or event[0] != "span":
                continue
            _, stage, key, t0, t1 = event[:5]
            if stage not in STAGES:
                continue
            attrs = event[5] if len(event) > 5 and isinstance(event[5], dict) else {}
            row = per_key.setdefault(key, {})
            row[stage] = row.get(stage, 0.0) + (t1 - t0)
            if "n" in attrs:
                n_by_key[key] = attrs["n"]

    batches = []
    for key, stages in sorted(per_key.items()):
        epilogue = stages.get("host_epilogue", 0.0)
        parts = {p: stages.get(p, 0.0) for p in EPILOGUE_PARTS}
        part_sum = sum(parts.values())
        row = {
            "batch_key": key,
            "n": n_by_key.get(key, 0),
            **{s: round(stages.get(s, 0.0), 6) for s in STAGES if s in stages},
            "epilogue_parts_s": round(part_sum, 6),
            "epilogue_unattributed_s": round(epilogue - part_sum, 6),
            "epilogue_rel_err": round(abs(part_sum - epilogue) / epilogue, 4)
            if epilogue > 0
            else 0.0,
        }
        batches.append(row)

    def total(stage: str) -> float:
        return sum(per_key[k].get(stage, 0.0) for k in per_key)

    epilogue_total = total("host_epilogue")
    parts_total = sum(total(p) for p in EPILOGUE_PARTS)
    totals = {
        "batches": len(batches),
        **{s: round(total(s), 6) for s in STAGES},
        "epilogue_parts_s": round(parts_total, 6),
        "epilogue_rel_err": round(abs(parts_total - epilogue_total) / epilogue_total, 4)
        if epilogue_total > 0
        else 0.0,
        "epilogue_share_of_batch": round(
            epilogue_total
            / max(
                1e-12,
                total("device_pack")
                + total("device_dispatch")
                + total("device_mask_readback")
                + epilogue_total,
            ),
            4,
        ),
    }
    return {"batches": batches, "totals": totals}


def render_table(report: dict) -> str:
    totals = report["totals"]
    lines = [
        f"device epilogue attribution — {totals['batches']} batch(es), "
        f"epilogue {totals.get('host_epilogue', 0.0):.4f}s "
        f"({totals['epilogue_share_of_batch']:.0%} of the device-plane "
        f"timeline), sub-span books balance to "
        f"{totals['epilogue_rel_err']:.1%}",
        f"{'batch':<18} {'n':>4} {'pack':>9} {'dispatch':>9} "
        f"{'readback':>9} {'epilogue':>9} {'unpack':>9} {'commit':>9} {'err':>6}",
    ]
    for row in report["batches"]:
        lines.append(
            f"{row['batch_key'][:16]:<18} {row['n']:>4} "
            f"{row.get('device_pack', 0.0):>9.4f} "
            f"{row.get('device_dispatch', 0.0):>9.4f} "
            f"{row.get('device_mask_readback', 0.0):>9.4f} "
            f"{row.get('host_epilogue', 0.0):>9.4f} "
            f"{row.get('epilogue_unpack', 0.0):>9.4f} "
            f"{row.get('epilogue_commit', 0.0):>9.4f} "
            f"{row['epilogue_rel_err']:>6.1%}"
        )
    return "\n".join(lines)
