"""The perf observatory: measurement as a first-class subsystem.

Every perf claim in this repo must survive the host-capacity-swing rule
(ROADMAP: this 1-core container varies 10-20x day to day). The modules
here turn the same-hour interleaved-A/B ritual each PR used to hand-roll
into shared, tested tooling:

- calibrate:      a pinned CPU-capacity probe + host-context snapshot,
                  run before/after every bench leg so records carry the
                  capacity the numbers were measured under;
- ledger:         the commit-keyed perf ledger — one schema-validated
                  JSONL record per bench/A/B run, appended by every
                  entry point under benchmark/, gated in tier-1;
- simnet_profile: per-component self-time attribution over a simnet
                  scenario's virtual-clock hot path (ROADMAP item 3's
                  10x target, named);
- epilogue:       per-batch attribution of the device pipeline's
                  host_epilogue span from the tpu/pipeline.py sub-span
                  stream (ROADMAP item 5's denominator).

The A/B driver itself lives in benchmark/ab.py and composes these.
"""

from . import calibrate, ledger  # noqa: F401
