"""narwhal-topo detectors: graph-level checks over the extracted topology.

Each detector is grounded in a failure this repo actually paid for:

| detector               | incident it guards against                         |
|------------------------|----------------------------------------------------|
| orphan-producer        | the PR-6 wedge: the standalone primary filled
|                        | `tx_execution_output` (no consumer anywhere) and
|                        | the executor's flush blocked forever at ~10k txs   |
| orphan-consumer        | an actor parked on a channel nothing ever feeds —
|                        | dead wiring that reads as a hang under test        |
| bounded-channel-cycle  | PR 6 made every channel bounded for backpressure;
|                        | a cycle of blocking sends across tasks is now a
|                        | real deadlock under load, not a latent one         |
| dropped-handle-escape  | task handles that cross a function boundary but are
|                        | never cancelled/drained on any shutdown path (the
|                        | PR-1/PR-2 shutdown-wedge class, whole-class view)  |
| wire-schema            | message tags 25/26/35 were hand-assigned in PRs
|                        | 4/6: duplicate tags or a registered class missing
|                        | its golden snapshot entry must fail statically     |
| cross-module-jit-purity| jit-purity (lint) used to stop at module borders;
|                        | an impure helper imported into a jitted kernel
|                        | still bakes trace-time state into the compile      |

Findings reuse narwhal-lint's machinery end to end: the same `Finding`
shape, the same `# lint: allow(<detector>)` inline suppressions (on the
anchor line or the comment line above it), and the same empty-baseline
discipline (tools/analysis/baseline.json only ever shrinks).
"""

from __future__ import annotations

import ast
import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Iterator

from tools.lint.engine import Baseline, Finding, _scan_allows

from .extractor import Program, Topology

DETECTORS: dict[str, "Detector"] = {}


def register(cls):
    det = cls()
    assert det.name not in DETECTORS, f"duplicate detector {det.name}"
    DETECTORS[det.name] = det
    return cls


@dataclass
class Context:
    """Everything a detector may need: the graph, the parsed program, and
    repo-anchored paths for the schema checks."""

    topology: Topology
    program: Program
    root: Path
    messages_path: str = "narwhal_tpu/messages.py"
    golden_path: str = "tests/snapshots/messages.json"
    _allows: dict = field(default_factory=dict)
    _lines: dict = field(default_factory=dict)

    def lines(self, rel: str) -> list[str]:
        if rel not in self._lines:
            for info in self.program.modules.values():
                if info.rel == rel:
                    self._lines[rel] = info.lines
                    break
            else:
                try:
                    self._lines[rel] = (
                        (self.root / rel).read_text(encoding="utf-8").splitlines()
                    )
                except OSError:
                    self._lines[rel] = []
        return self._lines[rel]

    def snippet(self, rel: str, line: int) -> str:
        lines = self.lines(rel)
        return lines[line - 1].strip() if 1 <= line <= len(lines) else ""

    def allowed(self, finding: Finding) -> bool:
        if finding.path not in self._allows:
            self._allows[finding.path] = _scan_allows(self.lines(finding.path))
        rules = self._allows[finding.path].get(finding.line, ())
        return finding.rule in rules or "*" in rules


class Detector:
    name: str = ""
    summary: str = ""

    def check(self, ctx: Context) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, ctx: Context, rel: str, line: int, message: str) -> Finding:
        return Finding(self.name, rel, line, 0, message, ctx.snippet(rel, line))


def _sites(ops, limit: int = 4) -> str:
    locs = sorted({f"{o.task} @ {o.path}:{o.line}" for o in ops})
    extra = f" (+{len(locs) - limit} more)" if len(locs) > limit else ""
    return "; ".join(locs[:limit]) + extra


# ---------------------------------------------------------------------------
# orphan-producer / orphan-consumer
# ---------------------------------------------------------------------------


@register
class OrphanProducer(Detector):
    name = "orphan-producer"
    summary = (
        "a channel some task sends into but NO task anywhere receives from: "
        "bounded channels fill, and the first blocking send after that wedges "
        "its sender forever (the PR-6 tx_execution_output wedge at ~10k txs)"
    )

    def check(self, ctx: Context) -> Iterator[Finding]:
        topo = ctx.topology
        for cid, ch in sorted(topo.live_channels().items()):
            sends = topo.senders(cid)
            if sends and not topo.receivers(cid):
                # Anchor at the first producing send, NOT the creation
                # site: metered channels share one factory line, and an
                # allow there would suppress every channel's findings.
                anchor = min(sends, key=lambda o: (o.path, o.line))
                yield self.finding(
                    ctx,
                    anchor.path,
                    anchor.line,
                    f"channel `{cid}` (capacity {ch.capacity}, created at "
                    f"{ch.path}:{ch.line}) has producers but no reachable "
                    f"consumer — it fills, then the first blocking send "
                    f"wedges its task forever. Producers: {_sites(sends)}. "
                    f"Wire a consumer (or drain-and-drop like __main__'s "
                    f"execution-output drain)",
                )


@register
class OrphanConsumer(Detector):
    name = "orphan-consumer"
    summary = (
        "a channel some task receives from but NO task anywhere sends into: "
        "the consumer is parked forever — dead wiring that presents as a "
        "hang (an actor that never advances, a shutdown that never drains)"
    )

    def check(self, ctx: Context) -> Iterator[Finding]:
        topo = ctx.topology
        for cid, ch in sorted(topo.live_channels().items()):
            recvs = topo.receivers(cid)
            if recvs and not topo.senders(cid):
                anchor = min(recvs, key=lambda o: (o.path, o.line))
                yield self.finding(
                    ctx,
                    anchor.path,
                    anchor.line,
                    f"channel `{cid}` (created at {ch.path}:{ch.line}) has "
                    f"consumers but no reachable producer — {_sites(recvs)} "
                    f"wait(s) forever. Either the producing path was never "
                    f"wired or the channel is dead",
                )


# ---------------------------------------------------------------------------
# bounded-channel-cycle
# ---------------------------------------------------------------------------


@register
class BoundedChannelCycle(Detector):
    name = "bounded-channel-cycle"
    summary = (
        "a cycle of BLOCKING sends through bounded channels across tasks: "
        "if every channel on the loop fills, every task on the loop blocks "
        "in send and nothing can ever drain — a backpressure deadlock "
        "(every channel is bounded since PR 6, so this is load-reachable)"
    )

    def check(self, ctx: Context) -> Iterator[Finding]:
        topo = ctx.topology
        graph = topo.wait_graph()
        for scc in _sccs(graph):
            chans = sorted(n[5:] for n in scc if n.startswith("chan:"))
            if not chans:
                continue
            cycle = _cycle_path(graph, scc)
            # Anchor at the first blocking-send SITE on the cycle (a
            # creation-site anchor would land on the shared metered
            # factory line and over-suppress).
            cycle_tasks = {n[5:] for n in scc if n.startswith("task:")}
            cycle_chans = set(chans)
            send_ops = [
                o
                for o in topo.ops
                if o.is_send
                and o.blocking
                and o.task in cycle_tasks
                and o.channel in cycle_chans
            ]
            anchor = min(send_ops, key=lambda o: (o.path, o.line))
            yield self.finding(
                ctx,
                anchor.path,
                anchor.line,
                "bounded-channel deadlock cycle: "
                + " -> ".join(_pretty(n) for n in cycle)
                + " -> "
                + _pretty(cycle[0])
                + ". If these channels fill together, every task on the "
                "loop blocks in send. Break it (try_send one edge, drain "
                "before send) or justify the capacity argument inline",
            )


def _sccs(graph: dict[str, set[str]]) -> list[frozenset]:
    """Tarjan (iterative), deterministic order; only cyclic SCCs (size > 1
    or an explicit self-loop) are returned, sorted."""
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    out: list[frozenset] = []
    counter = [0]
    nodes = sorted(set(graph) | {m for vs in graph.values() for m in vs})

    for start in nodes:
        if start in index:
            continue
        work = [(start, iter(sorted(graph.get(start, ()))))]
        index[start] = low[start] = counter[0]
        counter[0] += 1
        stack.append(start)
        on_stack.add(start)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(graph.get(nxt, ())))))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1 or node in graph.get(node, ()):
                    out.append(frozenset(comp))
    return sorted(out, key=lambda c: sorted(c))


def _cycle_path(graph: dict[str, set[str]], scc: frozenset) -> list[str]:
    """A deterministic representative cycle inside the SCC, starting at
    the lexicographically first channel node."""
    start = sorted(n for n in scc if n.startswith("chan:"))[0]
    path = [start]
    seen = {start}
    node = start
    while True:
        nxts = sorted(n for n in graph.get(node, ()) if n in scc)
        if not nxts:
            return path
        node = nxts[0]
        if node == start or node in seen:
            return path
        seen.add(node)
        path.append(node)


def _pretty(node: str) -> str:
    if node.startswith("chan:"):
        return f"[{node[5:]}]"
    return node[5:]


# ---------------------------------------------------------------------------
# dropped-handle-escape
# ---------------------------------------------------------------------------

_SPAWN_NAMES = {"ensure_future", "create_task"}
_DRAIN_FUNCS = {"drain_cancelled", "gather", "wait"}


@register
class DroppedHandleEscape(Detector):
    name = "dropped-handle-escape"
    summary = (
        "a task handle that crosses a function boundary (stored in an "
        "attribute, or returned by a spawn-like method whose caller drops "
        "it) with no shutdown path that cancels or drains it: at teardown "
        "the task lives on — the shutdown-wedge class, seen whole-program"
    )

    def check(self, ctx: Context) -> Iterator[Finding]:
        spawn_methods = self._task_returning_methods(ctx.program)
        for dotted in sorted(ctx.program.modules):
            info = ctx.program.modules[dotted]
            for cname in sorted(info.classes):
                yield from self._check_class(
                    ctx, info, info.classes[cname], spawn_methods
                )

    # -- which method NAMES hand a fresh task to their caller -----------
    # (Name-keyed, so `send` is deliberately excluded below: Watch.send /
    # FrameSender.send / NetworkClient.send collide on the name and only
    # the last returns a handle — that idiom has its own owner discipline
    # via cancel_handlers.)
    _NAME_DENYLIST = frozenset(
        {"send", "send_many", "try_send", "unreliable_send", "request", "write"}
    )

    def _task_returning_methods(self, program: Program) -> set:
        out: set[str] = set()
        for info in program.modules.values():
            for cls in info.classes.values():
                for mname, mnode in cls.methods.items():
                    if mname in self._NAME_DENYLIST:
                        continue
                    for node in ast.walk(mnode):
                        if (
                            isinstance(node, ast.Return)
                            and node.value is not None
                            and _mentions_spawn(node.value, mnode)
                        ):
                            out.add(mname)
        return out

    def _check_class(self, ctx, info, cls, spawn_methods) -> Iterator[Finding]:
        # 1. attrs that ever hold a task handle (directly or inside a
        #    literal/tuple/subscript), with the storing site remembered.
        held: dict[str, tuple[int, bool]] = {}  # attr -> (line, returned)
        for mname, mnode in sorted(cls.methods.items()):
            returned_names = self._returned_names(mnode)
            task_locals = self._task_locals(mnode, spawn_methods)
            for node in ast.walk(mnode):
                attr, line, value = self._stored_attr(node)
                if attr is None:
                    continue
                if not _is_task_expr(value, spawn_methods, task_locals):
                    continue
                returned = attr in returned_names or any(
                    n in returned_names for n in _names_in(value)
                )
                prev = held.get(attr)
                held[attr] = (
                    min(prev[0], line) if prev else line,
                    (prev[1] if prev else False) or returned,
                )
        if not held:
            drained: set[str] = set()
        else:
            drained = self._drained_attrs(cls)
        for attr in sorted(held):
            line, returned = held[attr]
            if returned or attr in drained:
                continue
            yield self.finding(
                ctx,
                info.rel,
                line,
                f"`self.{attr}` of `{cls.name}` holds task handle(s) but no "
                "method of the class cancels or drains it — at shutdown the "
                "task(s) survive the owner (cancel in a shutdown/close path, "
                "use drain_cancelled, or hand ownership to the caller by "
                "returning the handle)",
            )
        # 2. spawn-like call results dropped on the floor. An *awaited*
        #    `.spawn()` is the async-lifecycle idiom (returns addresses or
        #    None); only a bare un-awaited call drops a handle.
        for mname, mnode in sorted(cls.methods.items()):
            for node in ast.walk(mnode):
                if (
                    isinstance(node, ast.Expr)
                    and isinstance(node.value, ast.Call)
                    and isinstance(node.value.func, ast.Attribute)
                    and node.value.func.attr in spawn_methods
                ):
                    yield self.finding(
                        ctx,
                        info.rel,
                        node.lineno,
                        f"`.{node.value.func.attr}(...)` returns a task "
                        "handle that is dropped here — the spawned task can "
                        "never be cancelled or drained; store it in a "
                        "drained owner",
                    )

    def _returned_names(self, mnode) -> set[str]:
        out: set[str] = set()
        for node in ast.walk(mnode):
            if isinstance(node, ast.Return) and node.value is not None:
                out.update(_names_in(node.value))
        return out

    def _task_locals(self, mnode, spawn_methods) -> set[str]:
        out: set[str] = set()
        for node in ast.walk(mnode):
            if isinstance(node, ast.Assign) and _is_task_expr(
                node.value, spawn_methods, out
            ):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
        return out

    def _stored_attr(self, node):
        """(attr, line, value-expr) when `node` stores into a self attr:
        plain/containered assignment, subscript, or append/add/extend."""
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Attribute) and _is_self(t.value):
                    return t.attr, node.lineno, node.value
                if (
                    isinstance(t, ast.Subscript)
                    and isinstance(t.value, ast.Attribute)
                    and _is_self(t.value.value)
                ):
                    return t.value.attr, node.lineno, node.value
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ("append", "add", "extend")
            and isinstance(node.func.value, ast.Attribute)
            and _is_self(node.func.value.value)
            and node.args
        ):
            return node.func.value.attr, node.lineno, node.args[0]
        return None, 0, None

    def _drained_attrs(self, cls) -> set[str]:
        """Attrs mentioned in a statement unit that also cancels/drains.
        Units are simple statements and for-loops (`for t in self._tasks:
        t.cancel()` counts `_tasks`); whole try/def bodies do not bleed.
        A cancel through a local taken off the attr first (`t, self._x =
        self._x, None` then `t.cancel()`) credits the attr too."""
        out: set[str] = set()
        for mnode in cls.methods.values():
            # local name -> self attrs its binding expression mentions
            local_attrs: dict[str, set[str]] = {}
            units = []
            for node in ast.walk(mnode):
                if isinstance(
                    node,
                    (ast.Expr, ast.Assign, ast.AugAssign, ast.Return,
                     ast.For, ast.AsyncFor, ast.With, ast.AsyncWith),
                ):
                    units.append(node)
                if isinstance(node, ast.Assign):
                    attrs = {
                        sub.attr
                        for sub in ast.walk(node.value)
                        if isinstance(sub, ast.Attribute) and _is_self(sub.value)
                    }
                    if attrs:
                        for t in node.targets:
                            for n in ast.walk(t):
                                if isinstance(n, ast.Name):
                                    local_attrs.setdefault(n.id, set()).update(attrs)
            for unit in units:
                cancels = False
                for sub in ast.walk(unit):
                    if isinstance(sub, ast.Call):
                        f = sub.func
                        if isinstance(f, ast.Attribute) and f.attr in (
                            "cancel", "cancel_all",
                        ):
                            cancels = True
                        elif isinstance(f, ast.Name) and f.id in _DRAIN_FUNCS:
                            cancels = True
                        elif (
                            isinstance(f, ast.Attribute)
                            and f.attr in _DRAIN_FUNCS
                            # `asyncio.wait(...)`/`asyncio.gather(...)`
                            # drain; an unrelated method happening to be
                            # NAMED wait/gather does not.
                            and (
                                f.attr == "drain_cancelled"
                                or (
                                    isinstance(f.value, ast.Name)
                                    and f.value.id == "asyncio"
                                )
                            )
                        ):
                            cancels = True
                if not cancels:
                    continue
                for sub in ast.walk(unit):
                    if isinstance(sub, ast.Attribute) and _is_self(sub.value):
                        out.add(sub.attr)
                    elif isinstance(sub, ast.Name) and sub.id in local_attrs:
                        out.update(local_attrs[sub.id])
        return out


def _is_self(node) -> bool:
    return isinstance(node, ast.Name) and node.id == "self"


def _names_in(node) -> set[str]:
    if node is None:
        return set()
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _is_task_expr(node, spawn_methods, task_locals) -> bool:
    """STRUCTURAL task-expression check: the expression *is* a fresh task
    handle — a direct ensure_future/create_task call, an un-awaited call
    of a task-returning method, a local already known to hold one, or a
    container literal carrying one. Deliberately not `ast.walk`-based:
    `cert_task.result()` contains a task name but is not a task."""
    if node is None:
        return False
    if isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Name) and f.id in _SPAWN_NAMES:
            return True
        if isinstance(f, ast.Attribute) and f.attr in (
            _SPAWN_NAMES | spawn_methods
        ):
            return True
        return False
    if isinstance(node, ast.Name):
        return node.id in task_locals
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return any(
            _is_task_expr(e, spawn_methods, task_locals) for e in node.elts
        )
    if isinstance(node, ast.Dict):
        return any(
            _is_task_expr(v, spawn_methods, task_locals) for v in node.values
        )
    if isinstance(node, (ast.ListComp, ast.SetComp)):
        return _is_task_expr(node.elt, spawn_methods, task_locals)
    return False


def _mentions_spawn(node, scope) -> bool:
    """Does this return expression carry a freshly spawned task (directly
    or via a local assigned from one)?"""
    spawn_locals: set[str] = set()
    for sub in ast.walk(scope):
        if isinstance(sub, ast.Assign) and isinstance(sub.value, ast.Call):
            f = sub.value.func
            name = f.attr if isinstance(f, ast.Attribute) else getattr(f, "id", "")
            if name in _SPAWN_NAMES:
                for t in sub.targets:
                    if isinstance(t, ast.Name):
                        spawn_locals.add(t.id)
                    elif isinstance(t, ast.Attribute) and _is_self(t.value):
                        spawn_locals.add(f"self.{t.attr}")
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            f = sub.func
            name = f.attr if isinstance(f, ast.Attribute) else getattr(f, "id", "")
            if name in _SPAWN_NAMES:
                return True
        elif isinstance(sub, ast.Name) and sub.id in spawn_locals:
            return True
        elif (
            isinstance(sub, ast.Attribute)
            and _is_self(sub.value)
            and f"self.{sub.attr}" in spawn_locals
        ):
            return True
    return False


# ---------------------------------------------------------------------------
# wire-schema
# ---------------------------------------------------------------------------


@register
class WireSchema(Detector):
    name = "wire-schema"
    summary = (
        "static wire-schema check: every `@message(tag)` class must have a "
        "unique tag AND a golden entry in tests/snapshots/messages.json — "
        "tags 25/26/35 were hand-assigned across PRs 4/6 and a collision "
        "or an unsnapshotted format would only surface at decode time"
    )

    def check(self, ctx: Context) -> Iterator[Finding]:
        rel = ctx.messages_path
        path = ctx.root / rel
        if not path.exists():
            return
        try:
            tree = ast.parse(path.read_text(encoding="utf-8"))
        except SyntaxError:
            return
        golden_path = ctx.root / ctx.golden_path
        golden: set[str] = set()
        golden_ok = golden_path.exists()
        if golden_ok:
            try:
                golden = set(json.loads(golden_path.read_text(encoding="utf-8")))
            except (OSError, ValueError):
                golden_ok = False
        seen: dict[int, tuple[str, int]] = {}
        for node in ast.walk(tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for deco in node.decorator_list:
                if not (
                    isinstance(deco, ast.Call)
                    and isinstance(deco.func, ast.Name)
                    and deco.func.id == "message"
                    and deco.args
                    and isinstance(deco.args[0], ast.Constant)
                    and isinstance(deco.args[0].value, int)
                ):
                    continue
                tag = deco.args[0].value
                if tag in seen:
                    other, oline = seen[tag]
                    yield self.finding(
                        ctx,
                        rel,
                        node.lineno,
                        f"message tag {tag} on `{node.name}` collides with "
                        f"`{other}` (line {oline}) — the decode registry "
                        "would reject the second registration at import, "
                        "and a silent renumber is a wire break",
                    )
                else:
                    seen[tag] = (node.name, node.lineno)
                    key = f"{tag}:{node.name}"
                    if golden_ok and key not in golden:
                        yield self.finding(
                            ctx,
                            rel,
                            node.lineno,
                            f"registered message `{node.name}` (tag {tag}) "
                            f"has no golden entry `{key}` in "
                            f"{ctx.golden_path} — regenerate the snapshot "
                            "ADD-ONLY so the wire format is pinned",
                        )


# ---------------------------------------------------------------------------
# cross-module-jit-purity (delegates to the shared purity analysis)
# ---------------------------------------------------------------------------


@register
class CrossModuleJitPurity(Detector):
    name = "cross-module-jit-purity"
    summary = (
        "whole-package jit purity: functions reachable from a @jax.jit "
        "root in tpu/ must stay pure ACROSS module boundaries — an impure "
        "helper imported into a kernel runs once at trace time and is "
        "baked into / elided from every later dispatch"
    )

    def check(self, ctx: Context) -> Iterator[Finding]:
        from .purity import package_purity

        tpu_files = sorted(
            (ctx.root / info.rel)
            for info in ctx.program.modules.values()
            if "tpu" in Path(info.rel).parts[:-1]
        )
        if not tpu_files:
            return
        for imp in package_purity(tpu_files, ctx.root):
            if not imp.cross_module:
                continue  # same-module findings are narwhal-lint's beat
            if imp.allowed_rules & {"jit-purity", "*"}:
                continue  # one allow at the site covers both gates
            yield self.finding(ctx, imp.path, imp.line, imp.message)


# ---------------------------------------------------------------------------
# Runner (shares the lint engine's Result so its reporters work verbatim)
# ---------------------------------------------------------------------------

from tools.lint.engine import Result  # noqa: E402


def run_detectors(
    ctx: Context,
    detectors: dict | None = None,
    baseline: Baseline | None = None,
) -> Result:
    detectors = DETECTORS if detectors is None else detectors
    baseline = baseline or Baseline()
    new, baselined, suppressed = [], [], []
    for name in sorted(detectors):
        for finding in detectors[name].check(ctx):
            if ctx.allowed(finding):
                suppressed.append(finding)
            elif baseline.claim(finding):
                baselined.append(finding)
            else:
                new.append(finding)
    new.sort(key=lambda f: (f.path, f.line, f.rule))
    return Result(
        new, baselined, suppressed, baseline.stale(), len(ctx.program.modules)
    )
