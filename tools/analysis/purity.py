"""Cross-module jit-purity: the whole-package call graph under jax.jit.

narwhal-lint's `jit-purity` rule BFSes from `@jax.jit` roots through the
*same module's* call graph. That caveat was load-bearing: a kernel in
`tpu/verifier.py` that imports a helper from `tpu/ed25519.py` gets no
purity checking past the import — yet an impure helper (print, host RNG,
module-global mutation) behaves identically badly whether it lives one
module over or not: it runs once at trace time, then is baked into or
elided from every later dispatch of the compiled kernel.

This module builds the call graph across sibling modules (resolving
`from .ed25519 import foo` / `from . import ed25519; ed25519.foo(...)`)
and runs the same impurity checks on every reachable function. It is the
shared engine behind BOTH gates:

- `tools.lint.rules.JitPurity` calls `module_purity` while scanning a
  module in `tpu/`, yielding the cross-module findings its same-module
  BFS used to miss;
- `tools.analysis`'s `cross-module-jit-purity` detector calls
  `package_purity` over the whole `tpu/` package.

Kept dependency-free of tools.lint so the two packages can import each
other's leaves without a cycle.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path

_ALLOW_RE = re.compile(r"#\s*lint:\s*allow\(([A-Za-z0-9_\-*,\s]+)\)")

_IMPURE_MODULES = {"time", "random"}
_IMPURE_CALLS = {"print", "input"}
_JIT_NAMES = {
    "jax.jit",
    "jit",
    # The shared kernel registry's wrappers (tpu/kernel_registry.py, the
    # no-untracked-jit idiom): tracked_jit decoratees and sharded(fn, ...)
    # wraps are jit roots exactly like raw jax.jit ones.
    "tracked_jit",
    "kernel_registry.tracked_jit",
    "narwhal_tpu.tpu.kernel_registry.tracked_jit",
    "kernel_registry.sharded",
    "narwhal_tpu.tpu.kernel_registry.sharded",
}


@dataclass
class Impurity:
    path: str  # repo-relative posix path of the impure site
    line: int
    col: int
    snippet: str
    message: str
    func: str
    root: str  # the jit root function name
    root_path: str  # module the root lives in
    cross_module: bool
    allowed_rules: set = field(default_factory=set)  # inline allows at site


@dataclass
class _Mod:
    path: Path
    rel: str
    tree: ast.Module
    lines: list
    funcs: dict  # bare name -> ast def (module functions AND methods)
    aliases: dict  # local name -> dotted origin (as written)
    globals_: set


def _load(path: Path, root: Path) -> _Mod | None:
    try:
        source = path.read_text(encoding="utf-8")
        tree = ast.parse(source, filename=str(path))
    except (OSError, SyntaxError):
        return None
    try:
        rel = path.resolve().relative_to(root.resolve()).as_posix()
    except ValueError:
        rel = path.as_posix()
    funcs: dict = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            funcs.setdefault(node.name, node)
    aliases: dict = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and (node.module or node.level):
            mod = node.module or ""
            for a in node.names:
                aliases[a.asname or a.name] = f"{mod}.{a.name}" if mod else a.name
    globals_ = {
        t.id
        for stmt in tree.body
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign))
        for t in (stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target])
        if isinstance(t, ast.Name)
    }
    return _Mod(path, rel, tree, source.splitlines(), funcs, aliases, globals_)


def _dotted(node) -> str | None:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _resolve(node, aliases) -> str | None:
    d = _dotted(node)
    if d is None:
        return None
    head, _, rest = d.partition(".")
    origin = aliases.get(head)
    if origin is None:
        return d
    return f"{origin}.{rest}" if rest else origin


def _jit_roots(mod: _Mod) -> set:
    roots: set = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                d = _resolve(deco, mod.aliases)
                if d in _JIT_NAMES:
                    roots.add(node.name)
                elif isinstance(deco, ast.Call):
                    f = _resolve(deco.func, mod.aliases)
                    if f in _JIT_NAMES:
                        roots.add(node.name)
                    elif f in ("partial", "functools.partial") and deco.args:
                        if _resolve(deco.args[0], mod.aliases) in _JIT_NAMES:
                            roots.add(node.name)
        elif isinstance(node, ast.Call):
            if _resolve(node.func, mod.aliases) in _JIT_NAMES and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Name) and arg.id in mod.funcs:
                    roots.add(arg.id)
    return roots


class _Package:
    """Sibling modules of one directory, linked by imports."""

    def __init__(self, files, root: Path):
        self.root = root
        self.mods: dict[str, _Mod] = {}  # module basename -> _Mod
        for f in files:
            m = _load(Path(f), root)
            if m is not None:
                self.mods[Path(f).stem] = m

    def resolve_callee(self, mod_name: str, call: ast.Call):
        """-> (module basename, func name) or None. Same-module bare names
        and `self.helper(...)` attribute calls resolve locally (the lint
        rule's original semantics); imported names and `sibling.f(...)`
        resolve across modules."""
        mod = self.mods[mod_name]
        f = call.func
        if isinstance(f, ast.Name):
            if f.id in mod.funcs:
                return (mod_name, f.id)
            origin = mod.aliases.get(f.id)
            if origin and "." in origin:
                owner, _, sym = origin.rpartition(".")
                target = owner.rpartition(".")[2] or owner
                if target in self.mods and sym in self.mods[target].funcs:
                    return (target, sym)
            return None
        if isinstance(f, ast.Attribute):
            base = _dotted(f.value)
            if base is not None:
                origin = mod.aliases.get(base.partition(".")[0])
                if origin is not None:
                    target = origin.rpartition(".")[2] or origin
                    if target in self.mods and f.attr in self.mods[target].funcs:
                        return (target, f.attr)
            if f.attr in mod.funcs:
                # self.helper(...) / obj.helper(...): same-module method
                return (mod_name, f.attr)
        return None

    def jit_roots(self, mod_name: str) -> set:
        """(module, func) jit roots *declared in* `mod_name`: decorated
        functions, `name = jax.jit(fn)` wraps of local functions, AND
        cross-module wraps like `jax.jit(kernel.verify_batch_kernel
        .__wrapped__)` — the sharded-kernel idiom, where the root function
        lives one module over from the jit call."""
        mod = self.mods[mod_name]
        roots = {(mod_name, r) for r in _jit_roots(mod)}
        for node in ast.walk(mod.tree):
            if not (
                isinstance(node, ast.Call)
                and _resolve(node.func, mod.aliases) in _JIT_NAMES
                and node.args
            ):
                continue
            arg = node.args[0]
            dotted = _dotted(arg)
            if dotted is None:
                continue
            if dotted.endswith(".__wrapped__"):
                dotted = dotted[: -len(".__wrapped__")]
            head, _, rest = dotted.partition(".")
            if not rest or "." in rest:
                continue
            origin = mod.aliases.get(head)
            if origin is None:
                continue
            target = origin.rpartition(".")[2] or origin
            if target in self.mods and rest in self.mods[target].funcs:
                roots.add((target, rest))
        return roots

    def reachable(self, root_mods) -> dict:
        """BFS from the jit roots declared in `root_mods`: (module, func)
        -> (root func, module the root was DECLARED in). The declaring
        module owns the finding — when `verifier.py` jits a kernel that
        lives in `ed25519.py`, scanning ed25519 alone sees no root."""
        via: dict = {}
        queue: list = []
        for rm in root_mods:
            for (fmod, r) in sorted(self.jit_roots(rm)):
                if (fmod, r) not in via:
                    via[(fmod, r)] = (r, rm)
                    queue.append((fmod, r))
        while queue:
            mod_name, fname = queue.pop()
            mod = self.mods[mod_name]
            for node in ast.walk(mod.funcs[fname]):
                if not isinstance(node, ast.Call):
                    continue
                callee = self.resolve_callee(mod_name, node)
                if callee is not None and callee not in via:
                    via[callee] = via[(mod_name, fname)]
                    queue.append(callee)
        return via

    def allows_at(self, mod: _Mod, line: int) -> set:
        out: set = set()
        for ln in (line, line - 1):
            if 1 <= ln <= len(mod.lines):
                text = mod.lines[ln - 1]
                m = _ALLOW_RE.search(text)
                if m and (ln == line or text.lstrip().startswith("#")):
                    out.update(
                        r.strip() for r in m.group(1).split(",") if r.strip()
                    )
        return out

    def impurities(self, root_mods) -> list:
        out: list[Impurity] = []
        via = self.reachable(root_mods)
        for (mod_name, fname), (root, root_mod) in sorted(via.items()):
            mod = self.mods[mod_name]
            cross = mod_name != root_mod
            root_label = (
                f"jitted `{root}`"
                if not cross
                else f"jitted `{root}` ({self.mods[root_mod].rel})"
            )
            for line, col, msg in _check_func(mod, fname, root_label):
                snippet = (
                    mod.lines[line - 1].strip()
                    if 1 <= line <= len(mod.lines)
                    else ""
                )
                out.append(
                    Impurity(
                        mod.rel, line, col, snippet, msg, fname, root,
                        self.mods[root_mod].rel, cross,
                        self.allows_at(mod, line),
                    )
                )
        return out


def _check_func(mod: _Mod, fname: str, root_label: str):
    """The impurity checks, byte-compatible with narwhal-lint's rule."""
    func = mod.funcs[fname]
    local_names = {a.arg for a in getattr(func, "args", ast.arguments(args=[])).args}
    for node in ast.walk(func):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            for t in targets:
                if isinstance(t, ast.Name):
                    local_names.add(t.id)
    for node in ast.walk(func):
        if isinstance(node, ast.Global):
            yield (
                node.lineno,
                node.col_offset,
                f"`global {', '.join(node.names)}` inside `{fname}` "
                f"(reachable from {root_label}): global mutation is "
                "invisible to the traced kernel after compilation",
            )
        elif isinstance(node, ast.Call):
            target = _resolve(node.func, mod.aliases)
            if target is None:
                continue
            head = target.split(".")[0]
            if target in _IMPURE_CALLS or (
                head in _IMPURE_MODULES and head not in local_names
            ):
                yield (
                    node.lineno,
                    node.col_offset,
                    f"impure call `{target}(...)` in `{fname}` (reachable "
                    f"from {root_label}): runs once at trace time, then is "
                    "baked into / elided from the compiled kernel",
                )
            elif target.startswith(("numpy.random", "np.random")):
                yield (
                    node.lineno,
                    node.col_offset,
                    f"`{target}(...)` in `{fname}` (reachable from "
                    f"{root_label}): host RNG is trace-time constant under "
                    "jit; thread a jax.random key instead",
                )
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            for t in targets:
                base = t
                hops = 0
                while isinstance(base, (ast.Attribute, ast.Subscript)):
                    base = base.value
                    hops += 1
                if (
                    hops
                    and isinstance(base, ast.Name)
                    and base.id in mod.globals_
                    and base.id not in local_names
                ):
                    yield (
                        node.lineno,
                        node.col_offset,
                        f"mutation of module-level `{base.id}` in `{fname}` "
                        f"(reachable from {root_label}): happens at trace "
                        "time only, not per kernel invocation",
                    )


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------


def package_purity(files, root: Path) -> list:
    """All impurities reachable from any jit root in `files` (one
    directory's sibling modules), cross- and same-module alike."""
    pkg = _Package(files, Path(root))
    return pkg.impurities(sorted(pkg.mods))


def module_purity(module_path: Path, root: Path) -> list:
    """Impurities reachable from the jit roots *of this module*, following
    calls into same-directory sibling modules. Used by the lint rule: it
    keeps its own same-module reporting and takes the `cross_module`
    entries from here."""
    module_path = Path(module_path)
    files = sorted(
        p
        for p in module_path.parent.glob("*.py")
        if not p.name.endswith("_pb2.py")
    )
    if module_path not in files:
        files.append(module_path)
    pkg = _Package(files, Path(root))
    return pkg.impurities([module_path.stem])
