"""CLI: `python -m tools.analysis [flags]`.

Exit status mirrors narwhal-lint: 0 when clean (every finding suppressed
or baselined, artifact current when checked), 1 when new findings exist
or the checked-in topology artifact is stale, 2 on usage errors.

Typical invocations:

    python -m tools.analysis                        # detectors, the gate
    python -m tools.analysis --check-artifact       # + stale-artifact check
    python -m tools.analysis --write-artifact       # regenerate topology.json/.dot
    python -m tools.analysis --dot out.dot --json out.json
    python -m tools.analysis --list-rules
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

from tools.lint.engine import Baseline
from tools.lint.report import render_json, render_text

from .detectors import DETECTORS, Context, run_detectors
from .extractor import DEFAULT_PACKAGE, DEFAULT_ROOTS, extract

REPO_ROOT = Path(__file__).resolve().parents[2]
DEFAULT_BASELINE = Path(__file__).with_name("baseline.json")
ARTIFACT_JSON = Path(__file__).with_name("topology.json")
ARTIFACT_DOT = Path(__file__).with_name("topology.dot")


# ---------------------------------------------------------------------------
# Artifact serialization (canonical: sorted, line-number free so edits
# above a wiring site don't churn the checked-in file — the lint
# baseline's snippet-identity philosophy)
# ---------------------------------------------------------------------------


def topology_doc(topo, roots) -> dict:
    edges = sorted(
        {
            (op.task, op.channel, op.kind)
            for op in topo.ops
        }
    )
    live = topo.live_channels()
    return {
        "version": 1,
        "roots": sorted(roots),
        "channels": [
            {
                "id": cid,
                "capacity": ch.capacity,
                "path": ch.path,
            }
            for cid, ch in sorted(live.items())
        ],
        "tasks": sorted({op.task for op in topo.ops}),
        "edges": [
            {"task": t, "channel": c, "op": k} for t, c, k in edges
        ],
    }


def render_dot(doc: dict) -> str:
    out = ["digraph narwhal_topology {", "  rankdir=LR;",
           '  node [fontname="monospace", fontsize=10];']
    for ch in doc["channels"]:
        out.append(
            f'  "chan:{ch["id"]}" [shape=box, style=rounded, '
            f'label="{ch["id"]}\\ncap={ch["capacity"]}"];'
        )
    for t in doc["tasks"]:
        out.append(f'  "task:{t}" [shape=ellipse, label="{t}"];')
    for e in doc["edges"]:
        style = ', style=dashed' if e["op"].startswith("try_") else ""
        if e["op"] in ("send", "send_many", "try_send"):
            out.append(
                f'  "task:{e["task"]}" -> "chan:{e["channel"]}"'
                f' [label="{e["op"]}"{style}];'
            )
        else:
            out.append(
                f'  "chan:{e["channel"]}" -> "task:{e["task"]}"'
                f' [label="{e["op"]}"{style}];'
            )
    out.append("}")
    return "\n".join(out) + "\n"


def render_mermaid(doc: dict) -> str:
    """A README-embeddable pipeline diagram (flowchart LR)."""

    def nid(name: str) -> str:
        return (
            name.replace("/", "_").replace(".", "_").replace(":", "_")
            .replace("#", "_")
        )

    out = ["flowchart LR"]
    for ch in doc["channels"]:
        out.append(f'    C_{nid(ch["id"])}[("{ch["id"]} (cap {ch["capacity"]})")]')
    seen = set()
    for e in doc["edges"]:
        t, c = f'T_{nid(e["task"])}', f'C_{nid(e["channel"])}'
        if e["task"] not in seen:
            seen.add(e["task"])
            out.append(f'    T_{nid(e["task"])}["{e["task"]}"]')
        arrow = "-.->" if e["op"].startswith("try_") else "-->"
        if e["op"] in ("send", "send_many", "try_send"):
            out.append(f"    {t} {arrow} {c}")
        else:
            out.append(f"    {c} {arrow} {t}")
    # dedupe while preserving order
    deduped = list(dict.fromkeys(out))
    return "\n".join(deduped) + "\n"


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.analysis",
        description=(
            "narwhal-topo: whole-program actor/channel topology analyzer "
            "(orphan producers/consumers, bounded-channel deadlock cycles, "
            "dropped task handles, wire schema, cross-module jit purity)"
        ),
    )
    ap.add_argument("--root", type=Path, default=REPO_ROOT, help="repo root")
    ap.add_argument("--package", default=DEFAULT_PACKAGE)
    ap.add_argument(
        "--roots",
        action="append",
        default=None,
        metavar="FILE.py::Symbol",
        help=f"wiring roots (default: {', '.join(DEFAULT_ROOTS)})",
    )
    ap.add_argument("--format", choices=("text", "json"), default="text", dest="fmt")
    ap.add_argument("--baseline", type=Path, default=DEFAULT_BASELINE)
    ap.add_argument("--no-baseline", action="store_true")
    ap.add_argument("--write-baseline", action="store_true")
    ap.add_argument(
        "--rule", action="append", default=None, metavar="NAME",
        help="run only this detector (repeatable)",
    )
    ap.add_argument("--json", type=Path, default=None, help="write topology JSON")
    ap.add_argument("--dot", type=Path, default=None, help="write topology DOT")
    ap.add_argument("--mermaid", type=Path, default=None,
                    help="write a mermaid pipeline diagram ('-' for stdout)")
    ap.add_argument(
        "--write-artifact", action="store_true",
        help=f"regenerate the checked-in {ARTIFACT_JSON.name} + {ARTIFACT_DOT.name}",
    )
    ap.add_argument(
        "--check-artifact", action="store_true",
        help="fail (exit 1) when the checked-in topology.json is stale",
    )
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for name, det in sorted(DETECTORS.items()):
            print(f"{name}\n    {det.summary}")
        return 0

    detectors = DETECTORS
    if args.rule:
        unknown = set(args.rule) - set(DETECTORS)
        if unknown:
            ap.error(f"unknown detector(s): {', '.join(sorted(unknown))}")
        detectors = {n: DETECTORS[n] for n in args.rule}

    roots = tuple(args.roots) if args.roots else DEFAULT_ROOTS
    t0 = time.perf_counter()
    topo, extractor = extract(args.root, package=args.package, roots=roots)
    ctx = Context(topo, extractor.program, Path(args.root))
    baseline = Baseline() if args.no_baseline else Baseline.load(args.baseline)
    result = run_detectors(ctx, detectors=detectors, baseline=baseline)
    elapsed = time.perf_counter() - t0

    doc = topology_doc(topo, roots)
    if args.json:
        args.json.write_text(json.dumps(doc, indent=2) + "\n", encoding="utf-8")
    if args.dot:
        args.dot.write_text(render_dot(doc), encoding="utf-8")
    if args.mermaid:
        text = render_mermaid(doc)
        if str(args.mermaid) == "-":
            print(text, end="")
        else:
            args.mermaid.write_text(text, encoding="utf-8")
    if args.write_artifact:
        ARTIFACT_JSON.write_text(json.dumps(doc, indent=2) + "\n", encoding="utf-8")
        ARTIFACT_DOT.write_text(render_dot(doc), encoding="utf-8")
        print(
            f"artifact: {len(doc['channels'])} channels / {len(doc['edges'])} "
            f"edges written to {ARTIFACT_JSON} and {ARTIFACT_DOT}"
        )
        return 0

    if args.write_baseline:
        Baseline.dump(result.new + result.baselined, args.baseline)
        print(
            f"baseline: {len(result.new) + len(result.baselined)} finding(s) "
            f"written to {args.baseline}"
        )
        return 0

    stale_artifact = False
    if args.check_artifact:
        try:
            current = json.loads(ARTIFACT_JSON.read_text(encoding="utf-8"))
        except (OSError, ValueError):
            current = None
        stale_artifact = current != doc

    if args.fmt == "json":
        payload = json.loads(render_json(result))
        payload["channels"] = len(doc["channels"])
        payload["tasks"] = len(doc["tasks"])
        payload["artifact_stale"] = stale_artifact
        payload["ok"] = result.ok and not stale_artifact
        print(json.dumps(payload, indent=2))
    else:
        print(render_text(result, verbose=args.verbose))
        print(
            f"topology: {len(doc['channels'])} channels, "
            f"{len(doc['tasks'])} tasks ({elapsed:.2f}s)"
        )
        if stale_artifact:
            print(
                f"STALE ARTIFACT: {ARTIFACT_JSON} no longer matches the "
                "wiring — regenerate with `python -m tools.analysis "
                "--write-artifact`"
            )
    return 0 if (result.ok and not stale_artifact) else 1


if __name__ == "__main__":
    sys.exit(main())
