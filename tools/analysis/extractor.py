"""narwhal-topo extractor: the whole-program actor/channel topology.

narwhal-lint (tools/lint) gates *per-function* invariants; the bugs that
actually wedged this system were *whole-program* properties — a channel
filled by the executor that no task anywhere drains (the PR-6
`tx_execution_output` wedge), or a cycle of bounded channels between two
actors that can deadlock under load now that every edge is bounded. Those
properties live in the wiring: `node.py`/`cluster.py`/`__main__.py`
construct the actors, thread `Channel` objects through constructor
parameters and attributes, and spawn the run loops. This module recovers
that wiring statically.

It is an *abstract interpreter* over stdlib-`ast`, specialized to the
repo's actor idioms (the same trade narwhal-lint makes: precise for the
patterns this codebase uses, honest `Unknown` for everything else):

- **Values**: `ChannelVal` (a `Channel`/`metered_channel` creation site),
  `ObjectVal` (an instantiated class with an attribute map), `WatchVal`,
  `BoundMethodVal`, `CoroutineVal` (an un-awaited async-method call),
  collection values, and `UNKNOWN`.
- **Wiring**: `__init__` bodies are evaluated with arguments bound, so a
  channel created in `PrimaryNode` and passed down three constructors
  resolves to the same `ChannelVal` when `Core.run` finally receives on
  it. Local factory functions whose return expression constructs a
  channel (the ubiquitous `def chan(name, capacity)`) are followed.
  Both branches of `if`/`try` are executed (over-approximation), and
  conditional expressions prefer the channel-valued arm — the two arms
  of `metered_channel(...) if registry else Channel(...)` are alternative
  constructions of ONE logical channel.
- **Tasks**: `asyncio.ensure_future`/`create_task` of a bound-method or
  local-function coroutine starts a new *task context*; RPC handler
  registrations (`server.route(Msg, self._on_x)`) and bound methods
  passed as callbacks are task roots too. A coroutine handed to an
  unknown sink (`pool.push(self._stage(...))`) is swept as its own task
  at the end. Every send/recv op is recorded against the task that would
  block on it — a passive helper's sends (`Synchronizer.missing_payload`)
  belong to the calling task (`Core.run`), which is exactly what the
  deadlock-cycle detector needs.
- **Ops**: `.send`/`.send_many` (blocking) and `.try_send` (not) on a
  resolved `ChannelVal` are producer edges; `.recv` (blocking) and
  `.try_recv` are consumer edges.

The result (`Topology`) is a bipartite task/channel graph that detectors
query and the CLI serializes as the checked-in `topology.json` + DOT.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable

# ---------------------------------------------------------------------------
# Abstract values
# ---------------------------------------------------------------------------


class Unknown:
    """The single honest fallback."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "Unknown"


UNKNOWN = Unknown()


@dataclass(eq=False)
class ChannelVal:
    """One channel creation site, the graph's edge-carrier."""

    cid: str  # stable id: "role/name" for metered, "Owner.attr" otherwise
    label: str
    capacity: object  # int | "default" | "?"
    path: str  # repo-relative posix path of the creation site
    line: int

    def __repr__(self):
        return f"Channel<{self.cid}>"


@dataclass(eq=False)
class WatchVal:
    """channels.Watch / channels.Subscriber — broadcast state, not an edge."""


@dataclass(eq=False)
class ObjectVal:
    cls: "ClassInfo"
    ipath: str  # deterministic instance path, e.g. "PrimaryNode.primary"
    attrs: dict = field(default_factory=dict)

    def __repr__(self):
        return f"Object<{self.ipath}>"


@dataclass(eq=False)
class BoundMethodVal:
    obj: ObjectVal
    name: str


@dataclass(eq=False)
class BoundChannelMethod:
    channel: ChannelVal
    name: str  # send | send_many | try_send | recv | try_recv


@dataclass(eq=False)
class BoundCollectionMethod:
    """`.items()`/`.values()`/`.append(x)`... on a modeled collection."""

    coll: object  # CollectionVal | DictVal
    name: str


@dataclass(eq=False)
class CoroutineVal:
    """An async call not yet awaited: its body runs when awaited (same
    task) or spawned (new task)."""

    target: object  # BoundMethodVal | LocalFuncVal | FuncInfo
    args: list
    kwargs: dict
    consumed: bool = False


@dataclass(eq=False)
class LocalFuncVal:
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    env: dict  # closure snapshot
    owner: object  # ObjectVal | None
    module: "ModuleInfo"
    qual: str


@dataclass(eq=False)
class CollectionVal:
    kind: str  # list | tuple | set
    items: list


@dataclass(eq=False)
class DictVal:
    keys: list
    values: list


class UnionVal:
    """Join of alternative branch values; ops/lookups map over members."""

    def __init__(self, members: Iterable):
        flat = []
        for m in members:
            if isinstance(m, UnionVal):
                for mm in m.members:
                    if mm not in flat:
                        flat.append(mm)
            elif m is not None and m is not UNKNOWN and m not in flat:
                flat.append(m)
        self.members = flat


def join(*values):
    u = UnionVal(values)
    if not u.members:
        return UNKNOWN
    if len(u.members) == 1:
        return u.members[0]
    return u


def members_of(value) -> list:
    if isinstance(value, UnionVal):
        return value.members
    if value is UNKNOWN or value is None:
        return []
    return [value]


# ---------------------------------------------------------------------------
# Program model: modules, classes, functions
# ---------------------------------------------------------------------------


@dataclass
class FuncInfo:
    module: "ModuleInfo"
    node: ast.AST
    qual: str


@dataclass
class ClassInfo:
    module: "ModuleInfo"
    node: ast.ClassDef
    name: str
    methods: dict = field(default_factory=dict)

    def method(self, name: str):
        return self.methods.get(name)


@dataclass
class ModuleInfo:
    rel: str  # repo-relative posix path
    dotted: str  # e.g. narwhal_tpu.primary.core
    tree: ast.Module
    lines: list
    classes: dict = field(default_factory=dict)
    functions: dict = field(default_factory=dict)
    aliases: dict = field(default_factory=dict)  # local name -> full dotted
    globals_mut: dict = field(default_factory=dict)  # mutable global -> def line


def _module_dotted(rel: Path) -> str:
    parts = list(rel.with_suffix("").parts)
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class Program:
    """All parsed modules of one package, with name resolution."""

    def __init__(self, root: Path, package_dir: Path | None):
        self.root = Path(root)
        self.modules: dict[str, ModuleInfo] = {}
        if package_dir is not None and package_dir.is_dir():
            for path in sorted(package_dir.rglob("*.py")):
                if "__pycache__" in path.parts or path.name.endswith("_pb2.py"):
                    continue
                self.load(path)

    def load(self, path: Path) -> ModuleInfo | None:
        path = Path(path)
        try:
            rel = path.resolve().relative_to(self.root.resolve())
        except ValueError:
            rel = Path(path.name)
        dotted = _module_dotted(rel)
        if dotted in self.modules:
            return self.modules[dotted]
        try:
            source = path.read_text(encoding="utf-8")
            tree = ast.parse(source, filename=str(path))
        except (OSError, SyntaxError):
            return None
        info = ModuleInfo(rel.as_posix(), dotted, tree, source.splitlines())
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                ci = ClassInfo(info, node, node.name)
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        ci.methods[item.name] = item
                info.classes[node.name] = ci
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info.functions[node.name] = FuncInfo(info, node, f"{dotted}.{node.name}")
        info.aliases = self._aliases(info, rel)
        self._collect_mutable_globals(info)
        self.modules[dotted] = info
        return info

    _MUTABLE_FACTORIES = frozenset({
        "dict", "list", "set", "deque", "defaultdict", "Counter",
        "OrderedDict", "WeakSet", "WeakKeyDictionary", "WeakValueDictionary",
    })

    def _collect_mutable_globals(self, info: ModuleInfo) -> None:
        """Module-level names that hold shared mutable state: bindings to a
        mutable-container literal/factory, plus any name a function rebinds
        through a `global` declaration (an int counter rebound cross-task is
        just as shared as a dict)."""
        for node in info.tree.body:
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            else:
                continue
            mutable = isinstance(
                value,
                (ast.Dict, ast.List, ast.Set, ast.ListComp, ast.SetComp,
                 ast.DictComp),
            )
            if not mutable and isinstance(value, ast.Call):
                f = value.func
                name = (
                    f.attr if isinstance(f, ast.Attribute)
                    else f.id if isinstance(f, ast.Name) else ""
                )
                mutable = name in self._MUTABLE_FACTORIES
            if mutable:
                for t in targets:
                    if isinstance(t, ast.Name):
                        info.globals_mut.setdefault(t.id, node.lineno)
        for node in ast.walk(info.tree):
            if isinstance(node, ast.Global):
                for n in node.names:
                    info.globals_mut.setdefault(n, node.lineno)

    def _aliases(self, info: ModuleInfo, rel: Path) -> dict:
        """Local name -> absolute dotted origin, with relative imports
        normalized against the importing module's package."""
        pkg_parts = info.dotted.split(".") if info.dotted else []
        if rel.name != "__init__.py" and pkg_parts:
            pkg_parts = pkg_parts[:-1]
        out: dict[str, str] = {}
        for node in ast.walk(info.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    out[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                    mod = ".".join(base + ([node.module] if node.module else []))
                else:
                    mod = node.module or ""
                for a in node.names:
                    out[a.asname or a.name] = f"{mod}.{a.name}" if mod else a.name
        return out

    def resolve_symbol(self, dotted: str, depth: int = 0):
        """A full dotted symbol -> ClassInfo | FuncInfo | None, following
        package-__init__ re-export chains (narwhal_tpu.consensus.Dag ->
        narwhal_tpu.consensus.dag.Dag)."""
        if depth > 4 or "." not in dotted:
            return None
        mod_name, _, sym = dotted.rpartition(".")
        info = self.modules.get(mod_name)
        if info is None:
            return None
        if sym in info.classes:
            return info.classes[sym]
        if sym in info.functions:
            return info.functions[sym]
        reexport = info.aliases.get(sym)
        if reexport:
            return self.resolve_symbol(reexport, depth + 1)
        return None


# ---------------------------------------------------------------------------
# Topology
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Op:
    task: str
    channel: str  # cid
    kind: str  # send | send_many | try_send | recv | try_recv
    path: str
    line: int

    @property
    def is_send(self) -> bool:
        return self.kind in ("send", "send_many", "try_send")

    @property
    def blocking(self) -> bool:
        return self.kind in ("send", "send_many", "recv")


@dataclass(frozen=True)
class StateSite:
    """One attributed access to shared mutable state.

    `state` is `"<ipath>.<attr>"` for instance attributes (e.g.
    `"Core.round"`, `"StageTimer#1._pending"`) and `"<module>:<name>"`
    for module globals (e.g. `"narwhal_tpu.crypto:_VERIFY_CACHE"`).
    `task` is the owning task context (`"Core.run"`, `"cb:Core.process_vote"`)
    or `"init:<ipath>"` for construction-time accesses."""

    task: str
    state: str
    kind: str  # read | write
    path: str
    line: int

    @property
    def is_write(self) -> bool:
        return self.kind == "write"

    @property
    def is_global(self) -> bool:
        return ":" in self.state


def state_table(sites: Iterable[StateSite]) -> dict:
    """Index sites as {state: {"read": {task: [sites]}, "write": {...}}} —
    the query shape the narwhal-sched race detectors consume."""
    table: dict[str, dict[str, dict[str, list[StateSite]]]] = {}
    for s in sites:
        table.setdefault(s.state, {"read": {}, "write": {}})[s.kind].setdefault(
            s.task, []
        ).append(s)
    return table


class Topology:
    def __init__(self):
        self.channels: dict[str, ChannelVal] = {}
        self.ops: list[Op] = []
        self.tasks: set[str] = set()
        self._op_seen: set = set()

    def add_channel(self, ch: ChannelVal) -> None:
        self.channels.setdefault(ch.cid, ch)

    def record(self, task, channel, kind, path, line) -> None:
        key = (task, channel, kind, path, line)
        if key not in self._op_seen:
            self._op_seen.add(key)
            self.ops.append(Op(task, channel, kind, path, line))
        self.tasks.add(task)

    def live_channels(self) -> dict[str, ChannelVal]:
        """Channels with at least one op — creation sites discarded by a
        conditional arm never show up here."""
        used = {o.channel for o in self.ops}
        return {cid: ch for cid, ch in self.channels.items() if cid in used}

    # -- queries used by the detectors ---------------------------------
    def senders(self, cid: str) -> list[Op]:
        return [o for o in self.ops if o.channel == cid and o.is_send]

    def receivers(self, cid: str) -> list[Op]:
        return [o for o in self.ops if o.channel == cid and not o.is_send]

    def wait_graph(self) -> dict[str, set[str]]:
        """Directed wait-for graph for deadlock cycles: task -> channel on
        a *blocking* send (the task can block with the item in hand);
        channel -> task for each task that receives from it (the channel
        drains only while that task makes progress)."""
        g: dict[str, set[str]] = {}
        for op in self.ops:
            if op.is_send and op.blocking:
                g.setdefault(f"task:{op.task}", set()).add(f"chan:{op.channel}")
            elif not op.is_send:
                g.setdefault(f"chan:{op.channel}", set()).add(f"task:{op.task}")
        return g


# ---------------------------------------------------------------------------
# The interpreter
# ---------------------------------------------------------------------------

_CHANNEL_SENDS = {"send", "send_many", "try_send"}
_CHANNEL_RECVS = {"recv", "try_recv"}
_CHANNEL_OPS = _CHANNEL_SENDS | _CHANNEL_RECVS
_AWAIT_COMBINATORS = {"gather", "wait_for", "shield"}

MAX_DEPTH = 60
MAX_INSTANCES = 500


class Extractor:
    def __init__(self, program: Program):
        self.program = program
        self.topology = Topology()
        self._instance_count: dict[str, int] = {}
        self._visited: set = set()
        self._class_stack: list[str] = []
        self._pending_roots: list = []
        self._root_seen: set = set()
        self._coroutines: list[CoroutineVal] = []
        self._local_stack: list = []
        self._anon_chan = 0
        self.instances: list[ObjectVal] = []
        # Read/write-site attribution (consumed by tools/sched): every
        # access to an instance attribute or mutable module global, keyed
        # to the task context that performs it.
        self.state_sites: list[StateSite] = []
        self._state_seen: set = set()

    # -- public entry points -------------------------------------------
    def run_class_root(self, cls: ClassInfo) -> ObjectVal:
        obj = self.instantiate(cls, [], {}, hint=cls.name)
        for lifecycle in ("spawn", "run", "shutdown", "stop", "close"):
            if isinstance(obj, ObjectVal) and cls.method(lifecycle):
                self._queue_root(
                    f"{obj.ipath}.{lifecycle}", BoundMethodVal(obj, lifecycle)
                )
        self._drain_roots()
        return obj

    def run_function_root(self, func: FuncInfo) -> None:
        self._queue_root(func.qual.split(".")[-1], func)
        self._drain_roots()

    def _queue_root(self, name: str, target, coro: CoroutineVal | None = None):
        # Same instance+method spawned from several sites is ONE logical
        # task: walk it once so the topology stays canonical.
        if name in self._root_seen:
            return
        self._root_seen.add(name)
        self._pending_roots.append((name, target, coro))

    def _drain_roots(self) -> None:
        while True:
            while self._pending_roots:
                name, target, coro = self._pending_roots.pop(0)
                args = coro.args if coro is not None else []
                kwargs = coro.kwargs if coro is not None else {}
                self._call_target(name, target, args, kwargs)
            # Safety net: coroutines handed to unknown sinks (bounded
            # future pools etc.) run as their own tasks.
            leftovers = [c for c in self._coroutines if not c.consumed]
            if not leftovers:
                return
            for c in leftovers:
                c.consumed = True
                self._spawn_task(c)

    # -- instantiation --------------------------------------------------
    def instantiate(self, cls: ClassInfo, args, kwargs, hint: str | None = None):
        if cls.name in self._class_stack or len(self._class_stack) > 12:
            return UNKNOWN
        n = self._instance_count.get(cls.name, 0)
        self._instance_count[cls.name] = n + 1
        if sum(self._instance_count.values()) > MAX_INSTANCES:
            return UNKNOWN
        ipath = cls.name if n == 0 else f"{cls.name}#{n}"
        obj = ObjectVal(cls, ipath)
        self.instances.append(obj)
        init = cls.method("__init__")
        if init is not None:
            env = self._bind(init, [obj] + list(args), kwargs)
            self._class_stack.append(cls.name)
            try:
                self._exec_body(
                    init.body, env, cls.module, f"init:{ipath}", obj, 0
                )
            finally:
                self._class_stack.pop()
        return obj

    def _bind(self, func_node, args, kwargs) -> dict:
        env: dict = {}
        a = func_node.args
        params = [p.arg for p in a.args]
        for name, val in zip(params, args):
            env[name] = val
        params += [p.arg for p in a.kwonlyargs]
        for k, v in kwargs.items():
            if k in params:
                env[k] = v
        for p in params:
            env.setdefault(p, UNKNOWN)
        decls = set()
        for n in ast.walk(func_node):
            if isinstance(n, ast.Global):
                decls.update(n.names)
        if decls:
            env["__pyglobals__"] = frozenset(decls)
        return env

    # -- read/write-site attribution ------------------------------------
    _WIRING_VALS = (
        ChannelVal, WatchVal, ObjectVal, BoundMethodVal, BoundChannelMethod,
        BoundCollectionMethod, CoroutineVal, LocalFuncVal,
    )

    def _is_data(self, value) -> bool:
        """Wiring values (channels, actors, callables) are structure, not
        shared *data*; collections, scalars and UNKNOWN are state."""
        ms = members_of(value)
        if not ms:
            return True  # UNKNOWN / None: be honest, treat as data
        structural = self._WIRING_VALS + (ClassInfo, FuncInfo)
        return any(not isinstance(m, structural) for m in ms)

    def _record_state(self, ctx, state, kind, path, line) -> None:
        task = _task_name(ctx)
        key = (task, state, kind, path, line)
        if key not in self._state_seen:
            self._state_seen.add(key)
            self.state_sites.append(StateSite(task, state, kind, path, line))

    def _note_attr_read(self, recv, attr, module, ctx, line) -> None:
        if attr.startswith("__"):
            return
        for v in members_of(recv):
            if not isinstance(v, ObjectVal) or v.cls.method(attr) is not None:
                continue
            cur = v.attrs.get(attr)
            if cur is not None and not self._is_data(cur):
                continue  # channel/actor/callable attribute: wiring
            self._record_state(ctx, f"{v.ipath}.{attr}", "read", module.rel, line)

    def _note_container_write(self, base, env, module, ctx, selfobj, depth,
                              line) -> None:
        """`self.pending[k] = v` / `self.events.append(x)` / `_CACHE[k] = v`
        mutate the container held by the base attribute/global."""
        if isinstance(base, ast.Attribute):
            recv = self._eval(base.value, env, module, ctx, selfobj, depth)
            for obj in members_of(recv):
                if not isinstance(obj, ObjectVal):
                    continue
                if obj.cls.method(base.attr) is not None:
                    continue
                cur = obj.attrs.get(base.attr)
                if cur is not None and not self._is_data(cur):
                    continue
                self._record_state(
                    ctx, f"{obj.ipath}.{base.attr}", "write", module.rel, line
                )
        elif isinstance(base, ast.Name):
            if base.id not in env and base.id in module.globals_mut:
                self._record_state(
                    ctx, f"{module.dotted}:{base.id}", "write", module.rel, line
                )

    # In-place mutator methods on containers: a call through one of these
    # on a self-attribute or module-global receiver is a write site.
    _MUTATORS = frozenset({
        "append", "appendleft", "add", "update", "pop", "popleft", "popitem",
        "setdefault", "extend", "remove", "discard", "clear", "insert",
        "sort", "rotate",
    })

    # -- statement execution -------------------------------------------
    def _exec_body(self, body, env, module, ctx, selfobj, depth) -> None:
        if depth > MAX_DEPTH:
            return
        for stmt in body:
            self._exec_stmt(stmt, env, module, ctx, selfobj, depth)

    def _exec_stmt(self, stmt, env, module, ctx, selfobj, depth) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            env[stmt.name] = LocalFuncVal(
                stmt, dict(env), selfobj, module, f"{ctx}.{stmt.name}"
            )
        elif isinstance(stmt, ast.Assign):
            value = self._eval(
                stmt.value, env, module, ctx, selfobj, depth,
                hint=self._target_hint(stmt.targets),
            )
            for t in stmt.targets:
                self._assign(t, value, env, module, ctx, selfobj, depth)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            value = self._eval(
                stmt.value, env, module, ctx, selfobj, depth,
                hint=self._target_hint([stmt.target]),
            )
            self._assign(stmt.target, value, env, module, ctx, selfobj, depth)
        elif isinstance(stmt, ast.AugAssign):
            self._eval(stmt.value, env, module, ctx, selfobj, depth)
            t = stmt.target
            if isinstance(t, ast.Attribute):
                recv = self._eval(t.value, env, module, ctx, selfobj, depth)
                self._note_attr_read(recv, t.attr, module, ctx, t.lineno)
                for obj in members_of(recv):
                    if (
                        isinstance(obj, ObjectVal)
                        and obj.cls.method(t.attr) is None
                    ):
                        self._record_state(
                            ctx, f"{obj.ipath}.{t.attr}", "write",
                            module.rel, t.lineno,
                        )
            elif isinstance(t, ast.Name):
                if (
                    t.id in env.get("__pyglobals__", ())
                    and t.id in module.globals_mut
                ):
                    state = f"{module.dotted}:{t.id}"
                    self._record_state(ctx, state, "read", module.rel, t.lineno)
                    self._record_state(ctx, state, "write", module.rel, t.lineno)
        elif isinstance(stmt, ast.Expr):
            self._eval(stmt.value, env, module, ctx, selfobj, depth)
        elif isinstance(stmt, ast.Return):
            if stmt.value is not None:
                v = self._eval(stmt.value, env, module, ctx, selfobj, depth)
                env["__return__"] = env.get("__return__", []) + [v]
        elif isinstance(stmt, ast.If):
            self._eval(stmt.test, env, module, ctx, selfobj, depth)
            then_env, else_env = dict(env), dict(env)
            self._exec_body(stmt.body, then_env, module, ctx, selfobj, depth + 1)
            self._exec_body(stmt.orelse, else_env, module, ctx, selfobj, depth + 1)
            self._merge_env(env, then_env, else_env)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            it = self._eval(stmt.iter, env, module, ctx, selfobj, depth)
            self._bind_loop_target(stmt.target, it, env)
            self._exec_body(stmt.body, env, module, ctx, selfobj, depth + 1)
            self._exec_body(stmt.orelse, env, module, ctx, selfobj, depth + 1)
        elif isinstance(stmt, ast.While):
            self._eval(stmt.test, env, module, ctx, selfobj, depth)
            self._exec_body(stmt.body, env, module, ctx, selfobj, depth + 1)
            self._exec_body(stmt.orelse, env, module, ctx, selfobj, depth + 1)
        elif isinstance(stmt, ast.Try):
            self._exec_body(stmt.body, env, module, ctx, selfobj, depth + 1)
            for h in stmt.handlers:
                self._exec_body(h.body, env, module, ctx, selfobj, depth + 1)
            self._exec_body(stmt.orelse, env, module, ctx, selfobj, depth + 1)
            self._exec_body(stmt.finalbody, env, module, ctx, selfobj, depth + 1)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._eval(item.context_expr, env, module, ctx, selfobj, depth)
            self._exec_body(stmt.body, env, module, ctx, selfobj, depth + 1)
        elif isinstance(stmt, ast.Raise) and stmt.exc is not None:
            self._eval(stmt.exc, env, module, ctx, selfobj, depth)

    def _target_hint(self, targets) -> str | None:
        for t in targets:
            if isinstance(t, ast.Attribute):
                return t.attr
            if isinstance(t, ast.Name):
                return t.id
        return None

    def _merge_env(self, base, a, b) -> None:
        for k in set(a) | set(b):
            va, vb = a.get(k, UNKNOWN), b.get(k, UNKNOWN)
            if k == "__return__":
                # The return accumulator is a plain list, not a value.
                merged = []
                for branch in (va, vb):
                    if isinstance(branch, list):
                        merged.extend(branch)
                base[k] = merged
            else:
                base[k] = va if va is vb else join(va, vb)

    def _assign(self, target, value, env, module, ctx, selfobj, depth) -> None:
        if isinstance(target, ast.Name):
            if (
                target.id in env.get("__pyglobals__", ())
                and target.id in module.globals_mut
            ):
                self._record_state(
                    ctx, f"{module.dotted}:{target.id}", "write",
                    module.rel, target.lineno,
                )
            env[target.id] = value
        elif isinstance(target, ast.Attribute):
            recv = self._eval(target.value, env, module, ctx, selfobj, depth)
            for obj in members_of(recv):
                if isinstance(obj, ObjectVal):
                    if self._is_data(value):
                        self._record_state(
                            ctx, f"{obj.ipath}.{target.attr}", "write",
                            module.rel, target.lineno,
                        )
                    prev = obj.attrs.get(target.attr)
                    obj.attrs[target.attr] = (
                        value if prev is None else join(prev, value)
                    )
        elif isinstance(target, (ast.Tuple, ast.List)):
            items = None
            for v in members_of(value):
                if isinstance(v, CollectionVal):
                    items = v.items
            for i, el in enumerate(target.elts):
                item = items[i] if items and i < len(items) else UNKNOWN
                self._assign(el, item, env, module, ctx, selfobj, depth)
        elif isinstance(target, ast.Subscript):
            recv = self._eval(target.value, env, module, ctx, selfobj, depth)
            self._note_container_write(
                target.value, env, module, ctx, selfobj, depth, target.lineno
            )
            for c in members_of(recv):
                if isinstance(c, CollectionVal):
                    c.items.append(value)
                elif isinstance(c, DictVal):
                    c.values.append(value)

    def _bind_loop_target(self, target, iterable, env) -> None:
        """`for k, v in d.items()` / `for x in xs` value flow."""
        element = UNKNOWN
        pair = None
        for v in members_of(iterable):
            if isinstance(v, CollectionVal):
                element = join(element, *v.items)
            elif isinstance(v, DictVal):
                pair = (
                    join(*v.keys) if v.keys else UNKNOWN,
                    join(*v.values) if v.values else UNKNOWN,
                )
                element = join(element, *(v.keys or []))
        if isinstance(target, ast.Name):
            env[target.id] = element
        elif isinstance(target, (ast.Tuple, ast.List)):
            for i, el in enumerate(target.elts):
                if isinstance(el, ast.Name):
                    if pair is not None and i < 2:
                        env[el.id] = pair[i]
                    else:
                        env[el.id] = UNKNOWN

    # -- expression evaluation -----------------------------------------
    def _eval(self, node, env, module, ctx, selfobj, depth, hint=None):
        if depth > MAX_DEPTH:
            return UNKNOWN
        if isinstance(node, ast.Name):
            if node.id in env:
                return env[node.id]
            if node.id in module.globals_mut:
                self._record_state(
                    ctx, f"{module.dotted}:{node.id}", "read",
                    module.rel, node.lineno,
                )
            return self._module_symbol(node.id, module)
        if isinstance(node, ast.Attribute):
            recv = self._eval(node.value, env, module, ctx, selfobj, depth)
            self._note_attr_read(recv, node.attr, module, ctx, node.lineno)
            return self._attr(recv, node.attr)
        if isinstance(node, ast.Call):
            return self._call(node, env, module, ctx, selfobj, depth, hint)
        if isinstance(node, ast.Await):
            v = self._eval(node.value, env, module, ctx, selfobj, depth, hint)
            return self._consume_coroutine(v, ctx, depth)
        if isinstance(node, ast.IfExp):
            self._eval(node.test, env, module, ctx, selfobj, depth)
            a = self._eval(node.body, env, module, ctx, selfobj, depth, hint)
            b = self._eval(node.orelse, env, module, ctx, selfobj, depth, hint)
            # Alternative constructions of the same logical channel: keep
            # the first channel-valued arm as THE creation site.
            for v in (a, b):
                for m in members_of(v):
                    if isinstance(m, ChannelVal):
                        return m
            return join(a, b)
        if isinstance(node, ast.BoolOp):
            return join(
                *(
                    self._eval(v, env, module, ctx, selfobj, depth, hint)
                    for v in node.values
                )
            )
        if isinstance(node, (ast.List, ast.Tuple, ast.Set)):
            kind = type(node).__name__.lower()
            items = []
            for e in node.elts:
                v = self._eval(
                    e.value if isinstance(e, ast.Starred) else e,
                    env, module, ctx, selfobj, depth,
                )
                if isinstance(e, ast.Starred):
                    for c in members_of(v):
                        if isinstance(c, CollectionVal):
                            items.extend(c.items)
                else:
                    items.append(v)
            return CollectionVal(kind, items)
        if isinstance(node, ast.Dict):
            return DictVal(
                [
                    self._eval(k, env, module, ctx, selfobj, depth)
                    for k in node.keys
                    if k is not None
                ],
                [self._eval(v, env, module, ctx, selfobj, depth) for v in node.values],
            )
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            return CollectionVal(
                "list", [self._eval_comp(node, env, module, ctx, selfobj, depth)]
            )
        if isinstance(node, ast.DictComp):
            cenv = dict(env)
            for gen in node.generators:
                it = self._eval(gen.iter, cenv, module, ctx, selfobj, depth)
                self._bind_loop_target(gen.target, it, cenv)
            return DictVal(
                [self._eval(node.key, cenv, module, ctx, selfobj, depth)],
                [self._eval(node.value, cenv, module, ctx, selfobj, depth)],
            )
        if isinstance(node, ast.Subscript):
            recv = self._eval(node.value, env, module, ctx, selfobj, depth)
            self._eval(node.slice, env, module, ctx, selfobj, depth)
            out = UNKNOWN
            for c in members_of(recv):
                if isinstance(c, CollectionVal):
                    out = join(out, *c.items)
                elif isinstance(c, DictVal):
                    out = join(out, *c.values)
            return out
        if isinstance(node, ast.Constant):
            return node.value
        if isinstance(node, ast.NamedExpr):
            v = self._eval(node.value, env, module, ctx, selfobj, depth, hint)
            self._assign(node.target, v, env, module, ctx, selfobj, depth)
            return v
        if isinstance(node, (ast.Compare, ast.UnaryOp, ast.BinOp)):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr):
                    self._eval(child, env, module, ctx, selfobj, depth)
            return UNKNOWN
        return UNKNOWN

    def _eval_comp(self, node, env, module, ctx, selfobj, depth):
        cenv = dict(env)
        for gen in node.generators:
            it = self._eval(gen.iter, cenv, module, ctx, selfobj, depth)
            self._bind_loop_target(gen.target, it, cenv)
            for cond in gen.ifs:
                self._eval(cond, cenv, module, ctx, selfobj, depth)
        return self._eval(node.elt, cenv, module, ctx, selfobj, depth)

    def _module_symbol(self, name: str, module: ModuleInfo):
        origin = module.aliases.get(name)
        if origin is not None:
            resolved = self.program.resolve_symbol(origin)
            if resolved is not None:
                return resolved
            return origin  # dotted module marker (e.g. "asyncio")
        if name in module.classes:
            return module.classes[name]
        if name in module.functions:
            return module.functions[name]
        return UNKNOWN

    def _attr(self, recv, attr: str):
        out = []
        for v in members_of(recv):
            if isinstance(v, ChannelVal):
                if attr in _CHANNEL_OPS:
                    out.append(BoundChannelMethod(v, attr))
            elif isinstance(v, ObjectVal):
                if attr in v.attrs:
                    out.append(v.attrs[attr])
                elif v.cls.method(attr) is not None:
                    out.append(BoundMethodVal(v, attr))
            elif isinstance(v, (CollectionVal, DictVal)):
                out.append(BoundCollectionMethod(v, attr))
            elif isinstance(v, str):  # dotted module marker
                dotted = f"{v}.{attr}"
                resolved = self.program.resolve_symbol(dotted)
                out.append(resolved if resolved is not None else dotted)
        if not out:
            return UNKNOWN
        return join(*out)

    # -- calls ----------------------------------------------------------
    def _dotted_name(self, node) -> str | None:
        parts = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            parts.append(node.id)
            return ".".join(reversed(parts))
        return None

    def _call(self, node: ast.Call, env, module, ctx, selfobj, depth, hint=None):
        raw = self._dotted_name(node.func)
        resolved_raw = None
        if raw is not None and raw.split(".")[0] not in env:
            head, _, rest = raw.partition(".")
            origin = module.aliases.get(head, head)
            resolved_raw = f"{origin}.{rest}" if rest else origin

        # -- the sanctioned channel wrappers (channels.py) --------------
        if resolved_raw is not None:
            if resolved_raw == "metered_channel" or resolved_raw.endswith(
                "channels.metered_channel"
            ):
                return self._make_channel(
                    node, env, module, ctx, selfobj, depth, metered=True, hint=hint
                )
            if resolved_raw == "Channel" or resolved_raw.endswith(
                "channels.Channel"
            ):
                return self._make_channel(
                    node, env, module, ctx, selfobj, depth, metered=False, hint=hint
                )
            if (
                resolved_raw.endswith(("channels.Watch", "channels.Subscriber"))
                or resolved_raw == "Watch"
            ):
                for a in node.args:
                    self._eval(a, env, module, ctx, selfobj, depth)
                return WatchVal()

        # -- task spawns ------------------------------------------------
        if resolved_raw is not None and resolved_raw.split(".")[-1] in (
            "ensure_future",
            "create_task",
        ):
            for a in node.args:
                inner = self._eval(a, env, module, ctx, selfobj, depth)
                for v in members_of(inner):
                    if isinstance(v, CoroutineVal) and not v.consumed:
                        v.consumed = True
                        self._spawn_task(v)
            return UNKNOWN

        func_val = self._eval(node.func, env, module, ctx, selfobj, depth)
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in self._MUTATORS
        ):
            self._note_container_write(
                node.func.value, env, module, ctx, selfobj, depth, node.lineno
            )
        args = []
        for a in node.args:
            v = self._eval(
                a.value if isinstance(a, ast.Starred) else a,
                env, module, ctx, selfobj, depth,
            )
            if isinstance(a, ast.Starred):
                for c in members_of(v):
                    if isinstance(c, CollectionVal):
                        args.extend(c.items)
            else:
                args.append(v)
        kwargs = {
            kw.arg: self._eval(kw.value, env, module, ctx, selfobj, depth)
            for kw in node.keywords
            if kw.arg is not None
        }

        # Bound methods passed as callbacks (route handlers, done
        # callbacks, hooks) become task roots of their own.
        for v in list(args) + list(kwargs.values()):
            for m in members_of(v):
                if isinstance(m, BoundMethodVal):
                    self._queue_root(f"cb:{m.obj.ipath}.{m.name}", m)

        results = [
            self._apply(fv, node, args, kwargs, module, ctx, depth, hint)
            for fv in members_of(func_val)
        ]

        # Awaited combinators run their coroutine args on this task.
        if (
            resolved_raw is not None
            and resolved_raw.split(".")[-1] in _AWAIT_COMBINATORS
        ):
            for v in args:
                for m in members_of(v):
                    if isinstance(m, CoroutineVal):
                        self._consume_coroutine(m, ctx, depth)
                    elif isinstance(m, CollectionVal):
                        for item in m.items:
                            self._consume_coroutine(item, ctx, depth)
        return join(*results) if results else UNKNOWN

    def _apply(self, fv, node, args, kwargs, module, ctx, depth, hint=None):
        if isinstance(fv, BoundChannelMethod):
            self.topology.record(
                _task_name(ctx), fv.channel.cid, fv.name, module.rel, node.lineno
            )
            return UNKNOWN
        if isinstance(fv, BoundCollectionMethod):
            return self._collection_call(fv, args)
        if isinstance(fv, ClassInfo):
            return self.instantiate(fv, args, kwargs, hint=hint)
        if isinstance(fv, FuncInfo):
            if isinstance(fv.node, ast.AsyncFunctionDef):
                return self._coro(fv, args, kwargs)
            return self._walk_function(
                fv.node, fv.module, None, args, kwargs, ctx, depth, qual=fv.qual
            )
        if isinstance(fv, LocalFuncVal):
            if isinstance(fv.node, ast.AsyncFunctionDef):
                return self._coro(fv, args, kwargs)
            # Local sync helpers (the `chan(name, capacity)` factories)
            # are walked per call — each call creates a distinct channel —
            # with a stack guard instead of the visited set.
            return self._walk_function(
                fv.node, fv.module, fv.owner, args, kwargs, ctx, depth,
                closure=fv.env, qual=fv.qual, per_call=True,
            )
        if isinstance(fv, BoundMethodVal):
            method = fv.obj.cls.method(fv.name)
            if method is None:
                return UNKNOWN
            if isinstance(method, ast.AsyncFunctionDef):
                return self._coro(fv, args, kwargs)
            return self._walk_method(fv.obj, fv.name, args, kwargs, ctx, depth)
        return UNKNOWN

    def _collection_call(self, fv: BoundCollectionMethod, args):
        c, name = fv.coll, fv.name
        if isinstance(c, DictVal):
            if name == "items":
                return c  # loop targets unpack DictVal into (key, value)
            if name == "keys":
                return CollectionVal("list", list(c.keys))
            if name in ("values", "pop", "get", "setdefault", "popitem"):
                return CollectionVal("list", list(c.values))
        if isinstance(c, CollectionVal):
            if name in ("append", "add"):
                c.items.extend(args)
                return None
            if name == "extend" and args:
                for v in members_of(args[0]):
                    if isinstance(v, CollectionVal):
                        c.items.extend(v.items)
                return None
            if name == "pop":
                return join(*c.items) if c.items else UNKNOWN
            if name == "copy":
                return c
        return UNKNOWN

    def _coro(self, target, args, kwargs) -> CoroutineVal:
        c = CoroutineVal(target, args, kwargs)
        self._coroutines.append(c)
        return c

    def _consume_coroutine(self, v, ctx, depth):
        out = UNKNOWN
        consumed = False
        for m in members_of(v):
            if isinstance(m, CoroutineVal) and not m.consumed:
                m.consumed = True
                consumed = True
                out = join(out, self._run_coroutine(m, ctx, depth))
        return out if consumed else v

    def _run_coroutine(self, coro: CoroutineVal, ctx, depth):
        t = coro.target
        if isinstance(t, BoundMethodVal):
            return self._walk_method(t.obj, t.name, coro.args, coro.kwargs, ctx, depth)
        if isinstance(t, LocalFuncVal):
            return self._walk_function(
                t.node, t.module, t.owner, coro.args, coro.kwargs, ctx, depth,
                closure=t.env, qual=t.qual,
            )
        if isinstance(t, FuncInfo):
            return self._walk_function(
                t.node, t.module, None, coro.args, coro.kwargs, ctx, depth,
                qual=t.qual,
            )
        return UNKNOWN

    def _spawn_task(self, coro: CoroutineVal) -> None:
        t = coro.target
        if isinstance(t, BoundMethodVal):
            name = f"{t.obj.ipath}.{t.name}"
        elif isinstance(t, (LocalFuncVal, FuncInfo)):
            name = _short_qual(t.qual)
        else:
            return
        self._queue_root(f"task:{name}", t, coro)

    def _call_target(self, task_name, target, args, kwargs) -> None:
        if isinstance(target, BoundMethodVal):
            method = target.obj.cls.method(target.name)
            if method is None:
                return
            self._walk_method(
                target.obj, target.name, args, kwargs, task_name, 0, force=True
            )
        elif isinstance(target, LocalFuncVal):
            self._walk_function(
                target.node, target.module, target.owner, args, kwargs,
                task_name, 0, closure=target.env, qual=target.qual, force=True,
            )
        elif isinstance(target, FuncInfo):
            self._walk_function(
                target.node, target.module, None, args, kwargs, task_name, 0,
                qual=target.qual, force=True,
            )

    # -- function/method walking ---------------------------------------
    def _walk_method(self, obj, name, args, kwargs, ctx, depth, force=False):
        method = obj.cls.method(name)
        if method is None:
            return UNKNOWN
        key = (ctx, obj.ipath, name)
        if key in self._visited and not force:
            return UNKNOWN
        self._visited.add(key)
        env = self._bind(method, [obj] + list(args), kwargs)
        self._exec_body(method.body, env, obj.cls.module, ctx, obj, depth + 1)
        rets = env.get("__return__", [])
        return join(*rets) if rets else UNKNOWN

    def _walk_function(self, func_node, module, owner, args, kwargs, ctx, depth,
                       closure=None, qual="", force=False, per_call=False):
        key = (ctx, qual or id(func_node))
        if per_call:
            if key in self._local_stack:  # recursion guard
                return UNKNOWN
            self._local_stack.append(key)
        elif key in self._visited and not force:
            return UNKNOWN
        else:
            self._visited.add(key)
        try:
            env = dict(closure or {})
            env.pop("__return__", None)
            env.pop("__pyglobals__", None)
            env.update(self._bind(func_node, args, kwargs))
            self._exec_body(func_node.body, env, module, ctx, owner, depth + 1)
            rets = env.get("__return__", [])
            return join(*rets) if rets else UNKNOWN
        finally:
            if per_call:
                self._local_stack.pop()

    # -- channels -------------------------------------------------------
    def _make_channel(self, node, env, module, ctx, selfobj, depth, metered,
                      hint=None) -> ChannelVal:
        args = [self._eval(a, env, module, ctx, selfobj, depth) for a in node.args]
        kwargs = {
            kw.arg: self._eval(kw.value, env, module, ctx, selfobj, depth)
            for kw in node.keywords
            if kw.arg is not None
        }
        label = None
        if metered:
            role = args[1] if len(args) > 1 else kwargs.get("role", UNKNOWN)
            name = args[2] if len(args) > 2 else kwargs.get("name", UNKNOWN)
            capacity = args[3] if len(args) > 3 else kwargs.get("capacity", UNKNOWN)
            if isinstance(role, str) and isinstance(name, str):
                label = f"{role}/{name}"
        else:
            capacity = args[0] if args else kwargs.get("capacity", "default")
        if label is None:
            owner = selfobj.ipath if isinstance(selfobj, ObjectVal) else _task_name(ctx)
            attr = hint
            if attr is None:
                attr = f"anon{self._anon_chan}"
                self._anon_chan += 1
            label = f"{owner}.{attr}"
        if not isinstance(capacity, int):
            capacity = "default" if capacity in (None, UNKNOWN) else "?"
        cid, i = label, 2
        while cid in self.topology.channels:
            existing = self.topology.channels[cid]
            if (existing.path, existing.line) == (module.rel, node.lineno):
                return existing
            cid = f"{label}#{i}"
            i += 1
        ch = ChannelVal(cid, label, capacity, module.rel, node.lineno)
        self.topology.add_channel(ch)
        return ch


def _task_name(ctx: str) -> str:
    return ctx[5:] if ctx.startswith("task:") else ctx


def _short_qual(qual: str) -> str:
    """Strip root-context prefixes from nested-function quals:
    'task:Subscriber.run.forward' -> 'Subscriber.run.forward'."""
    return qual[5:] if qual.startswith("task:") else qual


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------


@dataclass
class RootSpec:
    """`path/to/module.py::Symbol` — a class (instantiated, with its
    lifecycle methods seeded) or a module-level function (walked as the
    embedder's task)."""

    path: str
    symbol: str

    @classmethod
    def parse(cls, spec: str) -> "RootSpec":
        path, _, symbol = spec.partition("::")
        if not symbol:
            raise ValueError(f"root spec {spec!r} needs 'file.py::Symbol'")
        return cls(path, symbol)


DEFAULT_PACKAGE = "narwhal_tpu"
DEFAULT_ROOTS = (
    # The role binary wires every production actor: PrimaryNode (internal
    # AND external consensus — both `if` arms execute), WorkerNode, and
    # the standalone primary's execution-output drain.
    "narwhal_tpu/__main__.py::_run_node",
)


def extract(
    root: Path,
    package: str = DEFAULT_PACKAGE,
    roots: Iterable[str] = DEFAULT_ROOTS,
) -> tuple[Topology, Extractor]:
    """Parse `package` under `root`, interpret the wiring from `roots`,
    and return the channel topology."""
    root = Path(root)
    pkg_dir = root / package if package else None
    program = Program(root, pkg_dir)
    extractor = Extractor(program)
    for spec in roots:
        rs = RootSpec.parse(spec)
        info = program.load(root / rs.path)
        if info is None:
            raise FileNotFoundError(rs.path)
        if rs.symbol in info.classes:
            extractor.run_class_root(info.classes[rs.symbol])
        elif rs.symbol in info.functions:
            extractor.run_function_root(info.functions[rs.symbol])
        else:
            raise ValueError(f"{rs.symbol} not found in {rs.path}")
    return extractor.topology, extractor
