"""narwhal-topo: whole-program actor/channel topology analyzer.

Usage: `python -m tools.analysis` — see tools/analysis/__main__.py for
flags and README.md § "Static analysis" for the detector catalog, the
checked-in topology artifact, and the regeneration workflow. Shares
narwhal-lint's Finding/suppression/baseline machinery (tools/lint).
"""

from .detectors import DETECTORS, Context, run_detectors  # noqa: F401
from .extractor import (  # noqa: F401
    DEFAULT_PACKAGE,
    DEFAULT_ROOTS,
    Extractor,
    Program,
    RootSpec,
    Topology,
    extract,
)
