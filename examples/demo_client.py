"""Demo of the external-consensus public API.

Mirrors /root/reference/examples/src/demo_client.rs: boot (or point at) a
committee running with external consensus, submit transactions, then walk the
API: Rounds -> NodeReadCausal -> GetCollections -> RemoveCollections.

Run standalone (boots an in-process 4-node cluster):
    python examples/demo_client.py
Or against a running node:
    python examples/demo_client.py --api HOST:PORT --key HEX --tx HOST:PORT
"""

from __future__ import annotations

import argparse
import asyncio
import sys

sys.path.insert(0, ".")

from narwhal_tpu.messages import (
    GetCollectionsRequest,
    NodeReadCausalRequest,
    RemoveCollectionsRequest,
    RoundsRequest,
    SubmitTransactionStreamMsg,
)
from narwhal_tpu.network import NetworkClient, RpcError


async def demo(api: str, public_key: bytes, tx_address: str | None) -> None:
    client = NetworkClient()
    try:
        if tx_address:
            txs = tuple(b"\x01" + i.to_bytes(8, "big") + b"\x00" * 23 for i in range(64))
            await client.request(tx_address, SubmitTransactionStreamMsg(txs))
            print(f"submitted {len(txs)} transactions to {tx_address}")

        rounds = None
        for _ in range(150):
            try:
                rounds = await client.request(api, RoundsRequest(public_key))
                if rounds.newest_round >= 2:
                    break
            except RpcError:
                pass
            await asyncio.sleep(0.2)
        assert rounds is not None, "API never answered Rounds"
        print(f"Rounds: oldest={rounds.oldest_round} newest={rounds.newest_round}")

        nrc = await client.request(
            api, NodeReadCausalRequest(public_key, rounds.newest_round)
        )
        print(f"NodeReadCausal({rounds.newest_round}): {len(nrc.digests)} collections")

        got = await client.request(api, GetCollectionsRequest(nrc.digests))
        n_batches = sum(len(b) for _, b, _ in got.results)
        n_txs = sum(len(t) for _, b, _ in got.results for _, t in b)
        print(f"GetCollections: {len(got.results)} collections, "
              f"{n_batches} batches, {n_txs} transactions")

        await client.request(
            api, RemoveCollectionsRequest(tuple(d for d, _, _ in got.results))
        )
        print(f"RemoveCollections: removed {len(got.results)} collections")
    finally:
        client.close()


async def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--api", help="consensus API address host:port")
    parser.add_argument("--key", help="authority public key (hex)")
    parser.add_argument("--tx", help="worker transactions address host:port")
    args = parser.parse_args()

    if args.api:
        await demo(args.api, bytes.fromhex(args.key), args.tx)
        return

    from narwhal_tpu.cluster import Cluster

    cluster = Cluster(size=4, workers=1, internal_consensus=False)
    await cluster.start()
    try:
        node = cluster.authorities[0]
        await demo(
            node.primary.api_address,
            node.name,
            node.worker_transactions_address(0),
        )
    finally:
        await cluster.shutdown()


if __name__ == "__main__":
    asyncio.run(main())
