"""Demo of the external-consensus public API over gRPC — the interoperable
edge any language's generated stubs can drive.

Mirrors /root/reference/examples/src/demo_client.rs against
narwhal_tpu/proto/narwhal.proto: submit transactions (Transactions), then
walk Rounds -> NodeReadCausal -> GetCollections -> RemoveCollections.

Run standalone (boots an in-process 4-node cluster):
    python examples/grpc_demo_client.py
Or against a running node:
    python examples/grpc_demo_client.py --api HOST:PORT --key HEX --tx HOST:PORT
"""

from __future__ import annotations

import argparse
import asyncio
import sys

sys.path.insert(0, ".")

import grpc

from narwhal_tpu.proto import narwhal_pb2 as pb


def _unary(channel, service, method, reply_cls):
    return channel.unary_unary(
        f"/narwhal.{service}/{method}",
        request_serializer=lambda m: m.SerializeToString(),
        response_deserializer=reply_cls.FromString,
    )


async def demo(api: str, public_key: bytes, tx_address: str | None) -> None:
    channels = []
    try:
        if tx_address:
            tx_chan = grpc.aio.insecure_channel(tx_address)
            channels.append(tx_chan)
            stream = tx_chan.stream_unary(
                "/narwhal.Transactions/SubmitTransactionStream",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=pb.Empty.FromString,
            )
            n = 64
            await stream(
                iter(
                    pb.Transaction(
                        transaction=b"\x01" + i.to_bytes(8, "big") + b"\x00" * 23
                    )
                    for i in range(n)
                )
            )
            print(f"submitted {n} transactions to {tx_address} (gRPC stream)")

        chan = grpc.aio.insecure_channel(api)
        channels.append(chan)
        rounds_call = _unary(chan, "Proposer", "Rounds", pb.RoundsResponse)
        rounds = None
        for _ in range(150):
            try:
                rounds = await rounds_call(pb.RoundsRequest(public_key=public_key))
                if rounds.newest_round >= 2:
                    break
            except grpc.aio.AioRpcError:
                pass
            await asyncio.sleep(0.2)
        assert rounds is not None, "API never answered Rounds"
        print(f"Rounds: oldest={rounds.oldest_round} newest={rounds.newest_round}")

        nrc = _unary(chan, "Proposer", "NodeReadCausal", pb.NodeReadCausalResponse)
        causal = await nrc(
            pb.NodeReadCausalRequest(public_key=public_key, round=rounds.newest_round)
        )
        ids = list(causal.collection_ids)
        print(f"NodeReadCausal(round={rounds.newest_round}): {len(ids)} collections")

        gc = _unary(chan, "Validator", "GetCollections", pb.GetCollectionsResponse)
        got = await gc(pb.CollectionRequest(collection_ids=ids[:4]))
        batches = sum(len(r.batches) for r in got.results)
        txs = sum(
            len(b.transactions) for r in got.results for b in r.batches
        )
        print(f"GetCollections: {len(got.results)} collections, {batches} batches, {txs} txs")

        rm = _unary(chan, "Validator", "RemoveCollections", pb.Empty)
        await rm(pb.CollectionRequest(collection_ids=ids[:4]))
        print(f"RemoveCollections: removed {len(ids[:4])} collections")
    finally:
        for c in channels:
            await c.close()


async def standalone() -> None:
    from narwhal_tpu.cluster import Cluster

    cluster = Cluster(size=4, workers=1, internal_consensus=False)
    await cluster.start()
    try:
        worker = cluster.authorities[0].workers[0].worker
        await demo(
            cluster.authorities[0].primary.grpc_api_address,
            cluster.authorities[0].name,
            worker.grpc_transactions_address,
        )
    finally:
        await cluster.shutdown()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--api", default=None, help="primary gRPC API host:port")
    ap.add_argument("--key", default=None, help="authority public key (hex)")
    ap.add_argument("--tx", default=None, help="worker gRPC Transactions host:port")
    args = ap.parse_args()
    if args.api:
        asyncio.run(demo(args.api, bytes.fromhex(args.key), args.tx))
    else:
        asyncio.run(standalone())


if __name__ == "__main__":
    main()
