"""CLI for the local benchmark (the reference's `fab local`):

    python -m benchmark --nodes 4 --workers 1 --rate 1000 --duration 20
"""

from __future__ import annotations

import argparse

from narwhal_tpu.config import Parameters

from .local import BenchParameters, LocalBench


def main() -> None:
    ap = argparse.ArgumentParser(prog="benchmark")
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--workers", type=int, default=1)
    ap.add_argument("--rate", type=int, default=1_000)
    ap.add_argument("--tx-size", type=int, default=512)
    ap.add_argument("--duration", type=int, default=20)
    ap.add_argument("--faults", type=int, default=0)
    ap.add_argument("--consensus-protocol", choices=("bullshark", "tusk"),
                    default="bullshark")
    ap.add_argument("--crypto-backend", choices=("cpu", "pool", "tpu"),
                    default="cpu")
    ap.add_argument("--dag-backend", choices=("cpu", "tpu"), default="cpu")
    ap.add_argument("--dag-shards", type=int, default=1)
    ap.add_argument("--max-header-delay", type=float, default=0.1,
                    help="proposer timer (s); slow it on core-starved hosts")
    ap.add_argument("--max-batch-delay", type=float, default=0.1)
    ap.add_argument("--mem-profiling", action="store_true",
                    help="tracemalloc dumps per node into .bench/")
    args = ap.parse_args()

    bench = LocalBench(
        BenchParameters(
            nodes=args.nodes,
            workers=args.workers,
            rate=args.rate,
            tx_size=args.tx_size,
            duration=args.duration,
            faults=args.faults,
            consensus_protocol=args.consensus_protocol,
            crypto_backend=args.crypto_backend,
            dag_backend=args.dag_backend,
            dag_shards=args.dag_shards,
            mem_profiling=args.mem_profiling,
        ),
        node_parameters=Parameters(
            max_header_delay=args.max_header_delay,
            max_batch_delay=args.max_batch_delay,
        ),
    )
    print(bench.run().result())


if __name__ == "__main__":
    main()
