"""Committee-scale liveness sweep: boot N in-process nodes, sample commit
progress over time, and account the control-plane wire cost per round.

Extends the hand-rolled run behind `benchmark/results/n50_liveness.json` into
a repeatable tool (the N=100 gate of ROADMAP item 1):

    python -m benchmark.liveness --nodes 50 --duration 240
    python -m benchmark.liveness --nodes 100 --duration 300 \
        --out benchmark/results/n100_liveness.json

No injected load: at these committee sizes on a small host each round is
thousands of signed+sealed control messages, so the assertion is liveness
(lockstep commits advancing on every node) and the headline wire metric is
bytes per committed round — process-wide (WireStats, comparable with the
pre-wire-diet seed) and per-primary by message type (the new
wire_bytes_sent_total{msg_type=} counters).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time


async def run_liveness(args) -> dict:
    from narwhal_tpu.cluster import Cluster
    from narwhal_tpu.config import Parameters
    from narwhal_tpu.network.rpc import WireStats

    cluster = Cluster(
        size=args.nodes,
        workers=args.workers,
        parameters=Parameters(
            max_header_delay=args.max_header_delay,
            max_batch_delay=args.max_batch_delay,
        ),
    )
    t0 = time.time()
    await cluster.start()
    boot_s = time.time() - t0
    print(f"booted {args.nodes} nodes in {boot_s:.0f}s", file=sys.stderr)

    def committed() -> list[float]:
        return [
            a.metric("consensus_last_committed_round") for a in cluster.authorities
        ]

    def primary_sent_by_type() -> dict[str, float]:
        out: dict[str, float] = {}
        for a in cluster.authorities:
            m = a.primary.registry.get("wire_bytes_sent_total")
            if m is None:
                continue
            for k, c in m._children.items():
                out[k[0]] = out.get(k[0], 0.0) + c.value
        return out

    samples = []
    wire0 = WireStats.snapshot()
    egress0 = primary_sent_by_type()
    rounds0 = committed()
    t_start = time.time()
    try:
        while time.time() - t_start < args.duration:
            await asyncio.sleep(args.sample_interval)
            rounds = committed()
            samples.append(
                {
                    "t_s": round(time.time() - t_start, 1),
                    "committed_min": min(rounds),
                    "committed_max": max(rounds),
                }
            )
            print(f"  t={samples[-1]['t_s']}s committed "
                  f"[{min(rounds)}, {max(rounds)}]", file=sys.stderr)
    finally:
        wire1 = WireStats.snapshot()
        egress1 = primary_sent_by_type()
        rounds1 = committed()
        await cluster.shutdown()

    window = time.time() - t_start
    progressed = max(r1 - r0 for r0, r1 in zip(rounds0, rounds1))
    min_progress = min(r1 - r0 for r0, r1 in zip(rounds0, rounds1))
    wire_bytes = wire1["bytes_sent"] - wire0["bytes_sent"]
    by_type = {
        k: round(egress1.get(k, 0.0) - egress0.get(k, 0.0), 1)
        for k in sorted(set(egress0) | set(egress1))
    }
    record = {
        "mode": "in-process liveness",
        "committee_size": args.nodes,
        "workers_per_node": args.workers,
        "parameters": {
            "max_header_delay_s": args.max_header_delay,
            "max_batch_delay_s": args.max_batch_delay,
        },
        "relay_fanout": os.environ.get("NARWHAL_RELAY_FANOUT", "default"),
        "header_wire": os.environ.get("NARWHAL_HEADER_WIRE", "default"),
        "boot_s": round(boot_s, 1),
        "samples": samples,
        "committed_rounds_in_window": round(progressed, 1),
        "committed_rounds_per_s": round(progressed / window, 4),
        # The liveness gate: every node advanced, and min==max lockstep at
        # the final sample means nobody was left behind.
        "all_nodes_progressed": min_progress > 0,
        "all_nodes_lockstep": min(rounds1) == max(rounds1),
        "wire_bytes_sent_in_window": wire_bytes,
        "wire_bytes_per_round": (
            round(wire_bytes / progressed, 1) if progressed else None
        ),
        # Per-primary egress per round (committee aggregate / N / rounds):
        # the wire-diet acceptance metric, from the per-link counters.
        "primary_egress_bytes_per_round": (
            round(sum(by_type.values()) / args.nodes / progressed, 1)
            if progressed
            else None
        ),
        "primary_egress_bytes_by_msg_type": by_type,
    }
    return record


def main() -> None:
    ap = argparse.ArgumentParser(prog="benchmark.liveness")
    ap.add_argument("--nodes", type=int, default=50)
    ap.add_argument("--workers", type=int, default=1)
    ap.add_argument("--duration", type=float, default=240.0)
    ap.add_argument("--sample-interval", type=float, default=20.0)
    ap.add_argument("--max-header-delay", type=float, default=1.0)
    ap.add_argument("--max-batch-delay", type=float, default=0.5)
    ap.add_argument("--note", default="")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    record = asyncio.run(run_liveness(args))
    if args.note:
        record["note"] = args.note
    print(json.dumps(record, indent=1))
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(record, f, indent=1)


if __name__ == "__main__":
    main()
