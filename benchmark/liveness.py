"""Committee-scale liveness sweep: boot N in-process nodes, sample commit
progress over time, and account the control-plane wire cost per round.

Two transports:

* **Real sockets** (default) — the loopback-TCP mode behind
  `benchmark/results/n50_liveness.json`. A committee's vote mesh costs
  ~2·N·(N-1) in-process fds, which hard-caps this mode near N=90 under the
  container's RLIMIT_NOFILE (the `n100_liveness.json` EMFILE failure); a
  preflight now fails fast with the arithmetic instead of dying mid-run.
* **simnet** (`--simnet`) — the virtual-clock in-memory fabric
  (narwhal_tpu/simnet): zero sockets, zero fds on the mesh, hundreds of
  nodes in one process, `--duration` measured in *virtual* seconds (wall
  cost is CPU only). This is the mode for N>90 committees.

    python -m benchmark.liveness --nodes 50 --duration 240
    python -m benchmark.liveness --nodes 200 --simnet --duration 10 \
        --out benchmark/results/simnet_n200_liveness.json

No injected load: at these committee sizes each round is thousands of
signed control messages, so the assertion is liveness (lockstep commits
advancing on every node) and the headline wire metric is bytes per
committed round.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import resource
import sys
import time


def pooling_enabled() -> bool:
    """Mirror config.connection_pool_effective's NARWHAL_POOL kill-switch
    without importing narwhal_tpu (the preflight must stay import-light)."""
    return os.environ.get("NARWHAL_POOL", "1").strip().lower() not in (
        "0", "false", "off",
    )


def estimate_required_fds(nodes: int, workers: int, pooled: bool = True) -> int:
    """Upper-bound fd demand of an N-node, W-worker in-process committee
    over real sockets. Every in-process TCP connection burns TWO fds (both
    endpoints live here).

    Pooled (connection_pool=True, the default): ONE multiplexed link per
    unordered node pair carries every lane — primary votes and all W
    worker meshes — so connections = N·(N-1)/2 pair links + N self links
    (primary<->own-worker control rides a node's link to itself). Crossed
    dials transiently double a pair's sockets until the loser
    linger-closes, so the socket term gets 25% boot-burst headroom.

    Legacy (NARWHAL_POOL=0): primary vote mesh N·(N-1) connections, one
    same-id worker mesh per lane N·(N-1)·W, primary<->own-worker control
    2·N·W. Either way add listeners (primary, typed api, grpc api = 3 per
    node; worker mesh + tx + grpc tx = 3 per worker) and a flat allowance
    for stores/logs/jax."""
    listeners = nodes * (3 + 3 * workers)
    if pooled:
        connections = nodes * (nodes - 1) // 2 + nodes
        return int(2 * connections * 1.25) + listeners + 256
    connections = nodes * (nodes - 1) * (1 + workers) + 2 * nodes * workers
    return 2 * connections + listeners + 256


def preflight_fd_check(
    nodes: int, workers: int, pooled: bool | None = None
) -> None:
    """Fail fast (and actionably) instead of mid-run EMFILE — the
    r9 n100_liveness.json failure mode."""
    if pooled is None:
        pooled = pooling_enabled()
    soft, _hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    needed = estimate_required_fds(nodes, workers, pooled)
    if needed > soft:
        model = (
            "≈N·(N-1)/2+N pooled pair links ×2 fds, + headroom + listeners"
            if pooled
            else "≈2·N·(N-1)·(1+W) legacy mesh sockets + listeners"
        )
        raise SystemExit(
            f"liveness preflight: N={nodes} W={workers} needs ~{needed:,} "
            f"fds ({model}) but "
            f"RLIMIT_NOFILE is {soft:,}. Raise `ulimit -n`, shrink the "
            "committee, or run this committee socket-free with --simnet "
            "(virtual-clock in-memory transport; no fd cost, N=200+ fits)."
        )


def process_fd_count() -> int:
    """Open fds in THIS process right now (the whole committee lives here,
    so this is the number the rlimit actually judges)."""
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:  # non-procfs platform
        return -1


def _pool_link_peaks(cluster) -> list[int]:
    """Per-node peak live pooled-link counts, one entry per booted node.

    ``cluster.authorities[i].primary`` is the PrimaryNode assembly; the
    Primary role that owns the LanePool sits one level in at ``.primary``.
    """
    peaks = []
    for a in cluster.authorities:
        node = a.primary
        if node is None:
            continue
        role = getattr(node, "primary", node)
        pool = getattr(role, "pool", None)
        if pool is not None:
            peaks.append(pool.peak_links)
    return peaks


async def run_liveness(args) -> dict:
    from narwhal_tpu.cluster import Cluster
    from narwhal_tpu.config import Parameters
    from narwhal_tpu.network.rpc import WireStats

    preflight_fd_check(args.nodes, args.workers)
    cluster = Cluster(
        size=args.nodes,
        workers=args.workers,
        parameters=Parameters(
            max_header_delay=args.max_header_delay,
            max_batch_delay=args.max_batch_delay,
            cert_format=args.cert_format,
            verify_rule=args.verify_rule,
        ),
    )
    fd_baseline = process_fd_count()
    t0 = time.time()
    await cluster.start(args.nodes - args.faults)
    boot_s = time.time() - t0
    peak_fds = process_fd_count()
    print(f"booted {args.nodes - args.faults} nodes in {boot_s:.0f}s "
          f"({peak_fds} fds open)", file=sys.stderr)

    def committed() -> list[float]:
        return [
            a.metric("consensus_last_committed_round")
            for a in cluster.authorities
            if a.primary is not None
        ]

    def primary_sent_by_type() -> dict[str, float]:
        out: dict[str, float] = {}
        for a in cluster.authorities:
            if a.primary is None:
                continue
            m = a.primary.registry.get("wire_bytes_sent_total")
            if m is None:
                continue
            for k, c in m._children.items():
                out[k[0]] = out.get(k[0], 0.0) + c.value
        return out

    samples = []
    wire0 = WireStats.snapshot()
    egress0 = primary_sent_by_type()
    rounds0 = committed()
    t_start = time.time()
    try:
        while time.time() - t_start < args.duration:
            await asyncio.sleep(args.sample_interval)
            peak_fds = max(peak_fds, process_fd_count())
            rounds = committed()
            samples.append(
                {
                    "t_s": round(time.time() - t_start, 1),
                    "committed_min": min(rounds),
                    "committed_max": max(rounds),
                }
            )
            print(f"  t={samples[-1]['t_s']}s committed "
                  f"[{min(rounds)}, {max(rounds)}]", file=sys.stderr)
    finally:
        peak_fds = max(peak_fds, process_fd_count())
        link_peaks = _pool_link_peaks(cluster)
        wire1 = WireStats.snapshot()
        egress1 = primary_sent_by_type()
        rounds1 = committed()
        telemetry = _scrape_node0(cluster)
        await cluster.shutdown()

    window = time.time() - t_start
    alive = args.nodes - args.faults
    record = _record(
        args, "in-process liveness", boot_s, samples, window,
        rounds0, rounds1, wire0, wire1, egress0, egress1,
        alive=alive,
    )
    record["telemetry_scrape"] = telemetry
    # Socket-wall accounting: the committee shares one process, so the
    # process-wide peak divided by booted nodes is the per-node fd story
    # (pooled target: O(N); legacy mesh: O(N·W)).
    record["fd_baseline"] = fd_baseline
    record["peak_process_fds"] = peak_fds
    record["peak_fds_per_node"] = (
        round((peak_fds - fd_baseline) / alive, 1) if peak_fds >= 0 else None
    )
    record["peak_pool_links_per_node"] = max(link_peaks, default=None)
    record["connection_pool"] = bool(link_peaks)
    return record


def _scrape_node0(cluster) -> dict:
    """Node 0's parsed scrape (buckets dropped) for the results record —
    the same surface Telemetry.Scrape serves over RPC, captured in-process
    because the committee lives in this process anyway."""
    from narwhal_tpu.metrics import scrape_snapshot

    for a in cluster.authorities:
        if a.primary is not None:
            return {"primary-0": scrape_snapshot(a.primary.registry)}
    return {}


def run_liveness_simnet(args) -> dict:
    """The same measurement over the simnet fabric: one process, zero
    sockets, virtual time. Boots the committee, lets `--duration` VIRTUAL
    seconds elapse, and reports the usual liveness/wire record plus the
    wall cost and the fabric's event count."""
    from narwhal_tpu.network import transport
    from narwhal_tpu.network.rpc import WireStats
    from narwhal_tpu.simnet import SimCluster, SimFabric, SimLoop

    loop = SimLoop()
    asyncio.set_event_loop(loop)
    fabric = SimFabric(seed=args.seed)
    transport.install(fabric)
    t_wall = time.time()

    async def drive() -> dict:
        from narwhal_tpu.config import Parameters

        cluster = SimCluster(
            size=args.nodes,
            fabric=fabric,
            workers=args.workers,
            auth=not args.no_auth,
            parameters=Parameters(
                max_header_delay=args.max_header_delay,
                max_batch_delay=args.max_batch_delay,
                cert_format=args.cert_format,
                verify_rule=args.verify_rule,
            ),
        )
        t0 = time.time()
        await cluster.start(args.nodes - args.faults)
        boot_s = time.time() - t0
        print(
            f"booted {args.nodes - args.faults} simnet nodes in {boot_s:.0f}s "
            f"(wall)",
            file=sys.stderr,
        )

        def committed() -> list[float]:
            return [
                a.metric("consensus_last_committed_round")
                for a in cluster.authorities
                if a.primary is not None
            ]

        def primary_sent_by_type() -> dict[str, float]:
            out: dict[str, float] = {}
            for a in cluster.authorities:
                if a.primary is None:
                    continue
                m = a.primary.registry.get("wire_bytes_sent_total")
                if m is None:
                    continue
                for k, c in m._children.items():
                    out[k[0]] = out.get(k[0], 0.0) + c.value
            return out

        samples = []
        wire0 = WireStats.snapshot()
        egress0 = primary_sent_by_type()
        rounds0 = committed()
        v_start = loop.time()
        ticks = max(1, int(args.duration / args.sample_interval))
        for _ in range(ticks):
            await asyncio.sleep(args.sample_interval)
            rounds = committed()
            samples.append(
                {
                    "t_virtual_s": round(loop.time() - v_start, 1),
                    "committed_min": min(rounds),
                    "committed_max": max(rounds),
                    "wall_s": round(time.time() - t_wall, 1),
                }
            )
            print(
                f"  t={samples[-1]['t_virtual_s']}s(virtual) committed "
                f"[{min(rounds)}, {max(rounds)}] wall={samples[-1]['wall_s']}s",
                file=sys.stderr,
            )
        window = loop.time() - v_start
        link_peaks = _pool_link_peaks(cluster)
        wire1 = WireStats.snapshot()
        egress1 = primary_sent_by_type()
        rounds1 = committed()
        telemetry = _scrape_node0(cluster)
        await cluster.shutdown()
        record = _record(
            args, "simnet liveness (virtual clock)", boot_s, samples, window,
            rounds0, rounds1, wire0, wire1, egress0, egress1,
            alive=args.nodes - args.faults,
        )
        record["telemetry_scrape"] = telemetry
        record["virtual_duration_s"] = round(window, 1)
        record["wall_s"] = round(time.time() - t_wall, 1)
        record["real_sockets"] = 0
        record["fabric_events"] = len(fabric.log)
        record["transport_auth"] = not args.no_auth
        record["seed"] = args.seed
        # Virtual analogue of the fd story: peak simultaneous fabric
        # connections, committee-wide and per booted node.
        alive = args.nodes - args.faults
        peak_conns = fabric.counters["peak_conns"]
        record["peak_fabric_conns"] = peak_conns
        record["peak_fds_per_node"] = round(2 * peak_conns / alive, 1)
        record["peak_pool_links_per_node"] = max(link_peaks, default=None)
        record["connection_pool"] = bool(link_peaks)
        return record

    try:
        return loop.run_until_complete(drive())
    finally:
        transport.uninstall()
        pending = [t for t in asyncio.all_tasks(loop) if not t.done()]
        for t in pending:
            t.cancel()
        if pending:
            loop.run_until_complete(asyncio.wait(pending, timeout=15.0))
        asyncio.set_event_loop(None)
        loop.close()


def _record(
    args, mode, boot_s, samples, window, rounds0, rounds1, wire0, wire1,
    egress0, egress1, alive,
) -> dict:
    progressed = max(r1 - r0 for r0, r1 in zip(rounds0, rounds1))
    min_progress = min(r1 - r0 for r0, r1 in zip(rounds0, rounds1))
    wire_bytes = wire1["bytes_sent"] - wire0["bytes_sent"]
    by_type = {
        k: round(egress1.get(k, 0.0) - egress0.get(k, 0.0), 1)
        for k in sorted(set(egress0) | set(egress1))
    }
    return {
        "mode": mode,
        "committee_size": args.nodes,
        "workers_per_node": args.workers,
        "faults": args.faults,
        # First-class experiment axes like W and faults: the certificate
        # wire form moves the control-plane byte floor, the accept rule
        # names the verification semantics the row ran under.
        "cert_format": args.cert_format,
        "verify_rule": args.verify_rule,
        "alive_nodes": alive,
        "parameters": {
            "max_header_delay_s": args.max_header_delay,
            "max_batch_delay_s": args.max_batch_delay,
        },
        "relay_fanout": os.environ.get("NARWHAL_RELAY_FANOUT", "default"),
        "header_wire": os.environ.get("NARWHAL_HEADER_WIRE", "default"),
        "boot_s": round(boot_s, 1),
        "samples": samples,
        "committed_rounds_in_window": round(progressed, 1),
        "committed_rounds_per_s": round(progressed / window, 4) if window else None,
        # The liveness gate: every node advanced, and min==max lockstep at
        # the final sample means nobody was left behind.
        "all_nodes_progressed": min_progress > 0,
        "all_nodes_lockstep": min(rounds1) == max(rounds1),
        "wire_bytes_sent_in_window": wire_bytes,
        "wire_bytes_per_round": (
            round(wire_bytes / progressed, 1) if progressed else None
        ),
        # Per-primary egress per round (committee aggregate / N / rounds):
        # the wire-diet acceptance metric, from the per-link counters.
        "primary_egress_bytes_per_round": (
            round(sum(by_type.values()) / alive / progressed, 1)
            if progressed
            else None
        ),
        "primary_egress_bytes_by_msg_type": by_type,
    }


def main() -> None:
    ap = argparse.ArgumentParser(prog="benchmark.liveness")
    ap.add_argument("--nodes", type=int, default=50)
    ap.add_argument("--workers", type=int, default=1)
    ap.add_argument("--faults", type=int, default=0,
                    help="boot N-faults nodes (reference bench parity)")
    ap.add_argument("--duration", type=float, default=240.0,
                    help="measurement window; VIRTUAL seconds under --simnet")
    ap.add_argument("--sample-interval", type=float, default=20.0)
    ap.add_argument("--max-header-delay", type=float, default=1.0)
    ap.add_argument("--max-batch-delay", type=float, default=0.5)
    ap.add_argument("--cert-format", choices=("full", "compact"),
                    default="compact",
                    help="certificate wire form (committee-wide axis; "
                    "compact = half-aggregated default, full = opt-out)")
    ap.add_argument("--verify-rule", choices=("strict", "cofactored"),
                    default="strict",
                    help="per-item ed25519 accept set")
    ap.add_argument("--simnet", action="store_true",
                    help="socket-free virtual-clock transport: no fd "
                    "ceiling, N=200+ committees fit in one process")
    ap.add_argument("--seed", type=int, default=0,
                    help="simnet determinism seed")
    ap.add_argument("--no-auth", action="store_true",
                    help="simnet only: skip transport handshakes/AEAD "
                    "(trusted in-memory medium; saves 2N(N-1) pure-Python "
                    "X25519 exchanges at boot)")
    ap.add_argument("--note", default="")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    if args.simnet:
        record = run_liveness_simnet(args)
    else:
        record = asyncio.run(run_liveness(args))
    if args.note:
        record["note"] = args.note
    print(json.dumps(record, indent=1))
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(record, f, indent=1)
    from tools.perf import ledger as perf_ledger

    perf_ledger.append(
        "liveness", record,
        scrape=record.get("telemetry_scrape"), argv=sys.argv[1:],
    )


if __name__ == "__main__":
    main()
