"""Remote multi-machine benchmark orchestration over SSH (the reference's
`fab remote` flow, benchmark/benchmark/remote.py:33-366 + fabfile.py, with
plain ssh/scp in place of Fabric and no cloud-provider coupling — hosts come
from a file instead of boto3).

Flow (mirroring Bench.run):
  1. `install`   — push the repo to every host (tar over ssh) and verify the
                   Python environment imports.
  2. `configure` — generate keys/committee/workers/parameters with real host
                   addresses, upload each node's config set.
  3. `start`     — launch primaries/workers/clients under nohup on their
                   hosts (faults f => last f nodes never start).
  4. `stop`      — kill narwhal processes everywhere.
  5. `logs`      — download logs and produce the same SUMMARY as the local
                   bench (LogParser is shared).

Hosts file: one "user@host" per line; node i uses line i (one validator per
machine, its workers collocated, like the reference's default).

    python -m benchmark.remote --hosts hosts.txt install
    python -m benchmark.remote --hosts hosts.txt run --rate 50000 --duration 60

The SSH transport is a small `Connection` class (run/put/get); tests inject
`LocalConnection`, which executes the same commands through a local shell,
so the whole orchestration logic is exercised without real machines — and a
BASELINE.json-shape config (10-50 nodes) is buildable in principle.
"""

from __future__ import annotations

import argparse
import json
import os
import shlex
import subprocess
import time

REMOTE_DIR = "~/narwhal-tpu"


class Connection:
    """Thin ssh/scp wrapper: run a command, push a file, pull a file."""

    node_env = ""  # extra VAR=val prefix for launched node commands

    def __init__(self, host: str, ssh_opts: tuple[str, ...] = ("-o", "BatchMode=yes")):
        self.host = host
        self.ssh_opts = list(ssh_opts)

    def run(
        self, command: str, check: bool = True, capture: bool = True
    ) -> subprocess.CompletedProcess:
        # capture=False is for fire-and-forget background launches: waiting
        # for pipe EOF can block on the nohup'd child, both locally and over
        # real ssh.
        kwargs: dict = dict(text=True, check=check, stdin=subprocess.DEVNULL)
        if capture:
            kwargs["capture_output"] = True
        else:
            kwargs["stdout"] = subprocess.DEVNULL
            kwargs["stderr"] = subprocess.DEVNULL
        return subprocess.run(["ssh", *self.ssh_opts, self.host, command], **kwargs)

    def put(self, local: str, remote: str) -> None:
        subprocess.run(
            ["scp", *self.ssh_opts, local, f"{self.host}:{remote}"], check=True
        )

    def get(self, remote: str, local: str) -> None:
        subprocess.run(
            ["scp", *self.ssh_opts, f"{self.host}:{remote}", local], check=True
        )


class LocalConnection(Connection):
    """Executes the same command surface through a local shell with a
    per-'host' root directory — lets tests (and single-machine dry runs)
    exercise the orchestration without sshd."""

    def __init__(self, host: str, root: str):
        super().__init__(host)
        self.root = root
        os.makedirs(root, exist_ok=True)

    @property
    def node_env(self) -> str:
        # The simulated hosts share this machine with the orchestrating
        # parent, which may hold SO_REUSEPORT placeholders on assigned
        # ports (e.g. the test's base_port); node children must co-bind
        # through them (RpcServer only sets reuse_port for ports proven
        # placeheld). Advertise the exact live list — never "all", which
        # would let genuinely duplicate servers co-bind silently. Real ssh
        # hosts keep the empty default: no placeholder exists there.
        from narwhal_tpu.config import placeheld_ports

        ports = placeheld_ports()
        if not ports:
            return ""
        return "NARWHAL_PLACEHELD_PORTS=" + ",".join(map(str, ports))

    def _localize(self, text: str) -> str:
        return text.replace("~", self.root)

    def run(
        self, command: str, check: bool = True, capture: bool = True
    ) -> subprocess.CompletedProcess:
        kwargs: dict = dict(text=True, check=check, stdin=subprocess.DEVNULL)
        if capture:
            kwargs["capture_output"] = True
        else:
            kwargs["stdout"] = subprocess.DEVNULL
            kwargs["stderr"] = subprocess.DEVNULL
        return subprocess.run(["bash", "-c", self._localize(command)], **kwargs)

    def put(self, local: str, remote: str) -> None:
        dest = self._localize(remote)
        os.makedirs(os.path.dirname(dest) or ".", exist_ok=True)
        subprocess.run(["cp", local, dest], check=True)

    def get(self, remote: str, local: str) -> None:
        os.makedirs(os.path.dirname(local) or ".", exist_ok=True)
        subprocess.run(["cp", self._localize(remote), local], check=True)


class RemoteBench:
    def __init__(
        self,
        hosts: list[str],
        workers: int = 1,
        base_port: int = 9000,
        connection_factory=Connection,
        work_dir: str = ".bench-remote",
    ):
        self.hosts = hosts
        self.workers = workers
        self.base_port = base_port
        self.conns = [connection_factory(h) for h in hosts]
        self.base = os.path.abspath(work_dir)
        os.makedirs(self.base, exist_ok=True)

    # -- 1. install --------------------------------------------------------
    def install(self) -> None:
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        tarball = os.path.join(self.base, "repo.tar.gz")
        subprocess.run(
            [
                "tar", "czf", tarball, "-C", repo,
                "--exclude=.git", "--exclude=.bench*", "--exclude=__pycache__",
                "--exclude=.jax_cache", "--exclude=.pytest_cache",
                "narwhal_tpu", "benchmark", "native",
            ],
            check=True,
        )
        for conn in self.conns:
            conn.run(f"mkdir -p {REMOTE_DIR}")
            conn.put(tarball, f"{REMOTE_DIR}/repo.tar.gz")
            conn.run(f"cd {REMOTE_DIR} && tar xzf repo.tar.gz")
            out = conn.run(
                f"cd {REMOTE_DIR} && python3 -c 'import narwhal_tpu; print(\"ok\")'"
            )
            assert "ok" in out.stdout, f"{conn.host}: environment check failed"

    # -- 2. configure ------------------------------------------------------
    def configure(self) -> dict:
        """Generate committee/worker/key/parameter files with the hosts'
        real addresses and upload each node's set."""
        import sys

        sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        from narwhal_tpu.config import (
            Authority,
            Committee,
            Parameters,
            WorkerCache,
            WorkerInfo,
        )
        from narwhal_tpu.crypto import KeyPair

        def bare_host(h: str) -> str:
            return h.split("@", 1)[-1]

        authorities, workers, key_docs = {}, {}, []
        for i, host in enumerate(self.hosts):
            kp, net_kp = KeyPair.generate(), KeyPair.generate()
            worker_kps = {w: KeyPair.generate() for w in range(self.workers)}
            key_docs.append(
                {
                    "name": kp.public.hex(),
                    "seed": kp.private_bytes().hex(),
                    "network_seed": net_kp.private_bytes().hex(),
                    "worker_network_seeds": {
                        str(w): k.private_bytes().hex() for w, k in worker_kps.items()
                    },
                }
            )
            addr = bare_host(host)
            # Per-node port block: unique even when several "hosts" resolve
            # to one machine (the LocalConnection test path).
            port = self.base_port + i * 100
            authorities[kp.public] = Authority(
                stake=1, primary_address=f"{addr}:{port}", network_key=net_kp.public
            )
            workers[kp.public] = {
                w: WorkerInfo(
                    name=worker_kps[w].public,
                    transactions=f"{addr}:{port + 1 + 2 * w}",
                    worker_address=f"{addr}:{port + 2 + 2 * w}",
                )
                for w in range(self.workers)
            }
        committee = Committee(authorities)
        committee.export(f"{self.base}/committee.json")
        WorkerCache(workers).export(f"{self.base}/workers.json")
        self.node_parameters = Parameters()
        self.node_parameters.export(f"{self.base}/parameters.json")
        for i, doc in enumerate(key_docs):
            with open(f"{self.base}/key-{i}.json", "w") as f:
                json.dump(doc, f)
        # Upload: every host gets the shared files + its own key.
        for i, conn in enumerate(self.conns):
            conn.run(f"mkdir -p {REMOTE_DIR}/configs")
            for name in ("committee.json", "workers.json", "parameters.json"):
                conn.put(f"{self.base}/{name}", f"{REMOTE_DIR}/configs/{name}")
            conn.put(f"{self.base}/key-{i}.json", f"{REMOTE_DIR}/configs/key.json")
        return {"committee": committee, "workers": workers}

    # -- 3/4. start / stop -------------------------------------------------
    def _node_cmd(self, role: str, log: str, extra: str = "", env: str = "") -> str:
        prefix = f"{env} " if env else ""
        return (
            f"cd {REMOTE_DIR} && {prefix}nohup python3 -m narwhal_tpu -v run "
            f"--keys configs/key.json --committee configs/committee.json "
            f"--workers configs/workers.json --parameters configs/parameters.json "
            f"--store db {role} {extra} < /dev/null > {log}.log 2>&1 &"
        )

    def start(self, faults: int = 0) -> None:
        alive = self.conns[: len(self.conns) - faults]
        for conn in alive:
            conn.run(
                self._node_cmd("primary", "primary", env=conn.node_env),
                capture=False,
            )
            for w in range(self.workers):
                conn.run(
                    self._node_cmd(
                        "worker", f"worker-{w}", f"--id {w}", env=conn.node_env
                    ),
                    capture=False,
                )

    def start_clients(self, rate: int, tx_size: int, faults: int = 0) -> None:
        import sys

        sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        from narwhal_tpu.config import WorkerCache

        cache = WorkerCache.import_(f"{self.base}/workers.json")
        lanes = [
            info.transactions
            for ws in cache.workers.values()
            for info in ws.values()
        ]
        alive = self.conns[: len(self.conns) - faults]
        share = max(1, rate // max(1, len(alive) * self.workers))
        nodes = " ".join(lanes)
        for i, conn in enumerate(alive):
            cache_keys = list(cache.workers)
            for w, info in cache.workers[cache_keys[i]].items():
                conn.run(
                    f"cd {REMOTE_DIR} && nohup python3 -m narwhal_tpu "
                    f"benchmark_client --target {info.transactions} "
                    f"--rate {share} --size {tx_size} --nodes {nodes} "
                    f"< /dev/null > client-{w}.log 2>&1 &",
                    capture=False,
                )

    def stop(self) -> None:
        for conn in self.conns:
            conn.run("pkill -f 'python3 -m narwhal_tpu' || true", check=False)

    # -- 5. logs -----------------------------------------------------------
    def collect_logs(self, faults: int = 0):
        from .logs import LogParser

        log_dir = os.path.join(self.base, "logs")
        os.makedirs(log_dir, exist_ok=True)
        for i, conn in enumerate(self.conns[: len(self.conns) - faults]):
            conn.get(f"{REMOTE_DIR}/primary.log", f"{log_dir}/primary-{i}.log")
            for w in range(self.workers):
                conn.get(
                    f"{REMOTE_DIR}/worker-{w}.log", f"{log_dir}/worker-{i}-{w}.log"
                )
                conn.get(
                    f"{REMOTE_DIR}/client-{w}.log", f"{log_dir}/client-{i}-{w}.log"
                )
        return LogParser.process(
            log_dir, faults=faults, parameters=getattr(self, "node_parameters", None)
        )

    def wait_booted(self, faults: int = 0, timeout: float = 120.0) -> None:
        """Poll every alive host's primary log for the boot line (the
        reference harness' 'successfully booted' wait). Python startup in
        some environments preloads heavyweight libraries, so a fixed sleep
        is not enough when many nodes share cores."""
        deadline = time.time() + timeout
        alive = self.conns[: len(self.conns) - faults]
        pending = list(alive)
        while pending and time.time() < deadline:
            still = []
            for conn in pending:
                out = conn.run(
                    f"grep -c 'successfully booted' {REMOTE_DIR}/primary.log "
                    f"{REMOTE_DIR}/worker-*.log 2>/dev/null | "
                    f"awk -F: '{{s+=$2}} END {{print s}}'",
                    check=False,
                )
                booted = int(out.stdout.strip() or 0)
                if booted < 1 + self.workers:
                    still.append(conn)
            pending = still
            if pending:
                time.sleep(1.0)
        if pending:
            raise TimeoutError(
                f"nodes never booted on: {[c.host for c in pending]}"
            )

    def run(self, rate: int, tx_size: int, duration: int, faults: int = 0):
        self.stop()
        # Fresh stores per run: configure() regenerates committee keys, so
        # recovering state persisted under an old committee would wedge the
        # nodes (LocalBench rmtree's its base dir for the same reason).
        for conn in self.conns:
            conn.run(f"rm -rf {REMOTE_DIR}/db-* {REMOTE_DIR}/*.log", check=False)
        self.start(faults=faults)
        self.wait_booted(faults=faults)
        self.start_clients(rate, tx_size, faults=faults)
        time.sleep(duration)
        self.stop()
        return self.collect_logs(faults=faults)


def main() -> None:
    ap = argparse.ArgumentParser(prog="benchmark.remote")
    ap.add_argument("--hosts", required=True, help="file: one user@host per line")
    ap.add_argument("--workers", type=int, default=1)
    sub = ap.add_subparsers(dest="command", required=True)
    sub.add_parser("install")
    sub.add_parser("configure")
    sub.add_parser("stop")
    runp = sub.add_parser("run")
    runp.add_argument("--rate", type=int, default=10_000)
    runp.add_argument("--tx-size", type=int, default=512)
    runp.add_argument("--duration", type=int, default=30)
    runp.add_argument("--faults", type=int, default=0)
    args = ap.parse_args()

    with open(args.hosts) as f:
        hosts = [line.strip() for line in f if line.strip()]
    bench = RemoteBench(hosts, workers=args.workers)
    if args.command == "install":
        bench.install()
    elif args.command == "configure":
        bench.configure()
    elif args.command == "stop":
        bench.stop()
    elif args.command == "run":
        bench.configure()
        parser = bench.run(args.rate, args.tx_size, args.duration, args.faults)
        print(parser.result())


if __name__ == "__main__":
    main()
