"""Aggregate repeated benchmark runs into mean/std records (the reference's
benchmark/benchmark/aggregate.py).

    python -m benchmark.aggregate run1.json run2.json run3.json --out agg.json

Runs are grouped by (committee_size, workers_per_node, faults, input_rate,
tx_size); numeric fields get `<key>` = mean and `<key>_std` = sample std.
"""

from __future__ import annotations

import argparse
import json
from collections import defaultdict
from statistics import mean, stdev

GROUP_KEYS = ("committee_size", "workers_per_node", "faults", "input_rate", "tx_size")


def aggregate(records: list[dict]) -> list[dict]:
    groups: dict[tuple, list[dict]] = defaultdict(list)
    for r in records:
        groups[tuple(r.get(k) for k in GROUP_KEYS)].append(r)
    out = []
    for key, rs in sorted(groups.items(), key=lambda kv: str(kv[0])):
        agg: dict = dict(zip(GROUP_KEYS, key))
        agg["runs"] = len(rs)
        numeric = {
            k
            for r in rs
            for k, v in r.items()
            if isinstance(v, (int, float)) and k not in GROUP_KEYS
        }
        for k in sorted(numeric):
            vals = [r[k] for r in rs if k in r]
            agg[k] = mean(vals)
            agg[k + "_std"] = stdev(vals) if len(vals) > 1 else 0.0
        out.append(agg)
    return out


def main() -> None:
    ap = argparse.ArgumentParser(prog="benchmark.aggregate")
    ap.add_argument("files", nargs="+")
    ap.add_argument("--out", default=".bench/aggregate.json")
    args = ap.parse_args()
    records: list[dict] = []
    for path in args.files:
        with open(path) as f:
            data = json.load(f)
        records.extend(data if isinstance(data, list) else [data])
    result = aggregate(records)
    import os

    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(result, f, indent=2)
    print(f"aggregated {len(records)} runs into {len(result)} groups -> {args.out}")


if __name__ == "__main__":
    main()
