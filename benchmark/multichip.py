"""Multi-chip device-plane scaling sweep: the bench.py multichip leg.

`python bench.py --multichip` (or `python -m benchmark.multichip`) runs a
per-device-count sweep over the virtual CPU mesh — each device count in
its OWN subprocess, because --xla_force_host_platform_device_count is
fixed at jax initialization — and writes
`benchmark/results/multichip_scaling.json`:

- per device count: the sharded verify throughput (staged msm pipeline,
  fixed bucket, median of timed steady-state dispatch windows) and the
  per-(kernel, mesh shape) compile walls from the kernel registry;
- for the acceptance device count (8): the full `__graft_entry__`
  dryrun_multichip contract (rc recorded — the MULTICHIP artifact's
  rc=124 compile-timeout failure mode is exactly what this leg guards),
  run TWICE when the persistent cache is enabled so the warm-process
  walls prove the once-per-container compile claim;
- an honest scaling note: on this host every "device" is a virtual CPU
  device sharing ONE physical core, so aggregate throughput cannot scale
  with device count — the curve validates compile scaling, sharding
  correctness and dispatch overhead, and the roofline arithmetic for a
  real multi-chip part is spelled out in the note.

The subprocesses opt in to the persistent compilation cache
(NARWHAL_JAX_CACHE_DIR, default `<repo>/.jax_cache_multichip`) so the
sweep pays each (kernel, mesh shape) compile once per container.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RESULTS = os.path.join(REPO, "benchmark", "results", "multichip_scaling.json")
MARK = "MULTICHIP-LEG-RESULT "

BUCKET = 512  # fixed verify bucket: divisible by every swept device count
LEG_TIMEOUT = 1800.0


def _leg_env(n_devices: int, cache_dir: str | None) -> dict:
    import re

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = re.sub(
        r"--xla_force_host_platform_device_count=\d+", "", env.get("XLA_FLAGS", "")
    )
    env["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={max(8, n_devices)}"
    ).strip()
    env["NARWHAL_TPU_PREWARM"] = "0"
    if cache_dir:
        env["NARWHAL_JAX_CACHE_DIR"] = cache_dir
    else:
        env.pop("NARWHAL_JAX_CACHE_DIR", None)
    return env


def _run_leg(n_devices: int, dryrun: bool, cache_dir: str | None) -> dict:
    """One device count in a fresh subprocess; returns its result record
    (rc, walls, verify rate), with rc != 0 surfaced, never swallowed."""
    cmd = [
        sys.executable,
        "-m",
        "benchmark.multichip",
        "--leg",
        str(n_devices),
    ]
    if dryrun:
        cmd.append("--dryrun")
    t0 = time.monotonic()
    try:
        proc = subprocess.run(
            cmd,
            cwd=REPO,
            env=_leg_env(n_devices, cache_dir),
            capture_output=True,
            text=True,
            timeout=LEG_TIMEOUT,
        )
        rc = proc.returncode
        out = proc.stdout
        tail = (proc.stdout + proc.stderr)[-1500:]
    except subprocess.TimeoutExpired as e:
        rc, out = 124, (e.stdout or "")
        tail = ((e.stdout or "") + (e.stderr or ""))[-1500:]
    record: dict = {
        "n_devices": n_devices,
        "rc": rc,
        "wall_s": round(time.monotonic() - t0, 1),
        "dryrun_included": dryrun,
    }
    for line in out.splitlines():
        if line.startswith(MARK):
            record.update(json.loads(line[len(MARK):]))
            break
    else:
        record["tail"] = tail
    return record


def _epilogue_profile() -> dict:
    """ROADMAP item 5's denominator, measured alongside the dryrun leg: a
    small traced FusedCertificatePipeline run (the test_multichip shape —
    fixed bucket 32 on a 4-device mesh) whose flight dump feeds
    tools/perf/epilogue.attribute into the per-batch breakdown. The
    sub-span books (epilogue_unpack + epilogue_commit vs host_epilogue)
    must balance within 10% — the acceptance gate for the attributor."""
    import jax

    from narwhal_tpu.consensus import ConsensusState
    from narwhal_tpu.fixtures import CommitteeFixture, make_signed_certificates
    from narwhal_tpu.stores import NodeStorage
    from narwhal_tpu.tpu.dag_kernels import TpuBullshark
    from narwhal_tpu.tpu.pipeline import FusedCertificatePipeline
    from narwhal_tpu.tpu.verifier import TpuVerifier, data_mesh
    from narwhal_tpu.tracing import Tracer
    from narwhal_tpu.types import Certificate
    from tools.perf import epilogue

    f = CommitteeFixture(size=4)
    genesis = {c.digest for c in Certificate.genesis(f.committee)}
    certs, _ = make_signed_certificates(f, 1, 10, genesis)
    tracer = Tracer(node="epilogue-profile", enabled=True, sample=1.0, ring=2048)
    verifier = TpuVerifier(
        max_bucket=32, msm_min_bucket=16, mode="item", fixed_bucket=True,
        mesh=data_mesh(4, devices=jax.devices("cpu")[:4]),
    )
    state = ConsensusState(Certificate.genesis(f.committee))
    engine = TpuBullshark(f.committee, NodeStorage(None).consensus_store, 50)
    pipe = FusedCertificatePipeline(verifier, engine, state, tracer=tracer)
    for lo in range(0, len(certs), 8):  # 8 certs x 3 sigs = 24 <= bucket 32
        pipe.feed(certs[lo:lo + 8])
    pipe.drain()
    return epilogue.attribute([tracer.dump()])


def leg_main(n_devices: int, dryrun: bool) -> None:
    """Subprocess body: sharded verify rate + compile walls (+ the driver
    dryrun contract when --dryrun). Emits ONE marked JSON line."""
    import numpy as np  # noqa: F401  (jax import ordering)

    import jax

    from narwhal_tpu.crypto import KeyPair
    from narwhal_tpu.tpu import kernel_registry
    from narwhal_tpu.tpu.verifier import TpuVerifier, data_mesh

    t_start = time.perf_counter()
    result: dict = {"cache_dir": os.environ.get("NARWHAL_JAX_CACHE_DIR", "")}

    if dryrun:
        import __graft_entry__

        t0 = time.perf_counter()
        __graft_entry__.dryrun_multichip(n_devices, devices=jax.devices("cpu"))
        result["dryrun_wall_s"] = round(time.perf_counter() - t0, 1)
        t0 = time.perf_counter()
        result["epilogue_attribution"] = _epilogue_profile()
        result["epilogue_profile_wall_s"] = round(time.perf_counter() - t0, 1)

    kp = KeyPair.generate()
    items = [
        (kp.public, b"mc%d" % i, kp.sign(b"mc%d" % i)) for i in range(BUCKET)
    ]
    # data_mesh(1) at n=1: the curve isolates device-count scaling on ONE
    # code path (the staged mesh pipeline) instead of comparing the
    # monolithic single-chip kernel against the staged one.
    mesh = data_mesh(n_devices)
    verifier = TpuVerifier(
        max_bucket=BUCKET,
        msm_min_bucket=16,
        mode="msm",
        fixed_bucket=True,
        mesh=mesh,
    )
    t0 = time.perf_counter()
    ok = verifier(items)  # first dispatch: trace + compile + run
    compile_wall = time.perf_counter() - t0
    if not all(ok):
        raise SystemExit("sharded verifier rejected a valid batch")

    # Steady state: pipelined submit/collect pairs (depth 2), median of
    # timed windows — the same shape bench.py's e2e loop uses, minus the
    # tunnel. On virtual CPU devices this is a 1-core aggregate.
    handles = [verifier.submit(items) for _ in range(2)]
    rates = []
    for _ in range(5):
        t0 = time.perf_counter()
        for _ in range(2):
            out = verifier.collect(handles.pop(0))
            if not all(out):
                raise SystemExit("steady-state verify verdicts changed")
            handles.append(verifier.submit(items))
        rates.append(2 * BUCKET / (time.perf_counter() - t0))
    for h in handles:
        verifier.collect(h)
    rates.sort()

    result.update(
        {
            "bucket": BUCKET,
            "verify_per_s": round(rates[len(rates) // 2], 1),
            "verify_per_s_min": round(rates[0], 1),
            "verify_per_s_max": round(rates[-1], 1),
            "first_dispatch_wall_s": round(compile_wall, 1),
            "compile_walls_s": kernel_registry.compile_walls_by_shape(),
            "compile_walls_detail": kernel_registry.compile_walls(),
            "total_wall_s": round(time.perf_counter() - t_start, 1),
        }
    )
    print(MARK + json.dumps(result), flush=True)


def main(argv: list[str] | None = None) -> None:
    argv = sys.argv[1:] if argv is None else argv
    if "--leg" in argv:
        i = argv.index("--leg")
        leg_main(int(argv[i + 1]), "--dryrun" in argv)
        return

    cache_dir = os.environ.get(
        "NARWHAL_JAX_CACHE_DIR", os.path.join(REPO, ".jax_cache_multichip")
    )
    legs = []
    for n in (1, 2, 4, 8):
        legs.append(_run_leg(n, dryrun=(n == 8), cache_dir=cache_dir))
        print(
            f"[multichip] n={n} rc={legs[-1]['rc']} "
            f"verify/s={legs[-1].get('verify_per_s')} "
            f"wall={legs[-1]['wall_s']}s",
            flush=True,
        )
    # Warm-cache rerun of the acceptance leg: with the persistent cache
    # populated, the same process-fresh 8-device leg must be dominated by
    # deserialization, proving the once-per-container compile claim (and
    # exercising the r5 cache-load crash path deliberately, in a
    # subprocess, where a loader crash would surface as rc != 0).
    warm = _run_leg(8, dryrun=True, cache_dir=cache_dir)
    print(
        f"[multichip] n=8 (warm cache) rc={warm['rc']} wall={warm['wall_s']}s",
        flush=True,
    )

    base = next((l.get("verify_per_s") for l in legs if l["n_devices"] == 1), None)
    curve = {
        str(l["n_devices"]): (
            round(l["verify_per_s"] / base, 2)
            if base and l.get("verify_per_s")
            else None
        )
        for l in legs
    }
    payload = {
        "metric": "multichip_verify_scaling",
        "bucket": BUCKET,
        "legs": legs,
        "warm_cache_leg": warm,
        "scaling_vs_1_device": curve,
        "ok": all(l["rc"] == 0 for l in legs) and warm["rc"] == 0,
        "note": (
            "All device counts are VIRTUAL CPU devices "
            "(--xla_force_host_platform_device_count) sharing this "
            "container's single physical core, so aggregate verify "
            "throughput cannot exceed the 1-core rate at any device count "
            "— the measured curve validates compile scaling (per-shape "
            "walls recorded per leg; registry guarantees one compile per "
            "(kernel, mesh shape)), sharding correctness and dispatch "
            "overhead, not silicon scaling. Roofline for a real 8-chip "
            "part: the staged msm pipeline is embarrassingly parallel "
            "over the data axis except one [4, NLIMB, W] cross-device "
            "reduce per bucket (~"
            + str(4 * 20 * 64 * 4)
            + " bytes/device) and the shared host Horner epilogue "
            "(~9 ms per 32k batch, BENCH_r05), so device-only scaling is "
            "min(K, device_rate*K / epilogue_rate): with BENCH_r05's "
            "286k/s single-chip device rate and the 3.6M/s epilogue "
            "ceiling (32768/9.14ms), 8 chips project to ~8x device "
            "compute, epilogue-capped at ~12.5x - i.e. >=4x at 8 devices "
            "holds on real silicon; this 1-core container measures ~1x "
            "by construction."
        ),
    }
    os.makedirs(os.path.dirname(RESULTS), exist_ok=True)
    with open(RESULTS, "w") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")
    print(f"[multichip] wrote {RESULTS} ok={payload['ok']}", flush=True)
    sys.path.insert(0, REPO)
    from tools.perf import ledger as perf_ledger

    perf_ledger.append("multichip", payload, argv=argv)
    if not payload["ok"]:
        raise SystemExit(1)


if __name__ == "__main__":
    main()
