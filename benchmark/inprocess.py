"""In-process committee benchmark: the whole committee as asyncio tasks in
ONE process (the test harness Cluster, reference
test_utils/src/cluster.rs:31-793), with rate-controlled load and
executed-transaction measurement.

Two reasons this exists next to the multi-process LocalBench:

1. Committee scaling on small hosts. A 20-node LocalBench spawns 60+
   Python processes; on a 1-2 core host the measurement is dominated by
   scheduler thrash, not the protocol. One asyncio process loses far less
   to context switching, so larger committees produce meaningful numbers.
2. TPU backends. Only one process can own the (tunneled) chip, so the
   crypto/DAG offload backends can serve a whole in-process committee —
   the only way on this host to measure offload as *system* throughput.

    python -m benchmark.inprocess --nodes 20 --rate 1000 --duration 40
    python -m benchmark.inprocess --nodes 20 --crypto-backend tpu ...

Emits one JSON record (tps/latency percentiles/config) on stdout and
optionally appends it to --out.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import statistics
import sys
import time


async def run_bench(args) -> dict:
    from narwhal_tpu.cluster import Cluster
    from narwhal_tpu.messages import SubmitTransactionStreamMsg
    from narwhal_tpu.network import NetworkClient

    from narwhal_tpu.config import Parameters

    if args.crypto_backend == "tpu" and not args.no_precompile:
        # Warm the merged-flush bucket ladder BEFORE the committee boots:
        # an in-protocol first compile (minutes, uncached) would otherwise
        # land inside the measurement window. One-time per machine — the
        # persistent .jax_cache serves later runs in seconds.
        from narwhal_tpu.tpu.verifier import VerifyService

        svc = VerifyService.shared("msm")  # Cluster defaults tpu->cofactored
        t0 = time.time()
        # One shape only: the service runs fixed-bucket, so this single
        # warm covers every flush (and the msm fallback kernel for
        # adversarial input).
        print(
            f"precompiling verify bucket {svc.verifier.max_bucket}...",
            file=sys.stderr,
        )
        svc.verifier.precompile((svc.verifier.max_bucket,))
        print(f"precompile done in {time.time() - t0:.0f}s", file=sys.stderr)

    cluster = Cluster(
        size=args.nodes,
        workers=args.workers,
        parameters=Parameters(
            max_header_delay=args.max_header_delay,
            max_batch_delay=args.max_batch_delay,
            # The whole in-process fleet shares one backend, so a tpu run
            # can uniformly use the cofactored accept set — the msm batch
            # kernel, the mode the precompile above warmed. (An explicit
            # Parameters bypasses Cluster's same-reasoning default.)
            verify_rule=(
                "cofactored" if args.crypto_backend == "tpu" else "strict"
            ),
            cert_format=args.cert_format,
        ),
        crypto_backend=args.crypto_backend,
        dag_backend=args.dag_backend,
        dag_shards=args.dag_shards,
        consensus_protocol=args.consensus_protocol,
    )
    await cluster.start(args.nodes - args.faults)
    await cluster.assert_progress(commit_threshold=2, timeout=args.warmup_timeout)

    alive = args.nodes - args.faults
    executed = [0] * alive
    # Per-node execution-order prefixes (first 9 bytes identify a sample
    # tx): compared up to the shortest node so in-flight tails at cancel
    # time can't fake a divergence, and count-only equality can't hide one.
    orders: list[list[bytes]] = [[] for _ in range(alive)]
    latencies: list[float] = []
    sent_at: dict[int, float] = {}

    async def drain(i: int) -> None:
        ch = cluster.authorities[i].primary.tx_execution_output
        while True:
            _, tx = await ch.recv()
            executed[i] += 1
            orders[i].append(bytes(tx[:9]))
            # Sample txs carry a sequence id (benchmark_client format:
            # 0x00 + u64 counter) for end-to-end latency.
            if i == 0 and tx[:1] == b"\x00":
                sid = int.from_bytes(tx[1:9], "big")
                t0 = sent_at.pop(sid, None)
                if t0 is not None:
                    latencies.append(time.time() - t0)

    drains = [asyncio.ensure_future(drain(i)) for i in range(alive)]
    client = NetworkClient()
    lanes = [
        cluster.authorities[i].worker_transactions_address(wid)
        for i in range(alive)
        for wid in range(args.workers)
    ]
    share = max(1, args.rate // len(lanes))
    next_sid = 0
    # Admission-control accounting: bursts the worker explicitly refused
    # (RESOURCE_EXHAUSTED) vs transport hiccups. Shed bursts are the
    # intended overload behavior, counted rather than logged per event.
    shed = {"bursts": 0, "txs": 0, "errors": 0}

    async def inject(lane: str) -> None:
        nonlocal next_sid
        end = time.time() + args.duration
        while time.time() < end:
            tick = time.time()
            txs = []
            for _ in range(share):
                next_sid += 1
                sid = next_sid
                sent_at[sid] = time.time()
                txs.append(
                    b"\x00" + sid.to_bytes(8, "big") + b"\x01" * (args.tx_size - 9)
                )
            try:
                await client.request(lane, SubmitTransactionStreamMsg(tuple(txs)))
            except Exception as e:
                if "RESOURCE_EXHAUSTED" in str(e):
                    shed["bursts"] += 1
                    shed["txs"] += len(txs)
                else:  # lane hiccup: drop this tick's share
                    shed["errors"] += 1
                    print(f"inject {lane}: {e}", file=sys.stderr)
                # Either way this tick's samples never entered the system.
                for tx in txs:
                    sent_at.pop(int.from_bytes(tx[1:9], "big"), None)
            await asyncio.sleep(max(0.0, 1.0 - (time.time() - tick)))

    from narwhal_tpu.network.rpc import WireStats

    def primary_sent_by_type(a) -> dict[str, float]:
        m = a.primary.registry.get("wire_bytes_sent_total")
        if m is None:
            return {}
        return {k[0]: c.value for k, c in m._children.items()}

    t_start = time.time()
    rounds_start = {
        a.name: a.metric("consensus_last_committed_round")
        for a in cluster.authorities[:alive]
    }
    wire_start = WireStats.snapshot()
    egress_start = [primary_sent_by_type(a) for a in cluster.authorities[:alive]]
    await asyncio.gather(*(inject(lane) for lane in lanes))
    await asyncio.sleep(args.drain_tail)
    window = time.time() - t_start
    wire_end = WireStats.snapshot()
    # Committed protocol rounds during the window: at committee sizes where
    # this 1-core host cannot push transactions through inside any window
    # (N=50: each round is ~7.5k signed control messages), rounds/s is the
    # meaningful backend-comparison metric.
    rounds_end = {
        a.name: a.metric("consensus_last_committed_round")
        for a in cluster.authorities[:alive]
    }
    committed_rounds = max(
        rounds_end[k] - rounds_start.get(k, 0) for k in rounds_end
    )
    # Per-PRIMARY egress from the per-link wire metrics (the quantity the
    # fanout tree + delta headers attack), by message type.
    egress_end = [primary_sent_by_type(a) for a in cluster.authorities[:alive]]
    egress_delta_by_type: dict[str, float] = {}
    egress_per_node = []
    for before, after in zip(egress_start, egress_end):
        node_total = 0.0
        for msg_type, value in after.items():
            d = value - before.get(msg_type, 0.0)
            node_total += d
            egress_delta_by_type[msg_type] = (
                egress_delta_by_type.get(msg_type, 0.0) + d
            )
        egress_per_node.append(node_total)
    mean_egress = sum(egress_per_node) / max(1, len(egress_per_node))
    for d in drains:
        d.cancel()
    client.close()
    # Embed node 0's scrape (counters/gauges + histogram sums) so the
    # results record is self-contained: any later A/B can recompute stage
    # means and wire rates without rerunning the bench.
    from narwhal_tpu.metrics import scrape_snapshot

    telemetry = {
        "primary-0": scrape_snapshot(cluster.authorities[0].primary.registry),
        "worker-0-0": scrape_snapshot(
            cluster.authorities[0].workers[0].registry
        ),
    }
    await cluster.shutdown()

    tps = executed[0] / window if executed[0] else 0.0
    wire_sent = wire_end["bytes_sent"] - wire_start["bytes_sent"]
    wire_frames = wire_end["frames_sent"] - wire_start["frames_sent"]
    lat_sorted = sorted(latencies)

    def pct(p: float) -> float:
        if not lat_sorted:
            return 0.0
        return lat_sorted[min(len(lat_sorted) - 1, int(p * len(lat_sorted)))]

    return {
        "mode": "in-process",
        "committee_size": args.nodes,
        "workers_per_node": args.workers,
        "faults": args.faults,
        "input_rate": args.rate,
        "tx_size": args.tx_size,
        "duration_s": round(window, 1),
        "consensus_protocol": args.consensus_protocol,
        "crypto_backend": args.crypto_backend,
        "dag_backend": args.dag_backend,
        "dag_shards": args.dag_shards,
        "cert_format": args.cert_format,
        "verify_rule": "cofactored" if args.crypto_backend == "tpu" else "strict",
        "executed_tps": round(tps, 1),
        "executed_total": executed[0],
        "committed_rounds_in_window": round(committed_rounds, 1),
        "committed_rounds_per_s": round(committed_rounds / window, 4),
        # Control-plane wire accounting (bytes-per-round is the quantity
        # the compact certificate form targets at byte-bound committees).
        "wire_bytes_sent_in_window": wire_sent,
        "wire_frames_sent_in_window": wire_frames,
        "wire_bytes_per_round": (
            round(wire_sent / committed_rounds, 1) if committed_rounds else None
        ),
        "wire_frames_per_round": (
            round(wire_frames / committed_rounds, 1) if committed_rounds else None
        ),
        # Per-PRIMARY control-plane egress (mean across nodes) from the
        # wire_bytes_sent_total{msg_type=} metrics — the r9 wire-diet
        # acceptance metric — plus the committee-wide breakdown by type.
        "primary_egress_bytes_per_round": (
            round(mean_egress / committed_rounds, 1) if committed_rounds else None
        ),
        "primary_egress_bytes_by_msg_type": {
            k: round(v, 1) for k, v in sorted(egress_delta_by_type.items())
        },
        "relay_fanout": os.environ.get("NARWHAL_RELAY_FANOUT", "default"),
        "header_wire": os.environ.get("NARWHAL_HEADER_WIRE", "default"),
        "identical_execution_prefix": (
            (lambda L: all(o[:L] == orders[0][:L] for o in orders))(
                min(len(o) for o in orders)
            )
            if orders
            else True
        ),
        "compared_prefix_len": min(len(o) for o in orders) if orders else 0,
        "e2e_latency_p50_ms": round(pct(0.50) * 1000, 1),
        "e2e_latency_p90_ms": round(pct(0.90) * 1000, 1),
        "e2e_latency_p95_ms": round(pct(0.95) * 1000, 1),
        "e2e_latency_p99_ms": round(pct(0.99) * 1000, 1),
        "latency_samples": len(lat_sorted),
        # Admission control: offered vs admitted load. delivered_rate is
        # what actually entered the system after shedding — under deliberate
        # overload the headline is bounded p50 at this rate, not the
        # offered one.
        "shed_bursts": shed["bursts"],
        "shed_txs": shed["txs"],
        "inject_errors": shed["errors"],
        "delivered_rate": round(
            max(0.0, args.rate - shed["txs"] / max(args.duration, 1e-9)), 1
        ),
        "pacing": os.environ.get("NARWHAL_PACING", "1") not in ("0", "false", "off"),
        "ingest_policy": os.environ.get("NARWHAL_INGEST_POLICY", "shed"),
        "trace": os.environ.get("NARWHAL_TRACE", "0"),
        "trace_sample": os.environ.get("NARWHAL_TRACE_SAMPLE", "1.0"),
        "telemetry_scrape": telemetry,
    }


def main() -> None:
    ap = argparse.ArgumentParser(prog="benchmark.inprocess")
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--workers", type=int, default=1)
    ap.add_argument("--rate", type=int, default=1_000)
    ap.add_argument("--tx-size", type=int, default=512)
    ap.add_argument("--duration", type=int, default=30)
    ap.add_argument("--drain-tail", type=float, default=5.0)
    ap.add_argument("--max-header-delay", type=float, default=0.05)
    ap.add_argument("--max-batch-delay", type=float, default=0.05)
    ap.add_argument("--warmup-timeout", type=float, default=120.0,
                    help="boot-to-first-commits window (TPU backends pay a\n"
                    "first-compile + tunnel-RTT warmup)")
    ap.add_argument("--faults", type=int, default=0)
    ap.add_argument("--consensus-protocol", choices=("bullshark", "tusk"),
                    default="bullshark")
    ap.add_argument("--crypto-backend", choices=("cpu", "pool", "tpu"),
                    default="cpu")
    ap.add_argument("--dag-backend", choices=("cpu", "tpu"), default="cpu")
    ap.add_argument("--dag-shards", type=int, default=1)
    ap.add_argument("--cert-format", choices=("full", "compact"),
                    default="compact",
                    help="certificate wire form (compact = half-aggregated "
                    "proofs broadcast by reference — the committee default; "
                    "full = the per-signer opt-out)")
    ap.add_argument("--no-precompile", action="store_true",
                    help="skip the tpu verify-bucket warmup before boot")
    ap.add_argument("--out", default=None,
                    help="append the JSON record to this file")
    args = ap.parse_args()

    record = asyncio.run(run_bench(args))
    print(json.dumps(record))
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        existing = []
        if os.path.exists(args.out):
            with open(args.out) as f:
                existing = json.load(f)
        existing.append(record)
        with open(args.out, "w") as f:
            json.dump(existing, f, indent=2)
    from tools.perf import ledger as perf_ledger

    perf_ledger.append(
        "inprocess", record,
        scrape=record.get("telemetry_scrape"), argv=sys.argv[1:],
    )


if __name__ == "__main__":
    main()
