"""Microbenchmarks: the criterion analogs.

Reference: /root/reference/types/benches/batch_digest.rs:10-37 (digesting a
serialized batch with vs without deserialization) and
consensus/benches/process_certificates.rs:18-80 (Bullshark certificate
processing over synthetic DAGs, with pprof flamegraphs).

    python -m benchmark.microbench            # all, one JSON line each
    python -m benchmark.microbench --profile  # + cProfile top functions

For whole-node profiles, run any role (or the local bench) with
NARWHAL_PROFILE=<dir>: every process dumps a cProfile .pstats on exit
(`python -m pstats <file>` or snakeviz to inspect) — the dhat/pprof plane.
"""

from __future__ import annotations

import argparse
import cProfile
import io
import json
import pstats
import time


def bench_batch_digest() -> list[dict]:
    """Serialized-batch digest vs decode-then-digest (batch_digest.rs)."""
    from narwhal_tpu.types import Batch, serialized_batch_digest

    batch = Batch(tuple(bytes([i % 256]) * 512 for i in range(1000)))
    raw = batch.to_bytes()
    out = []
    for name, fn in (
        ("serialized_batch_digest", lambda: serialized_batch_digest(raw)),
        ("decode_then_digest", lambda: Batch.from_bytes(raw).digest),
    ):
        fn()
        n, t0 = 0, time.perf_counter()
        while time.perf_counter() - t0 < 1.0:
            fn()
            n += 1
        dt = (time.perf_counter() - t0) / n
        out.append(
            {
                "metric": f"batch_digest_GBps[{name}]",
                "value": round(len(raw) / dt / 1e9, 3),
                "unit": "GB/s",
                "batch_bytes": len(raw),
            }
        )
    return out


def bench_process_certificates(size: int = 20, rounds: int = 50) -> list[dict]:
    """Bullshark + Tusk certificate processing over an optimal synthetic DAG
    (process_certificates.rs shape)."""
    from narwhal_tpu.consensus import Bullshark, ConsensusState, Tusk
    from narwhal_tpu.fixtures import CommitteeFixture, make_optimal_certificates
    from narwhal_tpu.stores import NodeStorage
    from narwhal_tpu.types import Certificate

    f = CommitteeFixture(size=size)
    genesis = {c.digest for c in Certificate.genesis(f.committee)}
    certs, _ = make_optimal_certificates(f.committee, 1, rounds, genesis)
    certs = list(certs)
    out = []
    for name, engine_cls in (("bullshark", Bullshark), ("tusk", Tusk)):
        engine = engine_cls(f.committee, NodeStorage(None).consensus_store, 50)
        state = ConsensusState(Certificate.genesis(f.committee))
        index = 0
        t0 = time.perf_counter()
        for c in certs:
            outp = engine.process_certificate(state, index, c)
            index += len(outp)
        dt = time.perf_counter() - t0
        out.append(
            {
                "metric": f"process_certificates_per_s[{name}]",
                "value": round(len(certs) / dt, 1),
                "unit": "certs/s",
                "committee": size,
                "rounds": rounds,
            }
        )
    return out


def bench_dag_service(
    sizes=(20, 50, 100), rounds: int = 24, concurrency: int = 16
) -> list[dict]:
    """External Dag service read_causal across committee sizes: host BFS,
    forced device reach_mask (sequential = the kernel+RTT truth, and
    `concurrency` coalesced readers sharing one fused dispatch), and the
    shipped adaptive measured-crossover routing (VERDICT r4 item 5 — the
    device path must never be *preferred* where it measures slower)."""
    import asyncio

    from narwhal_tpu.consensus.dag import Dag
    from narwhal_tpu.fixtures import CommitteeFixture, mock_certificate
    from narwhal_tpu.types import Certificate

    out = []
    for size in sizes:
        f = CommitteeFixture(size=size)
        keys = f.committee.authority_keys()
        prev = [c.digest for c in Certificate.genesis(f.committee)]
        certs = []
        # Payload-bearing certificates: empty-payload vertices are
        # compressible and the host walk would collapse to O(1) — the real
        # serving workload reports full causal histories.
        for r in range(1, rounds + 1):
            cur = []
            for i, pk in enumerate(keys):
                c = mock_certificate(
                    f.committee, pk, r, set(prev),
                    payload={bytes([r % 256, i % 256]) * 16: 0},
                )
                cur.append(c)
            certs.extend(cur)
            prev = [c.digest for c in cur]

        async def make_dag(backend: str, policy: str) -> tuple:
            kw = {} if backend == "cpu" else {"policy": policy}
            dag = Dag(f.committee, backend=backend, window=rounds + 8, **kw)
            for c in certs:
                await dag.insert(c)
            tips = certs[-size:]
            await dag.read_causal(tips[-1].digest)  # warm the host path
            if backend == "tpu":
                # Warm the device kernel OUTSIDE the timed window for
                # every policy: the adaptive router serves its first
                # requests from the host, so without this the kpad=1 jit
                # compile would land inside the measurement and inflate
                # the very metric the routing policy is judged on.
                async with dag._lock:
                    pos = dag._dev_eligible(tips[-1].digest)
                    if pos is not None:
                        dag._device_causal_many([(tips[-1].digest, pos)])
                        dag._dev_warmed.add(1)
            return dag, tips

        async def run_seq(backend: str, policy: str = "adaptive"):
            dag, tips = await make_dag(backend, policy)
            n, t0 = 0, time.perf_counter()
            while time.perf_counter() - t0 < 1.0:
                await dag.read_causal(tips[-1].digest)
                n += 1
            return (time.perf_counter() - t0) / n, dag.routing_stats()

        async def run_coalesced(c_readers: int):
            dag, tips = await make_dag("tpu", "device")
            starts = [tips[i % len(tips)].digest for i in range(c_readers)]
            # Untimed first fused gather: compiles the c_readers-wide kpad.
            await asyncio.gather(*(dag.read_causal(s) for s in starts))
            n, t0 = 0, time.perf_counter()
            while time.perf_counter() - t0 < 1.0:
                await asyncio.gather(*(dag.read_causal(s) for s in starts))
                n += c_readers
            return (time.perf_counter() - t0) / n, dag.routing_stats()

        runs = [
            ("cpu", lambda: run_seq("cpu")),
            ("tpu-device", lambda: run_seq("tpu", "device")),
            ("tpu-adaptive", lambda: run_seq("tpu", "adaptive")),
            (
                f"tpu-coalesced{concurrency}",
                lambda: run_coalesced(concurrency),
            ),
        ]
        for label, fn in runs:
            dt, stats = asyncio.run(fn())
            out.append(
                {
                    "metric": f"dag_service_read_causal_ms[{label}]",
                    "value": round(dt * 1000, 3),
                    "unit": "ms/call",
                    "committee": size,
                    "rounds": rounds,
                    "routing": stats,
                }
            )
    return out


def bench_codec() -> list[dict]:
    """Message encode/decode throughput on a payload-bearing header."""
    from narwhal_tpu.fixtures import CommitteeFixture
    from narwhal_tpu.messages import HeaderMsg, Writer, decode_message, encode_message

    f = CommitteeFixture(size=4)
    payload = {bytes([i]) * 32: 0 for i in range(32)}
    msg = HeaderMsg(f.header(author=0, round=1, payload=payload))
    tag, body = encode_message(msg)

    def encode_fresh():
        w = Writer()
        msg.encode(w)  # bypass the per-object memo: measure the real encoder
        return w.finish()

    out = []
    for name, fn in (
        ("encode", encode_fresh),
        ("decode", lambda: decode_message(tag, body)),
    ):
        fn()
        n, t0 = 0, time.perf_counter()
        while time.perf_counter() - t0 < 0.5:
            fn()
            n += 1
        dt = (time.perf_counter() - t0) / n
        out.append(
            {
                "metric": f"header_codec_per_s[{name}]",
                "value": round(1 / dt, 1),
                "unit": "ops/s",
                "wire_bytes": len(body),
            }
        )
    return out


def main() -> None:
    ap = argparse.ArgumentParser(prog="benchmark.microbench")
    ap.add_argument("--profile", action="store_true", help="cProfile the consensus bench")
    ap.add_argument("--dag-service", action="store_true",
                    help="also run the Dag-service read_causal cpu-vs-tpu bench")
    args = ap.parse_args()
    for rec in bench_batch_digest() + bench_codec() + bench_process_certificates():
        print(json.dumps(rec))
    if args.dag_service:
        for rec in bench_dag_service():
            print(json.dumps(rec))
    if args.profile:
        prof = cProfile.Profile()
        prof.enable()
        bench_process_certificates()
        prof.disable()
        s = io.StringIO()
        pstats.Stats(prof, stream=s).sort_stats("cumulative").print_stats(15)
        print(s.getvalue())


if __name__ == "__main__":
    main()
