"""Microbenchmarks: the criterion analogs.

Reference: /root/reference/types/benches/batch_digest.rs:10-37 (digesting a
serialized batch with vs without deserialization) and
consensus/benches/process_certificates.rs:18-80 (Bullshark certificate
processing over synthetic DAGs, with pprof flamegraphs).

    python -m benchmark.microbench            # all, one JSON line each
    python -m benchmark.microbench --profile  # + cProfile top functions

For whole-node profiles, run any role (or the local bench) with
NARWHAL_PROFILE=<dir>: every process dumps a cProfile .pstats on exit
(`python -m pstats <file>` or snakeviz to inspect) — the dhat/pprof plane.
"""

from __future__ import annotations

import argparse
import cProfile
import io
import json
import pstats
import time


def bench_batch_digest() -> list[dict]:
    """Serialized-batch digest vs decode-then-digest (batch_digest.rs)."""
    from narwhal_tpu.types import Batch, serialized_batch_digest

    batch = Batch(tuple(bytes([i % 256]) * 512 for i in range(1000)))
    raw = batch.to_bytes()
    out = []
    for name, fn in (
        ("serialized_batch_digest", lambda: serialized_batch_digest(raw)),
        ("decode_then_digest", lambda: Batch.from_bytes(raw).digest),
    ):
        fn()
        n, t0 = 0, time.perf_counter()
        while time.perf_counter() - t0 < 1.0:
            fn()
            n += 1
        dt = (time.perf_counter() - t0) / n
        out.append(
            {
                "metric": f"batch_digest_GBps[{name}]",
                "value": round(len(raw) / dt / 1e9, 3),
                "unit": "GB/s",
                "batch_bytes": len(raw),
            }
        )
    return out


def bench_process_certificates(size: int = 20, rounds: int = 50) -> list[dict]:
    """Bullshark + Tusk certificate processing over an optimal synthetic DAG
    (process_certificates.rs shape)."""
    from narwhal_tpu.consensus import Bullshark, ConsensusState, Tusk
    from narwhal_tpu.fixtures import CommitteeFixture, make_optimal_certificates
    from narwhal_tpu.stores import NodeStorage
    from narwhal_tpu.types import Certificate

    f = CommitteeFixture(size=size)
    genesis = {c.digest for c in Certificate.genesis(f.committee)}
    certs, _ = make_optimal_certificates(f.committee, 1, rounds, genesis)
    certs = list(certs)
    out = []
    for name, engine_cls in (("bullshark", Bullshark), ("tusk", Tusk)):
        engine = engine_cls(f.committee, NodeStorage(None).consensus_store, 50)
        state = ConsensusState(Certificate.genesis(f.committee))
        index = 0
        t0 = time.perf_counter()
        for c in certs:
            outp = engine.process_certificate(state, index, c)
            index += len(outp)
        dt = time.perf_counter() - t0
        out.append(
            {
                "metric": f"process_certificates_per_s[{name}]",
                "value": round(len(certs) / dt, 1),
                "unit": "certs/s",
                "committee": size,
                "rounds": rounds,
            }
        )
    return out


def bench_dag_service(
    sizes=(20, 50, 100), rounds: int = 24, concurrencies=(1, 4, 16)
) -> list[dict]:
    """External Dag service read_causal across (committee size, concurrent
    readers): host BFS, forced device reach_mask over the RESIDENT window
    (concurrent readers coalesce into one fused dispatch), and the shipped
    adaptive cost-model routing (ISSUE 1 — the device path must win at
    some measured (size, concurrency) point or be retired; the router must
    never *prefer* the slower path either way)."""
    import asyncio

    from narwhal_tpu.consensus.dag import Dag
    from narwhal_tpu.fixtures import CommitteeFixture, mock_certificate
    from narwhal_tpu.types import Certificate

    out = []
    for size in sizes:
        f = CommitteeFixture(size=size)
        keys = f.committee.authority_keys()
        prev = [c.digest for c in Certificate.genesis(f.committee)]
        certs = []
        # Payload-bearing certificates: empty-payload vertices are
        # compressible and the host walk would collapse to O(1) — the real
        # serving workload reports full causal histories.
        for r in range(1, rounds + 1):
            cur = []
            for i, pk in enumerate(keys):
                c = mock_certificate(
                    f.committee, pk, r, set(prev),
                    payload={bytes([r % 256, i % 256]) * 16: 0},
                )
                cur.append(c)
            certs.extend(cur)
            prev = [c.digest for c in cur]

        async def make_dag(backend: str, policy: str) -> tuple:
            kw = {} if backend == "cpu" else {"policy": policy}
            dag = Dag(f.committee, backend=backend, window=rounds + 8, **kw)
            for c in certs:
                await dag.insert(c)
            tips = certs[-size:]
            await dag.read_causal(tips[-1].digest)  # warm the host path
            return dag, tips

        async def run_conc(backend: str, policy: str, c_readers: int):
            """ms/call at `c_readers` concurrent readers per burst (the
            device path fuses each burst into one dispatch; the host path
            serves it sequentially under the service lock)."""
            dag, tips = await make_dag(backend, policy)
            starts = [tips[-1 - (i % len(tips))].digest for i in range(c_readers)]
            # Untimed warm bursts: compile the burst-width kpad (and the
            # resident-window sync kernels) outside the measurement, and
            # give the adaptive router its first measurements of each path.
            for _ in range(3):
                await asyncio.gather(*(dag.read_causal(s) for s in starts))
            n, t0 = 0, time.perf_counter()
            while time.perf_counter() - t0 < 0.8:
                await asyncio.gather(*(dag.read_causal(s) for s in starts))
                n += c_readers
            return (time.perf_counter() - t0) / n, dag.routing_stats()

        variants = [
            ("cpu", "cpu", "adaptive"),
            ("tpu-device", "tpu", "device"),
            ("tpu-adaptive", "tpu", "adaptive"),
        ]
        for conc in concurrencies:
            for label, backend, policy in variants:
                dt, stats = asyncio.run(run_conc(backend, policy, conc))
                out.append(
                    {
                        "metric": f"dag_service_read_causal_ms[{label}]",
                        "value": round(dt * 1000, 3),
                        "unit": "ms/call",
                        "committee": size,
                        "rounds": rounds,
                        "concurrency": conc,
                        "backend": _jax_backend(),
                        "routing": stats,
                    }
                )
    return out


def _jax_backend() -> str:
    try:
        import jax

        return jax.default_backend()
    except Exception:
        return "unknown"


def bench_codec() -> list[dict]:
    """Message encode/decode throughput on a payload-bearing header."""
    from narwhal_tpu.fixtures import CommitteeFixture
    from narwhal_tpu.messages import HeaderMsg, Writer, decode_message, encode_message

    f = CommitteeFixture(size=4)
    payload = {bytes([i]) * 32: 0 for i in range(32)}
    msg = HeaderMsg(f.header(author=0, round=1, payload=payload))
    tag, body = encode_message(msg)

    def encode_fresh():
        w = Writer()
        msg.encode(w)  # bypass the per-object memo: measure the real encoder
        return w.finish()

    out = []
    for name, fn in (
        ("encode", encode_fresh),
        ("decode", lambda: decode_message(tag, body)),
    ):
        fn()
        n, t0 = 0, time.perf_counter()
        while time.perf_counter() - t0 < 0.5:
            fn()
            n += 1
        dt = (time.perf_counter() - t0) / n
        out.append(
            {
                "metric": f"header_codec_per_s[{name}]",
                "value": round(1 / dt, 1),
                "unit": "ops/s",
                "wire_bytes": len(body),
            }
        )
    return out


def main() -> None:
    ap = argparse.ArgumentParser(prog="benchmark.microbench")
    ap.add_argument("--profile", action="store_true", help="cProfile the consensus bench")
    ap.add_argument("--dag-service", action="store_true",
                    help="also run the Dag-service read_causal cpu-vs-tpu bench")
    ap.add_argument("--out", default=None,
                    help="also write the selected benches as a JSON array to this path")
    args = ap.parse_args()
    rows = []
    if not args.dag_service:
        rows += bench_batch_digest() + bench_codec() + bench_process_certificates()
    else:
        rows += bench_dag_service()
    for rec in rows:
        print(json.dumps(rec))
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(rows, fh, indent=2)
            fh.write("\n")
    if args.profile:
        prof = cProfile.Profile()
        prof.enable()
        bench_process_certificates()
        prof.disable()
        s = io.StringIO()
        pstats.Stats(prof, stream=s).sort_stats("cumulative").print_stats(15)
        print(s.getvalue())


if __name__ == "__main__":
    main()
