"""Microbenchmarks: the criterion analogs.

Reference: /root/reference/types/benches/batch_digest.rs:10-37 (digesting a
serialized batch with vs without deserialization) and
consensus/benches/process_certificates.rs:18-80 (Bullshark certificate
processing over synthetic DAGs, with pprof flamegraphs).

    python -m benchmark.microbench            # all, one JSON line each
    python -m benchmark.microbench --profile  # + cProfile top functions

For whole-node profiles, run any role (or the local bench) with
NARWHAL_PROFILE=<dir>: every process dumps a cProfile .pstats on exit
(`python -m pstats <file>` or snakeviz to inspect) — the dhat/pprof plane.
"""

from __future__ import annotations

import argparse
import cProfile
import io
import json
import pstats
import time


def _percentiles(samples: list[float], scale: float = 1.0) -> dict:
    """p50/p95/p99 of a sample list (already-collected per-iteration times).
    Means hide the tail that latency work exists to control, so every bench
    that times per-iteration reports these alongside the mean."""
    if not samples:
        return {"p50": None, "p95": None, "p99": None}
    s = sorted(samples)

    def pct(p: float) -> float:
        return round(s[min(len(s) - 1, int(p * len(s)))] * scale, 3)

    return {"p50": pct(0.50), "p95": pct(0.95), "p99": pct(0.99)}


def bench_batch_digest() -> list[dict]:
    """Serialized-batch digest vs decode-then-digest (batch_digest.rs)."""
    from narwhal_tpu.types import Batch, serialized_batch_digest

    batch = Batch(tuple(bytes([i % 256]) * 512 for i in range(1000)))
    raw = batch.to_bytes()
    out = []
    for name, fn in (
        ("serialized_batch_digest", lambda: serialized_batch_digest(raw)),
        ("decode_then_digest", lambda: Batch.from_bytes(raw).digest),
    ):
        fn()
        n, t0 = 0, time.perf_counter()
        while time.perf_counter() - t0 < 1.0:
            fn()
            n += 1
        dt = (time.perf_counter() - t0) / n
        out.append(
            {
                "metric": f"batch_digest_GBps[{name}]",
                "value": round(len(raw) / dt / 1e9, 3),
                "unit": "GB/s",
                "batch_bytes": len(raw),
            }
        )
    return out


def bench_process_certificates(size: int = 20, rounds: int = 50) -> list[dict]:
    """Bullshark + Tusk certificate processing over an optimal synthetic DAG
    (process_certificates.rs shape)."""
    from narwhal_tpu.consensus import Bullshark, ConsensusState, Tusk
    from narwhal_tpu.fixtures import CommitteeFixture, make_optimal_certificates
    from narwhal_tpu.stores import NodeStorage
    from narwhal_tpu.types import Certificate

    f = CommitteeFixture(size=size)
    genesis = {c.digest for c in Certificate.genesis(f.committee)}
    certs, _ = make_optimal_certificates(f.committee, 1, rounds, genesis)
    certs = list(certs)
    out = []
    for name, engine_cls in (("bullshark", Bullshark), ("tusk", Tusk)):
        engine = engine_cls(f.committee, NodeStorage(None).consensus_store, 50)
        state = ConsensusState(Certificate.genesis(f.committee))
        index = 0
        t0 = time.perf_counter()
        for c in certs:
            outp = engine.process_certificate(state, index, c)
            index += len(outp)
        dt = time.perf_counter() - t0
        out.append(
            {
                "metric": f"process_certificates_per_s[{name}]",
                "value": round(len(certs) / dt, 1),
                "unit": "certs/s",
                "committee": size,
                "rounds": rounds,
            }
        )
    return out


def bench_dag_service(
    sizes=(20, 50, 100), rounds: int = 24, concurrencies=(1, 4, 16)
) -> list[dict]:
    """External Dag service read_causal across (committee size, concurrent
    readers): host BFS, forced device reach_mask over the RESIDENT window
    (concurrent readers coalesce into one fused dispatch), and the shipped
    adaptive cost-model routing (ISSUE 1 — the device path must win at
    some measured (size, concurrency) point or be retired; the router must
    never *prefer* the slower path either way)."""
    import asyncio

    from narwhal_tpu.consensus.dag import Dag
    from narwhal_tpu.fixtures import CommitteeFixture, mock_certificate
    from narwhal_tpu.types import Certificate

    out = []
    for size in sizes:
        f = CommitteeFixture(size=size)
        keys = f.committee.authority_keys()
        prev = [c.digest for c in Certificate.genesis(f.committee)]
        certs = []
        # Payload-bearing certificates: empty-payload vertices are
        # compressible and the host walk would collapse to O(1) — the real
        # serving workload reports full causal histories.
        for r in range(1, rounds + 1):
            cur = []
            for i, pk in enumerate(keys):
                c = mock_certificate(
                    f.committee, pk, r, set(prev),
                    payload={bytes([r % 256, i % 256]) * 16: 0},
                )
                cur.append(c)
            certs.extend(cur)
            prev = [c.digest for c in cur]

        async def make_dag(backend: str, policy: str) -> tuple:
            kw = {} if backend == "cpu" else {"policy": policy}
            dag = Dag(f.committee, backend=backend, window=rounds + 8, **kw)
            for c in certs:
                await dag.insert(c)
            tips = certs[-size:]
            await dag.read_causal(tips[-1].digest)  # warm the host path
            return dag, tips

        async def run_conc(backend: str, policy: str, c_readers: int):
            """ms/call at `c_readers` concurrent readers per burst (the
            device path fuses each burst into one dispatch; the host path
            serves it sequentially under the service lock)."""
            dag, tips = await make_dag(backend, policy)
            starts = [tips[-1 - (i % len(tips))].digest for i in range(c_readers)]
            # Untimed warm bursts: compile the burst-width kpad (and the
            # resident-window sync kernels) outside the measurement, and
            # give the adaptive router its first measurements of each path.
            for _ in range(3):
                await asyncio.gather(*(dag.read_causal(s) for s in starts))
            n, t0 = 0, time.perf_counter()
            while time.perf_counter() - t0 < 0.8:
                await asyncio.gather(*(dag.read_causal(s) for s in starts))
                n += c_readers
            return (time.perf_counter() - t0) / n, dag.routing_stats()

        variants = [
            ("cpu", "cpu", "adaptive"),
            ("tpu-device", "tpu", "device"),
            ("tpu-adaptive", "tpu", "adaptive"),
        ]
        for conc in concurrencies:
            for label, backend, policy in variants:
                dt, stats = asyncio.run(run_conc(backend, policy, conc))
                out.append(
                    {
                        "metric": f"dag_service_read_causal_ms[{label}]",
                        "value": round(dt * 1000, 3),
                        "unit": "ms/call",
                        "committee": size,
                        "rounds": rounds,
                        "concurrency": conc,
                        "backend": _jax_backend(),
                        "routing": stats,
                    }
                )
    return out


def bench_storage_group_commit(concurrency: int = 64) -> list[dict]:
    """Group-commit WAL vs the seed per-put flush: `concurrency` single-key
    puts issued together, sync API (one WAL append + flush each, the seed
    hot path) vs put_async (one fused record + one flush per group), at
    BOTH durability levels — `buffered` (seed semantics: flush() to the OS,
    process-crash durable) and `fsync` (machine-crash durable, the level
    where the amortized syscall dominates). The ISSUE-4 acceptance gate:
    the async path must be >=3x for 64 concurrent puts vs per-put flush."""
    import asyncio
    import shutil
    import tempfile

    from narwhal_tpu.storage import StorageEngine, StorageStats

    tmp = tempfile.mkdtemp(prefix="narwhal-storage-bench-")

    async def run_mode(mode: str, fsync: bool, budget: float) -> tuple[float, dict]:
        eng = StorageEngine(
            f"{tmp}/{mode}-{fsync}", use_native=False, fsync=fsync
        )
        cf = eng.column_family("bench")
        value = b"\x5a" * 256
        # warm
        if mode == "sync":
            for i in range(concurrency):
                cf.put(b"w%d" % i, value)
        else:
            await asyncio.gather(
                *(cf.put_async(b"w%d" % i, value) for i in range(concurrency))
            )
        before = StorageStats.snapshot()
        n, t0 = 0, time.perf_counter()
        while time.perf_counter() - t0 < budget:
            if mode == "sync":
                for i in range(concurrency):
                    cf.put(b"k%d" % i, value)
            else:
                await asyncio.gather(
                    *(
                        cf.put_async(b"k%d" % i, value)
                        for i in range(concurrency)
                    )
                )
            n += concurrency
        dt = time.perf_counter() - t0
        after = StorageStats.snapshot()
        eng.close()
        stats = {
            k: after[k] - before[k]
            for k in ("groups_committed", "ops_committed")
        }
        return n / dt, stats

    out = []
    for fsync in (False, True):
        level = "fsync" if fsync else "buffered"
        rates = {}
        for mode in ("sync", "group"):
            budget = 1.0 if not fsync or mode == "group" else 3.0
            rate, stats = asyncio.run(run_mode(mode, fsync, budget))
            rates[mode] = rate
            out.append(
                {
                    "metric": f"storage_puts_per_s[{mode},{level}]",
                    "value": round(rate, 1),
                    "unit": "puts/s",
                    "concurrency": concurrency,
                    **({"group_stats": stats} if mode == "group" else {}),
                }
            )
        out.append(
            {
                "metric": f"storage_group_commit_speedup[{level}]",
                "value": round(rates["group"] / rates["sync"], 2),
                "unit": "x",
                "concurrency": concurrency,
            }
        )
    shutil.rmtree(tmp, ignore_errors=True)
    return out


def bench_rpc_coalesce(k: int = 16) -> list[dict]:
    """Coalesced RPC writes: k requests in flight on one loopback
    connection (frames share socket flushes) vs k strictly sequential
    requests (one write+drain round-trip each)."""
    import asyncio

    from narwhal_tpu.messages import SubmitTransactionMsg
    from narwhal_tpu.network import NetworkClient
    from narwhal_tpu.network.rpc import RpcServer, WireStats

    async def run_bench() -> list[dict]:
        server = RpcServer()

        async def ack(msg, peer):
            return None

        server.route(SubmitTransactionMsg, ack)
        port = await server.start("127.0.0.1", 0)
        addr = f"127.0.0.1:{port}"
        net = NetworkClient()
        msg = SubmitTransactionMsg(b"\x42" * 64)
        await net.unreliable_send(addr, msg)  # connect + warm

        rows = []
        rates = {}
        for mode in ("sequential", "concurrent"):
            before = WireStats.snapshot()
            n, t0 = 0, time.perf_counter()
            while time.perf_counter() - t0 < 1.0:
                if mode == "sequential":
                    for _ in range(k):
                        await net.unreliable_send(addr, msg)
                else:
                    await asyncio.gather(
                        *(net.unreliable_send(addr, msg) for _ in range(k))
                    )
                n += k
            dt = time.perf_counter() - t0
            after = WireStats.snapshot()
            drains = after["drains"] - before["drains"]
            frames = after["frames_sent"] - before["frames_sent"]
            rates[mode] = n / dt
            rows.append(
                {
                    "metric": f"rpc_requests_per_s[{mode}]",
                    "value": round(n / dt, 1),
                    "unit": "reqs/s",
                    "in_flight": 1 if mode == "sequential" else k,
                    "frames_per_drain": round(frames / drains, 2) if drains else None,
                }
            )
        rows.append(
            {
                "metric": "rpc_coalesce_speedup",
                "value": round(rates["concurrent"] / rates["sequential"], 2),
                "unit": "x",
                "in_flight": k,
            }
        )
        net.close()
        await server.stop()
        return rows

    return asyncio.run(run_bench())


def bench_commit_path(
    batches_per_cert=(4, 16, 64), txs_per_batch=32, tx_bytes=128
) -> list[dict]:
    """Commit-to-execution payload staging, the three planes side by side:

    * per-batch   — the seed data plane: one RequestBatchMsg RPC per batch
                    digest (concurrently gathered, but still RPCs = batches);
    * coalesced   — one RequestBatchesMsg per (worker, certificate) group
                    through the real Subscriber staging path (RPCs = 1);
    * prefetch-warm — the Prefetcher already staged the payload at
                    certificate-acceptance time; commit staging is a pure
                    local store read (RPCs = 0).

    Reports ms/certificate and fetch RPCs per certificate for each mode —
    the ISSUE-5 acceptance gate is >=8x fewer RPCs per committed certificate
    for coalesced vs per-batch at 16 batches/cert."""
    import asyncio

    from narwhal_tpu.channels import Channel
    from narwhal_tpu.executor.prefetcher import Prefetcher
    from narwhal_tpu.executor.subscriber import Subscriber
    from narwhal_tpu.fixtures import CommitteeFixture
    from narwhal_tpu.messages import (
        RequestBatchesMsg,
        RequestBatchMsg,
        RequestedBatchesMsg,
        RequestedBatchMsg,
    )
    from narwhal_tpu.network import NetworkClient, RpcServer
    from narwhal_tpu.stores import NodeStorage
    from narwhal_tpu.types import Batch, ConsensusOutput

    async def run_point(n_batches: int) -> list[dict]:
        f = CommitteeFixture(size=4)
        batches = [
            Batch(
                tuple(
                    (b"%d-%d-" % (i, j)).ljust(tx_bytes, b"\x5a")
                    for j in range(txs_per_batch)
                )
            )
            for i in range(n_batches)
        ]
        by_digest = {b.digest: b.to_bytes() for b in batches}
        digests = list(by_digest)
        calls = {"rpcs": 0}
        srv = RpcServer()

        async def on_one(msg: RequestBatchMsg, peer):
            calls["rpcs"] += 1
            return RequestedBatchMsg(msg.digest, by_digest[msg.digest])

        async def on_many(msg: RequestBatchesMsg, peer):
            calls["rpcs"] += 1
            return RequestedBatchesMsg(
                tuple((d, True, by_digest[d]) for d in msg.digests)
            )

        srv.route(RequestBatchMsg, on_one)
        srv.route(RequestBatchesMsg, on_many)
        port = await srv.start("127.0.0.1", 0)
        from narwhal_tpu.config import WorkerInfo

        pk = f.authorities[0].public
        info = f.worker_cache.workers[pk][0]
        f.worker_cache.workers[pk][0] = WorkerInfo(
            name=info.name,
            transactions=info.transactions,
            worker_address=f"127.0.0.1:{port}",
        )
        storage = NodeStorage(None)
        temp = storage.temp_batch_store
        net = NetworkClient()
        sub = Subscriber(
            pk, f.worker_cache, net, temp,
            rx_consensus=Channel(10), tx_executor=Channel(10),
        )
        cert = f.certificate(
            f.header(author=0, round=1, payload={d: 0 for d in digests})
        )
        output = ConsensusOutput(certificate=cert, consensus_index=0)

        async def per_batch_stage():
            """The seed plane: one RPC per digest, gathered."""
            resps = await asyncio.gather(
                *(
                    net.request(f"127.0.0.1:{port}", RequestBatchMsg(d))
                    for d in digests
                )
            )
            return {r.digest: Batch.from_bytes(r.serialized_batch) for r in resps}

        async def coalesced_stage():
            _, staged, _t = await sub._stage(output, 0.0)
            temp.delete_all(digests)  # the core's per-certificate cleanup
            return staged

        async def warm_stage():
            _, staged, _t = await sub._stage(output, 0.0)
            return staged  # leave the store warm: every commit is a hit

        rows = []
        results = {}
        for mode, fn in (
            ("per-batch", per_batch_stage),
            ("coalesced", coalesced_stage),
            ("prefetch-warm", warm_stage),
        ):
            if mode == "prefetch-warm":
                # Warm exactly as production does: the prefetcher stages the
                # accepted certificate's payload ahead of the commit.
                pf = Prefetcher(
                    pk, f.worker_cache, net, temp, rx_accepted=Channel(10)
                )
                await pf._prefetch_burst([cert])
            staged = await fn()  # warm connections/compile nothing
            assert set(staged) == set(digests)
            rpcs0 = calls["rpcs"]
            samples: list[float] = []
            t0 = time.perf_counter()
            while time.perf_counter() - t0 < 0.5:
                it0 = time.perf_counter()
                await fn()
                samples.append(time.perf_counter() - it0)
            n = len(samples)
            dt = sum(samples) / n
            rpcs_per_cert = (calls["rpcs"] - rpcs0) / n
            results[mode] = rpcs_per_cert
            rows.append(
                {
                    "metric": f"commit_path_ms_per_cert[{mode}]",
                    "value": round(dt * 1000, 3),
                    "unit": "ms/cert",
                    "batches_per_cert": n_batches,
                    "txs_per_batch": txs_per_batch,
                    "rpcs_per_certificate": round(rpcs_per_cert, 2),
                    "latency_ms": _percentiles(samples, scale=1000),
                }
            )
        rows.append(
            {
                "metric": "commit_path_rpc_reduction[coalesced_vs_per_batch]",
                "value": round(
                    results["per-batch"] / max(results["coalesced"], 1e-9), 2
                ),
                "unit": "x",
                "batches_per_cert": n_batches,
            }
        )
        net.close()
        await srv.stop()
        return rows

    out = []
    for n_batches in batches_per_cert:
        out.extend(asyncio.run(run_point(n_batches)))
    return out


def bench_pacing(
    rates=(50, 400), duration: float = 2.0, ceiling: float = 0.1,
    floor: float = 0.005, batch_size: int = 500_000, tx_bytes: int = 128,
) -> list[dict]:
    """Ingest-to-seal latency through a real BatchMaker, fixed delay vs the
    adaptive pacing controller, at a light trickle and a heavier rate.

    Each transaction's latency is measured from channel send to the sealed
    batch containing it arriving downstream. The claim under test: with
    shallow queues the adaptive controller seals near its floor (sub-10ms
    p50 instead of ~ceiling/2 + ceiling tail), and the response is monotone
    — at higher occupancy the delay climbs back toward the ceiling rather
    than staying greedy."""
    import asyncio

    from narwhal_tpu.channels import Channel, Watch
    from narwhal_tpu.pacing import PacingController
    from narwhal_tpu.types import ReconfigureNotification
    from narwhal_tpu.worker.batch_maker import BatchMaker

    async def run_mode(rate: int, adaptive: bool) -> list[float]:
        rx: Channel = Channel(10_000)
        out: Channel = Channel(10_000)
        pacing = (
            PacingController(
                ceiling=ceiling, floor=floor, sources=[rx.occupancy, out.occupancy]
            )
            if adaptive
            else None
        )
        bm = BatchMaker(
            batch_size, ceiling, rx, out,
            Watch(ReconfigureNotification("boot")), pacing=pacing,
        )
        task = bm.spawn()
        sent: dict[int, float] = {}
        latencies: list[float] = []

        async def drain() -> None:
            while True:
                batch = await out.recv()
                t = time.perf_counter()
                for tx in batch.transactions:
                    sid = int.from_bytes(tx[:8], "big")
                    t0 = sent.pop(sid, None)
                    if t0 is not None:
                        latencies.append(t - t0)

        drainer = asyncio.ensure_future(drain())
        interval = 1.0 / rate
        end = time.perf_counter() + duration
        sid = 0
        while time.perf_counter() < end:
            sid += 1
            tx = sid.to_bytes(8, "big").ljust(tx_bytes, b"\x5a")
            frame = len(tx).to_bytes(4, "little") + tx
            sent[sid] = time.perf_counter()
            await rx.send((1, frame))
            await asyncio.sleep(interval)
        await asyncio.sleep(ceiling * 2)  # let the tail seal
        task.cancel()
        drainer.cancel()
        return latencies

    out = []
    for rate in rates:
        for label, adaptive in (("fixed", False), ("adaptive", True)):
            lat = asyncio.run(run_mode(rate, adaptive))
            out.append(
                {
                    "metric": f"pacing_seal_latency_ms[{label}]",
                    "value": round(1000 * sum(lat) / max(1, len(lat)), 3),
                    "unit": "ms (mean)",
                    "rate_tx_s": rate,
                    "ceiling_ms": ceiling * 1000,
                    "floor_ms": floor * 1000,
                    "samples": len(lat),
                    "latency_ms": _percentiles(lat, scale=1000),
                }
            )
    return out


def _jax_backend() -> str:
    try:
        import jax

        return jax.default_backend()
    except Exception:
        return "unknown"


def bench_codec() -> list[dict]:
    """Message encode/decode throughput on a payload-bearing header."""
    from narwhal_tpu.fixtures import CommitteeFixture
    from narwhal_tpu.messages import HeaderMsg, Writer, decode_message, encode_message

    f = CommitteeFixture(size=4)
    payload = {bytes([i]) * 32: 0 for i in range(32)}
    msg = HeaderMsg(f.header(author=0, round=1, payload=payload))
    tag, body = encode_message(msg)

    def encode_fresh():
        w = Writer()
        msg.encode(w)  # bypass the per-object memo: measure the real encoder
        return w.finish()

    out = []
    for name, fn in (
        ("encode", encode_fresh),
        ("decode", lambda: decode_message(tag, body)),
    ):
        fn()
        n, t0 = 0, time.perf_counter()
        while time.perf_counter() - t0 < 0.5:
            fn()
            n += 1
        dt = (time.perf_counter() - t0) / n
        out.append(
            {
                "metric": f"header_codec_per_s[{name}]",
                "value": round(1 / dt, 1),
                "unit": "ops/s",
                "wire_bytes": len(body),
            }
        )
    return out


def bench_compact_verify(
    committee_size: int = 50, batches: tuple = (1, 8, 32, 64)
) -> list[dict]:
    """Host compact-certificate proof verification: the batched
    randomized-linear-combination MSM (types.host_batch_verify_aggregates,
    what the cpu/pool group lane dispatches) vs the per-item
    host_verify_aggregate fallback, at the north-star committee size
    (quorum = 34 signers/cert at N=50). Fresh certificates per batch so the
    aggregate-verdict cache never hides the group math; the acceptance bar
    is >=5x per-signature at batch >= 32."""
    from narwhal_tpu.fixtures import CommitteeFixture
    from narwhal_tpu.types import (
        Certificate,
        Header,
        Vote,
        host_batch_verify_aggregates,
        host_verify_aggregate,
    )

    f = CommitteeFixture(size=committee_size)
    committee = f.committee
    quorum_n = 0
    stake = 0
    for pk in committee.authority_keys():
        quorum_n += 1
        stake += committee.stake(pk)
        if stake >= committee.quorum_threshold():
            break
    voters = f.authorities[:quorum_n]

    serial = 0

    def fresh_groups(count: int):
        nonlocal serial
        groups = []
        for _ in range(count):
            serial += 1
            author = f.authorities[serial % committee_size]
            h = Header.build(
                author.public, 1, 0,
                {serial.to_bytes(32, "little"): 0},
                frozenset(c.digest for c in Certificate.genesis(committee)),
                author.signature_service(),
            )
            votes = [
                Vote.for_header(h, a.public, a.signature_service()) for a in voters
            ]
            signers, sigs = zip(
                *sorted((committee.index_of(v.author), v.signature) for v in votes)
            )
            cert = Certificate.compact_from_votes(h, tuple(signers), tuple(sigs))
            groups.append(cert.aggregate_group(committee))
        return groups

    out = []
    for batch in batches:
        groups = fresh_groups(batch)
        sigs = sum(len(g[0]) for g in groups)
        t0 = time.perf_counter()
        assert all(host_batch_verify_aggregates(groups))
        batched_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        assert all(host_verify_aggregate(*g) for g in groups)
        per_item_s = time.perf_counter() - t0
        out.append(
            {
                "metric": f"compact_verify[N={committee_size},batch={batch}]",
                "signers_per_cert": quorum_n,
                "signatures": sigs,
                "batched_s": round(batched_s, 4),
                "per_item_s": round(per_item_s, 4),
                "batched_us_per_sig": round(1e6 * batched_s / sigs, 1),
                "per_item_us_per_sig": round(1e6 * per_item_s / sigs, 1),
                "speedup": round(per_item_s / batched_s, 2),
            }
        )
    return out


def bench_trace_waterfall(
    nodes: int = 4,
    rate: int = 200,
    duration: float = 10.0,
    tx_size: int = 64,
) -> list[dict]:
    """--trace-waterfall: boot a TRACED in-process committee, push load
    through to execution, and emit the causal answer the aggregate
    histograms cannot give — per-stage p50/p95 across every traced span,
    plus one committed certificate's end-to-end waterfall (stage windows
    normalized to its seal open) stitched from the flight recorders."""
    import asyncio
    import os

    os.environ["NARWHAL_TRACE"] = "1"  # before any Tracer is constructed
    os.environ.setdefault("NARWHAL_TRACE_SAMPLE", "1.0")

    from narwhal_tpu import tracing
    from narwhal_tpu.cluster import Cluster
    from narwhal_tpu.messages import SubmitTransactionStreamMsg
    from narwhal_tpu.network import NetworkClient

    async def run() -> list[dict]:
        cluster = Cluster(size=nodes, workers=1)
        await cluster.start()
        client = NetworkClient()
        executed = 0
        try:
            await cluster.assert_progress(commit_threshold=2, timeout=60.0)

            async def drain() -> None:
                nonlocal executed
                ch = cluster.authorities[0].primary.tx_execution_output
                while True:
                    await ch.recv()
                    executed += 1

            drainer = asyncio.ensure_future(drain())
            lane = cluster.authorities[0].worker_transactions_address(0)
            share = max(1, int(rate))
            end = time.time() + duration
            sid = 0
            while time.time() < end:
                tick = time.time()
                txs = []
                for _ in range(share):
                    sid += 1
                    txs.append(
                        b"\x00" + sid.to_bytes(8, "big") + b"\x01" * (tx_size - 9)
                    )
                try:
                    await client.request(lane, SubmitTransactionStreamMsg(tuple(txs)))
                except Exception:
                    pass  # shed/hiccup: the waterfall needs SOME certs, not all
                await asyncio.sleep(max(0.0, 1.0 - (time.time() - tick)))
            await asyncio.sleep(2.0)  # let in-flight certs close their spans
            dumps = tracing.live_dumps()
            drainer.cancel()
        finally:
            client.close()
            await cluster.shutdown()

        rows = [
            {"metric": f"trace_stage[{stage}]", "nodes": nodes, "rate": rate, **v}
            for stage, v in tracing.stage_percentiles(dumps).items()
        ]
        falls = tracing.waterfall(dumps)
        # Exemplar: the committed certificate whose waterfall carries the
        # most stages (a payload-bearing one reaches back to a seal span).
        best = max(
            (v for v in falls.values() if "commit" in v["stages"]),
            key=lambda v: len(v["stages"]),
            default=None,
        )
        if best is not None:
            t_open = min(t0 for t0, _ in best["stages"].values())
            rows.append(
                {
                    "metric": "trace_waterfall_exemplar",
                    "nodes": nodes,
                    "executed_txs": executed,
                    "stages_ms_from_open": {
                        stage: [
                            round((t0 - t_open) * 1000, 2),
                            round((t1 - t_open) * 1000, 2),
                        ]
                        for stage, (t0, t1) in best["stages"].items()
                    },
                    "end_to_end_ms": round(
                        (
                            max(t1 for _, t1 in best["stages"].values()) - t_open
                        )
                        * 1000,
                        2,
                    ),
                }
            )
        return rows

    return asyncio.run(run())


def main() -> None:
    ap = argparse.ArgumentParser(prog="benchmark.microbench")
    ap.add_argument("--profile", action="store_true", help="cProfile the consensus bench")
    ap.add_argument("--dag-service", action="store_true",
                    help="also run the Dag-service read_causal cpu-vs-tpu bench")
    ap.add_argument("--storage", action="store_true",
                    help="run ONLY the storage group-commit vs per-put-flush bench")
    ap.add_argument("--rpc-coalesce", action="store_true",
                    help="run ONLY the coalesced-vs-sequential RPC write bench")
    ap.add_argument("--commit-path", action="store_true",
                    help="run ONLY the commit->execution staging bench "
                         "(per-batch vs coalesced vs prefetch-warm)")
    ap.add_argument("--pacing", action="store_true",
                    help="run ONLY the adaptive-vs-fixed seal latency bench "
                         "(ingest->seal percentiles through a real BatchMaker)")
    ap.add_argument("--compact-verify", action="store_true",
                    help="run ONLY the batched-vs-per-item host compact "
                         "certificate proof verification bench")
    ap.add_argument("--trace-waterfall", action="store_true",
                    help="run ONLY the traced in-process committee bench: "
                         "per-stage span percentiles + one committed cert's "
                         "end-to-end waterfall (NARWHAL_TRACE forced on)")
    ap.add_argument("--nodes", type=int, default=4,
                    help="committee size for --trace-waterfall")
    ap.add_argument("--rate", type=int, default=200,
                    help="tx/s injected during --trace-waterfall")
    ap.add_argument("--duration", type=float, default=10.0,
                    help="load window in seconds for --trace-waterfall")
    ap.add_argument("--out", default=None,
                    help="also write the selected benches as a JSON array to this path")
    args = ap.parse_args()
    rows = []
    if args.trace_waterfall:
        rows += bench_trace_waterfall(
            nodes=args.nodes, rate=args.rate, duration=args.duration
        )
    elif args.storage:
        rows += bench_storage_group_commit()
    elif args.rpc_coalesce:
        rows += bench_rpc_coalesce()
    elif args.commit_path:
        rows += bench_commit_path()
    elif args.pacing:
        rows += bench_pacing()
    elif args.compact_verify:
        rows += bench_compact_verify()
    elif args.dag_service:
        rows += bench_dag_service()
    else:
        rows += bench_batch_digest() + bench_codec() + bench_process_certificates()
    for rec in rows:
        print(json.dumps(rec))
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(rows, fh, indent=2)
            fh.write("\n")
    import sys

    from tools.perf import ledger as perf_ledger

    perf_ledger.append("microbench", rows, argv=sys.argv[1:])
    if args.profile:
        prof = cProfile.Profile()
        prof.enable()
        bench_process_certificates()
        prof.disable()
        s = io.StringIO()
        pstats.Stats(prof, stream=s).sort_stats("cumulative").print_stats(15)
        print(s.getvalue())


if __name__ == "__main__":
    main()
