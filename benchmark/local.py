"""LocalBench: boot a real multi-process committee on localhost and measure.

Reference: /root/reference/benchmark/benchmark/local.py — generates keys and
committee files, spawns every primary/worker as its own OS process (tmux
there, subprocess here; each `python -m narwhal_tpu run ...` is the same
single-role binary shape as the reference's `node run`), injects load with
benchmark clients, then parses the logs. `faults: f` leaves the last f nodes
unbooted (the reference's only fault-injection mechanism).
"""

from __future__ import annotations

import json
import os
import shutil
import signal
import subprocess
import sys
import time
from dataclasses import dataclass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from narwhal_tpu.config import (
    Authority,
    Committee,
    Parameters,
    WorkerCache,
    WorkerInfo,
    get_available_port,
    release_all_ports,
)
from narwhal_tpu.crypto import KeyPair

from .logs import LogParser


def parse_telemetry_addr(log_text: str) -> str | None:
    """Extract the primary's gRPC telemetry endpoint from its boot log.

    The node prints ONE machine-readable `TELEMETRY_ADDR=<host:port>`
    line at spawn (narwhal_tpu/__main__.py) — the contract that replaced
    regexing the human "gRPC public API listening on ..." log line, which
    broke whenever the log format moved. The LAST occurrence wins (a
    restarted node rebinds); an empty value means the gRPC plane is not
    mounted and there is nothing to scrape."""
    addr = None
    for line in log_text.splitlines():
        line = line.strip()
        if line.startswith("TELEMETRY_ADDR="):
            value = line.split("=", 1)[1].strip()
            addr = value or None
    return addr


@dataclass
class BenchParameters:
    nodes: int = 4
    workers: int = 1
    rate: int = 1_000
    tx_size: int = 512
    duration: int = 20
    faults: int = 0
    consensus_protocol: str = "bullshark"  # | tusk
    crypto_backend: str = "cpu"  # | pool | tpu
    dag_backend: str = "cpu"  # | tpu
    dag_shards: int = 1  # committee-axis device shards (tpu backend)
    mem_profiling: bool = False  # reference mem_profiling bench param


class LocalBench:
    def __init__(self, bench: BenchParameters, node_parameters: Parameters | None = None):
        self.bench = bench
        self.node_parameters = node_parameters or Parameters(
            max_header_delay=0.1, max_batch_delay=0.1
        )
        if bench.crypto_backend == "tpu" and node_parameters is None:
            # Default only: the whole fleet runs the tpu backend, so the
            # committee can uniformly opt into the cofactored accept set —
            # unlocking the msm batch kernel. An explicitly passed
            # Parameters keeps its verify_rule (e.g. to benchmark the
            # strict per-item kernel).
            from dataclasses import replace

            self.node_parameters = replace(
                self.node_parameters, verify_rule="cofactored"
            )
        self.base = os.path.abspath(".bench")
        self.procs: list[subprocess.Popen] = []
        # Per-primary Telemetry.Scrape snapshots from the last run()
        # (gRPC, taken just before teardown; sweep.py embeds them).
        self.telemetry_scrapes: dict[str, dict] = {}
        # Per-child open-fd counts sampled at steady state just before
        # teardown (sweep.py records the max as the per-node fd figure).
        self.child_fd_counts: dict[int, int] = {}

    # -- config generation (local.py + config.py of the reference) ---------

    def _generate_configs(self):
        shutil.rmtree(self.base, ignore_errors=True)
        os.makedirs(self.base)
        keypairs = [KeyPair.generate() for _ in range(self.bench.nodes)]
        authorities = {}
        workers = {}
        for i, kp in enumerate(keypairs):
            network_kp = KeyPair.generate()
            worker_kps = {wid: KeyPair.generate() for wid in range(self.bench.workers)}
            with open(f"{self.base}/key-{i}.json", "w") as f:
                json.dump(
                    {
                        "name": kp.public.hex(),
                        "seed": kp.private_bytes().hex(),
                        "network_seed": network_kp.private_bytes().hex(),
                        "worker_network_seeds": {
                            str(wid): wkp.private_bytes().hex()
                            for wid, wkp in worker_kps.items()
                        },
                    },
                    f,
                )
            authorities[kp.public] = Authority(
                stake=1,
                primary_address=f"127.0.0.1:{get_available_port()}",
                network_key=network_kp.public,
            )
            workers[kp.public] = {
                wid: WorkerInfo(
                    name=worker_kps[wid].public,
                    transactions=f"127.0.0.1:{get_available_port()}",
                    worker_address=f"127.0.0.1:{get_available_port()}",
                )
                for wid in range(self.bench.workers)
            }
        committee = Committee(authorities)
        committee.export(f"{self.base}/committee.json")
        WorkerCache(workers).export(f"{self.base}/workers.json")
        self.node_parameters.export(f"{self.base}/parameters.json")
        return committee, workers

    # -- process control ---------------------------------------------------

    def _spawn(self, argv: list[str], log_path: str) -> None:
        log = open(log_path, "w")
        env = dict(os.environ, PYTHONPATH=os.path.dirname(self.base) or ".")
        # This parent assigned every node's ports and holds SO_REUSEPORT
        # placeholders for them until the fleet is up; the children must
        # co-bind through those placeholders (RpcServer only sets
        # reuse_port for ports it can prove are placeheld). Advertise the
        # EXACT list — a blanket "all" would reinstate silent co-binding
        # for genuinely duplicate servers.
        from narwhal_tpu.config import placeheld_ports

        env["NARWHAL_PLACEHELD_PORTS"] = ",".join(map(str, placeheld_ports()))
        if env.get("JAX_PLATFORMS") == "cpu":
            # The axon TPU plugin self-registers via sitecustomize whenever
            # PALLAS_AXON_POOL_IPS is set and wins over JAX_PLATFORMS; a
            # fleet of node subprocesses would then all dial the single
            # tunneled chip and stall in client init. An explicit cpu
            # request means virtual/CPU devices: keep the plugin out.
            env.pop("PALLAS_AXON_POOL_IPS", None)
        if self.bench.mem_profiling:
            env["NARWHAL_MEM_PROFILE"] = self.base
        self.procs.append(
            subprocess.Popen(
                [sys.executable, "-m", "narwhal_tpu", "-v", *argv],
                stdout=log,
                stderr=subprocess.STDOUT,
                env=env,
                cwd=os.path.dirname(os.path.abspath(__file__)) + "/..",
            )
        )

    def _wait_for_boot(self, paths: list[str], timeout: float = 180.0) -> None:
        """Block until every node log shows its boot line (the reference's
        fab-local pattern of parsing 'successfully booted'): the load window
        must not start while nodes are still importing jax/compiling —
        concurrent cold starts on a shared core can take tens of seconds,
        which would otherwise be billed to the measurement duration."""
        deadline = time.time() + timeout
        pending = set(paths)
        while pending and time.time() < deadline:
            for path in list(pending):
                try:
                    with open(path) as fh:
                        if "successfully booted" in fh.read():
                            pending.discard(path)
                except OSError:
                    pass
            for proc in self.procs:
                if proc.poll() not in (None, 0):
                    raise RuntimeError("a node process exited during boot")
            if pending:
                time.sleep(0.5)
        if pending:
            raise RuntimeError(
                f"nodes failed to boot within {timeout}s: {sorted(pending)}"
            )

    def _sample_child_fds(self) -> dict[int, int]:
        """Open-fd count of each live child (node or client) via procfs —
        the per-process number RLIMIT_NOFILE actually judges. Sampled at
        steady state, after every mesh/pool connection is up."""
        counts: dict[int, int] = {}
        for p in self.procs:
            if p.poll() is not None:
                continue
            try:
                counts[p.pid] = len(os.listdir(f"/proc/{p.pid}/fd"))
            except OSError:
                pass
        return counts

    def _kill_all(self) -> None:
        for p in self.procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        deadline = time.time() + 5
        for p in self.procs:
            try:
                p.wait(max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
        self.procs.clear()

    def _scrape_primaries(self, alive: int) -> dict:
        """Scrape each primary subprocess's gRPC Telemetry service (the
        raw-bytes mirror any process can hit) before teardown, keyed by
        node index. The bound address is ephemeral, so it is read from the
        node's own machine-readable TELEMETRY_ADDR= boot line. Best-effort:
        a bench record is still valid without its scrape."""
        from narwhal_tpu.metrics import parse_exposition

        try:
            import grpc
        except ImportError:
            return {}
        scrapes: dict[str, dict] = {}
        for i in range(alive):
            try:
                with open(f"{self.base}/primary-{i}.log") as fh:
                    addr = parse_telemetry_addr(fh.read())
                if addr is None:
                    continue
                with grpc.insecure_channel(addr) as channel:
                    text = channel.unary_unary(
                        "/narwhal.Telemetry/Scrape",
                        request_serializer=lambda b: b,
                        response_deserializer=lambda b: b,
                    )(b"", timeout=10).decode()
                scrapes[f"primary-{i}"] = {
                    name: {
                        "type": entry["type"],
                        "samples": {
                            k: v
                            for k, v in entry["samples"].items()
                            if not k.startswith("_bucket")
                        },
                    }
                    for name, entry in parse_exposition(text).items()
                }
            except Exception as e:  # scrape is diagnostics, never the bench
                print(f"telemetry scrape of primary-{i} failed: {e}")
        return scrapes

    def run(self, debug: bool = False) -> LogParser:
        bench = self.bench
        committee, workers = self._generate_configs()
        alive = bench.nodes - bench.faults
        keys = list(committee.authorities)
        common = [
            "--committee", f"{self.base}/committee.json",
            "--workers", f"{self.base}/workers.json",
            "--parameters", f"{self.base}/parameters.json",
        ]
        try:
            for i in range(alive):
                self._spawn(
                    ["run", "--keys", f"{self.base}/key-{i}.json", *common,
                     "--store", f"{self.base}/db-{i}", "primary",
                     "--consensus-protocol", bench.consensus_protocol,
                     "--crypto-backend", bench.crypto_backend,
                     "--dag-backend", bench.dag_backend,
                     "--dag-shards", str(bench.dag_shards)],
                    f"{self.base}/primary-{i}.log",
                )
                for wid in range(bench.workers):
                    self._spawn(
                        ["run", "--keys", f"{self.base}/key-{i}.json", *common,
                         "--store", f"{self.base}/db-{i}", "worker", "--id", str(wid)],
                        f"{self.base}/worker-{i}-{wid}.log",
                    )
            self._wait_for_boot(
                [f"{self.base}/primary-{i}.log" for i in range(alive)]
                + [
                    f"{self.base}/worker-{i}-{wid}.log"
                    for i in range(alive)
                    for wid in range(bench.workers)
                ]
            )
            # The children own the assigned ports now; free the parent's
            # placeholder fds so long sweeps don't creep toward the ulimit.
            release_all_ports()
            # One client per alive worker lane (local.py: rate share).
            lanes = [
                workers[keys[i]][wid].transactions
                for i in range(alive)
                for wid in range(bench.workers)
            ]
            share = max(1, bench.rate // len(lanes))
            for j, target in enumerate(lanes):
                self._spawn(
                    ["benchmark_client", "--target", target,
                     "--rate", str(share), "--size", str(bench.tx_size),
                     "--nodes", *lanes],
                    f"{self.base}/client-{j}.log",
                )
            time.sleep(bench.duration)
            # Scrape-then-kill: the telemetry surface is only reachable
            # while the fleet is alive (sweep.py embeds this in its rows).
            self.telemetry_scrapes = self._scrape_primaries(alive)
            self.child_fd_counts = self._sample_child_fds()
        finally:
            self._kill_all()
        return LogParser.process(
            self.base, faults=bench.faults, parameters=self.node_parameters
        )
