"""Commit-walk microbench: device adjacency-tensor kernels vs host order_dag.

The reference's per-commit hot loop is a pointer-chasing DFS
(/root/reference/consensus/src/utils.rs:11-101; criterion bench at
consensus/benches/process_certificates.rs:18-80). Here the same work is the
`TpuBullshark` walk (narwhal_tpu/tpu/dag_kernels.py): reachability as masked
[N, N] matmul scans over the round window, leader support as a stake dot
product. This bench streams a synthetic lossless DAG through both engines,
asserts identical commit sequences, and reports certificates processed per
second for each.

Usage: python -m benchmark.dag_walk_bench [--size 32] [--rounds 64] [--gc 50]
Prints one JSON line per engine.
"""

from __future__ import annotations

import argparse
import json
import os
import time


def run(size: int, rounds: int, gc: int) -> None:
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(__file__), "..", ".jax_cache"),
    )
    import jax

    jax.config.update(
        "jax_compilation_cache_dir", os.environ["JAX_COMPILATION_CACHE_DIR"]
    )

    from narwhal_tpu.consensus import Bullshark, ConsensusState
    from narwhal_tpu.fixtures import CommitteeFixture, make_optimal_certificates
    from narwhal_tpu.stores import NodeStorage
    from narwhal_tpu.tpu.dag_kernels import TpuBullshark
    from narwhal_tpu.types import Certificate

    f = CommitteeFixture(size=size)
    genesis = {c.digest for c in Certificate.genesis(f.committee)}
    certs, _ = make_optimal_certificates(f.committee, 1, rounds, genesis)
    certs = list(certs)

    def stream(engine):
        state = ConsensusState(Certificate.genesis(f.committee))
        seq, index = [], 0
        t0 = time.perf_counter()
        for c in certs:
            out = engine.process_certificate(state, index, c)
            index += len(out)
            seq.extend(o.certificate.digest for o in out)
        return time.perf_counter() - t0, seq

    host = Bullshark(f.committee, NodeStorage(None).consensus_store, gc)
    dev = TpuBullshark(f.committee, NodeStorage(None).consensus_store, gc, prewarm=False)

    # Warmup compiles the device kernels for this (W, N) shape.
    warm = TpuBullshark(f.committee, NodeStorage(None).consensus_store, gc, prewarm=False)
    stream(warm)

    host_dt, host_seq = stream(host)
    dev_dt, dev_seq = stream(dev)
    assert host_seq == dev_seq, "device commit sequence diverged from host"

    # Separate the device COMPUTE from the device->host readback: on a
    # tunneled chip the readback is a flat multi-ms round trip (µs on local
    # PCIe/ICI), so we report both the end-to-end stream rate and the
    # per-commit-event walk times that the hardware actually determines.
    import numpy as np

    from narwhal_tpu.tpu import dag_kernels as dk

    events = {"n": 0, "compute": 0.0, "readback": 0.0}
    orig = dk.chain_commit

    def timed(*a):
        t0 = time.perf_counter()
        out = orig(*a)
        jax.block_until_ready(out)
        t1 = time.perf_counter()
        np.asarray(out)
        t2 = time.perf_counter()
        events["n"] += 1
        events["compute"] += t1 - t0
        events["readback"] += t2 - t1
        return out

    dk.chain_commit = timed
    try:
        stream(TpuBullshark(f.committee, NodeStorage(None).consensus_store, gc, prewarm=False))
    finally:
        dk.chain_commit = orig

    # Pure device time of one chain_commit at this (W, N) shape, measured
    # with an on-device iteration chain + two-point differencing (the only
    # trustworthy method through the tunnel, whose flat dispatch/readback
    # latency otherwise dominates: see README "tunnel constraint").
    import jax.numpy as jnp
    from jax import lax

    win = dev.win
    parent_j = jnp.asarray(win.parent)
    present_j = jnp.asarray(win.present)
    lc = jnp.zeros((win.N,), jnp.int32)
    offs_j = jnp.zeros((1,), jnp.int32).at[0].set(win.W - 2)
    onehots_j = jnp.zeros((1, win.N), jnp.uint8).at[0, 0].set(1)

    def chained(reps):
        @jax.jit
        def f(parent, present, lc, offs, onehots):
            def body(i, acc):
                masks = dk.chain_commit(
                    parent, present, jnp.int32(gc), lc, jnp.int32(0), offs,
                    jnp.roll(onehots, i, axis=1),
                )
                return acc + jnp.sum(masks.astype(jnp.int32))
            return lax.fori_loop(0, reps, body, jnp.int32(0))
        return f

    def timed(fn, iters=3):
        ts = []
        int(fn(parent_j, present_j, lc, offs_j, onehots_j))
        for _ in range(iters):
            t0 = time.perf_counter()
            int(fn(parent_j, present_j, lc, offs_j, onehots_j))
            ts.append(time.perf_counter() - t0)
        return sorted(ts)[len(ts) // 2]

    # The walk is microseconds on device; thousands of chained reps are
    # needed for the delta to clear the tunnel's timing noise.
    t_small = timed(chained(2))
    t_big = timed(chained(4002))
    device_chain_ms = max(t_big - t_small, 0.0) / 4000 * 1000

    # Host per-event walk time for comparison: total host stream time is
    # dominated by the flatten (state bookkeeping is shared by both engines).
    n = len(certs)
    n_events = max(events["n"], 1)
    rows = [
        {
            "metric": "commit_walk_certs_per_s[host_order_dag]",
            "value": round(n / host_dt, 1),
            "unit": "certs/s",
        },
        {
            "metric": "commit_walk_certs_per_s[tpu_dag_kernels_e2e]",
            "value": round(n / dev_dt, 1),
            "unit": "certs/s",
        },
        {
            "metric": "commit_event_ms[host]",
            "value": round(host_dt / n_events * 1000, 2),
            "unit": "ms/event",
        },
        {
            "metric": "commit_event_ms[tpu_compute]",
            "value": round(events["compute"] / n_events * 1000, 2),
            "unit": "ms/event",
        },
        {
            "metric": "commit_event_ms[tpu_readback]",
            "value": round(events["readback"] / n_events * 1000, 2),
            "unit": "ms/event",
        },
        {
            "metric": "commit_event_ms[tpu_device_chain]",
            "value": round(device_chain_ms, 3),
            "unit": "ms/event",
        },
    ]
    for row in rows:
        row.update(
            committee=size,
            rounds=rounds,
            committed=len(host_seq),
            events=events["n"],
            backend=jax.default_backend(),
        )
        print(json.dumps(row))


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", type=int, default=32)
    ap.add_argument("--rounds", type=int, default=64)
    ap.add_argument("--gc", type=int, default=50)
    a = ap.parse_args()
    run(a.size, a.rounds, a.gc)
