"""Latency-throughput plots from sweep/aggregate result files (the
reference's Ploter, benchmark/benchmark/plot.py).

    python -m benchmark.plot .bench/sweep.json [more.json ...] --out tps.png

Each input file is one curve (labelled by its committee/worker shape);
points are (consensus TPS, consensus latency).
"""

from __future__ import annotations

import argparse
import json
import os


def load(path: str) -> list[dict]:
    with open(path) as f:
        data = json.load(f)
    return data if isinstance(data, list) else [data]


def label_for(results: list[dict], path: str) -> str:
    if not results:
        return os.path.basename(path)
    r = results[0]
    lbl = f"{r['committee_size']} nodes, {r['workers_per_node']} worker(s)"
    if r.get("faults"):
        lbl += f", {r['faults']} faults"
    return lbl


def plot(files: list[str], out: str, e2e: bool = False) -> str:
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    tps_key = "end_to_end_tps" if e2e else "consensus_tps"
    lat_key = "end_to_end_latency_ms" if e2e else "consensus_latency_ms"
    fig, ax = plt.subplots(figsize=(7, 4.5))
    for path in files:
        results = sorted(load(path), key=lambda r: r[tps_key])
        xs = [r[tps_key] / 1_000 for r in results]
        ys = [r[lat_key] / 1_000 for r in results]
        errs = [r.get(lat_key + "_std", 0) / 1_000 for r in results]
        ax.errorbar(
            xs, ys, yerr=errs if any(errs) else None,
            marker="o", capsize=3, label=label_for(results, path),
        )
    kind = "End-to-end" if e2e else "Consensus"
    ax.set_xlabel(f"{kind} throughput (k tx/s)")
    ax.set_ylabel(f"{kind} latency (s)")
    ax.grid(True, alpha=0.3)
    ax.legend()
    fig.tight_layout()
    fig.savefig(out, dpi=140)
    plt.close(fig)
    return out


def main() -> None:
    ap = argparse.ArgumentParser(prog="benchmark.plot")
    ap.add_argument("files", nargs="+", help="sweep/aggregate JSON files")
    ap.add_argument("--out", default=".bench/latency-throughput.png")
    ap.add_argument("--e2e", action="store_true", help="plot end-to-end metrics")
    args = ap.parse_args()
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    print("wrote", plot(args.files, args.out, args.e2e))


if __name__ == "__main__":
    main()
