"""MXU engagement experiment (VERDICT r3 item 4).

The ed25519 kernels run 13-bit-limb arithmetic on int32 VPU lanes while the
MXU (the chip's matmul systolic array, ~2 orders more int8/bf16 FLOPs) sits
idle. README round 3 hypothesized int8 packing of limb products could move
the multiplication work there. This script MEASURES the two candidate
mappings instead of hand-waving:

A. Field-mul limb convolution as a matmul.
   c[b, k] = sum_{i+j=k} a[b, i] * b[b, j] is per-item work with NO shared
   operand; the only matmul-shaped factorization is
       outer[b, i*j] = a[b, i] * b[b, j]    (still B*400 VPU multiplies)
       c = outer @ T                        (T[i*20+j, k] = [i+j == k])
   i.e. the MXU can only take over the REDUCTION (which schoolbook gets for
   free inside its multiply-accumulate), at the cost of materializing the
   [B, 400] outer product. Measured head-to-head below.

B. The DAG reach walk's link propagation as an MXU matmul.
   reach_mask's inner step is frontier' = links^T @ frontier over [N, N]
   uint8 adjacency — a real matmul with contraction N. At bench committee
   sizes (N <= 50) it underfills the 128x128 systolic tile; at N = 128
   walks batched B-wide it tiles exactly. Measured int32-VPU vs
   bf16-MXU-shaped.

Prints one JSON line per measurement. Two-point-differenced on-device
iteration chains cancel the tunnel's flat link latency (bench.py's method).
"""

from __future__ import annotations

import json
import time

def _enable_cache() -> None:
    from narwhal_tpu.tpu import enable_compilation_cache

    enable_compilation_cache()


def _chain_rate(make_fn, args, per_iter, spreads=(4096, 16384)):
    """items/s via two-point differencing of an on-device iteration chain.
    Uses MIN-of-5 (the latency lower bound is the robust statistic through
    a drifting link) and accepts the first spread whose delta clearly
    clears the small chain's time."""
    import numpy as np

    def timed(fn, iters=5):
        ts = []
        np.asarray(fn(*args))  # warm/compile
        for _ in range(iters):
            t0 = time.perf_counter()
            np.asarray(fn(*args))
            ts.append(time.perf_counter() - t0)
        return min(ts)

    small = timed(make_fn(2))
    for spread in spreads:
        big = timed(make_fn(2 + spread))
        delta = big - small
        if delta > max(0.5 * small, 0.05):
            return spread * per_iter / delta
    return None


def experiment_a(batch: int = 8192) -> list[dict]:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from narwhal_tpu.tpu import ed25519 as K

    NL = K.NLIMB
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.integers(0, 1 << 13, (NL, batch), dtype=np.int32))
    b = jnp.asarray(rng.integers(0, 1 << 13, (NL, batch), dtype=np.int32))

    def make_vpu(reps):
        @jax.jit
        def f(a, b):
            def body(i, acc):
                c = K.fe_mul(a + (i & 1), b)
                return acc + c[0]

            return lax.fori_loop(0, reps, body, jnp.zeros((batch,), jnp.int32))

        return f

    # MXU-shaped: [B, NL*NL] outer @ [NL*NL, 2NL-1] index-sum matrix.
    T = np.zeros((NL * NL, 2 * NL - 1), np.int8)
    for i in range(NL):
        for j in range(NL):
            T[i * NL + j, i + j] = 1
    Tj = jnp.asarray(T)

    def make_mxu(reps):
        @jax.jit
        def f(a, b):
            def body(i, acc):
                at = (a + (i & 1)).T  # [B, NL]
                bt = b.T
                outer = (at[:, :, None] * bt[:, None, :]).reshape(batch, NL * NL)
                c = lax.dot(
                    outer.astype(jnp.bfloat16),
                    Tj.astype(jnp.bfloat16),
                    preferred_element_type=jnp.float32,
                )  # [B, 2NL-1] — the reduction on the MXU
                return acc + c[:, 0].astype(jnp.int32)

            return lax.fori_loop(0, reps, body, jnp.zeros((batch,), jnp.int32))

        return f

    out = []
    for name, mk in (("vpu-schoolbook", make_vpu), ("mxu-outer-matmul", make_mxu)):
        rate = _chain_rate(mk, (a, b), batch)
        out.append(
            {
                "metric": f"fe_mul_per_s[{name}]",
                "value": round(rate, 1) if rate else None,
                "unit": "field-muls/s",
                "batch": batch,
                "note": "bf16 matmul path is NOT exact for 13-bit limb "
                "products (>=2^26 exceeds bf16's 8-bit mantissa); measured "
                "as an upper bound on the MXU formulation's speed only",
            }
        )
    return out


def experiment_b(n: int = 128, walks: int = 256, rounds: int = 32) -> list[dict]:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    rng = np.random.default_rng(1)
    links = (rng.random((rounds, n, n)) < 0.6).astype(np.uint8)
    frontier0 = (rng.random((walks, n)) < 0.5).astype(np.uint8)
    links_j = jnp.asarray(links)
    f0 = jnp.asarray(frontier0)

    def make_int32(reps):
        @jax.jit
        def f(links, f0):
            def body(i, acc):
                def step(fr, w):
                    nxt = (
                        fr.astype(jnp.int32) @ links[w].astype(jnp.int32) > 0
                    ).astype(jnp.int32)
                    return nxt, ()

                fr, _ = lax.scan(step, f0.astype(jnp.int32) + (i & 1), jnp.arange(rounds))
                return acc + jnp.sum(fr)

            return lax.fori_loop(0, reps, body, jnp.int32(0))

        return f

    def make_bf16(reps):
        @jax.jit
        def f(links, f0):
            def body(i, acc):
                def step(fr, w):
                    nxt = (
                        lax.dot(
                            fr.astype(jnp.bfloat16),
                            links[w].astype(jnp.bfloat16),
                            preferred_element_type=jnp.float32,
                        )
                        > 0
                    ).astype(jnp.bfloat16)
                    return nxt, ()

                fr, _ = lax.scan(
                    step, f0.astype(jnp.bfloat16) + (i & 1), jnp.arange(rounds)
                )
                return acc + jnp.sum(fr.astype(jnp.int32))

            return lax.fori_loop(0, reps, body, jnp.int32(0))

        return f

    per_iter = walks * rounds  # frontier-propagation steps per chain iter
    out = []
    for name, mk in (("int32-vpu", make_int32), ("bf16-mxu", make_bf16)):
        rate = _chain_rate(mk, (links_j, f0), per_iter)
        out.append(
            {
                "metric": f"reach_step_per_s[{name}]",
                "value": round(rate, 1) if rate else None,
                "unit": "frontier-steps/s",
                "committee": n,
                "walks": walks,
                "rounds": rounds,
            }
        )
    return out


def main() -> None:
    _enable_cache()
    for rec in experiment_a() + experiment_b():
        print(json.dumps(rec))


if __name__ == "__main__":
    main()
