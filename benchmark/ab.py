"""Self-calibrating interleaved A/B driver: the one way to claim a perf
delta on this host.

The host-capacity-swing rule (ROADMAP): this 1-core container varies
10-20x day to day, so BASE and HEAD must run interleaved in the same
minutes and every leg must carry the capacity it measured under. Every
prior PR hand-rolled that ritual (pacing_ab_r8, worker_shard_ab_r9,
compact_wire_ab_r10, trace_ab_r13 — four bespoke schemas); this driver
is the ritual as a tool:

  python -m benchmark.ab --base <rev> --bench inprocess --pairs 2 \
      -- --duration 10 --rate 300

- BASE legs run from a detached `git worktree` of --base; HEAD legs run
  from the working tree. Legs alternate base/head then head/base per
  pair so a monotone capacity drift cancels instead of biasing one side.
- A pinned CPU calibration probe (tools/perf/calibrate) brackets every
  leg; if the slowest probe of the run differs from the fastest by more
  than --calibration-gate the run REFUSES a verdict (`no-verdict`) —
  a number measured across a capacity cliff is not a measurement.
- The noise band is estimated from same-side repeat spread:
  max((max-min)/median) over the base legs and over the head legs. A
  head/base ratio inside the band is `null`; outside it is `win` or
  `regression` per --lower-is-better.
- The canonical verdict record lands in the perf ledger (kind "ab") and
  optionally --out; leg subprocesses run with the ledger disabled so one
  A/B run appends exactly one record.

An A/A run (`--base HEAD` on a clean tree) must come out `null`: that is
the self-test pinned by tests/test_perf_observatory.py fixtures and the
checked-in ab_aa_r14 artifact.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import subprocess
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from tools.perf import calibrate, ledger  # noqa: E402

BENCHES = ("inprocess", "liveness", "microbench")
DEFAULT_METRIC = {
    "inprocess": "executed_tps",
    "liveness": "committed_rounds_per_s",
    "microbench": None,  # rows differ per sub-bench: --metric is required
}


def extract_metric(doc, metric: str, select: str | None):
    """Pull the metric out of a leg's --out document.

    inprocess appends to an array (take the LAST record), liveness writes
    one object, microbench writes rows — `--select key=value` picks the
    row. `metric` is a dotted path into the chosen object.
    """
    if isinstance(doc, list):
        if select:
            k, _, v = select.partition("=")
            matches = [r for r in doc if str(r.get(k)) == v]
            if not matches:
                raise KeyError(f"no row matches --select {select!r}")
            doc = matches[-1]
        else:
            doc = doc[-1]
    for part in metric.split("."):
        if not isinstance(doc, dict) or part not in doc:
            raise KeyError(f"metric path {metric!r} missing at {part!r}")
        doc = doc[part]
    if not isinstance(doc, (int, float)) or isinstance(doc, bool):
        raise TypeError(f"metric {metric!r} is {type(doc).__name__}, not a number")
    return float(doc)


def run_leg(
    side: str,
    cwd: Path,
    bench: str,
    bench_args: list[str],
    metric: str,
    select: str | None,
    timeout_s: float,
) -> dict:
    """One subprocess bench leg, bracketed by calibration probes."""
    probe_before = calibrate.calibration_probe()
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.setdefault("NARWHAL_TPU_PREWARM", "0")
    env["NARWHAL_PERF_LEDGER"] = "0"  # the driver appends the one record
    with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as tmp:
        out_path = tmp.name
    try:
        os.unlink(out_path)  # inprocess treats an existing file as an array to extend
        cmd = [sys.executable, "-m", f"benchmark.{bench}", *bench_args, "--out", out_path]
        t0 = time.monotonic()
        proc = subprocess.run(
            cmd, cwd=cwd, env=env, capture_output=True, text=True, timeout=timeout_s
        )
        wall_s = time.monotonic() - t0
        if proc.returncode != 0:
            raise RuntimeError(
                f"{side} leg failed ({proc.returncode}): "
                f"{proc.stderr[-2000:] or proc.stdout[-2000:]}"
            )
        with open(out_path) as fh:
            doc = json.load(fh)
    finally:
        try:
            os.unlink(out_path)
        except OSError:
            pass
    probe_after = calibrate.calibration_probe()
    leg = {
        "side": side,
        "value": extract_metric(doc, metric, select),
        "wall_s": round(wall_s, 2),
        "calibration_before": probe_before,
        "calibration_after": probe_after,
    }
    # Socket-wall accounting rides along when the leg's bench records it
    # (liveness does), so pooled-vs-mesh fd pressure lands in the ledger.
    if isinstance(doc, dict) and doc.get("peak_fds_per_node") is not None:
        leg["peak_fds_per_node"] = doc["peak_fds_per_node"]
    return leg


def same_side_band(values: list[float]) -> float:
    """(max-min)/median over one side's repeats — the spread that same
    code on this same host produces, i.e. the floor under any claim."""
    if len(values) < 2:
        return float("inf")
    med = statistics.median(values)
    if med == 0:
        return float("inf")
    return (max(values) - min(values)) / abs(med)


def decide(
    base_values: list[float],
    head_values: list[float],
    probes: list[dict],
    *,
    lower_is_better: bool = False,
    calibration_gate: float = 0.5,
    min_band: float = 0.02,
) -> dict:
    """The verdict: win/null/regression, or no-verdict when the host
    drifted through the run. Pure so the fixtures can pin every branch."""
    if not base_values or not head_values:
        return {"verdict": "no-verdict", "reason": "a side produced no legs"}
    drift = 0.0
    for p in probes:
        for q in probes:
            drift = max(drift, calibrate.drift(p, q))
    band = max(same_side_band(base_values), same_side_band(head_values), min_band)
    base_med = statistics.median(base_values)
    head_med = statistics.median(head_values)
    verdict: dict = {
        "base_median": base_med,
        "head_median": head_med,
        "base_values": base_values,
        "head_values": head_values,
        "noise_band": band if band != float("inf") else None,
        "calibration_drift": round(drift, 4),
        "lower_is_better": lower_is_better,
    }
    if drift > calibration_gate:
        verdict["verdict"] = "no-verdict"
        verdict["reason"] = (
            f"host capacity swung {drift:.0%} mid-run "
            f"(gate {calibration_gate:.0%}): rerun when the host is quiet"
        )
        return verdict
    if band == float("inf") or base_med == 0:
        verdict["verdict"] = "no-verdict"
        verdict["reason"] = "need >=2 repeats per side for a noise band"
        return verdict
    ratio = head_med / base_med
    verdict["ratio"] = round(ratio, 4)
    delta = ratio - 1.0
    if abs(delta) <= band:
        verdict["verdict"] = "null"
        verdict["reason"] = (
            f"|{delta:+.1%}| inside the {band:.1%} same-side noise band"
        )
    else:
        improved = delta < 0 if lower_is_better else delta > 0
        verdict["verdict"] = "win" if improved else "regression"
        verdict["reason"] = (
            f"{delta:+.1%} vs a {band:.1%} noise band"
        )
    return verdict


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        epilog="bench args after `--` are passed through to the leg, e.g. "
        "`-- --duration 10 --rate 300`",
    )
    ap.add_argument("--base", required=True, help="git rev for the BASE legs")
    ap.add_argument("--bench", required=True, choices=BENCHES)
    ap.add_argument("--pairs", type=int, default=2,
                    help="interleaved base/head pairs (>=2 for a noise band)")
    ap.add_argument("--metric", default=None,
                    help="dotted path into the leg record (default per bench)")
    ap.add_argument("--select", default=None,
                    help="key=value row selector for list-shaped records")
    ap.add_argument("--lower-is-better", action="store_true",
                    help="the metric is a latency, not a throughput")
    ap.add_argument("--calibration-gate", type=float, default=0.5,
                    help="max relative capacity swing before refusing a verdict")
    ap.add_argument("--leg-timeout", type=float, default=900.0)
    ap.add_argument("--out", default=None, help="also write the verdict record here")
    ap.add_argument("bench_args", nargs="*",
                    help="passed through to `python -m benchmark.<bench>`")
    args = ap.parse_args(argv)

    metric = args.metric or DEFAULT_METRIC[args.bench]
    if not metric:
        ap.error(f"--metric is required for --bench {args.bench}")

    base_rev = subprocess.run(
        ["git", "rev-parse", args.base], cwd=REPO,
        capture_output=True, text=True, check=True,
    ).stdout.strip()
    head_rev = ledger.git_rev(REPO)

    worktree = Path(tempfile.mkdtemp(prefix="ab-base-"))
    subprocess.run(
        ["git", "worktree", "add", "--detach", str(worktree), base_rev],
        cwd=REPO, check=True, capture_output=True,
    )
    legs: list[dict] = []
    try:
        for pair in range(args.pairs):
            # Alternate leg order per pair so monotone drift cancels.
            order = ("base", "head") if pair % 2 == 0 else ("head", "base")
            for side in order:
                cwd = worktree if side == "base" else REPO
                print(
                    f"[pair {pair + 1}/{args.pairs}] {side} leg "
                    f"({base_rev[:10] if side == 'base' else head_rev[:10]}) ...",
                    flush=True,
                )
                leg = run_leg(
                    side, cwd, args.bench, list(args.bench_args),
                    metric, args.select, args.leg_timeout,
                )
                print(
                    f"  {metric}={leg['value']:.4g}  wall={leg['wall_s']}s  "
                    f"cal={leg['calibration_before']['ops_per_s']:.0f}->"
                    f"{leg['calibration_after']['ops_per_s']:.0f} ops/s",
                    flush=True,
                )
                legs.append(leg)
    finally:
        subprocess.run(
            ["git", "worktree", "remove", "--force", str(worktree)],
            cwd=REPO, capture_output=True,
        )

    probes = [leg["calibration_before"] for leg in legs] + [
        leg["calibration_after"] for leg in legs
    ]
    verdict = decide(
        [leg["value"] for leg in legs if leg["side"] == "base"],
        [leg["value"] for leg in legs if leg["side"] == "head"],
        probes,
        lower_is_better=args.lower_is_better,
        calibration_gate=args.calibration_gate,
    )
    verdict.update(
        {
            "metric": metric,
            "bench": args.bench,
            "base_rev": base_rev,
            "head_rev": head_rev,
            "pairs": args.pairs,
        }
    )
    record = {
        "verdict": verdict,
        "legs": legs,
        "bench_args": list(args.bench_args),
    }
    print(json.dumps(verdict, indent=1, sort_keys=True))
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(record, fh, indent=1, sort_keys=True)
            fh.write("\n")
    ledger.append(
        "ab",
        record,
        verdict=verdict,
        argv=["benchmark.ab", f"--base={args.base}", f"--bench={args.bench}"]
        + list(args.bench_args),
        rev=head_rev,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
