"""Saturation sweep: run the local bench across increasing input rates,
find the throughput knee, and emit a machine-readable result set.

The reference finds its knee by hand-editing fabfile parameters and re-running
`fab local`; this automates it:

    python -m benchmark.sweep --rates 5000 15000 30000 40000 --duration 20
    python -m benchmark.sweep --auto --duration 20      # geometric auto-sweep

Writes `.bench/sweep.json` (one record per run, LogParser.to_dict shape) and
prints a markdown table. Plot with `python -m benchmark.plot .bench/sweep.json`.
"""

from __future__ import annotations

import argparse
import json
import os

from narwhal_tpu.config import Parameters

from .local import BenchParameters, LocalBench
from .logs import ParseError


def run_once(rate: int, args) -> dict:
    bench = LocalBench(
        BenchParameters(
            nodes=args.nodes,
            workers=args.workers,
            rate=rate,
            tx_size=args.tx_size,
            duration=args.duration,
            faults=args.faults,
            consensus_protocol=args.consensus_protocol,
            crypto_backend=args.crypto_backend,
            dag_backend=args.dag_backend,
            dag_shards=args.dag_shards,
        ),
        node_parameters=Parameters(
            max_header_delay=args.max_header_delay,
            max_batch_delay=args.max_batch_delay,
            cert_format=args.cert_format,
            verify_rule=args.verify_rule,
        ),
    )
    parser = bench.run()
    record = parser.to_dict()
    record["consensus_protocol"] = args.consensus_protocol
    record["crypto_backend"] = args.crypto_backend
    record["dag_backend"] = args.dag_backend
    record["dag_shards"] = args.dag_shards
    # Self-describing A/B rows: W, the crash-fault count, and the
    # certificate wire form / accept rule are part of the experiment's
    # identity (the reference bench records `faults` too; cert_format moves
    # the wire floor the same way W moves the payload plane).
    record["workers_per_node"] = args.workers
    record["faults"] = args.faults
    record["cert_format"] = args.cert_format
    record["verify_rule"] = args.verify_rule
    # Socket-wall axis: worst per-process open-fd count across the fleet,
    # sampled at steady state (pooled transport target: O(N) per node).
    record["peak_fds_per_node"] = max(
        bench.child_fd_counts.values(), default=None
    )
    # Node 0's Telemetry.Scrape (gRPC, taken while the fleet was alive):
    # counters/gauges + histogram sums embedded so each sweep row is
    # self-contained for later A/Bs; other nodes' scrapes stay out to keep
    # rows bounded.
    record["telemetry_scrape"] = {
        "primary-0": bench.telemetry_scrapes.get("primary-0", {})
    }
    print(
        f"  rate {rate:>8,}: TPS {record['consensus_tps']:>10,.0f}  "
        f"lat {record['consensus_latency_ms']:>8,.0f} ms  "
        f"e2e {record['end_to_end_latency_ms']:>8,.0f} ms"
    )
    return record


def run_fault_rows(args) -> list[dict]:
    """The faults>0 axis, exercised: each row replays one seeded FaultPlan
    (narwhal_tpu.simnet.fuzz.generate_plan) on the simnet fabric — virtual
    clock, in-memory network — under the safety/liveness oracles. The seed
    IS the experiment's identity: the same seed replays the same schedule
    bit-identically, so a row here is reproducible where a wall-clock crash
    bench is not."""
    from narwhal_tpu.simnet import fuzz

    rows: list[dict] = []
    for seed in args.fault_seeds:
        plan = fuzz.generate_plan(
            seed, nodes=args.nodes, duration=args.fault_duration
        )
        ok, violation, result = fuzz.check_plan(
            plan,
            nodes=args.nodes,
            duration=args.fault_duration,
            load_rate=args.fault_load_rate,
            workers=args.workers,
        )
        rows.append(
            {
                "fault_plan_seed": seed,
                "plan": fuzz.describe_plan(plan),
                "faults": len(plan.events),
                "oracles_ok": ok,
                "violation": violation,
                "nodes": args.nodes,
                "duration_virtual_s": args.fault_duration,
                "load_rate": args.fault_load_rate,
                "rounds": list(result.rounds) if result else None,
                "commits": [len(c) for c in result.commits] if result else None,
                "event_log_digest": result.event_log_digest if result else None,
            }
        )
        events = [type(e).__name__ for e in plan.events]
        peak = max(result.rounds) if result and result.rounds else "-"
        print(
            f"  fault seed {seed}: {'ok' if ok else 'VIOLATION'}  "
            f"events {events}  peak round {peak}"
        )
    return rows


def sweep(args) -> list[dict]:
    results: list[dict] = []
    if args.auto:
        # Geometric ramp until TPS stops improving by >10% (the knee).
        rate = args.start_rate
        best = 0.0
        while True:
            try:
                record = run_once(rate, args)
            except ParseError as e:
                print(f"  rate {rate:,}: run failed ({e}); stopping sweep")
                break
            tps = record["consensus_tps"]
            if tps <= 0:
                print(f"  rate {rate:,}: no commits parsed; stopping sweep")
                break
            results.append(record)
            if tps < best * 1.1:
                break  # saturated: no meaningful gain from more input
            best = max(best, tps)
            rate *= 2
    else:
        for rate in args.rates:
            try:
                results.append(run_once(rate, args))
            except ParseError as e:
                print(f"  rate {rate:,}: run failed ({e})")
    return results


def render_table(results: list[dict]) -> str:
    lines = [
        "| input rate | consensus TPS | consensus lat | e2e lat |",
        "|---|---|---|---|",
    ]
    # FaultPlan rows have no rate axis; they are printed as they run.
    results = [r for r in results if "fault_plan_seed" not in r]
    for r in results:
        lines.append(
            f"| {r['input_rate']:,} | {r['consensus_tps']:,.0f} "
            f"| {r['consensus_latency_ms']:,.0f} ms "
            f"| {r['end_to_end_latency_ms']:,.0f} ms |"
        )
    if results:
        knee = max(results, key=lambda r: r["consensus_tps"])
        lines.append(
            f"\nknee: ~{knee['consensus_tps']:,.0f} tx/s "
            f"at input rate {knee['input_rate']:,}"
        )
    return "\n".join(lines)


def main() -> None:
    ap = argparse.ArgumentParser(prog="benchmark.sweep")
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--workers", type=int, default=1)
    ap.add_argument("--tx-size", type=int, default=512)
    ap.add_argument("--duration", type=int, default=20)
    ap.add_argument("--faults", type=int, default=0)
    ap.add_argument("--consensus-protocol", choices=("bullshark", "tusk"),
                    default="bullshark")
    ap.add_argument("--crypto-backend", choices=("cpu", "pool", "tpu"),
                    default="cpu")
    ap.add_argument("--dag-backend", choices=("cpu", "tpu"), default="cpu")
    ap.add_argument("--dag-shards", type=int, default=1)
    ap.add_argument("--cert-format", choices=("full", "compact"),
                    default="compact",
                    help="certificate wire form (committee-wide axis)")
    ap.add_argument("--verify-rule", choices=("strict", "cofactored"),
                    default="strict",
                    help="per-item ed25519 accept set (cofactored requires "
                    "--crypto-backend tpu)")
    ap.add_argument("--max-header-delay", type=float, default=0.1)
    ap.add_argument("--max-batch-delay", type=float, default=0.1)
    ap.add_argument("--rates", type=int, nargs="*", default=[5_000, 15_000, 30_000])
    ap.add_argument(
        "--fault-seeds", type=int, nargs="*", default=[],
        help="additionally run one simnet row per seed, each under the "
        "seeded FaultPlan that narwhal_tpu.simnet.fuzz.generate_plan "
        "derives from it (safety/liveness oracles applied)",
    )
    ap.add_argument(
        "--fault-load-rate", type=int, default=100,
        help="client tx/s injected during each FaultPlan row (virtual time)",
    )
    ap.add_argument(
        "--fault-duration", type=float, default=2.5,
        help="virtual seconds per FaultPlan row",
    )
    ap.add_argument("--auto", action="store_true", help="geometric ramp to the knee")
    ap.add_argument("--start-rate", type=int, default=2_000)
    ap.add_argument("--out", default=".bench/sweep.json")
    args = ap.parse_args()

    results = sweep(args) if (args.rates or args.auto) else []
    if args.fault_seeds:
        print("fault-plan rows (simnet, virtual clock):")
        results.extend(run_fault_rows(args))
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(results, f, indent=2)
    print(f"\nwrote {len(results)} records to {args.out}\n")
    print(render_table(results))
    import sys

    from tools.perf import ledger as perf_ledger

    perf_ledger.append("sweep", results, argv=sys.argv[1:])


if __name__ == "__main__":
    main()
