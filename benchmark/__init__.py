"""Benchmark harness: local cluster orchestration + log-derived metrics.

Reference design: /root/reference/benchmark/ (fabfile tasks, LocalBench,
LogParser). The measurement plane is structured log lines, identical in
spirit to the reference's `benchmark` feature logs.
"""
