"""LogParser: compute TPS / latency from node and client logs.

Reference: /root/reference/benchmark/benchmark/logs.py:171-244. Metrics:

- consensus TPS/BPS: committed batch bytes over [first proposal, last commit]
- consensus latency: commit time - proposal time, per batch digest
- end-to-end TPS: same bytes over [first client send, last commit]
- end-to-end latency: commit time of the batch containing a sample tx minus
  the client's send time for that sample

Log lines parsed (all emitted by the framework under normal INFO logging):
  primary:  "Created B<round>(<header>) -> <batch>"
            "Committed B<round>(<header>) -> <batch>"
  worker:   "Batch <digest> contains <n> B"
            "Batch <digest> contains sample tx <id>"
  client:   "Sending sample transaction <id>"
            "Transactions size: <n> B" / "Transactions rate: <n> tx/s"
"""

from __future__ import annotations

import glob
import os
from datetime import datetime, timezone
from re import findall, search
from statistics import mean


class ParseError(Exception):
    pass


def _ts(stamp: str) -> float:
    return (
        datetime.strptime(stamp, "%Y-%m-%dT%H:%M:%S.%f")
        .replace(tzinfo=timezone.utc)
        .timestamp()
    )


class LogParser:
    def __init__(
        self,
        clients: list[str],
        primaries: list[str],
        workers: list[str],
        faults: int = 0,
        parameters=None,  # narwhal_tpu.config.Parameters: echoed in SUMMARY
    ):
        self.faults = faults
        self.parameters = parameters
        self.committee_size = len(primaries) + faults
        self.workers_per_node = len(workers) // max(len(primaries), 1)

        # -- clients ------------------------------------------------------
        self.size = 512
        self.rate = []
        self.start: list[float] = []
        self.sent_samples: list[dict[int, float]] = []
        for log in clients:
            if search(r"Error", log) is not None:
                raise ParseError("Client(s) panicked")
            m = search(r"Transactions size: (\d+) B", log)
            if m:
                self.size = int(m.group(1))
            m = search(r"Transactions rate: (\d+) tx/s", log)
            if m:
                self.rate.append(int(m.group(1)))
            m = search(r"(.*?)Z .* Start sending transactions", log)
            if m:
                self.start.append(_ts(m.group(1)))
            samples = findall(r"(.*?)Z .* Sending sample transaction (\d+)", log)
            self.sent_samples.append({int(i): _ts(t) for t, i in samples})

        # -- primaries ----------------------------------------------------
        proposals: dict[str, float] = {}
        commits: dict[str, float] = {}
        for log in primaries:
            if search(r"ERROR|CRITICAL|Traceback", log) is not None:
                raise ParseError("Primary(s) panicked")
            for t, d in findall(r"(.*?)Z .* Created B\d+\([0-9a-f]+\) -> ([0-9a-f]+)", log):
                ts = _ts(t)
                if d not in proposals or ts < proposals[d]:
                    proposals[d] = ts
            for t, d in findall(r"(.*?)Z .* Committed B\d+\([0-9a-f]+\) -> ([0-9a-f]+)", log):
                ts = _ts(t)
                if d not in commits or ts < commits[d]:
                    commits[d] = ts
        self.proposals = proposals
        self.commits = {d: t for d, t in commits.items() if d in proposals}

        # -- workers ------------------------------------------------------
        self.sizes: dict[str, int] = {}
        self.received_samples: dict[int, str] = {}
        for log in workers:
            if search(r"ERROR|CRITICAL|Traceback", log) is not None:
                raise ParseError("Worker(s) panicked")
            for d, s in findall(r"Batch ([0-9a-f]+) contains (\d+) B", log):
                self.sizes[d] = int(s)
            for d, i in findall(r"Batch ([0-9a-f]+) contains sample tx (\d+)", log):
                self.received_samples[int(i)] = d

    @classmethod
    def process(cls, directory: str, faults: int = 0, parameters=None) -> "LogParser":
        def read(pattern: str) -> list[str]:
            out = []
            for path in sorted(glob.glob(os.path.join(directory, pattern))):
                with open(path, errors="replace") as f:
                    out.append(f.read())
            return out

        return cls(
            read("client-*.log"),
            read("primary-*.log"),
            read("worker-*.log"),
            faults,
            parameters=parameters,
        )

    # -- metrics (logs.py:165-208) ----------------------------------------

    def _committed_bytes(self) -> int:
        return sum(self.sizes.get(d, 0) for d in self.commits)

    def consensus_throughput(self) -> tuple[float, float, float]:
        if not self.commits:
            return 0.0, 0.0, 0.0
        start, end = min(self.proposals.values()), max(self.commits.values())
        duration = max(end - start, 1e-9)
        bps = self._committed_bytes() / duration
        return bps / self.size, bps, duration

    def consensus_latency(self) -> float:
        lat = [c - self.proposals[d] for d, c in self.commits.items()]
        return mean(lat) if lat else 0.0

    def end_to_end_throughput(self) -> tuple[float, float, float]:
        if not self.commits or not self.start:
            return 0.0, 0.0, 0.0
        start, end = min(self.start), max(self.commits.values())
        duration = max(end - start, 1e-9)
        bps = self._committed_bytes() / duration
        return bps / self.size, bps, duration

    def end_to_end_latency(self) -> float:
        lat = []
        for sent in self.sent_samples:
            for tx_id, batch in self.received_samples.items():
                if batch in self.commits and tx_id in sent:
                    lat.append(self.commits[batch] - sent[tx_id])
        return mean(lat) if lat else 0.0

    def to_dict(self) -> dict:
        """Machine-readable results for the sweep/plot/aggregate tooling."""
        c_tps, c_bps, duration = self.consensus_throughput()
        e_tps, e_bps, _ = self.end_to_end_throughput()
        return {
            "faults": self.faults,
            "committee_size": self.committee_size,
            "workers_per_node": self.workers_per_node,
            "input_rate": sum(self.rate),
            "tx_size": self.size,
            "duration_s": duration,
            "consensus_tps": c_tps,
            "consensus_bps": c_bps,
            "consensus_latency_ms": self.consensus_latency() * 1_000,
            "end_to_end_tps": e_tps,
            "end_to_end_bps": e_bps,
            "end_to_end_latency_ms": self.end_to_end_latency() * 1_000,
        }

    def result(self) -> str:
        c_tps, c_bps, duration = self.consensus_throughput()
        c_lat = self.consensus_latency() * 1_000
        e_tps, e_bps, _ = self.end_to_end_throughput()
        e_lat = self.end_to_end_latency() * 1_000
        # Node-parameter echo (the reference SUMMARY's config block,
        # benchmark/benchmark/logs.py:199-244).
        params = ""
        if self.parameters is not None:
            p = self.parameters
            params = (
                f" Header size: {p.header_size:,} B\n"
                f" Max header delay: {round(p.max_header_delay * 1000):,} ms\n"
                f" GC depth: {p.gc_depth:,} round(s)\n"
                f" Sync retry delay: {round(p.sync_retry_delay * 1000):,} ms\n"
                f" Sync retry nodes: {p.sync_retry_nodes:,} node(s)\n"
                f" batch size: {p.batch_size:,} B\n"
                f" Max batch delay: {round(p.max_batch_delay * 1000):,} ms\n"
            )
        return (
            "\n"
            "-----------------------------------------\n"
            " SUMMARY:\n"
            "-----------------------------------------\n"
            " + CONFIG:\n"
            f" Faults: {self.faults} node(s)\n"
            f" Committee size: {self.committee_size} node(s)\n"
            f" Worker(s) per node: {self.workers_per_node} worker(s)\n"
            f" Input rate: {sum(self.rate):,} tx/s\n"
            f" Transaction size: {self.size:,} B\n"
            f" Execution time: {round(duration):,} s\n"
            f"{params}"
            "\n"
            " + RESULTS:\n"
            f" Consensus TPS: {round(c_tps):,} tx/s\n"
            f" Consensus BPS: {round(c_bps):,} B/s\n"
            f" Consensus latency: {round(c_lat):,} ms\n"
            "\n"
            f" End-to-end TPS: {round(e_tps):,} tx/s\n"
            f" End-to-end BPS: {round(e_bps):,} B/s\n"
            f" End-to-end latency: {round(e_lat):,} ms\n"
            "-----------------------------------------\n"
        )
