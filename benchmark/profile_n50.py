"""Profile the N=50 in-process committee (VERDICT r4 item 4: find the
frame-path costs that bind the 1-core host, then native-lane them).

    python -m benchmark.profile_n50 [--nodes 50] [--duration 45]

Dumps cProfile stats to --out and prints the top cumulative/tottime
functions.
"""

from __future__ import annotations

import argparse
import asyncio
import cProfile
import io
import pstats


def main() -> None:
    ap = argparse.ArgumentParser(prog="benchmark.profile_n50")
    ap.add_argument("--nodes", type=int, default=50)
    ap.add_argument("--rate", type=int, default=100)
    ap.add_argument("--duration", type=int, default=45)
    ap.add_argument("--out", default="/tmp/narwhal_n50.pstats")
    ap.add_argument("--crypto-backend", default="cpu")
    ap.add_argument("--cert-format", default="full")
    args = ap.parse_args()

    from benchmark.inprocess import run_bench

    bench_args = argparse.Namespace(
        nodes=args.nodes,
        workers=1,
        rate=args.rate,
        tx_size=512,
        duration=args.duration,
        drain_tail=3.0,
        max_header_delay=0.05,
        max_batch_delay=0.05,
        warmup_timeout=600.0,
        faults=0,
        consensus_protocol="bullshark",
        crypto_backend=args.crypto_backend,
        dag_backend="cpu",
        dag_shards=1,
        cert_format=args.cert_format,
        no_precompile=True,
    )
    prof = cProfile.Profile()
    prof.enable()
    try:
        record = asyncio.run(run_bench(bench_args))
        print(record)
    except Exception as e:
        # The warmup/progress assert can fail on a thrashing host; the
        # frames burned up to that point are exactly the hot control-plane
        # paths we are profiling, so keep the stats either way.
        record = {"error": str(e)[:200]}
        print(record)
    finally:
        prof.disable()
        prof.dump_stats(args.out)
    for sort in ("tottime", "cumulative"):
        s = io.StringIO()
        pstats.Stats(prof, stream=s).sort_stats(sort).print_stats(25)
        print(s.getvalue())


if __name__ == "__main__":
    main()
