"""Generate committee/worker/key/parameter files for the docker-compose
localnet (reference: benchmark config generation, adapted to service DNS
names instead of localhost ports)."""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from narwhal_tpu.config import Authority, Committee, Parameters, WorkerCache, WorkerInfo
from narwhal_tpu.crypto import KeyPair

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "configs")
N = 4


def main() -> None:
    os.makedirs(OUT, exist_ok=True)
    keypairs = [KeyPair.generate() for _ in range(N)]
    authorities = {}
    workers = {}
    for i, kp in enumerate(keypairs):
        network_kp = KeyPair.generate()
        worker_kp = KeyPair.generate()
        with open(f"{OUT}/key-{i}.json", "w") as f:
            json.dump(
                {
                    "name": kp.public.hex(),
                    "seed": kp.private_bytes().hex(),
                    "network_seed": network_kp.private_bytes().hex(),
                    "worker_network_seeds": {"0": worker_kp.private_bytes().hex()},
                },
                f,
            )
        authorities[kp.public] = Authority(
            stake=1,
            primary_address=f"primary-{i}:4000",
            network_key=network_kp.public,
        )
        workers[kp.public] = {
            0: WorkerInfo(
                name=worker_kp.public,
                transactions=f"worker-{i}:4001",
                worker_address=f"worker-{i}:4002",
            )
        }
    Committee(authorities).export(f"{OUT}/committee.json")
    WorkerCache(workers).export(f"{OUT}/workers.json")
    Parameters().export(f"{OUT}/parameters.json")
    print(f"wrote configs for {N} validators to {OUT}")


if __name__ == "__main__":
    main()
