"""Perf observatory gates: calibration probe, commit-keyed ledger schema,
A/B verdict logic, epilogue attribution, simnet profiler attribution,
waterfall edge cases, and the TELEMETRY_ADDR boot-line contract.

The ledger schema tests here ARE the tier-1 gate the ledger docstring
promises: an unregistered record shape (new field, new kind, malformed
line) fails here instead of silently forking benchmark/results/."""

import json
import time
from pathlib import Path

import pytest

from benchmark import ab
from benchmark.local import parse_telemetry_addr
from narwhal_tpu import tracing
from tools.perf import calibrate, epilogue, ledger, simnet_profile

REPO = Path(__file__).resolve().parent.parent


# ---------------------------------------------------------------- calibrate


def test_calibration_probe_shape():
    probe = calibrate.calibration_probe(budget_s=0.02)
    for key in (
        "unix_time", "probe_s", "chain_ops", "ops_per_s",
        "loadavg_1m", "loadavg_5m", "loadavg_15m", "cpu_count",
    ):
        assert key in probe
    assert probe["ops_per_s"] > 0
    assert probe["chain_ops"] >= 1
    assert probe["probe_s"] == pytest.approx(0.02, rel=2.0)
    json.dumps(probe)  # JSON-ready by contract


def test_drift_is_symmetric_and_guards_nonpositive():
    a = {"ops_per_s": 100.0}
    b = {"ops_per_s": 150.0}
    assert calibrate.drift(a, a) == 0.0
    assert calibrate.drift(a, b) == pytest.approx(0.5)
    assert calibrate.drift(b, a) == pytest.approx(0.5)
    assert calibrate.drift(a, {"ops_per_s": 0.0}) == float("inf")
    assert calibrate.drift({}, b) == float("inf")


def test_host_context_snapshot():
    ctx = calibrate.host_context(probe_budget_s=0.01)
    assert "calibration" in ctx and ctx["calibration"]["ops_per_s"] > 0
    assert isinstance(ctx["concurrent"], list)
    # This test runs under pytest, so the self-excluding scan must not
    # count US — but a concurrent suite (the known flake source) would
    # flip the bool. Only the type is pinnable here.
    assert isinstance(ctx["concurrent_pytest"], bool)


# ------------------------------------------------------------------ ledger


def _valid_record(**overrides):
    record = {
        "schema": ledger.SCHEMA,
        "kind": "microbench",
        "git_rev": "deadbeef",
        "recorded_unix": time.time(),
        "host": {"calibration": {"ops_per_s": 1000.0}},
        "payload": {"x": 1},
    }
    record.update(overrides)
    return record


def test_ledger_accepts_valid_record():
    assert ledger.validate_record(_valid_record()) == []


def test_ledger_schema_is_closed():
    errors = ledger.validate_record(_valid_record(extra_field=1))
    assert any("unregistered field 'extra_field'" in e for e in errors)


def test_ledger_rejects_unregistered_kind():
    errors = ledger.validate_record(_valid_record(kind="bogus_bench"))
    assert any("unregistered kind" in e for e in errors)


def test_ledger_rejects_missing_required_and_bad_types():
    record = _valid_record()
    del record["git_rev"]
    record["payload"] = "not a dict"
    errors = ledger.validate_record(record)
    assert any("missing required field 'git_rev'" in e for e in errors)
    assert any("field 'payload'" in e for e in errors)
    assert ledger.validate_record("not even a dict")
    assert ledger.validate_record(
        _valid_record(schema="narwhal-perf-ledger/999")
    )


def test_ledger_requires_host_calibration():
    errors = ledger.validate_record(_valid_record(host={"loadavg": 1.0}))
    assert any("calibration" in e for e in errors)


def test_ledger_pins_verdict_vocabulary():
    ok = _valid_record(verdict={"verdict": "null"})
    assert ledger.validate_record(ok) == []
    bad = _valid_record(verdict={"verdict": "maybe-faster"})
    assert any("verdict.verdict" in e for e in ledger.validate_record(bad))


def test_ledger_append_read_roundtrip(tmp_path, monkeypatch):
    path = tmp_path / "ledger.jsonl"
    monkeypatch.setenv("NARWHAL_PERF_LEDGER_PATH", str(path))
    monkeypatch.setenv("NARWHAL_PERF_LEDGER", "1")
    rec = ledger.append(
        "microbench", {"rows": 3}, argv=["--fast"], note="unit test"
    )
    assert rec is not None and rec["kind"] == "microbench"
    ledger.append("ab", {"legs": []}, verdict={"verdict": "win"})
    # "fuzz" is a registered kind (deliberate KINDS extension): one record
    # per FaultPlan-fuzzer campaign, payload = the campaign summary.
    ledger.append("fuzz", {"count": 3, "ok": True, "failures": []})
    records = ledger.read_ledger(path)
    assert [r["kind"] for r in records] == ["microbench", "ab", "fuzz"]
    assert records[0]["argv"] == ["--fast"]
    assert records[1]["verdict"]["verdict"] == "win"
    # Every appended record carries the host calibration it measured under.
    assert all(r["host"]["calibration"]["ops_per_s"] > 0 for r in records)


def test_ledger_disabled_appends_nothing(tmp_path, monkeypatch):
    path = tmp_path / "ledger.jsonl"
    monkeypatch.setenv("NARWHAL_PERF_LEDGER_PATH", str(path))
    monkeypatch.setenv("NARWHAL_PERF_LEDGER", "0")
    assert ledger.append("microbench", {}) is None
    assert not path.exists()


def test_ledger_build_refuses_invalid():
    with pytest.raises(ValueError, match="unregistered kind"):
        ledger.build_record("bogus_bench", {})


def test_ledger_read_raises_on_malformed_line(tmp_path):
    path = tmp_path / "ledger.jsonl"
    path.write_text(json.dumps(_valid_record()) + "\nnot json\n")
    with pytest.raises(ValueError, match="malformed ledger line"):
        ledger.read_ledger(path)


def test_checked_in_ledger_is_schema_valid():
    """The gate over the real artifact: every line of the checked-in
    ledger must parse and validate (read_ledger raises otherwise)."""
    records = ledger.read_ledger(ledger.DEFAULT_PATH)
    for r in records:
        assert r["schema"] == ledger.SCHEMA
        assert r["kind"] in ledger.KINDS


def test_legacy_results_tolerated():
    """Pre-ledger benchmark/results/*.json stay loadable: the classifier
    must tag them `legacy`, never `error` — and stamped records must
    validate. Zero hard failures over the whole directory."""
    report = ledger.classify_results_dir()
    assert report, "benchmark/results/ should not be empty"
    errors = [r for r in report if r["status"] == "error"]
    assert errors == []
    assert all(r["status"] in {"ledger", "legacy"} for r in report)


# ------------------------------------------------------------ benchmark.ab


def test_extract_metric_paths():
    doc = {"a": {"b": 2.5}, "flat": 7}
    assert ab.extract_metric(doc, "a.b", None) == 2.5
    assert ab.extract_metric(doc, "flat", None) == 7.0
    rows = [{"bench": "x", "v": 1}, {"bench": "y", "v": 2}]
    assert ab.extract_metric(rows, "v", None) == 2.0  # last row
    assert ab.extract_metric(rows, "v", "bench=x") == 1.0
    with pytest.raises(KeyError):
        ab.extract_metric(rows, "v", "bench=zzz")
    with pytest.raises(KeyError):
        ab.extract_metric(doc, "a.missing", None)
    with pytest.raises(TypeError):
        ab.extract_metric({"s": "fast"}, "s", None)


def test_same_side_band():
    assert ab.same_side_band([100.0]) == float("inf")
    assert ab.same_side_band([100.0, 110.0]) == pytest.approx(10 / 105)
    assert ab.same_side_band([0.0, 0.0]) == float("inf")


_QUIET = [{"ops_per_s": 1000.0}, {"ops_per_s": 1010.0}]


def test_decide_null_on_aa():
    v = ab.decide([100.0, 102.0], [101.0, 100.0], _QUIET)
    assert v["verdict"] == "null"
    assert v["noise_band"] >= 0.02


def test_decide_win_and_regression():
    v = ab.decide([100.0, 101.0], [140.0, 141.0], _QUIET)
    assert v["verdict"] == "win"
    v = ab.decide([100.0, 101.0], [60.0, 61.0], _QUIET)
    assert v["verdict"] == "regression"


def test_decide_lower_is_better_flips_sides():
    latency_drop = ab.decide(
        [100.0, 101.0], [60.0, 61.0], _QUIET, lower_is_better=True
    )
    assert latency_drop["verdict"] == "win"
    latency_rise = ab.decide(
        [100.0, 101.0], [140.0, 141.0], _QUIET, lower_is_better=True
    )
    assert latency_rise["verdict"] == "regression"


def test_decide_refuses_verdict_on_calibration_drift():
    cliff = [{"ops_per_s": 1000.0}, {"ops_per_s": 100.0}]
    v = ab.decide([100.0, 101.0], [200.0, 201.0], cliff)
    assert v["verdict"] == "no-verdict"
    assert "capacity swung" in v["reason"]


def test_decide_refuses_verdict_without_repeats():
    v = ab.decide([100.0], [140.0], _QUIET)
    assert v["verdict"] == "no-verdict"
    assert ab.decide([], [1.0], _QUIET)["verdict"] == "no-verdict"


def test_decide_noise_band_swallows_small_delta():
    # Same-side spread of 20% must swallow a 10% head/base delta.
    v = ab.decide([100.0, 120.0], [110.0, 132.0], _QUIET)
    assert v["verdict"] == "null"


# ------------------------------------------------- epilogue attribution


def test_epilogue_attribute_books_balance_synthetic():
    dumps = [{
        "events": [
            ("span", "device_pack", "aa", 0.0, 0.1, {"n": 8}),
            ("span", "pack_items", "aa", 0.0, 0.06, {"n_items": 24}),
            ("span", "pack_groups", "aa", 0.06, 0.1, {"n_groups": 2}),
            ("span", "device_dispatch", "aa", 0.1, 0.12, {"n": 8}),
            ("span", "device_mask_readback", "aa", 0.5, 0.7, {"n": 8}),
            ("span", "host_epilogue", "aa", 0.7, 1.7, {"n": 8}),
            ("span", "epilogue_unpack", "aa", 0.7, 0.9, {"n": 8}),
            ("span", "epilogue_commit", "aa", 0.9, 1.7, {"n_accepted": 8}),
            ("span", "seal", "aa", 0.0, 1.0, None),  # non-device: ignored
        ]
    }]
    report = epilogue.attribute(dumps)
    assert report["totals"]["batches"] == 1
    row = report["batches"][0]
    assert row["n"] == 8
    assert row["epilogue_rel_err"] == pytest.approx(0.0, abs=1e-6)
    assert row["epilogue_parts_s"] == pytest.approx(1.0)
    assert report["totals"]["epilogue_rel_err"] <= 0.10
    # epilogue dominates this synthetic timeline: 1.0 of 1.32 total
    assert report["totals"]["epilogue_share_of_batch"] == pytest.approx(
        1.0 / 1.32, abs=0.01
    )
    table = epilogue.render_table(report)
    assert "books balance" in table and "aa" in table


def test_epilogue_attribute_reports_unattributed_drift():
    """A stage added inside host_epilogue WITHOUT a sub-span must surface
    as unattributed time / rel err, not vanish."""
    dumps = [{
        "events": [
            ("span", "host_epilogue", "bb", 0.0, 1.0, {"n": 4}),
            ("span", "epilogue_unpack", "bb", 0.0, 0.2, {"n": 4}),
            ("span", "epilogue_commit", "bb", 0.2, 0.6, {"n_accepted": 4}),
        ]
    }]
    row = epilogue.attribute(dumps)["batches"][0]
    assert row["epilogue_unattributed_s"] == pytest.approx(0.4)
    assert row["epilogue_rel_err"] == pytest.approx(0.4)


class _StubCert:
    is_compact = False

    def __init__(self, tag: int):
        self.digest = bytes([tag]) * 32

    def verify_items(self, committee):
        return [(self.digest, b"sig", b"pk")] * 3


class _StubVerifier:
    def submit(self, items):
        return list(items)

    def collect(self, handle):
        return [True] * len(handle)

    def submit_groups(self, groups):
        return list(groups)

    def collect_groups(self, handle):
        return [True] * len(handle)


class _StubEngine:
    committee = None

    def process_batch(self, state, index, accepted):
        return [("out", c.digest) for c in accepted]


def test_pipeline_emits_partitioned_sub_spans():
    """Drive the REAL FusedCertificatePipeline (stub device + engine) and
    assert the new pack/epilogue sub-spans partition their parents — the
    within-10% acceptance property, by construction."""
    from narwhal_tpu.tpu.pipeline import FusedCertificatePipeline

    tracer = tracing.Tracer(node="test", enabled=True, sample=1.0, ring=256)
    pipe = FusedCertificatePipeline(
        _StubVerifier(), _StubEngine(), state=None, depth=1, tracer=tracer
    )
    pipe.feed([_StubCert(1), _StubCert(2)], committee=object())
    pipe.feed([_StubCert(3)], committee=object())  # forces resolve of batch 1
    outs = pipe.drain()
    assert len(outs) == 3 and not pipe.rejected

    report = epilogue.attribute([tracer.dump()])
    assert report["totals"]["batches"] == 2
    for row in report["batches"]:
        for stage in (
            "device_pack", "pack_items", "pack_groups", "device_dispatch",
            "device_mask_readback", "host_epilogue",
            "epilogue_unpack", "epilogue_commit",
        ):
            assert stage in row, f"missing sub-span {stage}"
        # The books balance far inside the 10% acceptance gate: the two
        # epilogue sub-spans partition [t_epilogue, t_end] exactly.
        assert row["epilogue_rel_err"] <= 0.10
        assert row["pack_items"] + row["pack_groups"] <= row["device_pack"] + 1e-9
    assert report["totals"]["epilogue_rel_err"] <= 0.10


def test_epilogue_stages_registered_in_catalog():
    """Every device-plane span stage the attributor consumes must be a
    registered `span:<stage>` row in the metrics catalog."""
    catalog = json.loads((REPO / "tools" / "metrics_catalog.json").read_text())
    names = {row["name"] for row in catalog}
    for stage in epilogue.STAGES:
        assert f"span:{stage}" in names, f"span:{stage} not in catalog"


# ------------------------------------------------------ simnet profiler


def test_simnet_profile_classify_table():
    cases = {
        ("narwhal_tpu/simnet/fabric.py", "_deliver"): "fabric_deliver",
        ("narwhal_tpu/simnet/fabric.py", "append"): "event_log",
        ("narwhal_tpu/simnet/clock.py", "run_until"): "sim_clock",
        ("narwhal_tpu/network/auth.py", "seal"): "auth_aead",
        ("narwhal_tpu/crypto.py", "verify"): "signing",
        ("narwhal_tpu/network/rpc.py", "send"): "wire_rpc",
        ("narwhal_tpu/codec.py", "encode"): "codec",
        ("narwhal_tpu/primary/core.py", "process"): "protocol",
        ("/usr/lib/python3.11/asyncio/events.py", "run"): "asyncio_loop",
        ("/some/random/lib.py", "f"): "other",
    }
    for (filename, func), want in cases.items():
        assert simnet_profile.classify(filename, func) == want, (filename, func)


@pytest.mark.slow
def test_simnet_profile_attributes_hot_path():
    report = simnet_profile.profile_scenario(
        nodes=4, duration=1.5, load_rate=60, seed=11
    )
    assert report["total_self_s"] > 0
    # The acceptance floor: the component table must name >=80% of the
    # self time, or it has drifted from the code.
    assert report["attributed_share"] >= 0.8, report["components"]
    components = report["components"]
    # Ranked by share, descending; shares decompose (sum to ~1 with other).
    shares = [c["share"] for c in components]
    assert shares == sorted(shares, reverse=True)
    assert sum(shares) == pytest.approx(1.0, abs=0.01)
    counters = report["scenario"]["fabric_counters"]
    assert counters["delivers"] > 0 and counters["bytes_delivered"] > 0
    assert counters["transmits"] >= counters["delivers"]
    table = simnet_profile.render_table(report)
    assert "fabric" in table


# ------------------------------------------------- waterfall edge cases


def _span(stage, key, t0, t1):
    return ("span", stage, key, t0, t1, None)


def test_waterfall_orphan_span_becomes_root():
    wf = tracing.waterfall([{"events": [_span("seal", "aa", 0.0, 1.0)]}])
    assert "aa" in wf and wf["aa"]["stages"]["seal"] == [0.0, 1.0]
    assert wf["aa"]["ancestors"] == []


def test_waterfall_missing_link_yields_partial_chain():
    # The batch->header link dump was lost (node down): the certificate
    # still surfaces, just without the batch's seal stage.
    events = [
        _span("seal", "batch1", 0.0, 1.0),
        _span("commit", "cert1", 2.0, 3.0),
    ]
    wf = tracing.waterfall([{"events": events}])
    assert "cert1" in wf and "seal" not in wf["cert1"]["stages"]
    assert "batch1" in wf  # orphan root, not silently dropped


def test_waterfall_self_link_is_ignored():
    events = [
        ("link", "propose", "aa", "aa"),
        _span("commit", "aa", 0.0, 1.0),
    ]
    wf = tracing.waterfall([{"events": events}])
    assert wf["aa"]["ancestors"] == []


def test_waterfall_cyclic_links_terminate():
    # Two nodes disagreeing about link direction: a <-> b. Must neither
    # hang nor blow the stack; each root sees the other as lineage once.
    events = [
        ("link", "propose", "aa", "bb"),
        ("link", "propose", "bb", "aa"),
        _span("commit", "aa", 0.0, 1.0),
        _span("commit", "bb", 0.0, 1.0),
        _span("seal", "cc", 0.0, 0.5),
    ]
    wf = tracing.waterfall([{"events": events}])
    assert wf["aa"]["ancestors"] == ["bb"]
    assert wf["bb"]["ancestors"] == ["aa"]
    assert "cc" in wf


def test_waterfall_skips_malformed_events():
    events = [
        ("span", "seal"),            # too short for a span
        ("link", "propose", "aa"),   # too short for a link
        ("span",),                   # degenerate
        _span("commit", "dd", 0.0, 1.0),
    ]
    wf = tracing.waterfall([{"events": events}])
    assert list(wf) == ["dd"]


def test_waterfall_keeps_earliest_opening_span():
    events = [
        _span("seal", "aa", 5.0, 6.0),
        _span("seal", "aa", 1.0, 2.0),
        _span("commit", "aa", 7.0, 8.0),
    ]
    wf = tracing.waterfall([{"events": events}])
    assert wf["aa"]["stages"]["seal"] == [1.0, 2.0]


# --------------------------------------------- TELEMETRY_ADDR contract


def test_parse_telemetry_addr_units():
    assert parse_telemetry_addr("") is None
    assert parse_telemetry_addr("INFO nothing machine readable\n") is None
    assert parse_telemetry_addr("TELEMETRY_ADDR=127.0.0.1:9\n") == "127.0.0.1:9"
    # Last occurrence wins (a restarted node rebinds).
    two = "TELEMETRY_ADDR=127.0.0.1:9\nnoise\nTELEMETRY_ADDR=127.0.0.1:10\n"
    assert parse_telemetry_addr(two) == "127.0.0.1:10"
    # Empty value = no gRPC plane mounted.
    assert parse_telemetry_addr("TELEMETRY_ADDR=\n") is None
    # Leading whitespace tolerated; the '=' split keeps IPv6-ish colons.
    assert parse_telemetry_addr("  TELEMETRY_ADDR=[::1]:50\n") == "[::1]:50"


def test_parse_telemetry_addr_real_boot_log():
    """Pin the contract against a REAL primary boot log (captured from
    `python -m narwhal_tpu run ... primary` — see tests/artifacts/). If
    the node stops printing the machine-readable line, this fails before
    benchmark/local.py silently loses its telemetry scrapes."""
    log = (REPO / "tests" / "artifacts" / "primary_boot.log").read_text()
    addr = parse_telemetry_addr(log)
    assert addr is not None
    host, _, port = addr.rpartition(":")
    assert host and int(port) > 0
    # The legacy human log line also present -> both planes agree.
    assert f"gRPC public API listening on {addr}" in log
