"""Transport authentication: rogue sockets must not reach validator-internal
handlers, and authorization is per-role.

Reference behavior: the anemo mesh only accepts connections from known
ed25519 PeerIds (/root/reference/network/src/p2p.rs:26-158,
worker/src/worker.rs:137-146), so a random socket can never deliver a
Reconfigure("shutdown") or DeleteBatches to a worker. These tests prove the
same for the handshake-authenticated TCP mesh.
"""

import asyncio

from narwhal_tpu.config import WorkerInfo
from narwhal_tpu.crypto import KeyPair
from narwhal_tpu.fixtures import CommitteeFixture
from narwhal_tpu.messages import (
    CleanupMsg,
    DeleteBatchesMsg,
    ReconfigureMsg,
    SynchronizeMsg,
    WorkerBatchRequest,
)
from narwhal_tpu.network import (
    Credentials,
    NetworkClient,
    RpcError,
    RpcServer,
    committee_resolver,
)
from narwhal_tpu.stores import NodeStorage
from narwhal_tpu.worker import Worker


async def _spawn_authed_worker(f: CommitteeFixture, index: int = 0) -> Worker:
    a = f.authorities[index]
    worker = Worker(
        a.public,
        0,
        f.committee,
        f.worker_cache,
        f.parameters,
        NodeStorage(None).batch_store,
        network_keypair=a.worker_keypairs[0],
    )
    await worker.spawn()
    # Publish the bound mesh address so resolvers map it to the worker's key.
    info = f.worker_cache.workers[a.public][0]
    f.worker_cache.workers[a.public][0] = WorkerInfo(
        name=info.name,
        transactions=worker.transactions_address,
        worker_address=worker.worker_address,
    )
    return worker


def _credentials(f: CommitteeFixture, keypair: KeyPair) -> Credentials:
    return Credentials(
        keypair, committee_resolver(lambda: f.committee, lambda: f.worker_cache)
    )


def test_rogue_socket_cannot_shutdown_worker(run):
    """An unauthenticated socket can neither shut a worker down nor purge
    its store; the worker keeps serving its own (authenticated) primary."""

    async def scenario():
        f = CommitteeFixture(size=4, workers=1)
        worker = await _spawn_authed_worker(f)
        rogue = NetworkClient()
        own_primary = NetworkClient(
            credentials=_credentials(f, f.authorities[0].network_keypair)
        )
        try:
            ok = await rogue.unreliable_send(
                worker.worker_address, ReconfigureMsg("shutdown", ""), timeout=2.0
            )
            assert not ok, "rogue shutdown must be rejected"
            ok = await rogue.unreliable_send(
                worker.worker_address, DeleteBatchesMsg((b"\x01" * 32,)), timeout=2.0
            )
            assert not ok, "rogue delete must be rejected"
            assert worker.rx_reconfigure.value.kind == "boot"

            # The worker still serves its own primary after the attacks.
            assert await own_primary.unreliable_send(
                worker.worker_address, CleanupMsg(1), timeout=5.0
            )
        finally:
            rogue.close()
            own_primary.close()
            await worker.shutdown()

    run(scenario())


def test_wrong_role_is_unauthorized(run):
    """A *valid committee identity of the wrong role* is authenticated but
    not authorized: a peer authority's primary cannot drive this worker's
    control plane, while the same-lane peer worker may use the batch plane."""

    async def scenario():
        f = CommitteeFixture(size=4, workers=1)
        worker = await _spawn_authed_worker(f)
        peer_primary = NetworkClient(
            credentials=_credentials(f, f.authorities[1].network_keypair)
        )
        peer_worker = NetworkClient(
            credentials=_credentials(f, f.authorities[1].worker_keypairs[0])
        )
        try:
            try:
                await peer_primary.request(
                    worker.worker_address,
                    SynchronizeMsg((b"\x02" * 32,), f.authorities[1].public),
                    timeout=2.0,
                )
                raise AssertionError("peer primary must not drive Synchronize")
            except RpcError as e:
                assert "unauthorized" in str(e)
            try:
                await peer_primary.request(
                    worker.worker_address, DeleteBatchesMsg((b"\x03" * 32,)), timeout=2.0
                )
                raise AssertionError("peer primary must not delete batches")
            except RpcError as e:
                assert "unauthorized" in str(e)

            # Batch plane: the same-lane peer worker is allowed.
            resp = await peer_worker.request(
                worker.worker_address, WorkerBatchRequest((b"\x04" * 32,)), timeout=5.0
            )
            assert resp is not None
        finally:
            peer_primary.close()
            peer_worker.close()
            await worker.shutdown()

    run(scenario())


def test_session_aead_rejects_forged_and_replayed_frames():
    """Post-handshake frames are AEAD-sealed per direction with a counter
    nonce: a relay that forwarded the handshake verbatim still cannot read,
    inject, tamper with, or replay frames (it never learns the X25519
    shared secret, so it cannot produce a valid ciphertext)."""
    import os

    import pytest

    from narwhal_tpu.network.auth import AuthError, Session

    k_c2s, k_s2c = os.urandom(32), os.urandom(32)
    client = Session(send_key=k_c2s, recv_key=k_s2c)
    server = Session(send_key=k_s2c, recv_key=k_c2s)

    body = b"hello-frame"
    ct = client.seal_body(0, 1, 7, body)
    assert body not in ct  # encrypted, not just authenticated
    assert server.open_body(0, 1, 7, ct) == body  # legitimate frame passes

    ct2 = client.seal_body(0, 2, 7, body)
    # Tampered ciphertext.
    with pytest.raises(AuthError):
        server.open_body(0, 2, 7, bytes([ct2[0] ^ 1]) + ct2[1:])
    # Tampered header (AAD mismatch).
    with pytest.raises(AuthError):
        server.open_body(0, 99, 7, ct2)
    # Replay of the first frame (stale nonce).
    with pytest.raises(AuthError):
        server.open_body(0, 1, 7, ct)
    # The in-sequence original still decrypts after the failed attempts.
    assert server.open_body(0, 2, 7, ct2) == body


def test_authenticated_request_roundtrip_uses_macs(run):
    """A credentialed request to an auth server succeeds end-to-end (frames
    sealed both ways), and a plaintext frame injected onto the authenticated
    server port is torn down, not dispatched."""

    async def scenario():
        f = CommitteeFixture(size=4, workers=1)
        worker = await _spawn_authed_worker(f)
        own_primary = NetworkClient(
            credentials=_credentials(f, f.authorities[0].network_keypair)
        )
        try:
            assert await own_primary.unreliable_send(
                worker.worker_address, CleanupMsg(3), timeout=5.0
            )
            # Raw plaintext frame straight at the authed port: no dispatch.
            host, port = worker.worker_address.rsplit(":", 1)
            reader, writer = await asyncio.open_connection(host, int(port))
            import struct

            body = b""
            writer.write(struct.pack("<IBQH", len(body), 0, 1, CleanupMsg.TAG) + body)
            await writer.drain()
            # Server drops the connection (handshake never completed).
            got = await asyncio.wait_for(reader.read(1024), 6.0)
            # Either immediate close, or only the HELLO frame then close.
            assert b"" == got or got[4:5] == b"\x03", got
            writer.close()
        finally:
            own_primary.close()
            await worker.shutdown()

    run(scenario())


def test_client_rejects_wrong_server_identity(run):
    """A server presenting a key other than the committee's entry for that
    address (MITM / misdirected connection) is refused by the client."""

    async def scenario():
        f = CommitteeFixture(size=4, workers=1)
        imposter = RpcServer(auth_keypair=KeyPair.generate())
        port = await imposter.start("127.0.0.1", 0)
        addr = f"127.0.0.1:{port}"
        # Committee claims authority 0's primary lives at the imposter's port.
        from narwhal_tpu.config import Authority

        pk = f.authorities[0].public
        auth = f.committee.authorities[pk]
        f.committee.authorities[pk] = Authority(auth.stake, addr, auth.network_key)
        client = NetworkClient(
            credentials=_credentials(f, f.authorities[1].network_keypair)
        )
        try:
            try:
                await client.request(addr, CleanupMsg(1), timeout=2.0)
                raise AssertionError("client must refuse a wrong server identity")
            except RpcError as e:
                assert "handshake" in str(e)
        finally:
            client.close()
            await imposter.stop()

    run(scenario())


def test_authenticated_server_is_deny_by_default():
    """A route registered on an authenticated server without an allow
    predicate must be rejected at registration time: the handshake proves
    key possession, not committee membership, so an unrestricted route
    would silently be world-open (ADVICE r2)."""
    import pytest

    from narwhal_tpu.network.rpc import ALLOW_ANY

    srv = RpcServer(auth_keypair=KeyPair.generate())
    with pytest.raises(ValueError, match="deny-by-default"):
        srv.route(CleanupMsg, lambda msg, peer: None)
    # Explicit opt-out and explicit predicates still register.
    srv.route(CleanupMsg, lambda msg, peer: None, allow=ALLOW_ANY)
    srv.route(SynchronizeMsg, lambda msg, peer: None, allow=lambda p: False)
    # Unauthenticated (public-plane) servers keep the permissive default.
    RpcServer().route(CleanupMsg, lambda msg, peer: None)


def test_reference_keygen_draws_through_entropy_seam():
    """`_RefX25519PrivateKey.generate` (the no-OpenSSL backend's ephemeral
    keygen) must draw through the `set_entropy` seam, not os.urandom: when
    the reference class is aliased as X25519PrivateKey, seeded scenarios
    need deterministic ephemeral keys here too (the PR-9 nonce divergence,
    one layer down)."""
    from narwhal_tpu.network import auth

    drawn = []

    def fixed(n: int) -> bytes:
        drawn.append(n)
        return bytes(range(n))

    prev = auth.set_entropy(fixed)
    try:
        k1 = auth._RefX25519PrivateKey.generate()
        k2 = auth._RefX25519PrivateKey.generate()
    finally:
        auth.set_entropy(prev)
    assert drawn == [32, 32]
    assert k1._k == k2._k == bytes(range(32))
    # Seam restored: generation is entropic again.
    assert auth._RefX25519PrivateKey.generate()._k != k1._k
