"""Public consensus API integration tests: spawn real nodes in external-
consensus mode and exercise Validator/Proposer/Configuration end-to-end.

Mirrors /root/reference/primary/tests/integration_tests_{validator,proposer,
configuration}_api.rs (collections fetch/removal, rounds, node_read_causal,
network info updates)."""

import asyncio

import pytest

from narwhal_tpu.cluster import Cluster
from narwhal_tpu.messages import (
    GetCollectionsRequest,
    GetPrimaryAddressRequest,
    NewEpochRequest,
    NewNetworkInfoRequest,
    NodeReadCausalRequest,
    ReadCausalRequest,
    RemoveCollectionsRequest,
    RoundsRequest,
    SubmitTransactionStreamMsg,
)
from narwhal_tpu.network import NetworkClient, RpcError


async def _api_cluster():
    cluster = Cluster(size=4, workers=1, internal_consensus=False)
    await cluster.start()
    client = NetworkClient()
    # Drive some load so headers carry payload.
    target = cluster.authorities[0].worker_transactions_address(0)
    txs = tuple(bytes([7]) * 32 + bytes([i]) for i in range(32))
    await client.request(target, SubmitTransactionStreamMsg(txs))
    return cluster, client


async def _wait_rounds(client, api, pk, minimum, timeout=30.0):
    deadline = asyncio.get_event_loop().time() + timeout
    while True:
        try:
            resp = await client.request(api, RoundsRequest(pk))
            if resp.newest_round >= minimum:
                return resp
        except RpcError:
            pass
        if asyncio.get_event_loop().time() > deadline:
            raise AssertionError(f"rounds never reached {minimum}")
        await asyncio.sleep(0.2)


def test_proposer_and_validator_api(run):
    async def scenario():
        cluster, client = await _api_cluster()
        try:
            node = cluster.authorities[0]
            api = node.primary.api_address
            pk = node.name

            rounds = await _wait_rounds(client, api, pk, 2)
            assert rounds.oldest_round <= rounds.newest_round

            # NodeReadCausal at the newest round -> causal collection ids.
            nrc = await client.request(
                api, NodeReadCausalRequest(pk, rounds.newest_round)
            )
            assert len(nrc.digests) >= 1

            # ReadCausal from the same start.
            rc = await client.request(api, ReadCausalRequest(nrc.digests[0]))
            assert set(rc.digests) == set(nrc.digests)

            # GetCollections over the walked ids: every result resolves
            # (payload batches or an explicit per-collection error).
            got = await client.request(api, GetCollectionsRequest(nrc.digests))
            assert len(got.results) == len(nrc.digests)
            ok = [r for r in got.results if r[2] == ""]
            assert ok, f"no collection resolved: {[r[2] for r in got.results]}"
            assert any(batches for _, batches, _ in ok)

            # RemoveCollections of everything fetched succeeds (Empty/Ack).
            await client.request(
                api, RemoveCollectionsRequest(tuple(d for d, _, _ in got.results))
            )
            # Removed collections no longer resolve locally.
            again = await client.request(
                api, GetCollectionsRequest((got.results[0][0],))
            )
            assert again.results[0][2] != "" or not again.results[0][1]
        finally:
            client.close()
            await cluster.shutdown()

    run(scenario(), timeout=90.0)


def test_configuration_api(run):
    async def scenario():
        cluster, client = await _api_cluster()
        try:
            node = cluster.authorities[0]
            api = node.primary.api_address

            addr = await client.request(api, GetPrimaryAddressRequest())
            assert addr.address == node.primary.address

            with pytest.raises(RpcError, match="Not Implemented"):
                await client.request(api, NewEpochRequest(1))

            # Wrong epoch is rejected.
            validators = tuple(
                (pk, a.stake, a.primary_address)
                for pk, a in cluster.committee.authorities.items()
            )
            with pytest.raises(RpcError, match="does not match current epoch"):
                await client.request(api, NewNetworkInfoRequest(7, validators))

            # Correct epoch with identical info is accepted.
            await client.request(
                api, NewNetworkInfoRequest(cluster.committee.epoch, validators)
            )

            # Unknown key in the update is rejected.
            bad = ((b"\x05" * 32, 1, "127.0.0.1:1"),) + validators[1:]
            with pytest.raises(RpcError, match="unknown authority"):
                await client.request(
                    api, NewNetworkInfoRequest(cluster.committee.epoch, bad)
                )
        finally:
            client.close()
            await cluster.shutdown()

    run(scenario(), timeout=90.0)
