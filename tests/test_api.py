"""Public consensus API integration tests: spawn real nodes in external-
consensus mode and exercise Validator/Proposer/Configuration end-to-end.

Mirrors /root/reference/primary/tests/integration_tests_{validator,proposer,
configuration}_api.rs (collections fetch/removal, rounds, node_read_causal,
network info updates)."""

import asyncio

import pytest

from narwhal_tpu.cluster import Cluster
from narwhal_tpu.messages import (
    GetCollectionsRequest,
    GetPrimaryAddressRequest,
    NewEpochRequest,
    NewNetworkInfoRequest,
    NodeReadCausalRequest,
    ReadCausalRequest,
    RemoveCollectionsRequest,
    RoundsRequest,
    SubmitTransactionStreamMsg,
)
from narwhal_tpu.network import NetworkClient, RpcError


async def _api_cluster():
    cluster = Cluster(size=4, workers=1, internal_consensus=False)
    await cluster.start()
    client = NetworkClient()
    # Drive some load so headers carry payload.
    target = cluster.authorities[0].worker_transactions_address(0)
    txs = tuple(bytes([7]) * 32 + bytes([i]) for i in range(32))
    await client.request(target, SubmitTransactionStreamMsg(txs))
    return cluster, client


async def _wait_rounds(client, api, pk, minimum, timeout=30.0):
    deadline = asyncio.get_event_loop().time() + timeout
    while True:
        try:
            resp = await client.request(api, RoundsRequest(pk))
            if resp.newest_round >= minimum:
                return resp
        except RpcError:
            pass
        if asyncio.get_event_loop().time() > deadline:
            raise AssertionError(f"rounds never reached {minimum}")
        await asyncio.sleep(0.2)


def test_proposer_and_validator_api(run):
    async def scenario():
        cluster, client = await _api_cluster()
        try:
            node = cluster.authorities[0]
            api = node.primary.api_address
            pk = node.name

            rounds = await _wait_rounds(client, api, pk, 2)
            assert rounds.oldest_round <= rounds.newest_round

            # NodeReadCausal at the newest round -> causal collection ids.
            nrc = await client.request(
                api, NodeReadCausalRequest(pk, rounds.newest_round)
            )
            assert len(nrc.digests) >= 1

            # ReadCausal from the same start.
            rc = await client.request(api, ReadCausalRequest(nrc.digests[0]))
            assert set(rc.digests) == set(nrc.digests)

            # GetCollections over the walked ids: every result resolves
            # (payload batches or an explicit per-collection error).
            got = await client.request(api, GetCollectionsRequest(nrc.digests))
            assert len(got.results) == len(nrc.digests)
            ok = [r for r in got.results if r[2] == ""]
            assert ok, f"no collection resolved: {[r[2] for r in got.results]}"
            assert any(batches for _, batches, _ in ok)

            # RemoveCollections of everything fetched succeeds (Empty/Ack).
            await client.request(
                api, RemoveCollectionsRequest(tuple(d for d, _, _ in got.results))
            )
            # Removed collections no longer resolve locally.
            again = await client.request(
                api, GetCollectionsRequest((got.results[0][0],))
            )
            assert again.results[0][2] != "" or not again.results[0][1]
        finally:
            client.close()
            await cluster.shutdown()

    run(scenario(), timeout=90.0)


def test_configuration_api(run):
    async def scenario():
        cluster, client = await _api_cluster()
        try:
            node = cluster.authorities[0]
            api = node.primary.api_address

            addr = await client.request(api, GetPrimaryAddressRequest())
            assert addr.address == node.primary.address

            with pytest.raises(RpcError, match="Not Implemented"):
                await client.request(api, NewEpochRequest(1))

            # Wrong epoch is rejected.
            validators = tuple(
                (pk, a.stake, a.primary_address)
                for pk, a in cluster.committee.authorities.items()
            )
            with pytest.raises(RpcError, match="does not match current epoch"):
                await client.request(api, NewNetworkInfoRequest(7, validators))

            # Correct epoch with identical info is accepted.
            await client.request(
                api, NewNetworkInfoRequest(cluster.committee.epoch, validators)
            )

            # Unknown key in the update is rejected.
            bad = ((b"\x05" * 32, 1, "127.0.0.1:1"),) + validators[1:]
            with pytest.raises(RpcError, match="unknown authority"):
                await client.request(
                    api, NewNetworkInfoRequest(cluster.committee.epoch, bad)
                )
        finally:
            client.close()
            await cluster.shutdown()

    run(scenario(), timeout=90.0)


def test_validator_api_error_paths(run):
    """Unknown digests, unknown validators and malformed ids must come back
    as errors/empty results — never hangs or crashes (the reference's
    validator-API integration suite exercises exactly these,
    integration_tests_validator_api.rs)."""

    async def scenario():
        cluster, client = await _api_cluster()
        try:
            node = cluster.authorities[0]
            api = node.primary.api_address
            pk = node.name
            await _wait_rounds(client, api, pk, 2)

            # GetCollections of a digest that exists nowhere: per-collection
            # error, same-length results, service stays up.
            ghost = bytes([0xEE]) * 32
            got = await client.request(api, GetCollectionsRequest((ghost,)))
            assert len(got.results) == 1
            assert got.results[0][2] != ""  # explicit error string

            # ReadCausal from an unknown start: an error reply, not a hang.
            try:
                # client.request enforces its own 10s timeout -> RpcError.
                rc = await client.request(api, ReadCausalRequest(ghost))
                assert rc.digests == ()
            except RpcError:
                pass  # an explicit error is equally acceptable

            # Rounds for a key outside the committee: error, not a crash.
            try:
                resp = await client.request(api, RoundsRequest(bytes(32)))
                raise AssertionError(f"unknown validator answered: {resp}")
            except RpcError:
                pass

            # NodeReadCausal beyond any produced round: error/empty.
            try:
                nrc = await client.request(api, NodeReadCausalRequest(pk, 1 << 40))
                assert nrc.digests == ()
            except RpcError:
                pass

            # The service still works after all the garbage.
            rounds = await client.request(api, RoundsRequest(pk))
            assert rounds.newest_round >= 2
        finally:
            client.close()
            await cluster.shutdown()

    run(scenario(), timeout=90.0)


def test_cross_node_collection_fetch(run):
    """Collections authored by node B are retrievable through node A's
    Validator API (the reference's headline integration case: fetching
    collections that live on a peer — BlockWaiter + BlockSynchronizer)."""

    async def scenario():
        cluster, client = await _api_cluster()
        try:
            a, b = cluster.authorities[0], cluster.authorities[1]
            # Whether a given causal cut carries payload is a race between
            # batch sealing and header proposal (headers seal on the
            # max_header_delay timer even when payload-empty), so poll
            # advancing rounds until a resolved collection has batches —
            # sustaining load so later headers keep carrying payload.
            target = cluster.authorities[0].worker_transactions_address(0)
            deadline = asyncio.get_event_loop().time() + 60.0
            want_round = 2
            while True:
                txs = tuple(bytes([9]) * 32 + bytes([i]) for i in range(16))
                await client.request(target, SubmitTransactionStreamMsg(txs))
                # B's newest causal collections...
                rounds_b = await _wait_rounds(
                    client, b.primary.api_address, b.name, want_round
                )
                nrc = await client.request(
                    b.primary.api_address,
                    NodeReadCausalRequest(b.name, rounds_b.newest_round),
                )
                assert nrc.digests
                # ...fetched through A's API.
                got = await client.request(
                    a.primary.api_address, GetCollectionsRequest(nrc.digests),
                    timeout=30.0,  # covers the server-side peer-sync window
                )
                assert len(got.results) == len(nrc.digests)
                resolved = [r for r in got.results if r[2] == ""]
                assert resolved, (
                    f"nothing resolved cross-node: {[r[2] for r in got.results]}"
                )
                # At least one resolved collection must carry real batches,
                # so the fetch genuinely exercised payload retrieval rather
                # than only empty timer-driven headers.
                if any(batches for _, batches, _ in resolved):
                    break
                assert asyncio.get_event_loop().time() < deadline, (
                    "no resolved collection ever carried batches"
                )
                want_round = rounds_b.newest_round + 1
        finally:
            client.close()
            await cluster.shutdown()

    run(scenario(), timeout=120.0)
