"""BlockWaiter request orchestration: concurrent-request dedup, per-batch
worker deadline, bounded transport retry, per-worker fetch coalescing.

Reference semantics: /root/reference/primary/src/block_waiter.rs:45-845 —
one in-flight fetch per block digest (pending map), the worker fetch under a
10 s timeout mapped to BatchTimeout; a dead worker yields an error reply,
never a hang. Delta: a block's batch fetches group by target worker and ride
ONE coalesced RequestBatchesMsg per worker.
"""

import asyncio

from narwhal_tpu.config import WorkerInfo
from narwhal_tpu.fixtures import CommitteeFixture
from narwhal_tpu.messages import RequestBatchesMsg, RequestedBatchesMsg
from narwhal_tpu.network import NetworkClient, RpcServer
from narwhal_tpu.primary.block_waiter import BlockError, BlockWaiter
from narwhal_tpu.stores import NodeStorage
from narwhal_tpu.types import Batch


def _fixture_with_block(f, *batches: Batch):
    """Store a certificate whose payload names `batches` (worker 0); returns
    (certificate, certificate_store)."""
    storage = NodeStorage(None)
    header = f.header(author=0, round=1, payload={b.digest: 0 for b in batches})
    cert = f.certificate(header)
    storage.certificate_store.write(cert)
    return cert, storage.certificate_store


def _point_worker_at(f, port: int) -> None:
    """Rewire authority 0's worker 0 mesh address to `port`."""
    pk = f.authorities[0].public
    info = f.worker_cache.workers[pk][0]
    f.worker_cache.workers[pk][0] = WorkerInfo(
        name=info.name,
        transactions=info.transactions,
        worker_address=f"127.0.0.1:{port}",
    )


def _waiter(f, store, **kwargs) -> BlockWaiter:
    return BlockWaiter(
        f.authorities[0].public, f.worker_cache, store, NetworkClient(), **kwargs
    )


def _serve(*batches: Batch):
    """A coalesced-fetch handler answering from `batches` (misses are
    authoritative found=False entries, like the real worker)."""
    by_digest = {b.digest: b.to_bytes() for b in batches}

    async def on_request(msg: RequestBatchesMsg, peer):
        return RequestedBatchesMsg(
            tuple(
                (d, d in by_digest, by_digest.get(d, b""))
                for d in msg.digests
            )
        )

    return on_request


def test_concurrent_get_block_dedups_to_one_worker_rpc(run):
    """Two concurrent fetches of the same block issue ONE coalesced fetch to
    the worker (block_waiter.rs pending map)."""

    async def scenario():
        f = CommitteeFixture(size=4)
        batch = Batch((b"tx-one", b"tx-two"))
        cert, store = _fixture_with_block(f, batch)
        calls = 0
        srv = RpcServer()
        inner = _serve(batch)

        async def on_request(msg: RequestBatchesMsg, peer):
            nonlocal calls
            calls += 1
            await asyncio.sleep(0.1)  # hold both callers in flight
            return await inner(msg, peer)

        srv.route(RequestBatchesMsg, on_request)
        port = await srv.start("127.0.0.1", 0)
        _point_worker_at(f, port)
        waiter = _waiter(f, store)
        try:
            r1, r2 = await asyncio.gather(
                waiter.get_block(cert.digest), waiter.get_block(cert.digest)
            )
            assert calls == 1
            assert r1.batches == r2.batches
            assert r1.batches[0][1] == batch
            # After completion the pending entry is gone: a fresh fetch
            # issues a new RPC.
            await waiter.get_block(cert.digest)
            assert calls == 2
        finally:
            await srv.stop()

    run(scenario())


def test_multi_batch_block_coalesces_to_one_rpc(run):
    """A block naming many batches on one worker costs ONE RequestBatchesMsg
    round trip carrying every digest, not one RPC per batch."""

    async def scenario():
        f = CommitteeFixture(size=4)
        batches = [Batch((b"tx-%d" % i,)) for i in range(16)]
        cert, store = _fixture_with_block(f, *batches)
        calls = 0
        digests_seen: list = []
        srv = RpcServer()
        inner = _serve(*batches)

        async def on_request(msg: RequestBatchesMsg, peer):
            nonlocal calls
            calls += 1
            digests_seen.extend(msg.digests)
            return await inner(msg, peer)

        srv.route(RequestBatchesMsg, on_request)
        port = await srv.start("127.0.0.1", 0)
        _point_worker_at(f, port)
        waiter = _waiter(f, store)
        try:
            resp = await waiter.get_block(cert.digest)
            assert calls == 1
            assert sorted(digests_seen) == sorted(b.digest for b in batches)
            fetched = dict(resp.batches)
            for b in batches:
                assert fetched[b.digest] == b
        finally:
            await srv.stop()

    run(scenario())


def test_dead_worker_yields_block_error_not_hang(run):
    """A worker that is down (connection refused) produces a BatchError
    reply after the bounded retries — the executor's fetch never hangs."""

    async def scenario():
        f = CommitteeFixture(size=4)
        batch = Batch((b"tx",))
        cert, store = _fixture_with_block(f, batch)
        # Grab a port with no listener.
        from narwhal_tpu.config import get_available_port

        _point_worker_at(f, get_available_port())
        waiter = _waiter(f, store, retry_attempts=2, retry_delay=0.05)
        t0 = asyncio.get_event_loop().time()
        try:
            await waiter.get_block(cert.digest)
            raise AssertionError("dead worker must raise BlockError")
        except BlockError as e:
            assert e.kind == "BatchError"
        assert asyncio.get_event_loop().time() - t0 < 5.0

    run(scenario())


def test_slow_worker_maps_to_batch_timeout(run):
    """A worker that holds the connection past the per-batch deadline maps
    to BatchTimeout (block_waiter.rs 10 s timeout), not a transport error."""

    async def scenario():
        f = CommitteeFixture(size=4)
        batch = Batch((b"tx",))
        cert, store = _fixture_with_block(f, batch)
        srv = RpcServer()
        inner = _serve(batch)

        async def on_request(msg: RequestBatchesMsg, peer):
            await asyncio.sleep(30.0)
            return await inner(msg, peer)

        srv.route(RequestBatchesMsg, on_request)
        port = await srv.start("127.0.0.1", 0)
        _point_worker_at(f, port)
        waiter = _waiter(f, store, batch_timeout=0.3)
        try:
            try:
                await waiter.get_block(cert.digest)
                raise AssertionError("slow worker must raise BlockError")
            except BlockError as e:
                assert e.kind == "BatchTimeout"
        finally:
            await srv.stop()

    run(scenario())


def test_transient_worker_failure_retries_and_succeeds(run):
    """The first attempt hits a refused connection; the worker comes back
    before the retries are exhausted and the block resolves."""

    async def scenario():
        f = CommitteeFixture(size=4)
        batch = Batch((b"tx-a", b"tx-b"))
        cert, store = _fixture_with_block(f, batch)
        from narwhal_tpu.config import get_available_port

        port = get_available_port()
        _point_worker_at(f, port)
        waiter = _waiter(f, store, retry_attempts=4, retry_delay=0.2)

        srv = RpcServer()
        srv.route(RequestBatchesMsg, _serve(batch))

        async def bring_up_later():
            await asyncio.sleep(0.3)
            await srv.start("127.0.0.1", port)

        up = asyncio.ensure_future(bring_up_later())
        try:
            resp = await waiter.get_block(cert.digest)
            assert resp.batches[0][1] == batch
        finally:
            await up
            await srv.stop()

    run(scenario())


def test_worker_lacking_batch_is_authoritative_no_retry(run):
    """A found=False entry in a partial response is an authoritative answer:
    one RPC, immediate BatchError (retrying our own worker for a batch it
    doesn't have is the reference's BatchError reply path, not a retry
    case) — even when OTHER digests in the same response are found."""

    async def scenario():
        f = CommitteeFixture(size=4)
        have = Batch((b"tx-have",))
        lack = Batch((b"tx-lack",))
        cert, store = _fixture_with_block(f, have, lack)
        calls = 0
        srv = RpcServer()
        inner = _serve(have)  # `lack` answers found=False

        async def on_request(msg: RequestBatchesMsg, peer):
            nonlocal calls
            calls += 1
            return await inner(msg, peer)

        srv.route(RequestBatchesMsg, on_request)
        port = await srv.start("127.0.0.1", 0)
        _point_worker_at(f, port)
        waiter = _waiter(f, store)
        try:
            try:
                await waiter.get_block(cert.digest)
                raise AssertionError("missing batch must raise BlockError")
            except BlockError as e:
                assert e.kind == "BatchError"
            assert calls == 1
        finally:
            await srv.stop()

    run(scenario())


def test_corrupt_batch_bytes_rejected(run):
    """A worker returning bytes whose digest mismatches the requested batch
    digest is rejected (the zero-copy digest check)."""

    async def scenario():
        f = CommitteeFixture(size=4)
        batch = Batch((b"tx",))
        cert, store = _fixture_with_block(f, batch)
        srv = RpcServer()

        async def on_request(msg: RequestBatchesMsg, peer):
            return RequestedBatchesMsg(
                tuple((d, True, Batch((b"evil",)).to_bytes()) for d in msg.digests)
            )

        srv.route(RequestBatchesMsg, on_request)
        port = await srv.start("127.0.0.1", 0)
        _point_worker_at(f, port)
        waiter = _waiter(f, store)
        try:
            try:
                await waiter.get_block(cert.digest)
                raise AssertionError("corrupt batch must raise BlockError")
            except BlockError as e:
                assert e.kind == "BatchError"
        finally:
            await srv.stop()

    run(scenario())
