"""The driver-contract multi-chip dry run, under pytest.

MULTICHIP_r02.json shipped broken (`ok=false`) because nothing in CI ever
executed `__graft_entry__.dryrun_multichip` — the only multi-chip evidence
this environment can produce lived outside the test suite. These tests run
the exact driver entry points on conftest's 8 virtual CPU devices so any
regression in the sharded consensus step (mesh construction, in_shardings,
the unsharded comparison leg's device pinning) fails the suite instead of
the round artifact.
"""

import os

import jax
import numpy as np
import pytest

import __graft_entry__


def test_entry_compiles_and_runs():
    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    out.block_until_ready()
    # reach_mask returns a [W, N] mask covering the whole window.
    W, N, _ = args[0].shape
    assert out.shape == (W, N)


@pytest.mark.parametrize("n_devices", [8, 4, 2, 1])
def test_dryrun_multichip(n_devices):
    # Pass the CPU device list explicitly: under pytest the default backend
    # can still be the real chip (the axon plugin preregisters before
    # conftest's JAX_PLATFORMS=cpu applies), and dryrun's small-backend
    # fallback would not trigger for n_devices=1.
    cpus = jax.devices("cpu")
    if len(cpus) < n_devices:
        pytest.skip(f"need {n_devices} cpu devices")
    __graft_entry__.dryrun_multichip(n_devices, devices=cpus)


def test_dryrun_multichip_odd_mesh():
    """n_devices not divisible by 2 exercises the auth=1 mesh fallback."""
    cpus = jax.devices("cpu")
    if len(cpus) < 3:
        pytest.skip("need 3 cpu devices")
    __graft_entry__.dryrun_multichip(3, devices=cpus)


def test_dryrun_pins_unsharded_dispatch():
    """MULTICHIP_r04 regression class: module-level jitted kernels called
    through library code dispatch to the *process default backend* (the
    real chip on the bench host — version-skewed that day), not the dry
    run's pinned devices, so the CPU-mesh correctness artifact went red
    for a reason unrelated to sharding.

    Reproduce the failure mode on the virtual mesh: pin the dry run to the
    UPPER half of the 8 CPU devices, spy on the module-level chain_commit
    dispatch (the route an unmeshed TpuBullshark takes, including its
    device-resident DagWindow tensors), and assert no kernel output ever
    lands on a device outside the pinned list. Without
    `jax.default_device(devs[0])` pinning, those outputs land on the
    process default device (cpus[0]) and this test fails — exactly the
    class of bug the r02/r04 artifacts died on, which `devices=cpus`
    tests structurally cannot see.

    Runs in a SUBPROCESS (tests/_dryrun_guard.py): pinning to cpus[4:]
    compiles a second full kernel set for a non-default device, and
    XLA:CPU's compiler segfaulted when that compile landed on top of a
    long-lived suite process's accumulated state (r5; 125 GB free, so not
    memory) — process isolation keeps the guard deterministic."""
    import subprocess
    import sys

    script = os.path.join(os.path.dirname(__file__), "_dryrun_guard.py")
    proc = subprocess.run(
        [sys.executable, script],
        capture_output=True,
        text=True,
        timeout=1800,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(script))),
    )
    tail = (proc.stdout + proc.stderr)[-2000:]
    assert proc.returncode == 0, f"dryrun guard failed (rc={proc.returncode}): {tail}"
    assert "GUARD-OK" in proc.stdout or "SKIP" in proc.stdout, tail
