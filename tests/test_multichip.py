"""The driver-contract multi-chip dry run, under pytest.

MULTICHIP_r02.json shipped broken (`ok=false`) because nothing in CI ever
executed `__graft_entry__.dryrun_multichip` — the only multi-chip evidence
this environment can produce lived outside the test suite. These tests run
the exact driver entry points on conftest's 8 virtual CPU devices so any
regression in the sharded consensus step (mesh construction, in_shardings,
the unsharded comparison leg's device pinning) fails the suite instead of
the round artifact.
"""

import jax
import numpy as np
import pytest

import __graft_entry__


def test_entry_compiles_and_runs():
    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    out.block_until_ready()
    # reach_mask returns a [W, N] mask covering the whole window.
    W, N, _ = args[0].shape
    assert out.shape == (W, N)


@pytest.mark.parametrize("n_devices", [8, 4, 2, 1])
def test_dryrun_multichip(n_devices):
    # Pass the CPU device list explicitly: under pytest the default backend
    # can still be the real chip (the axon plugin preregisters before
    # conftest's JAX_PLATFORMS=cpu applies), and dryrun's small-backend
    # fallback would not trigger for n_devices=1.
    cpus = jax.devices("cpu")
    if len(cpus) < n_devices:
        pytest.skip(f"need {n_devices} cpu devices")
    __graft_entry__.dryrun_multichip(n_devices, devices=cpus)


def test_dryrun_multichip_odd_mesh():
    """n_devices not divisible by 2 exercises the auth=1 mesh fallback."""
    cpus = jax.devices("cpu")
    if len(cpus) < 3:
        pytest.skip("need 3 cpu devices")
    __graft_entry__.dryrun_multichip(3, devices=cpus)
