"""The driver-contract multi-chip dry run, under pytest.

MULTICHIP_r02.json shipped broken (`ok=false`) because nothing in CI ever
executed `__graft_entry__.dryrun_multichip` — the only multi-chip evidence
this environment can produce lived outside the test suite. These tests run
the exact driver entry points on conftest's 8 virtual CPU devices so any
regression in the sharded consensus step (mesh construction, in_shardings,
the unsharded comparison leg's device pinning) fails the suite instead of
the round artifact.
"""

import os

import jax
import numpy as np
import pytest

import __graft_entry__


def test_entry_compiles_and_runs():
    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    out.block_until_ready()
    # reach_mask returns a [W, N] mask covering the whole window.
    W, N, _ = args[0].shape
    assert out.shape == (W, N)


@pytest.mark.parametrize("n_devices", [8, 4, 2, 1])
def test_dryrun_multichip(n_devices):
    # Pass the CPU device list explicitly: under pytest the default backend
    # can still be the real chip (the axon plugin preregisters before
    # conftest's JAX_PLATFORMS=cpu applies), and dryrun's small-backend
    # fallback would not trigger for n_devices=1.
    cpus = jax.devices("cpu")
    if len(cpus) < n_devices:
        pytest.skip(f"need {n_devices} cpu devices")
    __graft_entry__.dryrun_multichip(n_devices, devices=cpus)


def test_dryrun_multichip_odd_mesh():
    """n_devices not divisible by 2 exercises the auth=1 mesh fallback."""
    cpus = jax.devices("cpu")
    if len(cpus) < 3:
        pytest.skip("need 3 cpu devices")
    __graft_entry__.dryrun_multichip(3, devices=cpus)


def test_dryrun_pins_unsharded_dispatch():
    """MULTICHIP_r04 regression class: module-level jitted kernels called
    through library code dispatch to the *process default backend* (the
    real chip on the bench host — version-skewed that day), not the dry
    run's pinned devices, so the CPU-mesh correctness artifact went red
    for a reason unrelated to sharding.

    Reproduce the failure mode on the virtual mesh: pin the dry run to the
    UPPER half of the 8 CPU devices, spy on the module-level chain_commit
    dispatch (the route an unmeshed TpuBullshark takes, including its
    device-resident DagWindow tensors), and assert no kernel output ever
    lands on a device outside the pinned list. Without
    `jax.default_device(devs[0])` pinning, those outputs land on the
    process default device (cpus[0]) and this test fails — exactly the
    class of bug the r02/r04 artifacts died on, which `devices=cpus`
    tests structurally cannot see.

    Runs in a SUBPROCESS (tests/_dryrun_guard.py): pinning to cpus[4:]
    compiles a second full kernel set for a non-default device, and
    XLA:CPU's compiler segfaulted when that compile landed on top of a
    long-lived suite process's accumulated state (r5; 125 GB free, so not
    memory) — process isolation keeps the guard deterministic."""
    import subprocess
    import sys

    script = os.path.join(os.path.dirname(__file__), "_dryrun_guard.py")
    proc = subprocess.run(
        [sys.executable, script],
        capture_output=True,
        text=True,
        timeout=1800,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(script))),
    )
    tail = (proc.stdout + proc.stderr)[-2000:]
    assert proc.returncode == 0, f"dryrun guard failed (rc={proc.returncode}): {tail}"
    assert "GUARD-OK" in proc.stdout or "SKIP" in proc.stdout, tail


def _mesh_verifier(mode="item"):
    """A mesh-sharded verifier sharing mesh (4-device 'data') and bucket
    (fixed 32) with the dryrun leg and tests/test_kernel_registry.py, so
    the whole suite pays each staged-kernel compile once per process."""
    from narwhal_tpu.tpu.verifier import TpuVerifier, data_mesh

    cpus = jax.devices("cpu")
    if len(cpus) < 4:
        pytest.skip("need 4 cpu devices")
    return TpuVerifier(
        max_bucket=32, msm_min_bucket=16, mode=mode, fixed_bucket=True,
        mesh=data_mesh(4, devices=cpus[:4]),
    )


def test_fused_pipeline_matches_sequential_host():
    """The tentpole's fusion leg: FusedCertificatePipeline (mesh-sharded
    verify -> one place_batch scatter per batch -> chain_commit with
    deferred readbacks) commits the IDENTICAL sequence to a host engine
    fed the same fully-signed stream one certificate at a time, with the
    host touching each certificate once."""
    from narwhal_tpu.consensus import Bullshark, ConsensusState
    from narwhal_tpu.fixtures import CommitteeFixture, make_signed_certificates
    from narwhal_tpu.stores import NodeStorage
    from narwhal_tpu.tpu.dag_kernels import TpuBullshark
    from narwhal_tpu.tpu.pipeline import FusedCertificatePipeline
    from narwhal_tpu.types import Certificate

    f = CommitteeFixture(size=4)
    genesis = {c.digest for c in Certificate.genesis(f.committee)}
    certs, _ = make_signed_certificates(f, 1, 10, genesis)

    host_state = ConsensusState(Certificate.genesis(f.committee))
    host = Bullshark(f.committee, NodeStorage(None).consensus_store, 50)
    host_out = []
    hi = 0
    for c in certs:
        outs = host.process_certificate(host_state, hi, c)
        hi += len(outs)
        host_out.extend(outs)
    assert host_out  # the optimal DAG commits

    pipe_state = ConsensusState(Certificate.genesis(f.committee))
    engine = TpuBullshark(f.committee, NodeStorage(None).consensus_store, 50)
    pipe = FusedCertificatePipeline(_mesh_verifier(), engine, pipe_state)
    for lo in range(0, len(certs), 8):  # 8 certs x 3 sigs = 24 <= bucket 32
        pipe.feed(certs[lo:lo + 8])
        assert len(pipe._inflight) <= pipe.depth  # double-buffered bound
    out = pipe.drain()
    assert not pipe.rejected
    assert [o.certificate.digest for o in out] == [
        o.certificate.digest for o in host_out
    ]
    assert [o.consensus_index for o in out] == [
        o.consensus_index for o in host_out
    ]
    assert pipe_state.last_committed == host_state.last_committed


def test_fused_pipeline_rejects_bad_signatures():
    """A certificate with a corrupted vote signature is rejected by the
    verify stage and never reaches the DAG window; the rest of its batch
    is unaffected."""
    from narwhal_tpu.consensus import ConsensusState
    from narwhal_tpu.fixtures import CommitteeFixture, make_signed_certificates
    from narwhal_tpu.tpu.dag_kernels import TpuBullshark
    from narwhal_tpu.tpu.pipeline import FusedCertificatePipeline
    from narwhal_tpu.types import Certificate

    f = CommitteeFixture(size=4)
    genesis = {c.digest for c in Certificate.genesis(f.committee)}
    certs, _ = make_signed_certificates(f, 1, 1, genesis)
    good = certs[:-1]
    victim = certs[-1]
    bad = Certificate(
        victim.header,
        victim.signers,
        victim.signatures[:-1] + (b"\x00" * 64,),
    )
    state = ConsensusState(Certificate.genesis(f.committee))
    engine = TpuBullshark(f.committee, None, 50)
    pipe = FusedCertificatePipeline(_mesh_verifier(), engine, state)
    pipe.feed(good + [bad])
    pipe.drain()
    assert pipe.rejected == [bad]
    idx = f.committee.index_of(bad.origin)
    assert engine.win.present[engine.win._off(1), idx] == 0  # never placed
    for cert in good:
        gidx = f.committee.index_of(cert.origin)
        assert engine.win.present[engine.win._off(1), gidx] == 1


def test_primary_node_shutdown_joins_prewarm_threads(run):
    """ISSUE 10 satellite: PrimaryNode.shutdown must bounded-join the
    background window prewarm compiles (dag_backend=tpu) so they cannot
    outlive the node and contend with a successor's foreground traces —
    previously only the atexit hook covered this, i.e. process exit, not
    node teardown."""
    from narwhal_tpu.fixtures import CommitteeFixture
    from narwhal_tpu.node import NodeStorage, PrimaryNode
    from narwhal_tpu.tpu import dag_kernels

    fx = CommitteeFixture(size=4)
    auth = fx.authorities[0]
    node = PrimaryNode(
        auth.keypair,
        fx.committee,
        fx.worker_cache,
        fx.parameters,
        NodeStorage(None),
        dag_backend="tpu",
    )
    calls = []
    orig = dag_kernels.join_prewarm_threads
    dag_kernels.join_prewarm_threads = lambda grace=60.0: calls.append(grace)
    try:
        run(node.shutdown(), timeout=60.0)
    finally:
        dag_kernels.join_prewarm_threads = orig
    assert calls, "shutdown did not join the prewarm threads"

    # A cpu-dag node must NOT import jax machinery at shutdown.
    node2 = PrimaryNode(
        auth.keypair,
        fx.committee,
        fx.worker_cache,
        fx.parameters,
        NodeStorage(None),
        dag_backend="cpu",
    )
    calls2 = []
    dag_kernels.join_prewarm_threads = lambda grace=60.0: calls2.append(grace)
    try:
        run(node2.shutdown(), timeout=60.0)
    finally:
        dag_kernels.join_prewarm_threads = orig
    assert not calls2
