"""FaultPlan fuzzer: generator invariants, campaign smoke, shrinker.

The fuzzer (narwhal_tpu/simnet/fuzz.py, CLI `python bench.py --fuzz`)
spends the simnet perf win on adversarial coverage: seeded random fault
schedules held to the safety/liveness oracles. These tests pin the three
contracts the campaign artifact depends on:

* the generator is deterministic per seed and only emits
  quorum-survivable plans (so an oracle violation is a finding, never a
  fuzzer artifact);
* a small campaign runs green and replays bit-identically — the tier-1
  smoke that keeps the entry point from rotting;
* the shrinker strips a planted failure down to a minimal reproducer
  that still trips the (stand-in) oracle.
"""

from __future__ import annotations

from narwhal_tpu.simnet import fuzz
from narwhal_tpu.simnet.plan import (
    Crash,
    Equivocate,
    FaultPlan,
    LinkFault,
    LinkSpec,
    Partition,
    Reconfigure,
)

# ---------------------------------------------------------------------------
# Generator: determinism + quorum survivability
# ---------------------------------------------------------------------------


def test_generate_plan_is_deterministic_and_seed_sensitive():
    a = fuzz.generate_plan(42)
    b = fuzz.generate_plan(42)
    assert a == b  # frozen dataclasses: structural equality is exact
    distinct = {repr(fuzz.generate_plan(seed)) for seed in range(16)}
    assert len(distinct) > 1  # seeds actually steer the draw


def test_generated_plans_are_quorum_survivable():
    """The generator's own safety envelope: at most f nodes byzantine or
    permanently down, partitions always heal with runway left, and every
    plan carries at least one event. If this envelope holds, a failing
    campaign row is a protocol finding, not a malformed plan."""
    nodes, duration = 4, 2.5
    f = (nodes - 1) // 3
    safe_end = duration - 1.2  # generate_plan's _RUNWAY
    for seed in range(40):
        plan = fuzz.generate_plan(seed, nodes=nodes, duration=duration)
        assert len(plan.events) >= 1
        permanent = sum(
            1
            for e in plan.events
            if isinstance(e, Crash) and e.restart_at is None
        )
        byzantine = sum(1 for e in plan.events if isinstance(e, Equivocate))
        assert permanent + byzantine <= f
        for e in plan.events:
            if isinstance(e, Partition):
                assert e.heal <= safe_end + 1e-9
                assert min(len(g) for g in e.groups) <= nodes // 2
            if isinstance(e, Crash) and e.restart_at is not None:
                assert e.restart_at <= safe_end + 1e-9
        # Until snapshot state-sync lands (ROADMAP item 1), a node that
        # restarts across an epoch change is stranded in the old epoch —
        # the generator must never pair a crash-with-restart with a
        # Reconfigure (the first campaign's only failure class).
        restarts = any(
            isinstance(e, Crash) and e.restart_at is not None
            for e in plan.events
        )
        reconfigures = any(isinstance(e, Reconfigure) for e in plan.events)
        assert not (restarts and reconfigures)


# ---------------------------------------------------------------------------
# Campaign smoke: the tier-1 guard on `bench.py --fuzz`
# ---------------------------------------------------------------------------


def test_fuzz_campaign_smoke_three_seeds_green_and_deterministic():
    """Three seeded scenarios through the full stack (oracles included),
    twice: every row green, both passes identical row-for-row. This is the
    determinism contract the ledger's campaign records rely on — seed k
    names the same scenario outcome on every run."""

    def go():
        return fuzz.run_campaign(
            count=3, base_seed=0, duration=2.0, shrink_failing=False
        )

    a = go()
    b = go()
    assert a["ok"] and b["ok"]
    assert len(a["scenarios"]) == 3
    assert a["scenarios"] == b["scenarios"]
    assert all(row["rounds"] >= 1 for row in a["scenarios"])


def test_checked_plan_replays_bit_identically_under_load():
    """Seeded-replay bit-identity (commits + event-log digest) with every
    optimization on the hot path enabled: shared verify plane with
    sign-time verdict seeding, fixed-base signing tables, batched fabric
    flushes, inline frame drains."""
    plan = fuzz.generate_plan(0, duration=2.0)
    ok_a, _, a = fuzz.check_plan(plan, duration=2.0, load_rate=60)
    ok_b, _, b = fuzz.check_plan(plan, duration=2.0, load_rate=60)
    assert ok_a and ok_b
    assert a.commits == b.commits
    assert a.rounds == b.rounds
    assert a.event_log_digest == b.event_log_digest
    assert a.event_log_len == b.event_log_len


# ---------------------------------------------------------------------------
# Shrinker: planted failure -> minimal reproducer
# ---------------------------------------------------------------------------


def test_shrink_minimizes_planted_failure_to_reproducer():
    """Plant a known-bad trigger (a partition whose window covers t=1.0)
    among noise events and a noisy default link. The shrinker must delete
    every event that is not the trigger, pull the default link to quiet,
    and hand back a plan that still trips the oracle stand-in."""
    plan = FaultPlan(
        seed=1,
        default_link=LinkSpec(latency=0.004, jitter=0.001, drop=0.01),
        events=(
            LinkFault(
                at=0.2, a=0, b=2, link=LinkSpec(latency=0.02), end=1.0
            ),
            Crash(at=0.3, node=1, restart_at=0.8),
            Partition(at=0.6, heal=1.4, groups=((0,), (1, 2, 3))),
        ),
    )

    def still_fails(candidate: FaultPlan) -> bool:
        return any(
            isinstance(e, Partition) and e.at <= 1.0 <= e.heal
            for e in candidate.events
        )

    assert still_fails(plan)
    minimal = fuzz.shrink(plan, still_fails)
    assert still_fails(minimal)  # the reproducer still trips the oracle
    assert len(minimal.events) == 1
    assert isinstance(minimal.events[0], Partition)
    # Parameter pass ran too: onset pulled earlier, link pulled to quiet.
    assert minimal.events[0].at < 0.6
    assert minimal.default_link == LinkSpec(latency=0.0, jitter=0.0, drop=0.0)


def test_shrink_is_bounded_by_max_checks():
    """A pathological predicate (always fails) cannot loop the shrinker:
    the candidate-evaluation budget caps total work."""
    plan = fuzz.generate_plan(3)
    calls = 0

    def always_fails(_candidate: FaultPlan) -> bool:
        nonlocal calls
        calls += 1
        return True

    fuzz.shrink(plan, always_fails, max_checks=10)
    assert calls <= 10
