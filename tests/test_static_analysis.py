"""narwhal-lint: the tier-1 static-analysis gate plus per-rule fixtures.

The gate test runs the analyzer over `narwhal_tpu/` and `tests/` and fails
on any non-baselined finding — this is how the actor/JAX invariants
(metered channels, non-blocking event loop, drainable task spawns, jit
purity, immutable decoded messages, no silent excepts) stay machine-checked
after this PR. Fixture tests pin each rule to one tripping and one clean
snippet so a rule regression (stops firing / starts overfiring) is caught
in the same run.
"""

from __future__ import annotations

import json
import subprocess
import sys
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
FIXTURES = REPO / "tests" / "lint_fixtures"

sys.path.insert(0, str(REPO))

from tools.lint import RULES, Baseline, Finding, run_lint  # noqa: E402
from tools.lint.__main__ import DEFAULT_BASELINE, main  # noqa: E402


def lint(*paths, baseline=None, rules=None):
    return run_lint([str(p) for p in paths], rules=rules, baseline=baseline, root=REPO)


# ---------------------------------------------------------------------------
# The gate
# ---------------------------------------------------------------------------


def test_tree_has_no_new_findings():
    """`python -m tools.lint narwhal_tpu/ tests/` must be clean modulo the
    checked-in baseline. If this fails: fix the finding, suppress it with a
    justified `# lint: allow(<rule>)`, or (last resort) regenerate the
    baseline via `python -m tools.lint --write-baseline narwhal_tpu/ tests/`."""
    baseline = Baseline.load(DEFAULT_BASELINE)
    result = lint(REPO / "narwhal_tpu", REPO / "tests", baseline=baseline)
    assert result.files_scanned > 50  # the walk found the tree
    details = "\n".join(
        f"{f.path}:{f.line}: [{f.rule}] {f.message}" for f in result.new
    )
    assert not result.new, f"new lint findings:\n{details}"


def test_baseline_has_no_stale_entries():
    """Grandfathered findings that get fixed must leave the baseline too,
    or the file silently re-authorizes a future regression."""
    baseline = Baseline.load(DEFAULT_BASELINE)
    result = lint(REPO / "narwhal_tpu", REPO / "tests", baseline=baseline)
    assert not result.stale_baseline, (
        f"stale baseline entries (regenerate with --write-baseline): "
        f"{result.stale_baseline}"
    )


def test_full_run_is_fast():
    """The analyzer must stay cheap enough to gate every tier-1 run."""
    t0 = time.perf_counter()
    lint(REPO / "narwhal_tpu", REPO / "tests")
    assert time.perf_counter() - t0 < 10.0


# ---------------------------------------------------------------------------
# Rule catalog / fixtures
# ---------------------------------------------------------------------------

EXPECTED_RULES = {
    "no-blocking-in-async",
    "no-raw-queue",
    "tracked-task-spawn",
    "jit-purity",
    "no-shared-decode-mutation",
    "no-silent-except",
    "no-sync-store-write-in-async",
    "no-per-item-rpc-in-loop",
    "no-unbounded-channel",
}

FIXTURE_FOR = {
    "no-blocking-in-async": ("blocking_trip.py", "blocking_clean.py"),
    "no-raw-queue": ("raw_queue_trip.py", "raw_queue_clean.py"),
    "tracked-task-spawn": ("task_spawn_trip.py", "task_spawn_clean.py"),
    "jit-purity": ("tpu/jit_purity_trip.py", "tpu/jit_purity_clean.py"),
    "no-shared-decode-mutation": (
        "decode_mutation_trip.py",
        "decode_mutation_clean.py",
    ),
    "no-silent-except": (
        "primary/silent_except_trip.py",
        "primary/silent_except_clean.py",
    ),
    "no-sync-store-write-in-async": (
        "primary/sync_store_write_trip.py",
        "primary/sync_store_write_clean.py",
    ),
    "no-per-item-rpc-in-loop": (
        "executor/per_item_rpc_trip.py",
        "executor/per_item_rpc_clean.py",
    ),
    "no-unbounded-channel": (
        "worker/unbounded_channel_trip.py",
        "worker/unbounded_channel_clean.py",
    ),
}


def test_rule_catalog_is_complete():
    assert EXPECTED_RULES <= set(RULES), sorted(RULES)
    assert set(FIXTURE_FOR) == EXPECTED_RULES
    for rule in RULES.values():
        assert rule.summary, f"{rule.name} has no summary"


@pytest.mark.parametrize("rule_name", sorted(EXPECTED_RULES))
def test_rule_trips_on_fixture(rule_name):
    trip, _ = FIXTURE_FOR[rule_name]
    result = lint(FIXTURES / trip, rules={rule_name: RULES[rule_name]})
    assert result.new, f"{rule_name} found nothing in {trip}"
    assert all(f.rule == rule_name for f in result.new)


@pytest.mark.parametrize("rule_name", sorted(EXPECTED_RULES))
def test_rule_passes_clean_fixture(rule_name):
    _, clean = FIXTURE_FOR[rule_name]
    result = lint(FIXTURES / clean, rules={rule_name: RULES[rule_name]})
    details = [(f.line, f.message) for f in result.new]
    assert not result.new, f"{rule_name} overfires on {clean}: {details}"


def test_fixture_finding_counts():
    """Pin the exact trip counts so a rule that silently loses coverage
    (fires on one pattern but stops on another) is caught, not just total
    silence."""
    counts = {
        "no-blocking-in-async": 5,  # sleep, aliased sleep, open, subprocess, .result()
        "no-raw-queue": 3,  # Queue, LifoQueue, from-import Queue
        "tracked-task-spawn": 3,  # create_task, ensure_future, loop.create_task
        "jit-purity": 4,  # print, time.time, global mutation, random under jit
        "no-shared-decode-mutation": 4,  # field, nested container, mutator, direct
        "no-silent-except": 2,  # pass-only swallow, broad unlogged catch
        "no-sync-store-write-in-async": 4,  # store write/put, engine batch, bare store
        "no-per-item-rpc-in-loop": 3,  # for+attr recv, async for, bare name
        "no-unbounded-channel": 3,  # bare, keyword-only gauge, attr form
    }
    for rule_name, expected in counts.items():
        trip, _ = FIXTURE_FOR[rule_name]
        result = lint(FIXTURES / trip, rules={rule_name: RULES[rule_name]})
        assert len(result.new) == expected, (
            rule_name,
            [(f.line, f.message) for f in result.new],
        )


# ---------------------------------------------------------------------------
# Suppressions
# ---------------------------------------------------------------------------


def test_inline_suppression(tmp_path):
    f = tmp_path / "mod.py"
    f.write_text(
        "import asyncio\n"
        "async def g():\n"
        "    import time\n"
        "    time.sleep(1)  # lint: allow(no-blocking-in-async)\n"
    )
    result = lint(f)
    assert not result.new
    assert len(result.suppressed) == 1
    assert result.suppressed[0].rule == "no-blocking-in-async"


def test_preceding_line_suppression(tmp_path):
    f = tmp_path / "mod.py"
    f.write_text(
        "import time\n"
        "async def g():\n"
        "    # warmup only, loop not running yet\n"
        "    # lint: allow(no-blocking-in-async)\n"
        "    time.sleep(1)\n"
    )
    result = lint(f)
    assert not result.new and len(result.suppressed) == 1


def test_suppression_is_rule_specific(tmp_path):
    f = tmp_path / "mod.py"
    f.write_text(
        "import time\n"
        "async def g():\n"
        "    time.sleep(1)  # lint: allow(no-raw-queue)\n"
    )
    result = lint(f)
    assert len(result.new) == 1  # wrong rule named -> not suppressed


# ---------------------------------------------------------------------------
# Baseline workflow
# ---------------------------------------------------------------------------


def test_baseline_grandfathers_and_detects_new(tmp_path):
    f = tmp_path / "mod.py"
    f.write_text("import time\nasync def g():\n    time.sleep(1)\n")
    first = lint(f)
    assert len(first.new) == 1

    bl_path = tmp_path / "baseline.json"
    Baseline.dump(first.new, bl_path)
    grandfathered = lint(f, baseline=Baseline.load(bl_path))
    assert not grandfathered.new and len(grandfathered.baselined) == 1

    # A NEW finding alongside the baselined one still fails the run, and
    # the baseline survives the original line moving.
    f.write_text(
        "import time\n\nasync def g():\n    time.sleep(1)\n    open('x')\n"
    )
    again = lint(f, baseline=Baseline.load(bl_path))
    assert len(again.baselined) == 1
    assert len(again.new) == 1 and "open" in again.new[0].snippet


def test_baseline_reports_stale_entries(tmp_path):
    bl_path = tmp_path / "baseline.json"
    ghost = Finding("no-raw-queue", "gone.py", 1, 0, "m", "asyncio.Queue()")
    Baseline.dump([ghost], bl_path)
    f = tmp_path / "mod.py"
    f.write_text("x = 1\n")
    result = lint(f, baseline=Baseline.load(bl_path))
    assert result.stale_baseline == [("no-raw-queue", "gone.py", "asyncio.Queue()")]


def test_syntax_error_is_a_finding(tmp_path):
    f = tmp_path / "broken.py"
    f.write_text("def oops(:\n")
    result = lint(f)
    assert len(result.new) == 1 and result.new[0].rule == "syntax-error"


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def test_cli_exit_codes_and_json(tmp_path):
    trip = FIXTURES / "raw_queue_trip.py"
    clean = FIXTURES / "raw_queue_clean.py"
    env_cwd = str(REPO)

    bad = subprocess.run(
        [sys.executable, "-m", "tools.lint", "--format", "json", str(trip)],
        capture_output=True,
        text=True,
        cwd=env_cwd,
    )
    assert bad.returncode == 1, bad.stderr
    payload = json.loads(bad.stdout)
    assert not payload["ok"] and payload["new"]
    assert {f["rule"] for f in payload["new"]} == {"no-raw-queue"}

    good = subprocess.run(
        [sys.executable, "-m", "tools.lint", str(clean)],
        capture_output=True,
        text=True,
        cwd=env_cwd,
    )
    assert good.returncode == 0, good.stdout + good.stderr


def test_cli_list_rules():
    assert main(["--list-rules"]) == 0


def test_fixture_dir_is_excluded_from_directory_walks():
    """Walking tests/ must skip lint_fixtures/ (so the tripping snippets
    never fail the gate), while explicit file arguments bypass excludes."""
    result = lint(REPO / "tests")
    assert not any("lint_fixtures" in f.path for f in result.new)
    explicit = lint(FIXTURES / "raw_queue_trip.py")
    assert explicit.new
