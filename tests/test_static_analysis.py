"""The tier-1 static-analysis gates: narwhal-lint, narwhal-topo AND
narwhal-sched, driven through the combined `python -m tools.check`
runner (one process, one shared whole-program extraction, one exit
code).

Part 1 (narwhal-lint): runs the per-function analyzer over `narwhal_tpu/`
and `tests/` and fails on any non-baselined finding — this is how the
actor/JAX invariants (metered channels, non-blocking event loop,
drainable task spawns, jit purity, immutable decoded messages, no silent
excepts) stay machine-checked. Fixture tests pin each rule to one
tripping and one clean snippet so a rule regression (stops firing /
starts overfiring) is caught in the same run.

Part 2 (narwhal-topo, tools/analysis): the whole-program gate — extracts
the actor/channel topology from the wiring roots and fails on orphan
producers/consumers, bounded-channel deadlock cycles, dropped task
handles, wire-schema drift, and cross-module jit impurity. The extracted
topology is pinned as a checked-in artifact (tools/analysis/topology.json
+ .dot): wiring changes without `python -m tools.analysis
--write-artifact` fail the stale-artifact test, exactly like a stale lint
baseline.

Part 3 (narwhal-sched, tools/sched): interleaving races (multi-task
mutation without a single-writer discipline, read-modify-write spanning
an await) over the extractor's task-attributed state sites, plus the
replay-determinism family (raw entropy beside the seeded seams, the
global random stream, id()-keyed ordering, effectful set iteration) that
machine-checks the two PR-9 divergences. Regression fixtures under
tests/sched_fixtures/ pin both PR-9 bugs verbatim.
"""

from __future__ import annotations

import json
import subprocess
import sys
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
FIXTURES = REPO / "tests" / "lint_fixtures"

sys.path.insert(0, str(REPO))

from tools.lint import RULES, Baseline, Finding, run_lint  # noqa: E402
from tools.lint.__main__ import DEFAULT_BASELINE, main  # noqa: E402


def lint(*paths, baseline=None, rules=None):
    return run_lint([str(p) for p in paths], rules=rules, baseline=baseline, root=REPO)


# ---------------------------------------------------------------------------
# The gate: ONE combined `tools.check` run feeds every tree-clean test
# (lint + topo + sched share it; topo and sched share one extraction).
# ---------------------------------------------------------------------------

from tools.check import run_check  # noqa: E402


@pytest.fixture(scope="module")
def check_report():
    return run_check(root=REPO)


def test_tree_has_no_new_findings(check_report):
    """`python -m tools.check` (lint plane) must be clean modulo the
    checked-in baseline. If this fails: fix the finding, suppress it with a
    justified `# lint: allow(<rule>)`, or (last resort) regenerate the
    baseline via `python -m tools.lint --write-baseline narwhal_tpu/ tests/`."""
    result = check_report.results["lint"]
    assert result.files_scanned > 50  # the walk found the tree
    details = "\n".join(
        f"{f.path}:{f.line}: [{f.rule}] {f.message}" for f in result.new
    )
    assert not result.new, f"new lint findings:\n{details}"


def test_baseline_has_no_stale_entries(check_report):
    """Grandfathered findings that get fixed must leave the baseline too,
    or the file silently re-authorizes a future regression."""
    result = check_report.results["lint"]
    assert not result.stale_baseline, (
        f"stale baseline entries (regenerate with --write-baseline): "
        f"{result.stale_baseline}"
    )


def test_combined_gate_is_fast(check_report):
    """All three planes in one process must stay cheap enough to gate
    every tier-1 run — one pin for the whole `tools.check` invocation."""
    assert check_report.elapsed < 25.0, check_report.timings


# ---------------------------------------------------------------------------
# Rule catalog / fixtures
# ---------------------------------------------------------------------------

EXPECTED_RULES = {
    "no-blocking-in-async",
    "no-raw-queue",
    "tracked-task-spawn",
    "jit-purity",
    "no-shared-decode-mutation",
    "no-silent-except",
    "no-sync-store-write-in-async",
    "no-per-item-rpc-in-loop",
    "no-unbounded-channel",
    "no-wall-clock-in-actors",
    "no-untracked-jit",
    "no-per-item-cert-verify",
    "metric-naming",
    "no-direct-peer-connection",
}

FIXTURE_FOR = {
    "no-blocking-in-async": ("blocking_trip.py", "blocking_clean.py"),
    "no-raw-queue": ("raw_queue_trip.py", "raw_queue_clean.py"),
    "tracked-task-spawn": ("task_spawn_trip.py", "task_spawn_clean.py"),
    "jit-purity": ("tpu/jit_purity_trip.py", "tpu/jit_purity_clean.py"),
    "no-shared-decode-mutation": (
        "decode_mutation_trip.py",
        "decode_mutation_clean.py",
    ),
    "no-silent-except": (
        "primary/silent_except_trip.py",
        "primary/silent_except_clean.py",
    ),
    "no-sync-store-write-in-async": (
        "primary/sync_store_write_trip.py",
        "primary/sync_store_write_clean.py",
    ),
    "no-per-item-rpc-in-loop": (
        "executor/per_item_rpc_trip.py",
        "executor/per_item_rpc_clean.py",
    ),
    "no-unbounded-channel": (
        "worker/unbounded_channel_trip.py",
        "worker/unbounded_channel_clean.py",
    ),
    "no-wall-clock-in-actors": (
        "primary/wall_clock_trip.py",
        "primary/wall_clock_clean.py",
    ),
    "no-untracked-jit": (
        "tpu/untracked_jit_trip.py",
        "tpu/untracked_jit_clean.py",
    ),
    "no-per-item-cert-verify": (
        "primary/cert_verify_trip.py",
        "primary/cert_verify_clean.py",
    ),
    "metric-naming": (
        "metric_naming_trip.py",
        "metric_naming_clean.py",
    ),
    "no-direct-peer-connection": (
        "worker/direct_peer_trip.py",
        "worker/direct_peer_clean.py",
    ),
}


def test_rule_catalog_is_complete():
    assert EXPECTED_RULES <= set(RULES), sorted(RULES)
    assert set(FIXTURE_FOR) == EXPECTED_RULES
    for rule in RULES.values():
        assert rule.summary, f"{rule.name} has no summary"


@pytest.mark.parametrize("rule_name", sorted(EXPECTED_RULES))
def test_rule_trips_on_fixture(rule_name):
    trip, _ = FIXTURE_FOR[rule_name]
    result = lint(FIXTURES / trip, rules={rule_name: RULES[rule_name]})
    assert result.new, f"{rule_name} found nothing in {trip}"
    assert all(f.rule == rule_name for f in result.new)


@pytest.mark.parametrize("rule_name", sorted(EXPECTED_RULES))
def test_rule_passes_clean_fixture(rule_name):
    _, clean = FIXTURE_FOR[rule_name]
    result = lint(FIXTURES / clean, rules={rule_name: RULES[rule_name]})
    details = [(f.line, f.message) for f in result.new]
    assert not result.new, f"{rule_name} overfires on {clean}: {details}"


def test_fixture_finding_counts():
    """Pin the exact trip counts so a rule that silently loses coverage
    (fires on one pattern but stops on another) is caught, not just total
    silence."""
    counts = {
        "no-blocking-in-async": 5,  # sleep, aliased sleep, open, subprocess, .result()
        "no-raw-queue": 3,  # Queue, LifoQueue, from-import Queue
        "tracked-task-spawn": 3,  # create_task, ensure_future, loop.create_task
        "jit-purity": 4,  # print, time.time, global mutation, random under jit
        "no-shared-decode-mutation": 4,  # field, nested container, mutator, direct
        "no-silent-except": 2,  # pass-only swallow, broad unlogged catch
        "no-sync-store-write-in-async": 4,  # store write/put, engine batch, bare store
        "no-per-item-rpc-in-loop": 3,  # for+attr recv, async for, bare name
        "no-unbounded-channel": 3,  # bare, keyword-only gauge, attr form
        # time.time, time.monotonic, aliased import, loop var, chained call
        "no-wall-clock-in-actors": 5,
        # raw @jax.jit decorator, partial(jax.jit, ...) form, jax.jit(f) call
        "no-untracked-jit": 3,
        # certificate.verify, cert.verify, raw host_verify_aggregate
        "no-per-item-cert-verify": 3,
        # bad snake_case, unknown subsystem, unitless histogram, unitless
        # perf histogram (perf is a registered subsystem; grammar holds)
        "metric-naming": 4,
        # transport dial, raw asyncio dial, PeerClient direct + attr form
        "no-direct-peer-connection": 4,
    }
    for rule_name, expected in counts.items():
        trip, _ = FIXTURE_FOR[rule_name]
        result = lint(FIXTURES / trip, rules={rule_name: RULES[rule_name]})
        assert len(result.new) == expected, (
            rule_name,
            [(f.line, f.message) for f in result.new],
        )


# ---------------------------------------------------------------------------
# Suppressions
# ---------------------------------------------------------------------------


def test_inline_suppression(tmp_path):
    f = tmp_path / "mod.py"
    f.write_text(
        "import asyncio\n"
        "async def g():\n"
        "    import time\n"
        "    time.sleep(1)  # lint: allow(no-blocking-in-async)\n"
    )
    result = lint(f)
    assert not result.new
    assert len(result.suppressed) == 1
    assert result.suppressed[0].rule == "no-blocking-in-async"


def test_preceding_line_suppression(tmp_path):
    f = tmp_path / "mod.py"
    f.write_text(
        "import time\n"
        "async def g():\n"
        "    # warmup only, loop not running yet\n"
        "    # lint: allow(no-blocking-in-async)\n"
        "    time.sleep(1)\n"
    )
    result = lint(f)
    assert not result.new and len(result.suppressed) == 1


def test_suppression_is_rule_specific(tmp_path):
    f = tmp_path / "mod.py"
    f.write_text(
        "import time\n"
        "async def g():\n"
        "    time.sleep(1)  # lint: allow(no-raw-queue)\n"
    )
    result = lint(f)
    assert len(result.new) == 1  # wrong rule named -> not suppressed


# ---------------------------------------------------------------------------
# Baseline workflow
# ---------------------------------------------------------------------------


def test_baseline_grandfathers_and_detects_new(tmp_path):
    f = tmp_path / "mod.py"
    f.write_text("import time\nasync def g():\n    time.sleep(1)\n")
    first = lint(f)
    assert len(first.new) == 1

    bl_path = tmp_path / "baseline.json"
    Baseline.dump(first.new, bl_path)
    grandfathered = lint(f, baseline=Baseline.load(bl_path))
    assert not grandfathered.new and len(grandfathered.baselined) == 1

    # A NEW finding alongside the baselined one still fails the run, and
    # the baseline survives the original line moving.
    f.write_text(
        "import time\n\nasync def g():\n    time.sleep(1)\n    open('x')\n"
    )
    again = lint(f, baseline=Baseline.load(bl_path))
    assert len(again.baselined) == 1
    assert len(again.new) == 1 and "open" in again.new[0].snippet


def test_baseline_reports_stale_entries(tmp_path):
    bl_path = tmp_path / "baseline.json"
    ghost = Finding("no-raw-queue", "gone.py", 1, 0, "m", "asyncio.Queue()")
    Baseline.dump([ghost], bl_path)
    f = tmp_path / "mod.py"
    f.write_text("x = 1\n")
    result = lint(f, baseline=Baseline.load(bl_path))
    assert result.stale_baseline == [("no-raw-queue", "gone.py", "asyncio.Queue()")]


def test_syntax_error_is_a_finding(tmp_path):
    f = tmp_path / "broken.py"
    f.write_text("def oops(:\n")
    result = lint(f)
    assert len(result.new) == 1 and result.new[0].rule == "syntax-error"


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def test_cli_exit_codes_and_json(tmp_path):
    trip = FIXTURES / "raw_queue_trip.py"
    clean = FIXTURES / "raw_queue_clean.py"
    env_cwd = str(REPO)

    bad = subprocess.run(
        [sys.executable, "-m", "tools.lint", "--format", "json", str(trip)],
        capture_output=True,
        text=True,
        cwd=env_cwd,
    )
    assert bad.returncode == 1, bad.stderr
    payload = json.loads(bad.stdout)
    assert not payload["ok"] and payload["new"]
    assert {f["rule"] for f in payload["new"]} == {"no-raw-queue"}

    good = subprocess.run(
        [sys.executable, "-m", "tools.lint", str(clean)],
        capture_output=True,
        text=True,
        cwd=env_cwd,
    )
    assert good.returncode == 0, good.stdout + good.stderr


def test_cli_list_rules():
    assert main(["--list-rules"]) == 0


def test_fixture_dir_is_excluded_from_directory_walks():
    """Walking tests/ must skip lint_fixtures/ (so the tripping snippets
    never fail the gate), while explicit file arguments bypass excludes."""
    result = lint(REPO / "tests")
    assert not any("lint_fixtures" in f.path for f in result.new)
    explicit = lint(FIXTURES / "raw_queue_trip.py")
    assert explicit.new


# ---------------------------------------------------------------------------
# Cross-module jit-purity (the retired same-module caveat)
# ---------------------------------------------------------------------------


def test_jit_purity_reports_cross_module_impurities():
    """Scanning the module that DECLARES the jit root must surface impure
    sites reached in sibling modules, anchored at their real location."""
    result = lint(
        FIXTURES / "tpu" / "xmod_root.py", rules={"jit-purity": RULES["jit-purity"]}
    )
    assert len(result.new) == 2, [(f.path, f.line, f.message) for f in result.new]
    assert all(f.path.endswith("xmod_helper.py") for f in result.new)
    kinds = " ".join(f.message for f in result.new)
    assert "print" in kinds and "time.time" in kinds


def test_jit_purity_cross_module_respects_inline_allow():
    """xmod_helper.warmed carries `# lint: allow(jit-purity)` — reachable
    and impure, but justified at its own site."""
    result = lint(
        FIXTURES / "tpu" / "xmod_root.py", rules={"jit-purity": RULES["jit-purity"]}
    )
    assert not any("perf_counter" in f.message for f in result.new)


def test_jit_purity_cross_module_clean_root():
    """A root that only reaches the pure sibling helper stays silent."""
    result = lint(
        FIXTURES / "tpu" / "xmod_clean_root.py",
        rules={"jit-purity": RULES["jit-purity"]},
    )
    assert not result.new, [(f.path, f.line) for f in result.new]


# ===========================================================================
# Part 2: narwhal-topo (tools/analysis) — the whole-program gate
# ===========================================================================

from tools.analysis import (  # noqa: E402
    DETECTORS,
    Context,
    extract,
    run_detectors,
)
from tools.analysis.__main__ import (  # noqa: E402
    ARTIFACT_JSON,
    DEFAULT_BASELINE as TOPO_BASELINE,
    topology_doc,
)
from tools.analysis.extractor import DEFAULT_ROOTS  # noqa: E402

TOPO_FIXTURES = REPO / "tests" / "topo_fixtures"


def _topo_ctx():
    topo, extractor = extract(REPO)
    return Context(topo, extractor.program, REPO)


def _fixture_result(fixture: str, symbol: str, rule: str):
    # package="" loads ONLY the fixture file: detectors that scan every
    # program module (dropped-handle-escape) must not see sibling
    # fixtures' deliberate violations.
    topo, extractor = extract(
        REPO,
        package="",
        roots=[f"tests/topo_fixtures/{fixture}::{symbol}"],
    )
    ctx = Context(topo, extractor.program, REPO)
    return run_detectors(ctx, detectors={rule: DETECTORS[rule]})


# -- the gate ---------------------------------------------------------------


def test_topo_tree_has_no_new_findings(check_report):
    """`python -m tools.check` (topo plane) must be clean modulo the
    (empty) baseline. If this fails: fix the wiring, or justify with an
    inline `# lint: allow(<detector>)` at the anchor site."""
    result = check_report.results["topo"]
    details = "\n".join(
        f"{f.path}:{f.line}: [{f.rule}] {f.message}" for f in result.new
    )
    assert not result.new, f"new topology findings:\n{details}"
    # The extraction actually modeled the pipeline (not a silent no-op).
    assert len(check_report.topology.live_channels()) >= 20
    assert len(check_report.topology.tasks) >= 30
    # The one justified suppression: the protocol-bounded core<->proposer
    # wait cycle (primary/core.py).
    assert any(f.rule == "bounded-channel-cycle" for f in result.suppressed)
    # The combined runner checked artifact currency in the same pass.
    assert not check_report.artifact_stale


def test_topo_baseline_stays_empty():
    """Like lint's: the topology baseline only ever shrinks, and it starts
    (and must stay) EMPTY — new findings are fixed or justified inline."""
    baseline = json.loads(TOPO_BASELINE.read_text(encoding="utf-8"))
    assert baseline["findings"] == []


def test_topo_detector_catalog_is_complete():
    expected = {
        "orphan-producer",
        "orphan-consumer",
        "bounded-channel-cycle",
        "dropped-handle-escape",
        "wire-schema",
        "cross-module-jit-purity",
    }
    assert expected == set(DETECTORS), sorted(DETECTORS)
    for det in DETECTORS.values():
        assert det.summary, f"{det.name} has no summary"


# -- the pinned topology artifact -------------------------------------------


def test_topology_artifact_is_current():
    """The checked-in topology.json must match a fresh extraction of the
    live codebase. Wiring changed? Regenerate with
    `python -m tools.analysis --write-artifact` and review the diff —
    that review IS the point of pinning the pipeline shape."""
    topo, _ = extract(REPO)
    fresh = topology_doc(topo, DEFAULT_ROOTS)
    checked_in = json.loads(ARTIFACT_JSON.read_text(encoding="utf-8"))
    assert fresh == checked_in, (
        "stale tools/analysis/topology.json — regenerate with "
        "`python -m tools.analysis --write-artifact` and review the diff"
    )


def test_topology_artifact_matches_known_pipeline():
    """Semantic pins on the real architecture: the load-bearing edges the
    paper's pipeline (workers -> primary -> consensus -> executor) implies
    must be present in the artifact."""
    doc = json.loads(ARTIFACT_JSON.read_text(encoding="utf-8"))
    edges = {(e["task"], e["channel"], e["op"]) for e in doc["edges"]}
    # The PR-6 wedge pair: executor output produced, drained by __main__.
    assert ("ExecutorCore.run", "node/execution_output", "send_many") in edges
    assert (
        "_run_node._drain_execution_output",
        "node/execution_output",
        "recv",
    ) in edges
    # Core feeds consensus; consensus feeds the executor and the primary.
    assert ("Core.run", "node/new_certificates", "send") in edges
    assert ("Consensus.run", "node/new_certificates", "recv") in edges
    assert ("Consensus.run", "node/consensus_output", "send") in edges
    assert ("Subscriber.run", "node/consensus_output", "recv") in edges
    # The speculative tap is non-blocking by design.
    assert ("Consensus.run", "node/accepted_certificates", "try_send") in edges
    # Worker pipeline: ingest -> batch maker -> quorum -> processor.
    assert ("BatchMaker.run", "worker/quorum_waiter", "send") in edges
    assert ("QuorumWaiter.run", "worker/quorum_waiter", "recv") in edges
    caps = {c["id"]: c["capacity"] for c in doc["channels"]}
    assert caps["node/execution_output"] == 10_000
    assert caps["primary/state_handler"] == 100


def test_topology_dot_artifact_exists_and_renders_channels():
    dot = (ARTIFACT_JSON.parent / "topology.dot").read_text(encoding="utf-8")
    assert "digraph" in dot
    assert "node/execution_output" in dot and "worker/batch_maker" in dot


# -- per-detector fixtures (tripping + clean, pinned counts) ----------------


def test_orphan_producer_flags_the_pr6_wedge_fixture():
    result = _fixture_result(
        "orphan_producer_trip.py", "MiniNode", "orphan-producer"
    )
    assert len(result.new) == 1, [(f.line, f.message) for f in result.new]
    assert "node/execution_output" in result.new[0].message


def test_orphan_producer_clean_fixture():
    result = _fixture_result(
        "orphan_producer_clean.py", "MiniNode", "orphan-producer"
    )
    assert not result.new, [(f.line, f.message) for f in result.new]


def test_orphan_consumer_fixtures():
    trip = _fixture_result("orphan_consumer_trip.py", "DeadNode", "orphan-consumer")
    assert len(trip.new) == 1, [(f.line, f.message) for f in trip.new]
    assert "tx_ghost" in trip.new[0].message
    clean = _fixture_result(
        "orphan_consumer_clean.py", "DeadNode", "orphan-consumer"
    )
    assert not clean.new, [(f.line, f.message) for f in clean.new]


def test_bounded_cycle_fixtures():
    trip = _fixture_result("cycle_trip.py", "CycleNode", "bounded-channel-cycle")
    assert len(trip.new) == 1, [(f.line, f.message) for f in trip.new]
    assert "Pinger.run" in trip.new[0].message
    assert "Ponger.run" in trip.new[0].message
    clean = _fixture_result("cycle_clean.py", "CycleNode", "bounded-channel-cycle")
    assert not clean.new, [(f.line, f.message) for f in clean.new]


def test_dropped_handle_fixtures():
    """Three escapes pinned: the attr-held task, the dict-tuple park, and
    the dropped spawn() result."""
    trip = _fixture_result("dropped_handle_trip.py", "Leaky", "dropped-handle-escape")
    assert len(trip.new) == 3, [(f.line, f.message) for f in trip.new]
    msgs = " | ".join(f.message for f in trip.new)
    assert "_task" in msgs and "pending" in msgs and "spawn" in msgs
    clean = _fixture_result(
        "dropped_handle_clean.py", "Tidy", "dropped-handle-escape"
    )
    assert not clean.new, [(f.line, f.message) for f in clean.new]


def test_wire_schema_fixture_and_real_registry():
    from tools.analysis.extractor import Program, Topology

    # Tripping fixture: one duplicate tag + one missing golden entry.
    program = Program(REPO, None)
    ctx = Context(
        Topology(),
        program,
        REPO,
        messages_path="tests/topo_fixtures/wire_schema_trip.py",
        golden_path="tests/topo_fixtures/wire_schema_golden.json",
    )
    result = run_detectors(ctx, detectors={"wire-schema": DETECTORS["wire-schema"]})
    assert len(result.new) == 2, [(f.line, f.message) for f in result.new]
    msgs = " | ".join(f.message for f in result.new)
    assert "collides" in msgs and "golden entry" in msgs
    # The real registry must be tag-unique and fully snapshotted.
    real = run_detectors(
        _topo_ctx(), detectors={"wire-schema": DETECTORS["wire-schema"]}
    )
    assert not real.new, [(f.line, f.message) for f in real.new]


def test_cross_module_jit_purity_detector_on_fixture_package():
    topo, extractor = extract(
        REPO,
        package="tests/lint_fixtures/tpu",
        roots=["tests/lint_fixtures/tpu/xmod_root.py::kernel"],
    )
    ctx = Context(topo, extractor.program, REPO)
    result = run_detectors(
        ctx,
        detectors={
            "cross-module-jit-purity": DETECTORS["cross-module-jit-purity"]
        },
    )
    assert len(result.new) == 2, [(f.path, f.line) for f in result.new]
    assert all(f.path.endswith("xmod_helper.py") for f in result.new)


# -- CLI --------------------------------------------------------------------


def test_topo_cli_gate_and_artifacts(tmp_path):
    """The satellite-task invocation: detectors + JSON/DOT artifacts in
    one run, exit 0 on the clean tree with a current checked-in artifact."""
    out_json, out_dot = tmp_path / "t.json", tmp_path / "t.dot"
    proc = subprocess.run(
        [
            sys.executable, "-m", "tools.analysis",
            "--check-artifact", "--format", "json",
            "--json", str(out_json), "--dot", str(out_dot),
        ],
        capture_output=True,
        text=True,
        cwd=str(REPO),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["ok"] and not payload["artifact_stale"]
    doc = json.loads(out_json.read_text())
    assert doc == json.loads(ARTIFACT_JSON.read_text(encoding="utf-8"))
    assert "digraph" in out_dot.read_text()


def test_topo_cli_exit_code_on_findings():
    proc = subprocess.run(
        [
            sys.executable, "-m", "tools.analysis",
            "--package", "tests/topo_fixtures",
            "--roots", "tests/topo_fixtures/cycle_trip.py::CycleNode",
            "--rule", "bounded-channel-cycle",
            "--no-baseline", "--format", "json",
        ],
        capture_output=True,
        text=True,
        cwd=str(REPO),
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert not payload["ok"]
    assert {f["rule"] for f in payload["new"]} == {"bounded-channel-cycle"}


def test_topo_cli_list_rules():
    from tools.analysis.__main__ import main as topo_main

    assert topo_main(["--list-rules"]) == 0


# (per-plane perf pins are folded into test_combined_gate_is_fast — one
# <25s pin for the whole tools.check run; narwhal-sched keeps its own
# acceptance pin in Part 3.)


# ===========================================================================
# Part 3: narwhal-sched (tools/sched) — races + replay determinism
# ===========================================================================

from tools.sched import RULES as SCHED_RULES  # noqa: E402
from tools.sched import run_sched  # noqa: E402
from tools.sched.__main__ import DEFAULT_BASELINE as SCHED_BASELINE  # noqa: E402
from tools.sched.__main__ import main as sched_main  # noqa: E402

SCHED_FIXTURES = REPO / "tests" / "sched_fixtures"

SCHED_EXPECTED_RULES = {
    "multi-task-mutation",
    "await-interleaved-rmw",
    "raw-entropy",
    "unseeded-random",
    "id-keyed-ordering",
    "unordered-iteration",
}


def sched_scan(*paths, roots=(), baseline=None):
    """Syntactic-only run (package='', no extraction) over fixture files;
    pass roots to run the whole-program race rules too."""
    return run_sched(
        [str(p) for p in paths],
        root=REPO,
        package="",
        roots=tuple(roots),
        baseline=baseline,
    )


# -- the gate ---------------------------------------------------------------


def test_sched_tree_has_no_new_findings(check_report):
    """`python -m tools.check` (sched plane) must be clean modulo the
    (empty) baseline: fix the race, or justify the deliberate idiom with
    an inline `# lint: allow(<rule>)` at the anchor site."""
    result = check_report.results["sched"]
    assert result.files_scanned > 50
    details = "\n".join(
        f"{f.path}:{f.line}: [{f.rule}] {f.message}" for f in result.new
    )
    assert not result.new, f"new sched findings:\n{details}"
    # The tree's deliberate idioms (co-hosted caches, seeded global
    # stream, register/await/cleanup) are documented inline, not silent.
    assert len(result.suppressed) >= 20


def test_sched_baseline_stays_empty():
    """The sched baseline starts (and must stay) EMPTY — new findings are
    fixed or justified inline, never grandfathered."""
    baseline = json.loads(SCHED_BASELINE.read_text(encoding="utf-8"))
    assert baseline["findings"] == []


def test_sched_rule_catalog_is_complete():
    assert set(SCHED_RULES) == SCHED_EXPECTED_RULES
    for rule in SCHED_RULES.values():
        assert rule.summary


# -- PR-9 regressions: the two bugs these rules exist to re-find ------------


def test_refinds_pr9_set_partition_bug():
    """The connection-set iteration in set_partition (hash-order resets)
    must trip unordered-iteration at the loop."""
    result = sched_scan(SCHED_FIXTURES / "pr9_partition.py")
    assert [(f.rule, f.line) for f in result.new] == [
        ("unordered-iteration", 21)
    ]
    assert "hash" in result.new[0].message


def test_refinds_pr9_urandom_nonce_bug():
    """The os.urandom handshake nonce must trip raw-entropy at the draw."""
    result = sched_scan(SCHED_FIXTURES / "pr9_nonce.py")
    assert [(f.rule, f.line) for f in result.new] == [("raw-entropy", 14)]
    assert "set_entropy" in result.new[0].message


# -- per-rule trip/clean fixtures with pinned counts ------------------------


def test_determinism_fixture_finding_counts():
    """det_trip.py: one finding per shape, pinned; det_clean.py: zero."""
    trip = sched_scan(SCHED_FIXTURES / "det_trip.py")
    counts: dict[str, int] = {}
    for f in trip.new:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    assert counts == {
        "raw-entropy": 1,  # uuid.uuid4
        "unseeded-random": 3,  # module-as-RNG, global draw, Random()
        "id-keyed-ordering": 1,
        "unordered-iteration": 1,
    }
    clean = sched_scan(SCHED_FIXTURES / "det_clean.py")
    assert not clean.new, [(f.rule, f.line) for f in clean.new]


def test_race_fixture_finding_counts():
    """races_trip.py (driven from its `main` wiring root): exactly one
    multi-task-mutation (Board poked from Writer AND Reader) and one
    await-interleaved-rmw (Counter.bump's read/await/write); the
    disciplined twin is silent."""
    trip = sched_scan(
        SCHED_FIXTURES / "races_trip.py",
        roots=("tests/sched_fixtures/races_trip.py::main",),
    )
    assert sorted((f.rule, f.line) for f in trip.new) == [
        ("await-interleaved-rmw", 30),
        ("multi-task-mutation", 39),
    ]
    clean = sched_scan(
        SCHED_FIXTURES / "races_clean.py",
        roots=("tests/sched_fixtures/races_clean.py::main",),
    )
    assert not clean.new, [(f.rule, f.line) for f in clean.new]


# -- extractor attribution (the StateSite API) ------------------------------


def test_extractor_attributes_sites_to_tasks():
    """The race detectors are only as good as the extractor's read/write
    attribution: one task writes, another reads, and every site must be
    keyed to the task that performs it."""
    topo, extractor = extract(
        REPO, package="", roots=["tests/sched_fixtures/races_trip.py::main"]
    )
    by_state: dict[str, dict[str, set[str]]] = {}
    for s in extractor.state_sites:
        by_state.setdefault(s.state, {"read": set(), "write": set()})[
            s.kind
        ].add(s.task)
    slots = by_state["Board.slots"]
    assert slots["write"] == {"init:Board", "Writer.run", "Reader.run"}
    assert {"Writer.run", "Reader.run"} <= slots["read"]
    count = by_state["Counter.count"]
    assert {"Writer.run", "Reader.run"} <= count["write"]
    # And the race rules see exactly one runtime-shared unencapsulated
    # state with multiple writers (the finding count pinned above).
    result = sched_scan(
        SCHED_FIXTURES / "races_trip.py",
        roots=("tests/sched_fixtures/races_trip.py::main",),
    )
    assert sum(f.rule == "multi-task-mutation" for f in result.new) == 1


# -- suppression ------------------------------------------------------------


def test_sched_inline_allow(tmp_path):
    src = tmp_path / "seam.py"
    src.write_text(
        "import os\n\n\n"
        "def default_entropy(n):\n"
        "    # the seam's own production default\n"
        "    return os.urandom(n)  # lint: allow(raw-entropy)\n",
        encoding="utf-8",
    )
    result = run_sched([str(src)], root=tmp_path, package="", roots=())
    assert not result.new
    assert [f.rule for f in result.suppressed] == ["raw-entropy"]


# -- CLI --------------------------------------------------------------------


def test_sched_cli_exit_codes_and_json():
    bad = subprocess.run(
        [
            sys.executable, "-m", "tools.sched",
            "tests/sched_fixtures/pr9_nonce.py",
            "--format", "json", "--no-baseline",
            "--package", "", "--roots",
        ],
        capture_output=True,
        text=True,
        cwd=str(REPO),
    )
    assert bad.returncode == 1, bad.stdout + bad.stderr
    payload = json.loads(bad.stdout)
    assert not payload["ok"]
    assert {f["rule"] for f in payload["new"]} == {"raw-entropy"}
    good = subprocess.run(
        [
            sys.executable, "-m", "tools.sched",
            "tests/sched_fixtures/det_clean.py",
            "--format", "json", "--no-baseline",
            "--package", "", "--roots",
        ],
        capture_output=True,
        text=True,
        cwd=str(REPO),
    )
    assert good.returncode == 0, good.stdout + good.stderr
    assert json.loads(good.stdout)["ok"]


def test_sched_cli_list_rules(capsys):
    assert sched_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for name in SCHED_EXPECTED_RULES:
        assert name in out


# -- --diff mode (pre-commit: only changed files) ---------------------------


def _git(repo: Path, *args: str) -> None:
    subprocess.run(
        ["git", "-C", str(repo), "-c", "user.email=t@t", "-c", "user.name=t",
         *args],
        check=True,
        capture_output=True,
    )


def test_diff_mode_scans_only_changed_files(tmp_path):
    """Synthetic two-commit repo: b.py has violated since the base rev,
    a.py picks one up in the working tree — `--diff BASE` must report the
    a.py finding and stay silent about unchanged b.py."""
    _git(tmp_path, "init", "-q")
    (tmp_path / "a.py").write_text("X = 1\n", encoding="utf-8")
    (tmp_path / "b.py").write_text(
        "import os\n\nNONCE = os.urandom(8)\n", encoding="utf-8"
    )
    _git(tmp_path, "add", "a.py", "b.py")
    _git(tmp_path, "commit", "-q", "-m", "base")
    base = "HEAD"
    (tmp_path / "a.py").write_text(
        "import uuid\n\nTOKEN = uuid.uuid4().hex\n", encoding="utf-8"
    )
    result = run_sched(
        [str(tmp_path)],
        root=tmp_path,
        package="",
        roots=(),
        diff_base=base,
    )
    assert [(f.path, f.rule) for f in result.new] == [("a.py", "raw-entropy")]
    # Without --diff the unchanged violation is reported too.
    full = run_sched([str(tmp_path)], root=tmp_path, package="", roots=())
    assert {f.path for f in full.new} == {"a.py", "b.py"}


# -- performance ------------------------------------------------------------


def test_sched_full_run_is_fast():
    """The acceptance pin: extraction + every detector over
    `narwhal_tpu/ tests/` in under 15s."""
    t0 = time.perf_counter()
    run_sched(
        [str(REPO / "narwhal_tpu"), str(REPO / "tests")],
        root=REPO,
        baseline=Baseline.load(SCHED_BASELINE),
    )
    assert time.perf_counter() - t0 < 15.0


# -- the combined runner's CLI ----------------------------------------------


def test_check_cli_combined_json():
    """`python -m tools.check --json`: one invocation, three planes, one
    exit code — the single command SKILL.md and pre-commit use."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.check", "--json"],
        capture_output=True,
        text=True,
        cwd=str(REPO),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["ok"] and not payload["artifact_stale"]
    assert set(payload) >= {"lint", "topo", "sched", "ok", "elapsed"}
    for plane in ("lint", "topo", "sched"):
        assert payload[plane]["ok"], plane
