"""Adaptive pacing, end-to-end admission control, and per-stage latency
tracing (ISSUE 6).

Covers the pacing controller's response curve (shallow-queue floor,
deep-queue ceiling, monotonicity), the backpressure state's hysteresis and
staleness fail-open, bounded-vs-unbounded backlog with admission control on
vs off, the on-the-wire RESOURCE_EXHAUSTED shed through a real Worker, the
BatchMaker's fixed-deadline (non-idle-timeout) seal semantics, and a cluster
smoke test asserting the whole *_stage_latency_seconds pipeline records.
"""

import asyncio
import time
from dataclasses import replace

import pytest

from narwhal_tpu.channels import Channel, Watch
from narwhal_tpu.metrics import Registry
from narwhal_tpu.pacing import (
    BackpressureState,
    IngestGate,
    IngestOverloadError,
    PacingController,
    StageTimer,
)
from narwhal_tpu.types import ReconfigureNotification
from narwhal_tpu.worker.batch_maker import BatchMaker


def _watch():
    return Watch(ReconfigureNotification("boot"))


def _chunk(*txs: bytes) -> tuple[int, bytes]:
    return len(txs), b"".join(len(t).to_bytes(4, "little") + t for t in txs)


# ---------------------------------------------------------------------------
# PacingController
# ---------------------------------------------------------------------------


def _controller(**kw):
    kw.setdefault("ceiling", 0.1)
    kw.setdefault("floor", 0.005)
    return PacingController(**kw)


def test_pacing_shallow_queue_fast_seal():
    """Occupancy at/under the low band -> the delay is the floor."""
    c = _controller(sources=[lambda: 0.0])
    for _ in range(10):
        assert c.delay() == pytest.approx(0.005)


def test_pacing_deep_queue_ceiling():
    """Occupancy at/over the high band -> the delay is the ceiling."""
    c = _controller(sources=[lambda: 1.0])
    for _ in range(50):  # let the EWMA converge
        d = c.delay()
    assert d == pytest.approx(0.1)


def test_pacing_monotone_response():
    """The delay is non-decreasing in occupancy over the whole range."""
    delays = []
    for occ in [i / 20 for i in range(21)]:
        # alpha=1 disables smoothing so this reads the pure response curve.
        c = _controller(ewma_alpha=1.0, sources=[lambda o=occ: o])
        delays.append(c.delay())
    assert delays == sorted(delays)
    assert delays[0] == pytest.approx(0.005)
    assert delays[-1] == pytest.approx(0.1)


def test_pacing_ewma_smooths_bursts():
    """One empty sample after a long full stretch must not drop the delay
    to the floor (sawtooth occupancy would otherwise flap modes)."""
    c = _controller(sources=[lambda: 1.0])
    for _ in range(50):
        c.delay()
    c._sources = [lambda: 0.0]
    assert c.delay() > 0.05  # still near ceiling after one shallow sample


def test_pacing_ceiling_under_floor_honors_operator():
    """max_*_delay configured below the adaptive floor wins verbatim."""
    c = PacingController(ceiling=0.001, floor=0.05, sources=[lambda: 0.0])
    assert c.delay() == pytest.approx(0.001)
    c2 = PacingController(ceiling=0.001, floor=0.05, sources=[lambda: 1.0])
    for _ in range(50):
        d = c2.delay()
    assert d == pytest.approx(0.001)


# ---------------------------------------------------------------------------
# BackpressureState / IngestGate
# ---------------------------------------------------------------------------


def test_backpressure_hysteresis():
    now = [0.0]
    s = BackpressureState(high=0.8, low=0.5, stale_after=60.0, clock=lambda: now[0])
    assert not s.overloaded()
    s.update(0.85)
    assert s.overloaded()
    s.update(0.7)  # between low and high: stays tripped
    assert s.overloaded()
    s.update(0.4)  # below low: releases
    assert not s.overloaded()
    s.update(0.7)  # between bands from below: stays released
    assert not s.overloaded()


def test_backpressure_stale_fails_open():
    """A worker that stops hearing its primary must not shed forever."""
    now = [0.0]
    s = BackpressureState(high=0.8, low=0.5, stale_after=2.0, clock=lambda: now[0])
    s.update(1.0)
    assert s.level() == 1.0 and s.overloaded()
    now[0] = 3.0  # past stale_after with no update
    assert s.level() == 0.0
    assert not s.overloaded()


def test_ingest_gate_rejects_unknown_policy():
    with pytest.raises(ValueError):
        IngestGate(policy="bogus")


def test_ingest_gate_shed_and_readmit(run):
    level = [0.0]
    gate = IngestGate(policy="shed", local_sources=[lambda: level[0]], high=0.8, low=0.5)

    async def scenario():
        await gate.admit()  # empty: admits
        level[0] = 0.9
        with pytest.raises(IngestOverloadError) as ei:
            await gate.admit()
        assert "RESOURCE_EXHAUSTED" in str(ei.value)
        level[0] = 0.7  # hysteresis: still tripped between the bands
        with pytest.raises(IngestOverloadError):
            await gate.admit()
        level[0] = 0.3
        await gate.admit()  # released

    run(scenario())


def test_ingest_gate_block_policy(run):
    level = [1.0]
    gate = IngestGate(
        policy="block", local_sources=[lambda: level[0]],
        high=0.8, low=0.5, block_timeout=5.0, block_poll=0.01,
    )

    async def scenario():
        async def release():
            await asyncio.sleep(0.1)
            level[0] = 0.0

        rel = asyncio.ensure_future(release())
        t0 = time.monotonic()
        await gate.admit()  # blocks until the level falls, then admits
        assert 0.05 < time.monotonic() - t0 < 2.0
        await rel
        # And with the level pinned high, the bounded block sheds.
        level[0] = 1.0
        gate.block_timeout = 0.1
        with pytest.raises(IngestOverloadError):
            await gate.admit()

    run(scenario())


def test_admission_bounds_backlog_gate_on_vs_off(run):
    """The overload claim at component level: a producer pushing far past
    capacity leaves a BOUNDED queue behind the gate (sheds past the high
    watermark) and an unbounded-growth queue without it (policy off)."""

    async def scenario():
        async def offer(gate: IngestGate, ch: Channel, n: int) -> int:
            accepted = 0
            for i in range(n):
                try:
                    await gate.admit()
                except IngestOverloadError:
                    continue
                ch.try_send(i)
                accepted += 1
            return accepted

        cap = 1_000
        ch_on: Channel = Channel(cap)
        gate_on = IngestGate(
            policy="shed", local_sources=[ch_on.occupancy], high=0.05, low=0.02
        )
        accepted = await offer(gate_on, ch_on, 500)
        # Trips at 5% occupancy (50 items) and, with nothing draining,
        # never re-admits: the backlog is bounded at the watermark.
        assert ch_on.depth() <= int(0.05 * cap) + 1
        assert accepted == ch_on.depth()

        ch_off: Channel = Channel(cap)
        gate_off = IngestGate(
            policy="off", local_sources=[ch_off.occupancy], high=0.05, low=0.02
        )
        await offer(gate_off, ch_off, 500)
        # Same offered load, no admission control: backlog grows with the
        # offered load, sailing far past the watermark.
        assert ch_off.depth() == 500

    run(scenario())


def test_backpressure_level_folds_three_signals():
    """The pushed level sees depth, service-time saturation, and collapse:
    shallow channels + slow commits must still trip the watermark (the
    measured 1-core overload mode), and a full commit stall pins 1.0."""
    from narwhal_tpu.pacing import backpressure_level

    # Healthy: shallow queues, fast commits.
    assert backpressure_level([0.01, 0.0], 0.2, 0.3, 4.0, 0.75) < 0.1
    # Deep queue alone trips (executor lagging consensus).
    assert backpressure_level([0.9], 0.2, 0.3, 4.0, 0.75) == pytest.approx(0.9)
    # Service-time saturation: channels shallow, commit EWMA at the target
    # -> exactly the high watermark; over the target -> above it.
    assert backpressure_level([0.01], 4.0, 0.3, 4.0, 0.75) == pytest.approx(0.75)
    assert backpressure_level([0.01], 8.0, 0.3, 4.0, 0.75) == 1.0
    # Collapse: no commit for longer than the target pins 1.0 even with no
    # EWMA to read.
    assert backpressure_level([0.0], None, 10.0, 4.0, 0.75) == 1.0
    # target=0 disables the latency signals entirely.
    assert backpressure_level([0.1], 100.0, 100.0, 0.0, 0.75) == pytest.approx(0.1)


def test_stage_timer_ewma_tracks_recent():
    reg = Registry()
    hist = reg.histogram("node_stage_latency_seconds", "", labels=("stage",))
    t = StageTimer(hist, "commit", ewma_alpha=0.5)
    assert t.ewma is None
    t.observe(1.0)
    assert t.ewma == pytest.approx(1.0)
    t.observe(3.0)
    assert t.ewma == pytest.approx(2.0)  # recent-weighted, not lifetime mean


# ---------------------------------------------------------------------------
# Worker ingest: the RESOURCE_EXHAUSTED shed on the wire
# ---------------------------------------------------------------------------


def test_worker_sheds_on_downstream_backpressure(run):
    """BackpressureMsg(level high) -> typed submissions answer
    RESOURCE_EXHAUSTED; level low -> admission resumes. The full wire path:
    client -> RpcServer -> gate -> ERR frame."""
    from narwhal_tpu.fixtures import CommitteeFixture
    from narwhal_tpu.messages import BackpressureMsg, SubmitTransactionMsg
    from narwhal_tpu.network import NetworkClient, RpcError
    from narwhal_tpu.stores import NodeStorage
    from narwhal_tpu.worker import Worker

    async def scenario():
        f = CommitteeFixture(size=4, workers=1)
        w = Worker(
            f.authorities[0].public, 0, f.committee, f.worker_cache,
            f.parameters, NodeStorage(None).batch_store,
        )
        await w.spawn()
        client = NetworkClient()
        try:
            await client.request(w.transactions_address, SubmitTransactionMsg(b"ok-1"))

            await client.request(
                w.worker_address, BackpressureMsg.from_level(1.0)
            )
            assert w.backpressure.level() == pytest.approx(1.0)
            with pytest.raises(RpcError) as ei:
                await client.request(
                    w.transactions_address, SubmitTransactionMsg(b"shed-me")
                )
            assert "RESOURCE_EXHAUSTED" in str(ei.value)
            assert w.registry.value("worker_ingest_shed") >= 1

            await client.request(
                w.worker_address, BackpressureMsg.from_level(0.0)
            )
            await client.request(w.transactions_address, SubmitTransactionMsg(b"ok-2"))
        finally:
            client.close()
            await w.shutdown()

    run(scenario())


def test_worker_ingest_policy_off_keeps_seed_behavior(run):
    """ingest_policy=off: even a pinned-high downstream level never sheds
    (the documented escape hatch back to unbounded queueing)."""
    from narwhal_tpu.fixtures import CommitteeFixture
    from narwhal_tpu.messages import BackpressureMsg, SubmitTransactionMsg
    from narwhal_tpu.network import NetworkClient
    from narwhal_tpu.stores import NodeStorage
    from narwhal_tpu.worker import Worker

    async def scenario():
        f = CommitteeFixture(size=4, workers=1)
        params = replace(f.parameters, ingest_policy="off")
        w = Worker(
            f.authorities[0].public, 0, f.committee, f.worker_cache,
            params, NodeStorage(None).batch_store,
        )
        await w.spawn()
        client = NetworkClient()
        try:
            await client.request(w.worker_address, BackpressureMsg.from_level(1.0))
            for i in range(5):
                await client.request(
                    w.transactions_address, SubmitTransactionMsg(bytes([i]) * 16)
                )
            assert w.registry.value("worker_ingest_shed") == 0
        finally:
            client.close()
            await w.shutdown()

    run(scenario())


# ---------------------------------------------------------------------------
# BatchMaker: seal semantics under fixed and adaptive delays
# ---------------------------------------------------------------------------


def test_batch_maker_trickle_seals_at_fixed_deadline(run):
    """The seal timer is a FIXED deadline measured from the last seal, not
    an idle timeout: a steady sub-batch-size trickle arriving faster than
    the delay still seals every max_batch_delay (an idle-timeout reset on
    each arrival would never seal)."""

    async def scenario():
        rx, tx_out = Channel(1_000), Channel(100)
        bm = BatchMaker(1_000_000, 0.08, rx, tx_out, _watch())  # no pacing
        task = bm.spawn()

        async def trickle():
            for i in range(25):  # one tx every 20ms for 0.5s
                await rx.send(_chunk(b"t%02d" % i))
                await asyncio.sleep(0.02)

        await trickle()
        await asyncio.sleep(0.1)  # let the final window seal
        task.cancel()
        batches = []
        while True:
            b = tx_out.try_recv()
            if b is None:
                break
            batches.append(b)
        # ~0.5s of trickle at an 0.08s deadline: expect ~6 seals; at least
        # 3 proves the deadline fires regardless of arrivals, and multiple
        # txs per batch proves the deadline did NOT reset per arrival.
        assert len(batches) >= 3
        assert sum(len(b.transactions) for b in batches) == 25
        assert max(len(b.transactions) for b in batches) >= 2

    run(scenario())


def test_batch_maker_adaptive_seals_near_floor(run):
    """With a pacing controller and shallow queues, a lone transaction
    seals near the floor instead of waiting out the configured ceiling."""

    async def scenario():
        rx, tx_out = Channel(1_000), Channel(100)
        pacing = PacingController(
            ceiling=5.0, floor=0.005, sources=[rx.occupancy, tx_out.occupancy]
        )
        bm = BatchMaker(1_000_000, 5.0, rx, tx_out, _watch(), pacing=pacing)
        task = bm.spawn()
        t0 = time.monotonic()
        await rx.send(_chunk(b"lonely"))
        batch = await asyncio.wait_for(tx_out.recv(), 1.0)  # << the 5s ceiling
        assert time.monotonic() - t0 < 1.0
        assert batch.transactions == (b"lonely",)
        task.cancel()

    run(scenario())


def test_batch_maker_deep_queue_keeps_ceiling(run):
    """With the EWMA pinned at full occupancy the effective delay is the
    ceiling — throughput mode accumulates instead of sealing greedily."""

    async def scenario():
        rx, tx_out = Channel(1_000), Channel(100)
        pacing = PacingController(ceiling=0.3, floor=0.001, sources=[lambda: 1.0])
        for _ in range(50):
            pacing.observe()  # converge the EWMA to saturated
        bm = BatchMaker(1_000_000, 0.3, rx, tx_out, _watch(), pacing=pacing)
        task = bm.spawn()
        await rx.send(_chunk(b"tx-a"))
        await asyncio.sleep(0.05)
        assert tx_out.try_recv() is None  # not sealed at the floor cadence
        batch = await asyncio.wait_for(tx_out.recv(), 2.0)  # ceiling seal
        assert batch.transactions == (b"tx-a",)
        task.cancel()

    run(scenario())


# ---------------------------------------------------------------------------
# StageTimer
# ---------------------------------------------------------------------------


def test_stage_timer_records_and_bounds():
    reg = Registry()
    hist = reg.histogram("node_stage_latency_seconds", "", labels=("stage",))
    now = [100.0]
    t = StageTimer(hist, "commit", max_pending=4, clock=lambda: now[0])
    t.start("a")
    now[0] = 100.25
    assert t.stop("a") == pytest.approx(0.25)
    assert reg.value("node_stage_latency_seconds", "commit") == 1
    assert t.stop("a") is None  # idempotent
    # Re-delivery must not reset the clock.
    t.start("b")
    now[0] = 101.0
    t.start("b")
    assert t.stop("b") == pytest.approx(0.75)
    # The pending map is bounded: oldest keys evict, never-stopped keys
    # cannot leak.
    for k in range(10):
        t.start(k)
    assert len(t._pending) <= 4
    assert t.stop(0) is None  # evicted
    assert t.stop(9) is not None


def test_stage_timer_one_span_window_per_key():
    """Once a key closes, a straggler re-start must NOT open a second,
    later window: a re-propose/re-deliver after certify already closed
    would otherwise mint a certify span with t0 past the commit, and —
    once the true span ages out of the trace ring — invert the
    waterfall's causality (the residual certify/commit race)."""
    reg = Registry()
    hist = reg.histogram("node_stage_latency_seconds", "", labels=("stage",))
    now = [100.0]
    t = StageTimer(hist, "certify", clock=lambda: now[0])
    t.start("k")
    now[0] = 100.5
    assert t.stop("k") == pytest.approx(0.5)
    # Straggler re-open long after the close: latched to a no-op.
    now[0] = 104.0
    t.start("k")
    assert t.stop("k") is None
    assert reg.value("node_stage_latency_seconds", "certify") == 1
    # The latch is bounded: the oldest closed keys fall out and only
    # then may a key legitimately open a fresh window.
    t2 = StageTimer(hist, "certify", clock=lambda: now[0], max_closed=2)
    for k in ("a", "b", "c"):
        t2.start(k)
        t2.stop(k)
    t2.start("a")  # "a" evicted from the closed latch
    assert t2.stop("a") is not None
    t2.start("c")  # "c" still latched
    assert t2.stop("c") is None


# ---------------------------------------------------------------------------
# Cluster: kwargs satellite + the stage pipeline end to end
# ---------------------------------------------------------------------------


def test_cluster_delay_kwargs_override():
    from narwhal_tpu.cluster import Cluster

    c = Cluster(size=4, max_header_delay=0.123, max_batch_delay=0.456)
    assert c.parameters.max_header_delay == pytest.approx(0.123)
    assert c.parameters.max_batch_delay == pytest.approx(0.456)
    # An explicit Parameters still wins outright.
    from narwhal_tpu.config import Parameters

    p = Parameters(max_header_delay=0.9)
    c2 = Cluster(size=4, parameters=p, max_header_delay=0.1)
    assert c2.parameters.max_header_delay == pytest.approx(0.9)


def test_stage_latency_pipeline_end_to_end(run):
    """Boot a committee, push transactions through to execution, and assert
    every stage histogram recorded: worker seal, primary propose+certify,
    consensus commit, executor execute — the decomposable latency plane the
    17-second opaque p50 turns into. Also proves the primary's
    backpressure push reaches its workers."""
    from narwhal_tpu.cluster import Cluster
    from narwhal_tpu.messages import SubmitTransactionStreamMsg
    from narwhal_tpu.network import NetworkClient

    async def scenario():
        cluster = Cluster(size=4, workers=1)
        await cluster.start()
        client = NetworkClient()
        try:
            await cluster.assert_progress(commit_threshold=2, timeout=30.0)
            txs = tuple(
                b"\x01" + i.to_bytes(8, "big") + b"\x5a" * 55 for i in range(64)
            )
            await client.request(
                cluster.authorities[0].worker_transactions_address(0),
                SubmitTransactionStreamMsg(txs),
            )
            # Wait until node 0 executes payload (the full pipeline ran).
            out = cluster.authorities[0].primary.tx_execution_output
            await asyncio.wait_for(out.recv(), 30.0)

            deadline = asyncio.get_event_loop().time() + 30.0
            def stages(a):
                r = a.primary.registry
                wr = cluster.authorities[0].workers[0].registry
                return {
                    "seal": wr.value("worker_stage_latency_seconds", "seal"),
                    "propose": r.value("primary_stage_latency_seconds", "propose"),
                    "certify": r.value("primary_stage_latency_seconds", "certify"),
                    "commit": r.value("consensus_stage_latency_seconds", "commit"),
                    "execute": r.value("executor_stage_latency_seconds", "execute"),
                }

            a0 = cluster.authorities[0]
            while True:
                counts = stages(a0)
                if all(v > 0 for v in counts.values()):
                    break
                if asyncio.get_event_loop().time() > deadline:
                    raise AssertionError(f"stage histograms incomplete: {counts}")
                await asyncio.sleep(0.2)

            # The admission-control push leg is alive: the worker heard a
            # fresh level from its primary within the staleness window.
            bp = a0.workers[0].worker.backpressure
            assert (
                time.monotonic() - bp._updated_at
                < cluster.parameters.backpressure_stale_after
            )
        finally:
            client.close()
            await cluster.shutdown()

    run(scenario(), timeout=90.0)


def test_pacing_env_kill_switch(monkeypatch):
    """NARWHAL_PACING=0 pins the fixed-timer seed behavior: no controllers
    are constructed anywhere in the worker."""
    from narwhal_tpu.fixtures import CommitteeFixture
    from narwhal_tpu.stores import NodeStorage
    from narwhal_tpu.worker import Worker

    f = CommitteeFixture(size=4, workers=1)
    monkeypatch.setenv("NARWHAL_PACING", "0")
    w = Worker(
        f.authorities[0].public, 0, f.committee, f.worker_cache,
        f.parameters, NodeStorage(None).batch_store,
    )
    assert w.batch_pacing is None
    monkeypatch.delenv("NARWHAL_PACING")
    w2 = Worker(
        f.authorities[0].public, 0, f.committee, f.worker_cache,
        f.parameters, NodeStorage(None).batch_store,
    )
    assert w2.batch_pacing is not None
    assert w2.batch_pacing.ceiling == pytest.approx(f.parameters.max_batch_delay)
