"""Property-based tests (hypothesis): randomized invariants the reference
guards with proptest.

- Codec roundtrip fuzz over the ENTIRE message registry (the reference's
  serde equivalence tests, types/src/tests/batch_serde.rs:88 and
  node/tests/formats.rs): decode(encode(m)) == m and the wire form is a
  fixed point (canonical encoding stability).
- Compressed-DAG invariants on random DAGs
  (/root/reference/dag/src/lib.rs:289-377): parents() only ever returns
  incompressible nodes, compression preserves reachability into the
  incompressible set, bft visits every live ancestor exactly once.
- Host ordering invariants on random lossy DAGs: order_dag output is
  duplicate-free, causally closed under the committed set, and sorted by
  (round, origin).
- WAL torn-tail fuzz: a log truncated at EVERY byte offset recovers to a
  prefix of the committed operations (tests/test_storage.py covers a single
  truncation point; this sweeps them all).
"""

import random as pyrandom

import pytest

pytest.importorskip("hypothesis", reason="property tests need hypothesis")
from hypothesis import HealthCheck, given, settings
from hypothesis import strategies as st

from narwhal_tpu import messages as M
from narwhal_tpu.messages import REGISTRY, decode_message, encode_message
from narwhal_tpu.types import Batch, Certificate, Header, Vote

# -- strategies ------------------------------------------------------------

digest = st.binary(min_size=32, max_size=32)
pubkey = digest
signature = st.binary(min_size=64, max_size=64)
rnd = st.integers(min_value=0, max_value=2**62)
small_bytes = st.binary(max_size=96)
short_text = st.text(max_size=48)

batches = st.builds(Batch, st.lists(small_bytes, max_size=4).map(tuple))

headers = st.builds(
    Header,
    author=pubkey,
    round=rnd,
    epoch=st.integers(min_value=0, max_value=2**31),
    payload=st.dictionaries(digest, st.integers(min_value=0, max_value=2**31), max_size=3),
    parents=st.frozensets(digest, max_size=3),
    signature=signature,
)

votes = st.builds(
    Vote,
    header_digest=digest,
    round=rnd,
    epoch=st.integers(min_value=0, max_value=2**31),
    origin=pubkey,
    author=pubkey,
    signature=signature,
)

certificates = st.builds(
    Certificate,
    header=headers,
    signers=st.lists(
        st.integers(min_value=0, max_value=200), max_size=4, unique=True
    ).map(lambda xs: tuple(sorted(xs))),
    signatures=st.lists(signature, max_size=4).map(tuple),
)

_digest_tuple = st.lists(digest, max_size=4).map(tuple)

_r32 = st.binary(min_size=32, max_size=32)
compact_certificates = st.builds(
    Certificate,
    header=headers,
    signers=st.lists(
        st.integers(min_value=0, max_value=200), max_size=4, unique=True
    ).map(lambda xs: tuple(sorted(xs))),
    signatures=st.lists(_r32, max_size=4).map(tuple),
    agg_s=_r32,
)

MESSAGE_STRATEGIES = {
    M.Ack: st.builds(M.Ack),
    M.HeaderMsg: st.builds(M.HeaderMsg, headers),
    M.VoteMsg: st.builds(M.VoteMsg, votes),
    M.CertificateMsg: st.builds(
        M.CertificateMsg, st.one_of(certificates, compact_certificates)
    ),
    M.CertificateRefMsg: st.builds(
        M.CertificateRefMsg,
        header_digest=digest,
        round=rnd,
        epoch=st.integers(min_value=0, max_value=2**31),
        origin=pubkey,
        signers=st.lists(
            st.integers(min_value=0, max_value=200), max_size=4, unique=True
        ).map(lambda xs: tuple(sorted(xs))),
        rs=st.lists(_r32, max_size=4).map(tuple),
        agg_s=_r32,
    ),
    M.CertificatesRequest: st.builds(M.CertificatesRequest, _digest_tuple, pubkey),
    M.CertificatesBatchRequest: st.builds(
        M.CertificatesBatchRequest, _digest_tuple, pubkey
    ),
    M.CertificatesBatchResponse: st.builds(
        M.CertificatesBatchResponse,
        st.lists(st.tuples(digest, st.none() | certificates), max_size=3).map(tuple),
    ),
    M.CertificatesRangeRequest: st.builds(
        M.CertificatesRangeRequest, rnd, rnd, pubkey
    ),
    M.CertificatesRangeResponse: st.builds(M.CertificatesRangeResponse, _digest_tuple),
    M.PayloadAvailabilityRequest: st.builds(
        M.PayloadAvailabilityRequest, _digest_tuple, pubkey
    ),
    M.PayloadAvailabilityResponse: st.builds(
        M.PayloadAvailabilityResponse,
        st.lists(st.tuples(digest, st.booleans()), max_size=4).map(tuple),
    ),
    M.SynchronizeMsg: st.builds(M.SynchronizeMsg, _digest_tuple, pubkey),
    M.CleanupMsg: st.builds(M.CleanupMsg, rnd),
    M.RequestBatchMsg: st.builds(M.RequestBatchMsg, digest),
    M.RequestBatchesMsg: st.builds(M.RequestBatchesMsg, _digest_tuple),
    M.DeleteBatchesMsg: st.builds(M.DeleteBatchesMsg, _digest_tuple),
    M.ReconfigureMsg: st.builds(M.ReconfigureMsg, short_text, short_text),
    M.OurBatchMsg: st.builds(M.OurBatchMsg, digest, st.integers(0, 2**31)),
    M.OthersBatchMsg: st.builds(M.OthersBatchMsg, digest, st.integers(0, 2**31)),
    M.RequestedBatchMsg: st.builds(
        M.RequestedBatchMsg, digest, small_bytes, st.booleans()
    ),
    M.RequestedBatchesMsg: st.builds(
        M.RequestedBatchesMsg,
        st.lists(st.tuples(digest, st.booleans(), small_bytes), max_size=3).map(
            tuple
        ),
    ),
    M.DeletedBatchesMsg: st.builds(M.DeletedBatchesMsg, _digest_tuple),
    M.WorkerErrorMsg: st.builds(M.WorkerErrorMsg, short_text),
    M.WorkerBatchMsg: st.builds(M.WorkerBatchMsg, small_bytes),
    M.WorkerBatchRequest: st.builds(M.WorkerBatchRequest, _digest_tuple),
    M.WorkerBatchResponse: st.builds(
        M.WorkerBatchResponse, st.lists(small_bytes, max_size=3).map(tuple)
    ),
    M.SubmitTransactionMsg: st.builds(M.SubmitTransactionMsg, small_bytes),
    M.SubmitTransactionStreamMsg: st.builds(
        M.SubmitTransactionStreamMsg,
        st.lists(small_bytes, max_size=3).map(tuple),
        st.none(),
    ),
    M.GetCollectionsRequest: st.builds(M.GetCollectionsRequest, _digest_tuple),
    M.GetCollectionsResponse: st.builds(
        M.GetCollectionsResponse,
        st.lists(
            st.tuples(
                digest,
                st.lists(
                    st.tuples(digest, st.lists(small_bytes, max_size=2).map(tuple)),
                    max_size=2,
                ).map(tuple),
                short_text,
            ),
            max_size=2,
        ).map(tuple),
    ),
    M.RemoveCollectionsRequest: st.builds(M.RemoveCollectionsRequest, _digest_tuple),
    M.ReadCausalRequest: st.builds(M.ReadCausalRequest, digest),
    M.ReadCausalResponse: st.builds(M.ReadCausalResponse, _digest_tuple),
    M.RoundsRequest: st.builds(M.RoundsRequest, pubkey),
    M.RoundsResponse: st.builds(M.RoundsResponse, rnd, rnd),
    M.NodeReadCausalRequest: st.builds(M.NodeReadCausalRequest, pubkey, rnd),
    M.NewNetworkInfoRequest: st.builds(
        M.NewNetworkInfoRequest,
        st.integers(0, 2**31),
        st.lists(st.tuples(pubkey, st.integers(0, 2**31), short_text), max_size=3).map(
            tuple
        ),
    ),
    M.GetPrimaryAddressRequest: st.builds(M.GetPrimaryAddressRequest),
    M.GetPrimaryAddressResponse: st.builds(M.GetPrimaryAddressResponse, short_text),
    M.NewEpochRequest: st.builds(M.NewEpochRequest, st.integers(0, 2**31)),
}

# Messages whose decode intentionally normalizes the representation (lazy
# wire-form carriers): field equality does not hold, canonical stability must.
_NORMALIZING = {M.SubmitTransactionStreamMsg}


def test_registry_fully_covered():
    """Every registered message tag has a fuzz strategy — adding a message
    without one fails CI here."""
    missing = [cls.__name__ for cls in REGISTRY.values() if cls not in MESSAGE_STRATEGIES]
    assert not missing, f"no strategy for: {missing}"


@given(st.data())
@settings(max_examples=300, deadline=None, suppress_health_check=[HealthCheck.too_slow])
def test_message_roundtrip_whole_registry(data):
    cls = data.draw(st.sampled_from(sorted(REGISTRY.values(), key=lambda c: c.TAG)))
    msg = data.draw(MESSAGE_STRATEGIES[cls])
    tag, body = encode_message(msg)
    assert tag == cls.TAG
    decoded = decode_message(tag, body)
    if cls not in _NORMALIZING:
        assert decoded == msg
    # Canonical stability: the wire form is a fixed point of decode∘encode.
    tag2, body2 = encode_message(decoded)
    assert (tag2, body2) == (tag, body)


# -- compressed DAG invariants ---------------------------------------------


class _Vertex:
    def __init__(self, digest, parents, compressible):
        self._digest = digest
        self._parents = parents
        self._compressible = compressible

    @property
    def digest(self):
        return self._digest

    def parents(self):
        return list(self._parents)

    def compressible(self):
        return self._compressible


dag_shapes = st.lists(
    st.tuples(
        st.integers(min_value=0, max_value=3),  # parent picks per node
        st.booleans(),  # compressible?
    ),
    min_size=1,
    max_size=40,
)


@given(dag_shapes, st.integers(0, 2**32))
@settings(max_examples=100, deadline=None)
def test_node_dag_compression_invariants(shape, seed):
    """dag/src/lib.rs:289-377: after arbitrary insert + make_compressible
    sequences, parents() never returns a compressible digest, and every
    parents() entry is an ancestor in the original edge relation."""
    from narwhal_tpu.dag import NodeDag

    rng = pyrandom.Random(seed)
    dag = NodeDag()
    inserted = []  # digests in insertion order
    edges = {}  # digest -> original parent digests
    compressible = set()
    for i, (nparents, comp) in enumerate(shape):
        d = i.to_bytes(32, "big")
        parents = (
            [rng.choice(inserted) for _ in range(min(nparents, len(inserted)))]
            if inserted
            else []
        )
        parents = list(dict.fromkeys(parents))
        dag.try_insert(_Vertex(d, parents, comp))
        inserted.append(d)
        edges[d] = parents
        if comp:
            compressible.add(d)
            dag.make_compressible(d)

    # Transitive ancestor sets in the ORIGINAL relation.
    ancestors = {}
    for d in inserted:
        anc = set()
        stack = list(edges[d])
        while stack:
            p = stack.pop()
            if p in anc:
                continue
            anc.add(p)
            stack.extend(edges[p])
        ancestors[d] = anc

    for d in inserted:
        if not dag.contains_live(d):
            continue
        got = dag.parents(d)
        for p in got:
            assert p not in compressible, "compressed parent leaked"
            assert p in ancestors[d], "parents() must stay within ancestors"
        # Compression preserves reachability: every incompressible ancestor
        # reachable only through compressible nodes must still be reachable
        # through parents() links.
        reach = set()
        stack = list(got)
        while stack:
            p = stack.pop()
            if p in reach or not dag.contains_live(p):
                continue
            reach.add(p)
            stack.extend(dag.parents(p))
        wanted = {
            a
            for a in ancestors[d]
            if a not in compressible and dag.contains_live(a)
        }
        assert wanted <= reach | set(got), "compression lost an ancestor"


# -- ordering invariants ----------------------------------------------------


@given(
    st.integers(min_value=4, max_value=7),  # committee size
    st.integers(min_value=3, max_value=12),  # rounds
    st.floats(min_value=0.0, max_value=0.4),  # failure probability
    st.integers(0, 2**32),
)
@settings(max_examples=25, deadline=None)
def test_order_dag_invariants(size, rounds, failure, seed):
    """order_dag (consensus/src/utils.rs:55-101): duplicate-free, sorted by
    (round, origin), and closed under uncommitted causal history."""
    from narwhal_tpu.consensus import Bullshark, ConsensusState
    from narwhal_tpu.fixtures import CommitteeFixture, make_certificates
    from narwhal_tpu.stores import NodeStorage
    from narwhal_tpu.types import Certificate

    f = CommitteeFixture(size=size)
    genesis = {c.digest for c in Certificate.genesis(f.committee)}
    certs, _ = make_certificates(
        f.committee, 1, rounds, genesis,
        failure_probability=failure, rng=pyrandom.Random(seed),
    )
    state = ConsensusState(Certificate.genesis(f.committee))
    engine = Bullshark(f.committee, NodeStorage(None).consensus_store, 50)
    index = 0
    committed = []
    for c in certs:
        out = engine.process_certificate(state, index, c)
        index += len(out)
        committed.extend(o.certificate for o in out)

    digests = [c.digest for c in committed]
    assert len(digests) == len(set(digests)), "duplicate commit"
    committed_set = set(digests)
    by_digest = {c.digest: c for c in certs}
    # The per-authority implicit-commit rule (utils.rs:86-89 / state.update):
    # once a round R of authority A is committed, A's certificates at rounds
    # <= R are skipped forever — they count as covered, not as holes.
    max_committed_round = {}
    for cert in committed:
        max_committed_round[cert.origin] = max(
            max_committed_round.get(cert.origin, 0), cert.round
        )
    for cert in committed:
        for parent in cert.header.parents:
            parent_cert = by_digest.get(parent)
            if parent_cert is None:
                continue  # genesis
            assert (
                parent in committed_set
                or parent_cert.round
                <= max_committed_round.get(parent_cert.origin, 0)
            ), "causal hole in committed sequence"


# -- WAL torn-tail sweep -----------------------------------------------------


def test_wal_recovers_any_truncation(tmp_path):
    """Truncate the log at every byte offset: recovery must never raise and
    must yield a prefix of the committed operation sequence."""
    from narwhal_tpu.storage import StorageEngine

    path = str(tmp_path / "wal")
    engine = StorageEngine(path, use_native=False)
    cf_a = engine.column_family("a")
    cf_b = engine.column_family("b")
    states = []  # state after each record

    def snapshot():
        return (
            sorted(cf_a.iter()),
            sorted(cf_b.iter()),
        )

    states.append(snapshot())
    ops = []
    rng = pyrandom.Random(7)
    for i in range(12):
        k = bytes([i]) * 4
        v = rng.randbytes(rng.randint(0, 40))
        if i % 3 == 2:
            cf_a.delete(bytes([i - 1]) * 4)
        elif i % 2:
            cf_b.put(k, v)
        else:
            cf_a.put(k, v)
        states.append(snapshot())
    engine.close()

    with open(path + "/wal.log", "rb") as fobj:
        full = fobj.read()

    for cut in range(len(full) + 1):
        with open(path + "/wal.log", "wb") as fobj:
            fobj.write(full[:cut])
        eng2 = StorageEngine(path, use_native=False)
        got = (
            sorted(eng2.column_family("a").iter()),
            sorted(eng2.column_family("b").iter()),
        )
        eng2.close()
        assert got in states, f"truncation at {cut} is not a committed prefix"
    # Restore the intact log (leave tmp_path consistent).
    with open(path + "/wal.log", "wb") as fobj:
        fobj.write(full)
