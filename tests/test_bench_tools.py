"""Unit tests for the benchmark tooling: aggregate grouping/statistics and
the latency-throughput plotter (the reference's aggregate.py / plot.py)."""

import json

from benchmark.aggregate import aggregate
from benchmark.plot import plot
from benchmark.sweep import render_table


def _record(rate, tps, lat, **over):
    rec = {
        "faults": 0,
        "committee_size": 4,
        "workers_per_node": 1,
        "input_rate": rate,
        "tx_size": 512,
        "duration_s": 20.0,
        "consensus_tps": tps,
        "consensus_bps": tps * 512,
        "consensus_latency_ms": lat,
        "end_to_end_tps": tps * 0.98,
        "end_to_end_bps": tps * 512 * 0.98,
        "end_to_end_latency_ms": lat * 1.4,
    }
    rec.update(over)
    return rec


def test_aggregate_groups_and_stats():
    runs = [
        _record(10_000, 9_800, 250),
        _record(10_000, 10_200, 270),
        _record(20_000, 18_000, 600),
    ]
    agg = aggregate(runs)
    assert len(agg) == 2
    by_rate = {a["input_rate"]: a for a in agg}
    assert by_rate[10_000]["runs"] == 2
    assert by_rate[10_000]["consensus_tps"] == 10_000
    assert by_rate[10_000]["consensus_tps_std"] > 0
    assert by_rate[20_000]["runs"] == 1
    assert by_rate[20_000]["consensus_tps_std"] == 0.0


def test_plot_writes_png(tmp_path):
    sweep = [_record(r, min(r, 26_000) * 0.95, 200 + r / 100) for r in (5_000, 15_000, 30_000)]
    path = tmp_path / "sweep.json"
    path.write_text(json.dumps(sweep))
    out = plot([str(path)], str(tmp_path / "curve.png"))
    assert (tmp_path / "curve.png").stat().st_size > 1_000
    assert out.endswith("curve.png")


def test_sweep_table_finds_knee():
    results = [_record(5_000, 4_900, 200), _record(30_000, 26_000, 900), _record(40_000, 25_500, 1_800)]
    table = render_table(results)
    assert "knee: ~26,000" in table
    assert "| 5,000 |" in table


def test_fd_preflight_estimates_and_fails_fast(monkeypatch):
    """The liveness preflight, honest for BOTH transport models: legacy
    N=100 W=1 demands ~2·N·(N-1)·2 fds (the r9 n100_liveness.json EMFILE
    at ~19.8k mesh sockets under a 20k limit) and fails BEFORE boot with a
    message pointing at --simnet; pooled collapses that to one link per
    node pair and fits the same rlimit."""
    import resource

    import pytest

    from benchmark.liveness import estimate_required_fds, preflight_fd_check

    # Legacy estimate must at least cover the measured N=100 failure
    # (~19.8k mesh sockets => ~40k fds both-endpoints-in-process).
    assert estimate_required_fds(100, 1, pooled=False) > 19_800
    # Pooled: N(N-1)/2 pair links + N self links, worker lanes ride them —
    # ~13.5k fds, comfortably under the 20k rlimit that EMFILEd r9.
    assert estimate_required_fds(100, 1, pooled=True) < 20_000
    assert (
        estimate_required_fds(100, 1, pooled=True)
        < estimate_required_fds(100, 1, pooled=False)
    )
    # Monotone in both axes, in both models.
    for pooled in (True, False):
        assert estimate_required_fds(100, 2, pooled) > estimate_required_fds(
            100, 1, pooled
        )
        assert estimate_required_fds(200, 1, pooled) > estimate_required_fds(
            100, 1, pooled
        )

    monkeypatch.setattr(
        resource, "getrlimit", lambda which: (20_000, 20_000)
    )
    with pytest.raises(SystemExit) as err:
        preflight_fd_check(100, 1, pooled=False)
    msg = str(err.value)
    assert "--simnet" in msg and "RLIMIT_NOFILE" in msg
    # The pooled model fits the very rlimit that EMFILEd the legacy mesh.
    preflight_fd_check(100, 1, pooled=True)
    # The default resolves pooling from NARWHAL_POOL (on unless disabled).
    monkeypatch.setenv("NARWHAL_POOL", "0")
    with pytest.raises(SystemExit):
        preflight_fd_check(100, 1)
    monkeypatch.delenv("NARWHAL_POOL")
    preflight_fd_check(100, 1)
    # A committee that fits passes silently in either model.
    preflight_fd_check(10, 1, pooled=False)
