"""Block-services scenarios mirroring
/root/reference/primary/src/block_synchronizer/tests/: certificates that
exist only on peers, unresponsive-peer failover with score demotion, and
payload availability rotation across providers.
"""

import asyncio

from narwhal_tpu.config import Authority, WorkerInfo
from narwhal_tpu.fixtures import CommitteeFixture
from narwhal_tpu.messages import (
    CertificatesBatchRequest,
    CertificatesBatchResponse,
    PayloadAvailabilityRequest,
    PayloadAvailabilityResponse,
    SynchronizeMsg,
)
from narwhal_tpu.network import NetworkClient, RpcServer
from narwhal_tpu.primary.block_synchronizer import BlockSynchronizer, PeerScores
from narwhal_tpu.stores import NodeStorage


async def _mock_peer_primary(f, index, certs_by_digest, available=()):
    """A scripted peer primary serving CertificatesBatch and
    PayloadAvailability (the PrimaryToPrimaryMockServer pattern,
    test_utils/src/lib.rs:176-359)."""
    srv = RpcServer()

    async def on_batch(msg: CertificatesBatchRequest, peer):
        return CertificatesBatchResponse(
            tuple((d, certs_by_digest.get(d)) for d in msg.digests)
        )

    async def on_availability(msg: PayloadAvailabilityRequest, peer):
        return PayloadAvailabilityResponse(
            tuple((d, d in available) for d in msg.digests)
        )

    srv.route(CertificatesBatchRequest, on_batch)
    srv.route(PayloadAvailabilityRequest, on_availability)
    port = await srv.start("127.0.0.1", 0)
    pk = f.authorities[index].public
    auth = f.committee.authorities[pk]
    f.committee.authorities[pk] = Authority(
        auth.stake, f"127.0.0.1:{port}", auth.network_key
    )
    return srv


def _make_sync(f, tx_loopback=None):
    storage = NodeStorage(None)
    sync = BlockSynchronizer(
        f.authorities[0].public,
        f.committee,
        f.worker_cache,
        storage.certificate_store,
        storage.payload_store,
        NetworkClient(),
        f.parameters,
        tx_loopback=tx_loopback,
    )
    return sync, storage


def test_fetch_certificates_held_only_by_peers(run):
    """A certificate absent locally is fetched from whichever peer has it,
    verified, and looped back to the Core (handler.rs:200-260)."""

    async def scenario():
        from narwhal_tpu.channels import Channel

        f = CommitteeFixture(size=4, workers=1)
        cert = f.certificate(f.header(author=1, round=1))
        servers = [
            await _mock_peer_primary(f, 1, {}),  # peer without it
            await _mock_peer_primary(f, 2, {cert.digest: cert}),  # peer with it
            await _mock_peer_primary(f, 3, {}),
        ]
        loopback = Channel(10)
        sync, _ = _make_sync(f, tx_loopback=loopback)
        try:
            got = await sync.synchronize_block_headers([cert.digest], timeout=5.0)
            assert [c.digest for c in got] == [cert.digest]
            injected = await asyncio.wait_for(loopback.recv(), 2.0)
            assert injected.digest == cert.digest
        finally:
            sync.network.close()
            for s in servers:
                await s.stop()

    run(scenario())


def test_unresponsive_peer_is_penalized_and_failed_over(run):
    """One peer address is dead: the fetch still succeeds from the others
    and the dead peer's standing drops below theirs (peers.rs weights)."""

    async def scenario():
        f = CommitteeFixture(size=4, workers=1)
        cert = f.certificate(f.header(author=1, round=1))
        dead_pk = f.authorities[1].public
        # Point the dead peer at a port nothing listens on.
        auth = f.committee.authorities[dead_pk]
        f.committee.authorities[dead_pk] = Authority(
            auth.stake, "127.0.0.1:1", auth.network_key
        )
        servers = [
            await _mock_peer_primary(f, 2, {cert.digest: cert}),
            await _mock_peer_primary(f, 3, {cert.digest: cert}),
        ]
        sync, _ = _make_sync(f)
        try:
            got = await sync.synchronize_block_headers([cert.digest], timeout=5.0)
            assert [c.digest for c in got] == [cert.digest]
            dead_score = sync.peers.score(dead_pk)
            live_scores = [
                sync.peers.score(f.authorities[i].public) for i in (2, 3)
            ]
            assert dead_score < min(live_scores), (dead_score, live_scores)
            assert dead_score < PeerScores.INITIAL
        finally:
            sync.network.close()
            for s in servers:
                await s.stop()

    run(scenario())


def test_payload_sync_rotates_providers(run):
    """Two peers declare payload availability; the first Synchronize attempt
    targets one, and when nothing arrives the retry targets the OTHER
    (availability rotation, vs. round 1's providers[0] forever)."""

    async def scenario():
        from dataclasses import replace

        f = CommitteeFixture(size=4, workers=1)
        batch_digest = b"\x07" * 32
        cert = f.certificate(f.header(author=1, round=1, payload={batch_digest: 0}))

        servers = [
            await _mock_peer_primary(f, 1, {}, available={cert.digest}),
            await _mock_peer_primary(f, 2, {}, available={cert.digest}),
            await _mock_peer_primary(f, 3, {}, available=()),
        ]
        # Our own worker: capture Synchronize targets.
        targets = []
        worker_srv = RpcServer()

        async def on_sync(msg: SynchronizeMsg, peer):
            targets.append(msg.target)

        worker_srv.route(SynchronizeMsg, on_sync)
        wport = await worker_srv.start("127.0.0.1", 0)
        me = f.authorities[0].public
        info = f.worker_cache.workers[me][0]
        f.worker_cache.workers[me][0] = WorkerInfo(
            name=info.name,
            transactions=info.transactions,
            worker_address=f"127.0.0.1:{wport}",
        )

        f.parameters = replace(f.parameters, sync_retry_delay=0.1)
        sync, storage = _make_sync(f)
        try:
            done = await sync.synchronize_block_payloads([cert], timeout=0.5)
            assert done == []  # nothing ever arrived
            distinct = set(targets)
            assert len(targets) >= 2, targets
            assert len(distinct) >= 2, "retries must rotate to another provider"
            assert distinct <= {f.authorities[1].public, f.authorities[2].public}

            # Now the payload arrives: the sync completes promptly.
            async def deliver():
                await asyncio.sleep(0.05)
                storage.payload_store.write(batch_digest, 0)

            task = asyncio.ensure_future(deliver())
            done = await sync.synchronize_block_payloads([cert], timeout=2.0)
            assert [c.digest for c in done] == [cert.digest]
            await task
        finally:
            sync.network.close()
            for s in servers:
                await s.stop()
            await worker_srv.stop()

    run(scenario())


async def _mock_range_peer(f, index, range_digests, certs_by_digest):
    """A scripted peer that advertises `range_digests` for any range request
    and serves `certs_by_digest` (possibly tampered/wrong) on batch fetch."""
    from narwhal_tpu.messages import (
        CertificatesRangeRequest,
        CertificatesRangeResponse,
    )

    srv = RpcServer()

    async def on_range(msg: CertificatesRangeRequest, peer):
        return CertificatesRangeResponse(tuple(range_digests))

    async def on_batch(msg, peer):
        from narwhal_tpu.messages import CertificatesBatchResponse

        return CertificatesBatchResponse(
            tuple((d, certs_by_digest.get(d)) for d in msg.digests)
        )

    from narwhal_tpu.messages import CertificatesBatchRequest as _CBR

    srv.route(CertificatesRangeRequest, on_range)
    srv.route(_CBR, on_batch)
    port = await srv.start("127.0.0.1", 0)
    pk = f.authorities[index].public
    auth = f.committee.authorities[pk]
    f.committee.authorities[pk] = Authority(
        auth.stake, f"127.0.0.1:{port}", auth.network_key
    )
    return srv


def _tampered(cert):
    """Certificate with one vote signature corrupted (quorum intact on
    paper, cryptographically invalid)."""
    from dataclasses import replace as dc_replace

    sigs = list(cert.signatures)
    sigs[0] = bytes(64)
    return dc_replace(cert, signatures=tuple(sigs))


def test_fetched_certificate_with_bad_signature_is_rejected(run):
    """A peer serving a certificate whose vote signature is corrupt: the
    fetch must neither return it nor loop it back to the Core
    (handler.rs re-injects only VERIFIED certificates)."""

    async def scenario():
        from narwhal_tpu.channels import Channel

        f = CommitteeFixture(size=4, workers=1)
        cert = f.certificate(f.header(author=1, round=1))
        bad = _tampered(cert)
        servers = [
            await _mock_peer_primary(f, 1, {cert.digest: bad}),
            await _mock_peer_primary(f, 2, {cert.digest: bad}),
            await _mock_peer_primary(f, 3, {cert.digest: bad}),
        ]
        loopback = Channel(10)
        sync, storage = _make_sync(f, tx_loopback=loopback)
        try:
            got = await sync.synchronize_block_headers([cert.digest], timeout=3.0)
            assert got == []
            assert loopback.empty()
            assert not storage.certificate_store.contains(cert.digest)
        finally:
            sync.network.close()
            for s in servers:
                await s.stop()

    run(scenario())


def test_peer_answering_with_wrong_certificates_is_ignored(run):
    """A peer that answers the batch request with certificates for OTHER
    digests (a misbehaving or confused peer) contributes nothing; the
    honest peer's certificate is still collected."""

    async def scenario():
        f = CommitteeFixture(size=4, workers=1)
        wanted = f.certificate(f.header(author=1, round=1))
        decoy = f.certificate(f.header(author=2, round=1))

        srv_lies = RpcServer()

        async def on_batch_lies(msg: CertificatesBatchRequest, peer):
            return CertificatesBatchResponse(
                tuple((d, decoy) for d in msg.digests)
            )

        srv_lies.route(CertificatesBatchRequest, on_batch_lies)
        port = await srv_lies.start("127.0.0.1", 0)
        pk = f.authorities[1].public
        auth = f.committee.authorities[pk]
        f.committee.authorities[pk] = Authority(
            auth.stake, f"127.0.0.1:{port}", auth.network_key
        )
        servers = [
            srv_lies,
            await _mock_peer_primary(f, 2, {wanted.digest: wanted}),
            await _mock_peer_primary(f, 3, {}),
        ]
        sync, _ = _make_sync(f)
        try:
            got = await sync.synchronize_block_headers([wanted.digest], timeout=5.0)
            assert [c.digest for c in got] == [wanted.digest]
        finally:
            sync.network.close()
            for s in servers:
                await s.stop()

    run(scenario())


def test_range_sync_with_empty_peer_responses(run):
    """Every peer answers the range request with an empty digest list: the
    catch-up returns promptly with nothing to fetch (no error, no hang)."""

    async def scenario():
        f = CommitteeFixture(size=4, workers=1)
        servers = [await _mock_range_peer(f, i, [], {}) for i in (1, 2, 3)]
        sync, _ = _make_sync(f)
        try:
            got = await asyncio.wait_for(
                sync.synchronize_range(0, timeout=2.0), 5.0
            )
            assert got == []
        finally:
            sync.network.close()
            for s in servers:
                await s.stop()

    run(scenario())


def test_range_sync_threshold_excludes_minority_digests(run):
    """A digest advertised by only one of three answering peers falls below
    the 0.5 response-ratio threshold (mod.rs:58) and is not fetched; a
    digest advertised by all is."""

    async def scenario():
        f = CommitteeFixture(size=4, workers=1)
        majority = f.certificate(f.header(author=1, round=1))
        minority = f.certificate(f.header(author=2, round=1))
        certs = {majority.digest: majority, minority.digest: minority}
        servers = [
            await _mock_range_peer(f, 1, [majority.digest, minority.digest], certs),
            await _mock_range_peer(f, 2, [majority.digest], certs),
            await _mock_range_peer(f, 3, [majority.digest], certs),
        ]
        from narwhal_tpu.channels import Channel

        loopback = Channel(10)
        sync, storage = _make_sync(f, tx_loopback=loopback)
        try:
            wanted = await sync.synchronize_range(0, timeout=3.0)
            assert majority.digest in wanted
            assert minority.digest not in wanted
            # The fetched majority certificate is handed to the Core for
            # storage/causal completion; the minority one never is.
            injected = await asyncio.wait_for(loopback.recv(), 2.0)
            assert injected.digest == majority.digest
            assert loopback.empty()
        finally:
            sync.network.close()
            for s in servers:
                await s.stop()

    run(scenario())


def test_range_sync_with_malformed_certificates(run):
    """Peers advertise a digest but serve a cryptographically invalid
    certificate for it: the catch-up completes without storing or
    loopback-injecting the garbage."""

    async def scenario():
        from narwhal_tpu.channels import Channel

        f = CommitteeFixture(size=4, workers=1)
        cert = f.certificate(f.header(author=1, round=1))
        bad = _tampered(cert)
        servers = [
            await _mock_range_peer(f, i, [cert.digest], {cert.digest: bad})
            for i in (1, 2, 3)
        ]
        loopback = Channel(10)
        sync, storage = _make_sync(f, tx_loopback=loopback)
        try:
            await asyncio.wait_for(sync.synchronize_range(0, timeout=2.0), 10.0)
            assert loopback.empty()
            assert not storage.certificate_store.contains(cert.digest)
        finally:
            sync.network.close()
            for s in servers:
                await s.stop()

    run(scenario())


def test_range_sync_all_peers_dead(run):
    """No peer reachable: range catch-up returns [] promptly instead of
    hanging the restart path."""

    async def scenario():
        f = CommitteeFixture(size=4, workers=1)
        for i in (1, 2, 3):
            pk = f.authorities[i].public
            auth = f.committee.authorities[pk]
            f.committee.authorities[pk] = Authority(
                auth.stake, "127.0.0.1:1", auth.network_key
            )
        sync, _ = _make_sync(f)
        try:
            t0 = asyncio.get_event_loop().time()
            got = await asyncio.wait_for(
                sync.synchronize_range(0, timeout=2.0), 10.0
            )
            assert got == []
            assert asyncio.get_event_loop().time() - t0 < 5.0
        finally:
            sync.network.close()

    run(scenario())


def test_payload_availability_liar_fails_over_to_author(run):
    """A peer declares payload availability but its workers never deliver:
    the rotation falls back to other targets (including the certificate
    author) instead of hammering the liar forever. We assert the worker
    receives Synchronize commands naming DIFFERENT targets across retries."""

    async def scenario():
        from dataclasses import replace

        f = CommitteeFixture(size=4, workers=1)
        batch_digest = b"\x07" * 32
        cert = f.certificate(
            f.header(author=1, round=1, payload={batch_digest: 0})
        )
        liar_pk = f.authorities[2].public
        servers = [
            await _mock_peer_primary(f, 2, {}, available=(cert.digest,)),
        ]
        # Our own worker 0: capture Synchronize targets.
        targets = []
        wsrv = RpcServer()

        async def on_sync(msg: SynchronizeMsg, peer):
            targets.append(msg.target)

        wsrv.route(SynchronizeMsg, on_sync)
        wport = await wsrv.start("127.0.0.1", 0)
        me = f.authorities[0].public
        info = f.worker_cache.workers[me][0]
        f.worker_cache.workers[me][0] = WorkerInfo(
            name=info.name, transactions=info.transactions,
            worker_address=f"127.0.0.1:{wport}",
        )
        sync, _ = _make_sync(f)
        sync.parameters = replace(sync.parameters, sync_retry_delay=0.2)
        try:
            got = await sync.synchronize_block_payloads([cert], timeout=1.5)
            assert got == []  # nothing ever arrives
            assert len(targets) >= 2
            assert len(set(targets)) >= 2, f"never rotated: {targets}"
            assert liar_pk in targets  # the declared provider was tried
        finally:
            sync.network.close()
            await wsrv.stop()
            for s in servers:
                await s.stop()

    run(scenario())
