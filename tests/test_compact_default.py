"""Compact certificates as the committee-wide default (ISSUE 11).

The half-aggregated certificate form is no longer gated to TPU-crypto
committees: every backend verifies proofs through a batched cofactored
path — the device msm group lane on tpu nodes, one bucket-method MSM per
flush on cpu/pool nodes (types.host_batch_verify_aggregates, dispatched by
the AsyncVerifierPool's coalescing group lane). These tests pin:

- symmetric ConfigError boot validation (verify_rule AND cert_format);
- a cpu-backend committee booting and committing under the compact
  default, with `full` a working opt-out;
- verdict equivalence of the batched host path against the per-item
  reference on tampered proofs (bit-flipped agg_s, wrong signer bitmap,
  malformed points) plus its one-flush coalescing;
- the mixed catch-up paths: a peer that missed the CertificateRefMsg
  broadcast rebuilds from its header store (hit) or fetches the full
  certificate from the origin (miss), byte-round-tripping either way.
"""

from __future__ import annotations

import asyncio
from dataclasses import replace

import pytest

from narwhal_tpu.channels import Channel
from narwhal_tpu.cluster import Cluster
from narwhal_tpu.config import ConfigError
from narwhal_tpu.fixtures import CommitteeFixture
from narwhal_tpu.types import (
    Certificate,
    Header,
    Vote,
    host_verify_aggregate,
)


def _compact_cert(fx, committee, serial: int, voters=None, author=None):
    author = author if author is not None else fx.authorities[serial % fx.size]
    h = Header.build(
        author.public,
        1,
        committee.epoch,
        {serial.to_bytes(32, "little"): 0},
        frozenset(c.digest for c in Certificate.genesis(committee)),
        author.signature_service(),
    )
    votes = [
        Vote.for_header(h, a.public, a.signature_service())
        for a in (voters or fx.authorities[:3])
    ]
    signers, sigs = zip(
        *sorted((committee.index_of(v.author), v.signature) for v in votes)
    )
    return Certificate.compact_from_votes(h, tuple(signers), tuple(sigs))


# ---------------------------------------------------------------------------
# Boot validation: symmetric ConfigError
# ---------------------------------------------------------------------------


def test_boot_validation_is_symmetric_config_error():
    """verify_rule typos used to fall through to backend-specific errors
    while cert_format failed fast — both (and header_wire, and the
    cofactored-needs-tpu cross-check) now raise ConfigError at assembly."""
    from narwhal_tpu.node import NodeStorage, PrimaryNode

    fx = CommitteeFixture(size=4)
    auth = fx.authorities[0]

    def make(params, **kw):
        return PrimaryNode(
            auth.keypair, fx.committee, fx.worker_cache, params, NodeStorage(None), **kw
        )

    with pytest.raises(ConfigError, match="verify_rule"):
        make(replace(fx.parameters, verify_rule="cofactered"))
    with pytest.raises(ConfigError, match="cert_format"):
        make(replace(fx.parameters, cert_format="compat"))
    with pytest.raises(ConfigError, match="header_wire"):
        make(replace(fx.parameters, header_wire="deltas"))
    with pytest.raises(ConfigError, match="cofactored"):
        make(replace(fx.parameters, verify_rule="cofactored"), crypto_backend="cpu")


def test_compact_default_wires_batched_pool_on_cpu_backend():
    """Under the compact default a cpu-backend node gets the async verifier
    stage (certificate proofs must batch, not host-verify per item inline);
    the full-format opt-out keeps the reference's inline cpu path."""
    from narwhal_tpu.node import NodeStorage, PrimaryNode

    fx = CommitteeFixture(size=4)
    auth = fx.authorities[0]
    assert fx.parameters.cert_format == "compact"  # the flipped default
    node = PrimaryNode(
        auth.keypair, fx.committee, fx.worker_cache, fx.parameters, NodeStorage(None)
    )
    assert node.crypto_pool is not None
    assert node.primary.verifier_stage is not None
    # Catch-up fetches share the same batched lane.
    assert node.block_synchronizer.crypto_pool is node.crypto_pool

    full = PrimaryNode(
        auth.keypair,
        fx.committee,
        fx.worker_cache,
        replace(fx.parameters, cert_format="full"),
        NodeStorage(None),
    )
    assert full.crypto_pool is None
    assert full.primary.verifier_stage is None


# ---------------------------------------------------------------------------
# Batched host path: coalescing + tampered-proof rejection
# ---------------------------------------------------------------------------


def test_pool_group_lane_coalesces_and_rejects_tampered_proofs(run):
    """Concurrent verify_aggregate calls seal into ONE batched dispatch
    (certificate groups per flush, not items), and the batched verdicts
    match the per-item reference on every adversarial shape: bit-flipped
    agg_s, wrong signer bitmap, non-point R bytes."""
    from narwhal_tpu.tpu.verifier import AsyncVerifierPool
    from narwhal_tpu.types import host_batch_verify_aggregates

    fx = CommitteeFixture(size=4)
    committee = fx.committee
    honest = [_compact_cert(fx, committee, i) for i in range(3)]
    flipped = _compact_cert(fx, committee, 10)
    flipped = Certificate(
        flipped.header,
        flipped.signers,
        flipped.signatures,
        bytes([flipped.agg_s[0] ^ 1]) + flipped.agg_s[1:],
    )
    bitmap = _compact_cert(fx, committee, 11)
    # Same proof, different claimed signer set (still quorum-sized).
    bitmap = Certificate(
        bitmap.header, (0, 1, 3), bitmap.signatures, bitmap.agg_s
    )
    torn = _compact_cert(fx, committee, 12)
    torn = Certificate(
        torn.header,
        torn.signers,
        (b"\xff" * 32,) + torn.signatures[1:],
        torn.agg_s,
    )
    certs = honest + [flipped, bitmap, torn]
    groups = [c.aggregate_group(committee) for c in certs]

    dispatches = []

    def counting_backend(gs):
        dispatches.append(len(gs))
        return host_batch_verify_aggregates(gs)

    async def scenario():
        pool = AsyncVerifierPool(group_backend=counting_backend, max_delay=0.05)
        try:
            results = await asyncio.gather(
                *(pool.verify_aggregate(*g) for g in groups)
            )
        finally:
            await pool.close()
        return results

    results = run(scenario(), timeout=60.0)
    assert results == [True, True, True, False, False, False]
    # All six groups sealed into one flush: groups per dispatch, not items.
    assert dispatches == [6], dispatches
    # Verdict equivalence against the per-item cofactored reference.
    assert results == [host_verify_aggregate(*g) for g in groups]


def test_verifier_stage_forwards_honest_and_drops_tampered_compact(run):
    """The stage submits compact certificates as GROUPS through the pool:
    an honest certificate comes out PreVerified, a tampered proof never
    reaches the Core."""
    from narwhal_tpu.primary.verifier_stage import PreVerified, VerifierStage
    from narwhal_tpu.tpu.verifier import AsyncVerifierPool

    fx = CommitteeFixture(size=4)
    committee = fx.committee
    good = _compact_cert(fx, committee, 0)
    bad = _compact_cert(fx, committee, 1)
    bad = Certificate(
        bad.header, bad.signers, bad.signatures,
        bytes([bad.agg_s[0] ^ 0x80]) + bad.agg_s[1:],
    )

    async def scenario():
        out = Channel(16)
        pool = AsyncVerifierPool(max_delay=0.01)
        stage = VerifierStage(committee, fx.worker_cache, pool, out)
        try:
            await stage.submit(good)
            await stage.submit(bad)
            got = await asyncio.wait_for(out.recv(), timeout=20.0)
            assert isinstance(got, PreVerified)
            assert got.inner.to_bytes() == good.to_bytes()
            # The tampered certificate is dropped, not forwarded.
            await asyncio.sleep(0.5)
            assert out.try_recv() is None
        finally:
            stage.shutdown()
            await pool.close()

    run(scenario(), timeout=60.0)


# ---------------------------------------------------------------------------
# Mixed catch-up: CertificateRefMsg hit + fetch fallback
# ---------------------------------------------------------------------------


def test_certificate_ref_hit_and_fetch_fallback_byte_roundtrip(run, tmp_path):
    """A node that missed the CertificateRefMsg broadcast recovers the full
    certificate either from its own header store (hit: it voted on the
    header) or by fetching from the origin via the Helper's batch route
    (block_synchronizer-style miss) — and the rebuilt certificate
    byte-round-trips in both cases."""
    from narwhal_tpu.messages import CertificateRefMsg

    async def scenario():
        cluster = Cluster(size=4, workers=1, store_base=str(tmp_path))
        await cluster.start()
        try:
            await cluster.assert_progress(commit_threshold=2, timeout=60.0)
            node0, node1 = cluster.authorities[0], cluster.authorities[1]
            store0 = node0.primary.storage.certificate_store
            cert = next(
                c
                for c in store0.after_round(1)
                if c.is_compact and c.origin == node0.name
            )

            captured: list = []
            p1 = node1.primary.primary

            async def capture(msg) -> None:
                captured.append(msg)

            # The patched ingest also sees live peer traffic (headers,
            # votes): resolution assertions filter for the exact
            # certificate digest.
            p1._ingest = capture  # type: ignore[method-assign]

            def resolved(wanted):
                return [
                    m
                    for m in captured
                    if isinstance(m, Certificate) and m.digest == wanted.digest
                ]

            # HIT: node1 voted on this header, so its header store rebuilds
            # the certificate locally — byte-identical to the original.
            await p1._on_certificate_ref(
                CertificateRefMsg.from_certificate(cert), peer="test"
            )
            hits = resolved(cert)
            assert hits, "header-store hit did not resolve"
            assert hits[0].to_bytes() == cert.to_bytes()

            # MISS: a certificate node1 never saw the header of. Plant it
            # in the origin's store so the Helper can serve the fetch.
            fx0 = cluster.fixture.authorities[0]
            fresh = _compact_cert(
                cluster.fixture,
                cluster.committee,
                4242,
                voters=cluster.fixture.authorities[:3],
                author=fx0,
            )
            store0.write(fresh)
            assert node1.primary.storage.header_store.read(
                fresh.header.digest
            ) is None
            captured.clear()
            await p1._on_certificate_ref(
                CertificateRefMsg.from_certificate(fresh), peer="test"
            )
            # The resolver waits 0.5 s for an in-flight HeaderMsg, then
            # fetches from the origin.
            for _ in range(100):
                if resolved(fresh):
                    break
                await asyncio.sleep(0.1)
            fetched = resolved(fresh)
            assert fetched, "fetch fallback did not resolve"
            assert fetched[0].to_bytes() == fresh.to_bytes()
        finally:
            await cluster.shutdown()

    run(scenario(), timeout=180.0)
