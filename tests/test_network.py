"""RPC mesh tests — real loopback sockets like the reference's mock servers
(/root/reference/test_utils/src/lib.rs:176-359)."""

import asyncio

import pytest

from narwhal_tpu.channels import Channel
from narwhal_tpu.messages import (
    Ack,
    CertificateMsg,
    SubmitTransactionMsg,
    WorkerBatchMsg,
    WorkerBatchRequest,
    WorkerBatchResponse,
)
from narwhal_tpu.network import (
    NetworkClient,
    RetryConfig,
    RpcError,
    RpcServer,
    RpcTimeout,
)
from narwhal_tpu.fixtures import CommitteeFixture
from narwhal_tpu.types import Batch


def test_request_response(run):
    async def scenario():
        server = RpcServer()
        received = Channel(100)

        async def on_batch(msg, peer):
            await received.send(msg)
            return None  # ack

        async def on_batch_request(msg: WorkerBatchRequest, peer):
            return WorkerBatchResponse((b"batch-bytes",))

        server.route(WorkerBatchMsg, on_batch)
        server.route(WorkerBatchRequest, on_batch_request)
        port = await server.start("127.0.0.1", 0)

        net = NetworkClient()
        addr = f"127.0.0.1:{port}"
        batch = Batch((b"tx",))

        # oneway + ack
        ok = await net.unreliable_send(addr, WorkerBatchMsg(batch.to_bytes()))
        assert ok
        got = await asyncio.wait_for(received.recv(), 1.0)
        assert got.batch() == batch

        # typed rpc
        resp = await net.request(addr, WorkerBatchRequest((batch.digest,)))
        assert isinstance(resp, WorkerBatchResponse)
        assert resp.batches == (b"batch-bytes",)

        net.close()
        await server.stop()

    run(scenario())


def test_reliable_send_escalates_deadline_for_slow_peer(run):
    """A slow-but-alive handler must not be retried into congestion
    collapse: the reliable send escalates its per-attempt deadline, so the
    handler runs a couple of times, not once per backoff tick (the N=50
    frame-storm fence)."""

    async def scenario():
        server = RpcServer()
        calls = 0

        async def slow(msg, peer):
            nonlocal calls
            calls += 1
            await asyncio.sleep(0.35)  # beyond the first two deadlines
            return None

        server.route(WorkerBatchMsg, slow)
        port = await server.start("127.0.0.1", 0)
        net = NetworkClient(RetryConfig(initial=0.01, max_elapsed=None))
        handle = net.send(
            f"127.0.0.1:{port}", WorkerBatchMsg(Batch((b"t",)).to_bytes()),
            timeout=0.1,  # first deadlines miss; escalation must kick in
        )
        assert await asyncio.wait_for(handle.task, 10.0)
        # Fixed 0.1 s deadlines would need ~4+ handler executions before
        # luck; escalation (0.1 -> 0.2 -> 0.4) succeeds by the third.
        assert calls <= 3, calls
        net.close()
        await server.stop()

    run(scenario())


class _ScriptedPeer:
    """PeerClient stand-in: raises the scripted failures in order, then
    acks, recording the per-attempt deadline the client chose."""

    def __init__(self, script):
        self.script = list(script)
        self.timeouts = []

    async def request(self, msg, timeout):
        self.timeouts.append(timeout)
        if self.script:
            raise self.script.pop(0)
        return Ack()

    def close(self):
        pass


def test_reliable_send_does_not_escalate_on_connection_refused(run):
    """Connection-refused fails instantly — it says nothing about the
    peer's speed, so a restarting peer must keep getting the configured
    deadline, not an ever-doubling one."""

    async def scenario():
        net = NetworkClient(RetryConfig(initial=0.001, max_elapsed=None, jitter=0))
        peer = _ScriptedPeer([ConnectionRefusedError("refused")] * 4)
        net._peers["127.0.0.1:9"] = peer
        handle = net.send("127.0.0.1:9", Ack(), timeout=1.0)
        assert await asyncio.wait_for(handle.task, 5.0)
        assert peer.timeouts == [1.0] * 5  # never inflated
        net.close()

    run(scenario())


def test_reliable_send_resets_deadline_after_timeout_escalation(run):
    """Only timeout-class failures escalate, and any non-timeout failure
    resets the deadline: timeout, timeout -> 1x, 2x, 4x; then a refused
    connect drops the next attempt back to the configured 1x."""

    async def scenario():
        net = NetworkClient(RetryConfig(initial=0.001, max_elapsed=None, jitter=0))
        peer = _ScriptedPeer(
            [
                RpcTimeout("slow"),
                RpcTimeout("slow"),
                ConnectionRefusedError("restarting"),
                RpcTimeout("slow"),
            ]
        )
        net._peers["127.0.0.1:9"] = peer
        handle = net.send("127.0.0.1:9", Ack(), timeout=1.0)
        assert await asyncio.wait_for(handle.task, 5.0)
        assert peer.timeouts == [1.0, 2.0, 4.0, 1.0, 2.0]
        net.close()

    run(scenario())


def test_unreliable_send_to_dead_peer(run):
    async def scenario():
        net = NetworkClient()
        ok = await net.unreliable_send("127.0.0.1:1", SubmitTransactionMsg(b"x"), timeout=1.0)
        assert not ok
        net.close()

    run(scenario())


def test_reliable_send_retries_until_server_appears(run):
    async def scenario():
        from narwhal_tpu.config import get_available_port

        port = get_available_port()
        addr = f"127.0.0.1:{port}"
        net = NetworkClient(RetryConfig(initial=0.02, max_elapsed=None))
        received = Channel(10)

        handle = net.send(addr, SubmitTransactionMsg(b"hello"))
        await asyncio.sleep(0.1)  # several failed attempts

        server = RpcServer()

        async def on_tx(msg, peer):
            await received.send(msg)
            return None

        server.route(SubmitTransactionMsg, on_tx)
        await server.start("127.0.0.1", port)

        assert await asyncio.wait_for(handle, 5.0) is True
        got = await asyncio.wait_for(received.recv(), 1.0)
        assert got.transaction == b"hello"
        net.close()
        await server.stop()

    run(scenario())


def test_reliable_send_cancel(run):
    async def scenario():
        net = NetworkClient(RetryConfig(initial=0.02, max_elapsed=None))
        handle = net.send("127.0.0.1:1", SubmitTransactionMsg(b"x"))
        await asyncio.sleep(0.05)
        handle.cancel()
        with pytest.raises(asyncio.CancelledError):
            await handle
        net.close()

    run(scenario())


def test_handler_error_becomes_rpc_error(run):
    async def scenario():
        server = RpcServer()

        async def boom(msg, peer):
            raise ValueError("kaboom")

        server.route(SubmitTransactionMsg, boom)
        port = await server.start("127.0.0.1", 0)
        net = NetworkClient()
        with pytest.raises(RpcError, match="kaboom"):
            await net.request(f"127.0.0.1:{port}", SubmitTransactionMsg(b"x"))
        # connection survives an error response
        with pytest.raises(RpcError):
            await net.request(f"127.0.0.1:{port}", SubmitTransactionMsg(b"y"))
        net.close()
        await server.stop()

    run(scenario())


def test_broadcast_and_lucky(run):
    async def scenario():
        servers, addrs, chans = [], [], []
        for _ in range(4):
            s = RpcServer()
            ch = Channel(10)

            async def make(ch_):
                async def on(msg, peer):
                    await ch_.send(msg)

                return on

            s.route(CertificateMsg, await make(ch))
            port = await s.start("127.0.0.1", 0)
            servers.append(s)
            addrs.append(f"127.0.0.1:{port}")
            chans.append(ch)

        f = CommitteeFixture(size=4)
        cert = f.certificate(f.header(author=0, round=1))
        net = NetworkClient()

        handles = net.broadcast(addrs, CertificateMsg(cert))
        results = await asyncio.gather(*handles)
        assert results == [True] * 4
        for ch in chans:
            got = await asyncio.wait_for(ch.recv(), 1.0)
            assert got.certificate == cert

        oks = await net.lucky_broadcast(addrs, CertificateMsg(cert), nodes=2)
        assert sum(oks) == 2

        net.close()
        for s in servers:
            await s.stop()

    run(scenario())


def test_large_frame(run):
    async def scenario():
        server = RpcServer()

        async def echo(msg: WorkerBatchMsg, peer):
            return WorkerBatchResponse((msg.serialized_batch,))

        server.route(WorkerBatchMsg, echo)
        port = await server.start("127.0.0.1", 0)
        net = NetworkClient()
        big = Batch(tuple(bytes([i % 256]) * 512 for i in range(2000)))  # ~1MB
        resp = await net.request(
            f"127.0.0.1:{port}", WorkerBatchMsg(big.to_bytes()), timeout=10.0
        )
        assert resp.batches[0] == big.to_bytes()
        net.close()
        await server.stop()

    run(scenario())


class _MockTransportWriter:
    """StreamWriter stand-in recording every write and drain."""

    def __init__(self):
        self.chunks: list[bytes] = []
        self.drains = 0

    def write(self, data: bytes) -> None:
        self.chunks.append(bytes(data))

    async def drain(self) -> None:
        self.drains += 1


def test_frame_sender_coalesces_one_drain_byte_identical(run):
    """K frames enqueued in one loop turn must reach the transport as ONE
    drain whose bytes are exactly the K sequentially-written frames, in
    enqueue order (the coalescer must never reorder or re-frame)."""
    from narwhal_tpu.network.rpc import KIND_REQ, FrameSender, _write_frame

    async def scenario():
        mock = _MockTransportWriter()
        sender = FrameSender(mock)
        frames = [(KIND_REQ, rid, 7, b"body-%d" % rid) for rid in range(1, 9)]
        for f in frames:
            sender.send(*f)
        # Nothing hits the transport until the drainer task runs.
        assert mock.chunks == [] and mock.drains == 0
        await asyncio.sleep(0)  # let the drainer run once
        assert mock.drains == 1, "8 same-turn frames must share one drain"

        sequential = _MockTransportWriter()
        for f in frames:
            _write_frame(sequential, *f)
        assert b"".join(mock.chunks) == b"".join(sequential.chunks)

    run(scenario())


def test_rpc_coalescing_equivalence_concurrent_vs_sequential(run):
    """K concurrent sends through one connection must deliver frames that
    are byte-identical (tag+body), complete, and rid-ordered relative to
    the frames a sequential run delivers — coalescing only changes how
    many socket flushes carry them."""
    from narwhal_tpu.network import rpc as rpc_mod

    async def scenario():
        received: list[tuple[int, int, bytes]] = []
        orig_read = rpc_mod._read_frame

        async def spy_read(reader, session=None, counters=None):
            kind, rid, tag, lane, body = await orig_read(reader, session, counters)
            received.append((kind, tag, bytes(body)))
            return kind, rid, tag, lane, body

        rpc_mod._read_frame = spy_read
        try:
            server = RpcServer()

            async def on_tx(msg, peer):
                return None  # ack

            server.route(SubmitTransactionMsg, on_tx)
            port = await server.start("127.0.0.1", 0)
            net = NetworkClient()
            addr = f"127.0.0.1:{port}"
            msgs = [SubmitTransactionMsg(b"tx-%d" % i) for i in range(8)]

            # Concurrent: one connection, 8 requests in flight together.
            assert all(
                await asyncio.gather(
                    *(net.unreliable_send(addr, m) for m in msgs)
                )
            )
            concurrent = [r for r in received if r[0] == 0]  # REQ frames
            received.clear()

            # Sequential baseline on a fresh connection.
            net.peer(addr).close()
            for m in msgs:
                assert await net.unreliable_send(addr, m)
            sequential = [r for r in received if r[0] == 0]

            assert concurrent == sequential  # byte-identical, same order
            net.close()
            await server.stop()
        finally:
            rpc_mod._read_frame = orig_read

    run(scenario())


def test_wire_stats_records_frames_per_drain(run):
    """The coalescing instrumentation: drains and the frames-per-drain
    histogram advance, and frame counts reconcile with drains."""
    from narwhal_tpu.network.rpc import KIND_REQ, FrameSender, WireStats

    async def scenario():
        before = WireStats.snapshot()
        mock = _MockTransportWriter()
        sender = FrameSender(mock)
        for rid in range(4):
            sender.send(KIND_REQ, rid, 1, b"x")
        await asyncio.sleep(0)
        after = WireStats.snapshot()
        assert after["drains"] == before["drains"] + 1
        bucket4 = after["frames_per_drain"].get(4, 0)
        assert bucket4 == before["frames_per_drain"].get(4, 0) + 1

    run(scenario())


def test_duplicate_server_fails_fast_without_placeholder(run):
    """Two RpcServers on the same explicit port must NOT silently co-bind
    (reuse_port splitting connections nondeterministically): a port that no
    allocator placeholder reserves is bound plainly, so the duplicate gets
    EADDRINUSE (ADVICE r3). Ports actually placeheld by
    config.get_available_port still co-bind through the placeholder."""
    from narwhal_tpu.config import get_available_port, port_is_placeheld
    from narwhal_tpu.network.rpc import RpcServer

    async def scenario():
        port = get_available_port()
        assert port_is_placeheld(port)
        a = RpcServer()
        await a.start("127.0.0.1", port)  # binds through the placeholder
        assert not port_is_placeheld(port)  # placeholder released on bind
        b = RpcServer()
        try:
            with pytest.raises(OSError):
                await b.start("127.0.0.1", port)
        finally:
            await a.stop()

    run(scenario(), timeout=30.0)


def test_placeheld_ports_env_enables_cobind(run, monkeypatch):
    """A harness parent that assigned the ports advertises its placeholders
    via NARWHAL_PLACEHELD_PORTS; children then co-bind with reuse_port."""
    from narwhal_tpu.config import port_is_placeheld

    monkeypatch.setenv("NARWHAL_PLACEHELD_PORTS", "all")
    assert port_is_placeheld(12345)
    monkeypatch.setenv("NARWHAL_PLACEHELD_PORTS", "7001, 7002")
    assert port_is_placeheld(7002)
    assert not port_is_placeheld(7003)

    async def noop():
        pass

    run(noop(), timeout=5.0)


def test_env_advertised_port_not_reusable_after_first_bind(run, monkeypatch):
    """The parent's NARWHAL_PLACEHELD_PORTS advertisement is spawn-time
    static; once a server in this process binds an advertised port, a
    second server on the same port (same node started twice, one port
    assigned to two roles) must fail fast instead of co-binding through
    the stale advertisement."""
    from narwhal_tpu.config import get_available_port, release_port
    from narwhal_tpu.network.rpc import RpcServer

    async def scenario():
        port = get_available_port()
        release_port(port)  # simulate: the placeholder lives in a parent
        monkeypatch.setenv("NARWHAL_PLACEHELD_PORTS", str(port))
        a = RpcServer()
        await a.start("127.0.0.1", port)
        b = RpcServer()
        try:
            with pytest.raises(OSError):
                await b.start("127.0.0.1", port)
        finally:
            await a.stop()
        # After stop, the advertisement applies again (node restart flow).
        c = RpcServer()
        await c.start("127.0.0.1", port)
        await c.stop()

    run(scenario(), timeout=30.0)
