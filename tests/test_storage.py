"""Storage tests, mirroring /root/reference/storage/src/certificate_store.rs
tests: write/read, round index, notify_read wake-up, crash recovery replay."""

import asyncio

import pytest

from narwhal_tpu.fixtures import CommitteeFixture, make_optimal_certificates
from narwhal_tpu.storage import StorageEngine
from narwhal_tpu.stores import CertificateStore, NodeStorage
from narwhal_tpu.types import Certificate


def _dag(rounds=3):
    f = CommitteeFixture(size=4)
    genesis = {c.digest for c in Certificate.genesis(f.committee)}
    certs, _ = make_optimal_certificates(f.committee, 1, rounds, genesis)
    return f, certs


def test_engine_basic(tmp_path):
    eng = StorageEngine(str(tmp_path / "db"))
    cf = eng.column_family("test")
    cf.put(b"k1", b"v1")
    cf.put_all([(b"k2", b"v2"), (b"k3", b"v3")])
    assert cf.get(b"k1") == b"v1"
    assert cf.get_all([b"k2", b"missing"]) == [b"v2", None]
    cf.delete(b"k2")
    assert cf.get(b"k2") is None
    eng.close()

    # recovery replays the WAL
    eng2 = StorageEngine(str(tmp_path / "db"))
    cf2 = eng2.column_family("test")
    assert cf2.get(b"k1") == b"v1"
    assert cf2.get(b"k2") is None
    assert cf2.get(b"k3") == b"v3"
    eng2.close()


def test_torn_tail_discarded(tmp_path):
    eng = StorageEngine(str(tmp_path / "db"))
    cf = eng.column_family("t")
    cf.put(b"a", b"1")
    eng.close()
    # corrupt: append garbage simulating a torn write
    with open(str(tmp_path / "db" / "wal.log"), "ab") as f:
        f.write(b"\xde\xad\xbe\xef\x01")
    eng2 = StorageEngine(str(tmp_path / "db"))
    assert eng2.column_family("t").get(b"a") == b"1"
    eng2.close()


def test_certificate_store_roundtrip(tmp_path):
    f, certs = _dag()
    store = NodeStorage(str(tmp_path / "db"))
    cs = store.certificate_store
    cs.write_all(certs)
    for c in certs:
        assert cs.read(c.digest) == c
        assert cs.contains(c.digest)
    assert cs.last_round() == 3
    assert cs.last_round(certs[0].origin) == 3
    assert len(cs.after_round(3)) == 4
    assert len(cs.after_round(2)) == 8
    store.close()

    # reopen: everything still there (crash recovery)
    store2 = NodeStorage(str(tmp_path / "db"))
    assert store2.certificate_store.read(certs[0].digest) == certs[0]
    assert store2.certificate_store.last_round() == 3
    store2.close()


def test_certificate_store_delete():
    f, certs = _dag()
    cs = CertificateStore(StorageEngine(None))
    cs.write_all(certs)
    cs.delete(certs[0].digest)
    assert cs.read(certs[0].digest) is None
    assert cs.last_round(certs[0].origin) == 3


def test_notify_read(run):
    async def scenario():
        f, certs = _dag()
        cs = CertificateStore(StorageEngine(None))
        target = certs[5]

        async def waiter():
            return await cs.notify_read(target.digest)

        task = asyncio.create_task(waiter())
        await asyncio.sleep(0.01)
        assert not task.done()
        cs.write(target)
        got = await asyncio.wait_for(task, 1.0)
        assert got == target

        # already-written path returns immediately
        got2 = await asyncio.wait_for(cs.notify_read(target.digest), 1.0)
        assert got2 == target

    run(scenario())


def test_notify_read_cancellation(run):
    async def scenario():
        eng = StorageEngine(None)
        cf = eng.column_family("x")
        t1 = asyncio.create_task(cf.notify_read(b"k"))
        t2 = asyncio.create_task(cf.notify_read(b"k"))
        await asyncio.sleep(0)
        t1.cancel()
        await asyncio.sleep(0)
        cf.put(b"k", b"v")
        assert await asyncio.wait_for(t2, 1.0) == b"v"

    run(scenario())


def test_group_commit_coalesces_concurrent_puts(tmp_path, run):
    """64 concurrent put_async calls must share O(1) fused WAL records —
    the group-commit contract — and every write must survive recovery."""
    from narwhal_tpu.storage import StorageStats

    async def scenario():
        eng = StorageEngine(str(tmp_path / "db"), use_native=False)
        cf = eng.column_family("t")
        before = StorageStats.snapshot()
        futs = [cf.put_async(b"k%d" % i, b"v%d" % i) for i in range(64)]
        # Visible through the memtable BEFORE the commit future resolves.
        assert cf.get(b"k7") == b"v7"
        assert not futs[0].done()
        await asyncio.gather(*futs)
        after = StorageStats.snapshot()
        groups = after["groups_committed"] - before["groups_committed"]
        ops = after["ops_committed"] - before["ops_committed"]
        assert ops >= 64
        assert groups <= 4, f"64 concurrent puts took {groups} flushes"
        eng.close()

        eng2 = StorageEngine(str(tmp_path / "db"), use_native=False)
        cf2 = eng2.column_family("t")
        assert all(cf2.get(b"k%d" % i) == b"v%d" % i for i in range(64))
        eng2.close()

    run(scenario())


def test_group_commit_notify_read_fires_before_flush(run):
    """notify_read waiters are part of the memtable-visibility contract:
    they wake on the write itself, not on the group's durability."""

    async def scenario():
        eng = StorageEngine(None)
        cf = eng.column_family("x")
        waiter = asyncio.create_task(cf.notify_read(b"k"))
        await asyncio.sleep(0)
        fut = cf.put_async(b"k", b"v")
        assert await asyncio.wait_for(waiter, 1.0) == b"v"
        await fut

    run(scenario())


def test_sync_write_orders_after_pending_group(tmp_path, run):
    """A sync write issued while a commit group is open must persist the
    group's ops FIRST (WAL order == memtable apply order), resolve the
    group's future, and stay durable itself."""

    async def scenario():
        eng = StorageEngine(str(tmp_path / "db"), use_native=False)
        cf = eng.column_family("t")
        futs = [cf.put_async(b"g%d" % i, b"1") for i in range(8)]
        cf.put(b"sync", b"2")  # drains + persists the pending group inline
        assert all(f.done() for f in futs)
        await asyncio.gather(*futs)
        eng.close()
        eng2 = StorageEngine(str(tmp_path / "db"), use_native=False)
        cf2 = eng2.column_family("t")
        assert cf2.get(b"g0") == b"1" and cf2.get(b"sync") == b"2"
        eng2.close()

    run(scenario())


def test_torn_tail_of_fused_group_record_is_atomic(tmp_path, run):
    """Crash atomicity of group commit: a torn tail inside a FUSED record
    discards the WHOLE group on replay — no partial group is ever applied
    — while fully-flushed earlier records survive."""

    async def scenario():
        eng = StorageEngine(str(tmp_path / "db"), use_native=False)
        cf = eng.column_family("t")
        cf.put(b"base", b"ok")  # record 1, fully flushed
        # One loop turn of concurrent puts -> ONE fused record.
        futs = [cf.put_async(b"grp%d" % i, b"v" * 32) for i in range(16)]
        await asyncio.gather(*futs)
        eng.close()

    run(scenario())

    wal = tmp_path / "db" / "wal.log"
    data = wal.read_bytes()
    # Parse record boundaries; the last record is the fused group.
    import struct as _s

    pos, bounds = 0, []
    while pos + 8 <= len(data):
        (plen,) = _s.unpack_from("<I", data, pos)
        bounds.append((pos, pos + 8 + plen))
        pos += 8 + plen
    assert len(bounds) == 2, f"expected base + one fused record, got {len(bounds)}"
    start, end = bounds[-1]
    assert end - start > 16 * 32  # really carries all 16 ops
    # Tear mid-record: keep the header and half the body.
    wal.write_bytes(data[: start + (end - start) // 2])

    eng2 = StorageEngine(str(tmp_path / "db"), use_native=False)
    cf2 = eng2.column_family("t")
    assert cf2.get(b"base") == b"ok"
    present = [i for i in range(16) if cf2.get(b"grp%d" % i) is not None]
    assert present == [], f"partial group replayed: {present}"
    eng2.close()


def test_consensus_store():
    f, certs = _dag()
    ns = NodeStorage(None)
    cs = ns.consensus_store
    assert cs.last_consensus_index() == 0
    last = {certs[0].origin: 1}
    cs.write_consensus_state(last, 0, certs[0].digest)
    cs.write_consensus_state({certs[1].origin: 1}, 1, certs[1].digest)
    assert cs.last_consensus_index() == 2
    lc = cs.read_last_committed()
    assert lc[certs[0].origin] == 1
    assert cs.read_sequenced_digests_after(1) == [(1, certs[1].digest)]


def test_vote_digest_store(tmp_path):
    ns = NodeStorage(str(tmp_path / "db"))
    pk = b"\x01" * 32
    ns.vote_digest_store.write(pk, 7, b"\x02" * 32)
    assert ns.vote_digest_store.read(pk) == (7, b"\x02" * 32)
    ns.close()
    ns2 = NodeStorage(str(tmp_path / "db"))
    assert ns2.vote_digest_store.read(pk) == (7, b"\x02" * 32)  # survives restart
    ns2.close()


def test_payload_store():
    ns = NodeStorage(None)
    d = b"\x03" * 32
    assert not ns.payload_store.contains(d, 0)
    ns.payload_store.write(d, 0)
    assert ns.payload_store.contains(d, 0)
    assert not ns.payload_store.contains(d, 1)
    ns.payload_store.delete_all([(d, 0)])
    assert not ns.payload_store.contains(d, 0)


class TestNativeEngine:
    """The C++ engine (native/storage_engine.cpp) must be byte-compatible
    with the Python WAL format in both directions, and behave identically."""

    def _roundtrip(self, tmp_path, writer_native, reader_native):
        from narwhal_tpu.storage import StorageEngine

        path = str(tmp_path / f"interop-{writer_native}-{reader_native}")
        e = StorageEngine(path, use_native=writer_native)
        if writer_native and e._native is None:
            import pytest

            pytest.skip("native engine unavailable")
        cf = e.column_family("alpha")
        cf.put(b"k1", b"v1")
        cf.put_all([(b"k2", b"v2"), (b"k3", b"v3")])
        cf.delete(b"k2")
        e.column_family("beta").put(b"x", b"y" * 1000)
        e.close()

        r = StorageEngine(path, use_native=reader_native)
        cf2 = r.column_family("alpha")
        assert cf2.get(b"k1") == b"v1"
        assert cf2.get(b"k2") is None
        assert cf2.get(b"k3") == b"v3"
        assert len(cf2) == 2
        assert sorted(cf2.keys()) == [b"k1", b"k3"]
        assert r.column_family("beta").get(b"x") == b"y" * 1000
        r.close()

    def test_native_writes_python_reads(self, tmp_path):
        self._roundtrip(tmp_path, True, False)

    def test_python_writes_native_reads(self, tmp_path):
        self._roundtrip(tmp_path, False, True)

    def test_native_roundtrip_and_compact(self, tmp_path):
        from narwhal_tpu.storage import StorageEngine

        path = str(tmp_path / "native-compact")
        e = StorageEngine(path, use_native=True)
        if e._native is None:
            import pytest

            pytest.skip("native engine unavailable")
        cf = e.column_family("cf")
        for i in range(100):
            cf.put(i.to_bytes(4, "big"), bytes([i % 256]) * 64)
        cf.delete_all(i.to_bytes(4, "big") for i in range(50))
        e._native.compact()
        e.close()
        r = StorageEngine(path, use_native=True)
        cf2 = r.column_family("cf")
        assert len(cf2) == 50
        assert cf2.get((75).to_bytes(4, "big")) == bytes([75]) * 64
        assert cf2.get((10).to_bytes(4, "big")) is None
        r.close()

    def test_native_torn_tail_truncated(self, tmp_path):
        from narwhal_tpu.storage import StorageEngine

        path = str(tmp_path / "native-torn")
        e = StorageEngine(path, use_native=True)
        if e._native is None:
            import pytest

            pytest.skip("native engine unavailable")
        cf = e.column_family("cf")
        cf.put(b"good", b"data")
        e.close()
        with open(f"{path}/wal.log", "ab") as f:
            f.write(b"\xff\xff\xff\x00garbage-torn-record")
        r = StorageEngine(path, use_native=True)
        assert r.column_family("cf").get(b"good") == b"data"
        r.close()


def test_native_engine_sanitizers():
    """ASan/UBSan job for the C++ engine (SURVEY §5.3): full CRUD +
    compaction + reopen recovery + torn-tail sweep under sanitizers."""
    import shutil
    import subprocess
    import os

    import pytest

    if shutil.which("g++") is None:
        pytest.skip("no g++ in environment")
    script = os.path.join(
        os.path.dirname(__file__), "..", "native", "sanitize.sh"
    )
    if not os.path.exists(script):
        pytest.skip("native/sanitize.sh not present")
    proc = subprocess.run(
        ["bash", script], capture_output=True, text=True, timeout=240
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "sanitizers clean" in proc.stdout
