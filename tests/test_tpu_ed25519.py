"""TPU ed25519 kernel: field/point correctness vs the integer reference, and
end-to-end batch verification equivalence with the host library (the
fastcrypto-trait seam, SURVEY §2.3)."""

import random

import numpy as np
import pytest

from narwhal_tpu.crypto import KeyPair, verify as host_verify
from narwhal_tpu.tpu import ed25519 as k
from narwhal_tpu.tpu import ed25519_ref as ref
from narwhal_tpu.tpu.verifier import TpuVerifier


def test_field_ops_match_bigint():
    rng = random.Random(1)
    import jax

    mul = jax.jit(k.fe_mul)
    add = jax.jit(k.fe_add)
    sub = jax.jit(k.fe_sub)
    inv = jax.jit(k.fe_invert)
    for _ in range(20):
        a, b = rng.randrange(ref.P), rng.randrange(ref.P)
        la, lb = k.int_to_limbs(a), k.int_to_limbs(b)
        assert k.limbs_to_int(mul(la, lb)) % ref.P == a * b % ref.P
        assert k.limbs_to_int(add(la, lb)) % ref.P == (a + b) % ref.P
        assert k.limbs_to_int(sub(la, lb)) % ref.P == (a - b) % ref.P
    a = rng.randrange(1, ref.P)
    assert k.limbs_to_int(inv(k.int_to_limbs(a))) % ref.P == pow(a, ref.P - 2, ref.P)
    # canonicalization handles values in [p, 2p)
    assert k.limbs_to_int(k.fe_canonical(k.int_to_limbs(ref.P + 5))) == 5


def test_point_ops_match_reference():
    import jax.numpy as jnp

    def to_ext(p):
        return tuple(jnp.asarray(k.int_to_limbs(c)) for c in p)

    def from_ext(e):
        return tuple(k.limbs_to_int(k.fe_canonical(e[i])) for i in range(4))

    p1 = ref.point_mul(987654321, ref.G)
    p2 = ref.point_mul(123456789, ref.G)
    assert ref.point_equal(from_ext(k.pt_add(to_ext(p1), to_ext(p2))), ref.point_add(p1, p2))
    assert ref.point_equal(from_ext(k.pt_double(to_ext(p1))), ref.point_double(p1))
    assert ref.point_equal(from_ext(k.pt_add(to_ext(ref.IDENTITY), to_ext(p1))), p1)
    assert ref.point_equal(from_ext(k.pt_add(to_ext(p1), to_ext(ref.point_neg(p1)))), ref.IDENTITY)
    # Cached-form addition (the 8-mul hot-path add): same group law.
    assert ref.point_equal(
        from_ext(k.pt_add_cached(to_ext(p1), k.pt_cache(to_ext(p2)))),
        ref.point_add(p1, p2),
    )
    assert ref.point_equal(
        from_ext(k.pt_add_cached(to_ext(p1), k.pt_cache(to_ext(ref.IDENTITY)))), p1
    )
    # Z2 == 1 variant (host affine table constants): normalize p2 first.
    zinv = pow(p2[2], ref.P - 2, ref.P)
    x2, y2 = p2[0] * zinv % ref.P, p2[1] * zinv % ref.P
    p2_affine = (x2, y2, 1, x2 * y2 % ref.P)
    yp, ym, _z, t2d = k.pt_cache(to_ext(p2_affine))
    assert ref.point_equal(
        from_ext(k.pt_add_cached_z1(to_ext(p1), (yp, ym, t2d))),
        ref.point_add(p1, p2),
    )


# The kernel-dispatch tests below trace the full EC verify/msm programs into
# XLA — ~4-5 min of compile on this 1-core CPU host standalone, and run
# IN-SUITE the trace can freeze outright against leftover service threads
# from earlier tests (observed wedged in a Thread.join inside jax's
# const-folding). They run per-file / nightly; tier-1 keeps the pure-math
# field/point equivalence checks above.
_kernel_dispatch = pytest.mark.slow


@pytest.fixture(scope="module")
def verifier():
    # One small bucket => one XLA compile for the whole test module (the
    # CPU-backend compile dominates test wall-clock otherwise).
    return TpuVerifier(max_bucket=16)


@_kernel_dispatch
def test_batch_verify_valid_and_corrupted(verifier):
    rng = random.Random(2)
    keys = [KeyPair.generate() for _ in range(8)]
    items = []
    expected = []
    for i in range(40):
        kp = keys[i % len(keys)]
        msg = bytes([i]) * (1 + i % 17)
        sig = kp.sign(msg)
        kind = i % 5
        if kind == 0:
            items.append((kp.public, msg, sig))
            expected.append(True)
        elif kind == 1:  # corrupt signature R
            bad = bytearray(sig)
            bad[rng.randrange(32)] ^= 1 << rng.randrange(8)
            items.append((kp.public, msg, bytes(bad)))
            expected.append(False)
        elif kind == 2:  # corrupt signature S
            bad = bytearray(sig)
            bad[32 + rng.randrange(31)] ^= 1 << rng.randrange(8)
            items.append((kp.public, msg, bytes(bad)))
            expected.append(False)
        elif kind == 3:  # wrong message
            items.append((kp.public, msg + b"!", sig))
            expected.append(False)
        else:  # wrong key
            items.append((keys[(i + 1) % len(keys)].public, msg, sig))
            expected.append(False)
    got = verifier(items)
    assert got == expected
    assert got == [host_verify(pk, m, s) for pk, m, s in items]


@_kernel_dispatch
def test_batch_verify_malformed_inputs(verifier):
    kp = KeyPair.generate()
    sig = kp.sign(b"x")
    high_s = sig[:32] + (ref.L + 1).to_bytes(32, "little")
    noncanon_r = (ref.P + 3).to_bytes(32, "little") + sig[32:]
    items = [
        (kp.public, b"x", b"short"),
        (b"\x00" * 31, b"x", sig),
        (kp.public, b"x", high_s),
        (kp.public, b"x", noncanon_r),
        (b"\xff" * 32, b"x", sig),  # y >= p: non-canonical pubkey
        (kp.public, b"x", sig),
    ]
    assert verifier(items) == [False, False, False, False, False, True]


@_kernel_dispatch
def test_batch_verify_odd_sizes(verifier):
    kp = KeyPair.generate()
    for n in (1, 3, 17):
        items = [(kp.public, bytes([j]), kp.sign(bytes([j]))) for j in range(n)]
        assert verifier(items) == [True] * n


@_kernel_dispatch
def test_async_pool_coalesces():
    import asyncio

    from narwhal_tpu.tpu.verifier import AsyncVerifierPool

    calls = []

    def backend(items):
        calls.append(len(items))
        from narwhal_tpu.crypto import _host_batch_verify

        return _host_batch_verify(items)

    async def scenario():
        pool = AsyncVerifierPool(backend=backend, max_batch=8, max_delay=0.01)
        kp = KeyPair.generate()
        sigs = [(kp.public, bytes([i]), kp.sign(bytes([i]))) for i in range(8)]
        results = await asyncio.gather(*(pool.verify(*item) for item in sigs))
        assert all(results)
        assert not await pool.verify(kp.public, b"other", sigs[0][2])
        await pool.close()

    asyncio.run(scenario())
    assert calls[0] == 8  # first batch flushed by size, not per item


# -- random-linear-combination batch mode (msm_verify_kernel) ---------------


@pytest.fixture(scope="module")
def msm_verifier():
    # msm_min_bucket lowered so the small test batches exercise the msm
    # path; production keeps small buckets on the per-item kernel.
    return TpuVerifier(max_bucket=16, msm_min_bucket=16, mode="msm")


def _items(n, tag=0):
    kps = [KeyPair.generate() for _ in range(min(n, 5))]
    out = []
    for i in range(n):
        kp = kps[i % len(kps)]
        msg = bytes([tag, i]) * 10
        out.append((kp.public, msg, kp.sign(msg)))
    return out


@_kernel_dispatch
def test_msm_valid_batch_passes(msm_verifier):
    items = _items(16)
    assert msm_verifier(items) == [True] * 16


@_kernel_dispatch
def test_msm_corrupted_signature_isolated(msm_verifier):
    """A failed batch falls back to the per-item kernel and flags exactly
    the corrupted signature."""
    items = _items(16, tag=1)
    pk, msg, sig = items[7]
    items[7] = (pk, msg, sig[:10] + bytes([sig[10] ^ 1]) + sig[11:])
    assert msm_verifier(items) == [True] * 7 + [False] + [True] * 8


@_kernel_dispatch
def test_msm_wrong_message_isolated(msm_verifier):
    items = _items(16, tag=2)
    items[3] = (items[3][0], b"different", items[3][2])
    assert msm_verifier(items) == [True] * 3 + [False] + [True] * 12


@_kernel_dispatch
def test_msm_malformed_inputs_excluded(msm_verifier):
    from narwhal_tpu.tpu import ed25519 as kernel

    items = _items(16, tag=3)
    items[0] = (b"\x01" * 31, b"x", b"\x02" * 64)  # short key
    items[1] = (
        items[1][0],
        items[1][1],
        items[1][2][:32] + (kernel.ref.L + 1).to_bytes(32, "little"),  # S >= L
    )
    assert msm_verifier(items) == [False, False] + [True] * 14


@_kernel_dispatch
def test_msm_padding_is_inert(msm_verifier):
    """9 items pad to a 16-bucket with zero rows; zero z makes them
    identity terms, so the batch still passes."""
    assert msm_verifier(_items(9, tag=4)) == [True] * 9


@_kernel_dispatch
def test_small_buckets_stay_on_item_kernel():
    v = TpuVerifier(max_bucket=16, msm_min_bucket=512)
    handle = v.submit(_items(4, tag=5))
    kinds = [entry[0] for entry in handle[2]]
    assert kinds == ["item"]
    assert v.collect(handle) == [True] * 4


@_kernel_dispatch
def test_msm_torsion_defect_is_deterministic(msm_verifier):
    """A signature under a torsion-carrying public key (A' = A + T, T of
    small order) is where cofactored and strict verification disagree. The
    msm mode must be DETERMINISTIC — cofactored, like ed25519-dalek's
    batch_verify — never a coin flip over the random z_i (which would let
    two honest verifiers of the same bytes disagree)."""
    import os

    from narwhal_tpu.tpu import ed25519 as kernel

    ref = kernel.ref
    # A small-order (torsion) point: [L]P for random P, non-identity.
    while True:
        y = int.from_bytes(os.urandom(32), "little") % ref.P
        x = ref.recover_x(y, 0)
        if x is None:
            continue
        p0 = (x, y, 1, x * y % ref.P)
        t = ref.point_mul(ref.L, p0)
        if t[0] % ref.P != 0 or (t[1] - t[2]) % ref.P != 0:
            break
    # Raw-scalar keypair, torsion-shifted public key, hand-crafted sig:
    # S'B - k'A' - R = -k'T (pure torsion residual).
    while True:
        a_scalar = int.from_bytes(os.urandom(32), "little") % ref.L
        a_point = ref.point_mul(a_scalar, ref.G)
        pk_t = ref.compress(ref.point_add(a_point, t))
        msg = b"torsion probe"
        r_scalar = int.from_bytes(os.urandom(32), "little") % ref.L
        r_bytes = ref.compress(ref.point_mul(r_scalar, ref.G))
        k = ref.sha512_mod_l(r_bytes, pk_t, msg)
        # k odd => gcd(k, 8) = 1 => [k]T is non-identity for ANY
        # non-identity 8-torsion T (k % 8 != 0 alone is NOT enough: T may
        # have order 2 or 4, and an even k annihilates it — the rare flake
        # this loop previously had).
        if k % 2 == 1:
            break
    s = (r_scalar + k * a_scalar) % ref.L
    sig = r_bytes + s.to_bytes(32, "little")
    assert not ref.verify(pk_t, msg, sig)  # strict (cofactorless) rejects

    items = _items(15, tag=9) + [(pk_t, msg, sig)]
    results = [msm_verifier(items) for _ in range(4)]
    # Deterministic across independent random z draws, and cofactored:
    # the torsion-defect signature is uniformly ACCEPTED.
    assert all(r == results[0] for r in results)
    assert results[0] == [True] * 16

    # Same torsion signature in a FAILING bucket (a corrupted co-passenger
    # forces the per-item fallback): the verdict must not change — the
    # fallback also answers with the device's cofactored rule.
    items2 = _items(14, tag=10) + [(pk_t, msg, sig)]
    pk0, msg0, sig0 = items2[0]
    items2[0] = (pk0, msg0, sig0[:8] + bytes([sig0[8] ^ 1]) + sig0[9:])
    results2 = [msm_verifier(items2) for _ in range(3)]
    assert all(r == results2[0] for r in results2)
    assert results2[0] == [False] + [True] * 14


@_kernel_dispatch
def test_native_scalar_pipeline_matches_python():
    """native/scalar_ops.cpp (batched SHA-512 challenge + canonicality
    prechecks + msm fold scalars) must be bit-identical to the pure-Python
    twin across valid, malformed and boundary inputs — the native path is
    what the pipelined verifier runs in production."""
    import os

    from narwhal_tpu.crypto import KeyPair
    from narwhal_tpu.tpu.verifier import TpuVerifier, _scalar_lib

    lib = _scalar_lib()
    if lib is None:
        pytest.skip("native toolchain unavailable")

    v = TpuVerifier(max_bucket=16)
    kp = KeyPair.generate()
    L = v.kernel.ref.L
    P = v.kernel.ref.P
    items = []
    for i in range(64):
        msg = os.urandom(i % 7 * 33)  # varied lengths incl. 0
        sig = kp.sign(msg)
        items.append((kp.public, msg, sig))
    # Adversarial rows: wrong lengths, non-canonical s, non-canonical A/R
    # encodings (y >= p under the masked top bit), corrupt signature.
    items[3] = (b"short", items[3][1], items[3][2])
    items[9] = (items[9][0], items[9][1], b"x" * 63)
    bad_s = items[11][2][:32] + (L + 5).to_bytes(32, "little")
    items[11] = (items[11][0], items[11][1], bad_s)
    items[17] = ((P + 3).to_bytes(32, "little"), items[17][1], items[17][2])
    bad_r = (2**255 - 1).to_bytes(32, "little") + items[23][2][32:]
    items[23] = (items[23][0], items[23][1], bad_r)

    pn, an, rn, sn, kn = v._precheck_native(items, lib)
    pp, ap, rp, sp, kp_ = v._precheck_py(items)
    assert (pn == pp).all()
    assert not pn[3] and not pn[9] and not pn[11] and not pn[17] and not pn[23]
    assert pn.sum() == 64 - 5
    idx = pn.nonzero()[0]
    assert (an[idx] == ap[idx]).all()
    assert (kn[idx] == kp_[idx]).all()

    import numpy as np

    k_rows = np.ascontiguousarray(kn[idx])
    s_rows = np.ascontiguousarray(sn[idx])
    rnd = os.urandom(16 * len(idx))
    ak_n, sum_n = v._fold_native(lib, k_rows, s_rows, rnd)
    ak_p, sum_p = v._fold_py(k_rows, s_rows, rnd)
    assert (ak_n == ak_p).all()
    assert sum_n == sum_p


@_kernel_dispatch
def test_verifier_python_fallback_matches_native(monkeypatch):
    """With NARWHAL_NATIVE disabled the verifier must produce the same
    verdicts through the pure-Python packing path."""
    from narwhal_tpu.crypto import KeyPair
    from narwhal_tpu import native as native_mod
    from narwhal_tpu.tpu.verifier import TpuVerifier

    kp = KeyPair.generate()
    items = []
    for i in range(20):
        msg = b"m%d" % i
        items.append((kp.public, msg, kp.sign(msg)))
    items[4] = (items[4][0], items[4][1], items[4][2][:32] + b"\0" * 32)
    items[8] = (b"", items[8][1], items[8][2])

    v = TpuVerifier(max_bucket=16)
    with_native = v(items)
    monkeypatch.setattr(native_mod, "_scalar", None)
    monkeypatch.setattr(native_mod, "_scalar_tried", True)
    without = v(items)
    assert with_native == without
    assert not with_native[4] and not with_native[8]
    assert sum(with_native) == 18


@_kernel_dispatch
def test_group_lane_aggregate_verify(run):
    """The device aggregate lane for compact certificates: submit_groups
    fuses several half-aggregated proofs into one msm dispatch (doubled
    rows, per-group random outer weights); honest groups pass, a tampered
    group is isolated by the host fallback without affecting the others."""
    import asyncio

    from narwhal_tpu.fixtures import CommitteeFixture
    from narwhal_tpu.types import Certificate, Vote
    from narwhal_tpu.tpu.verifier import TpuVerifier, VerifyService

    fx = CommitteeFixture(size=4)

    def make_group(round_, tamper=False):
        h = fx.header(author=0, round=round_)
        signers, sigs = [], []
        for a in fx.authorities:
            v = Vote.for_header(h, a.public, a.keypair)
            signers.append(fx.committee.index_of(a.public))
            sigs.append(v.signature)
        cc = Certificate.compact_from_votes(h, tuple(signers), tuple(sigs))
        if tamper:
            cc = Certificate(
                cc.header, cc.signers, cc.signatures,
                bytes([cc.agg_s[0] ^ 1]) + cc.agg_s[1:],
            )
        return cc.aggregate_group(fx.committee)

    groups = [make_group(1), make_group(2), make_group(3, tamper=True)]

    v = TpuVerifier(max_bucket=64, msm_min_bucket=16, mode="msm")
    # Direct kernel path.
    verdicts = v.collect_groups(v.submit_groups(groups))
    assert verdicts == [True, True, False]

    # Through the service's group lane (merged dispatch).
    svc = VerifyService(v, max_batch=64, max_delay=0.002)
    try:
        async def scenario():
            return await asyncio.gather(
                *(svc.verify_aggregate(*g) for g in groups)
            )

        assert run(scenario(), timeout=120.0) == [True, True, False]
    finally:
        svc.shutdown()


@_kernel_dispatch
def test_group_chunk_bisect_keeps_honest_groups_off_host(monkeypatch):
    """Advisor r4 (medium): one bad compact cert in a fused chunk must NOT
    force pure-Python re-verification of every group in that chunk — the
    failed combined check bisects by re-dispatching each group as its own
    device msm chunk, and only the still-failing group touches the host
    verifier (DoS amplification fence: an attacker's bad cert costs the
    attacker's group a host walk, nobody else's)."""
    from narwhal_tpu import types as types_mod
    from narwhal_tpu.fixtures import CommitteeFixture
    from narwhal_tpu.types import Certificate, Vote
    from narwhal_tpu.tpu.verifier import TpuVerifier

    fx = CommitteeFixture(size=4)

    def make_group(round_, tamper=False):
        h = fx.header(author=0, round=round_)
        signers, sigs = [], []
        for a in fx.authorities:
            v = Vote.for_header(h, a.public, a.keypair)
            signers.append(fx.committee.index_of(a.public))
            sigs.append(v.signature)
        cc = Certificate.compact_from_votes(h, tuple(signers), tuple(sigs))
        if tamper:
            cc = Certificate(
                cc.header, cc.signers, cc.signatures,
                bytes([cc.agg_s[0] ^ 1]) + cc.agg_s[1:],
            )
        return cc.aggregate_group(fx.committee)

    groups = [make_group(r) for r in range(1, 4)] + [make_group(4, tamper=True)]

    host_calls = []
    real_host = types_mod.host_verify_aggregate

    def counting(items, zs, s_agg):
        host_calls.append(s_agg)
        return real_host(items, zs, s_agg)

    monkeypatch.setattr(types_mod, "host_verify_aggregate", counting)
    v = TpuVerifier(max_bucket=64, msm_min_bucket=16, mode="msm")
    verdicts = v.collect_groups(v.submit_groups(groups))
    assert verdicts == [True, True, True, False]
    # Exactly ONE host walk: the attacker's own group.
    assert len(host_calls) == 1
    assert host_calls[0] == groups[3][2]


@_kernel_dispatch
def test_staged_kernels_match_monolith():
    """The mesh path's STAGED kernels (decompress -> straus -> verdict;
    msm_window) must be BIT-equal to the monolithic traces they split —
    raw strict/cofactored lanes and raw msm window accumulators, not just
    verdicts — on a batch mixing valid, forged and corrupt rows. Run on a
    1-device data mesh so only the staging differs, never the sharding."""
    from narwhal_tpu.tpu.verifier import _sharded_kernels, data_mesh

    rng = np.random.default_rng(7)
    keys = [KeyPair.generate() for _ in range(4)]
    items = []
    for i in range(16):
        kp = keys[i % len(keys)]
        msg = bytes([i]) * (1 + i % 9)
        sig = kp.sign(msg)
        if i % 5 == 1:
            sig = sig[:32] + bytes(32)  # garbage S (canonical, wrong)
        elif i % 5 == 3:
            msg = msg + b"!"  # wrong message
        items.append((kp.public, msg, sig))

    # Pack exactly as TpuVerifier.submit does (all rows pass precheck).
    v = TpuVerifier(max_bucket=16)
    precheck, a_all, r_all, s_all, k_all = v._precheck_py(items)
    assert precheck.all()
    a_y = k.bytes_to_limbs(a_all).astype(np.int16)
    r_y = k.bytes_to_limbs(r_all).astype(np.int16)
    a_sign = (a_all[:, 31] >> 7).astype(np.int8)
    r_sign = (r_all[:, 31] >> 7).astype(np.int8)
    k_digits = k.bytes_to_digits(k_all).astype(np.int8)
    s_digits = k.bytes_to_digits(s_all).astype(np.int8)

    item_fn, msm_fn = _sharded_kernels(k, data_mesh(1), "data")

    mono_strict, mono_cof = k.verify_batch_kernel(
        a_y, a_sign, r_y, r_sign, k_digits, s_digits
    )
    st_strict, st_cof = item_fn(a_y, a_sign, r_y, r_sign, k_digits, s_digits)
    assert np.array_equal(np.asarray(mono_strict), np.asarray(st_strict))
    assert np.array_equal(np.asarray(mono_cof), np.asarray(st_cof))
    assert np.asarray(mono_strict).sum() > 0  # batch had valid rows
    assert not np.asarray(mono_strict).all()  # ... and invalid ones

    ak_digits = rng.integers(0, 16, (16, 64)).astype(np.int8)
    z_digits = rng.integers(0, 16, (16, 32)).astype(np.int8)
    mono_va, mono_vr, mono_valid = k.msm_accumulate_kernel(
        a_y, a_sign, r_y, r_sign, ak_digits, z_digits
    )
    st_va, st_vr, st_valid = msm_fn(
        a_y, a_sign, r_y, r_sign, ak_digits, z_digits
    )
    assert np.array_equal(np.asarray(mono_va), np.asarray(st_va))
    assert np.array_equal(np.asarray(mono_vr), np.asarray(st_vr))
    assert np.array_equal(np.asarray(mono_valid), np.asarray(st_valid))
