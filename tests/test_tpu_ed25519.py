"""TPU ed25519 kernel: field/point correctness vs the integer reference, and
end-to-end batch verification equivalence with the host library (the
fastcrypto-trait seam, SURVEY §2.3)."""

import random

import numpy as np
import pytest

from narwhal_tpu.crypto import KeyPair, verify as host_verify
from narwhal_tpu.tpu import ed25519 as k
from narwhal_tpu.tpu import ed25519_ref as ref
from narwhal_tpu.tpu.verifier import TpuVerifier


def test_field_ops_match_bigint():
    rng = random.Random(1)
    import jax

    mul = jax.jit(k.fe_mul)
    add = jax.jit(k.fe_add)
    sub = jax.jit(k.fe_sub)
    inv = jax.jit(k.fe_invert)
    for _ in range(20):
        a, b = rng.randrange(ref.P), rng.randrange(ref.P)
        la, lb = k.int_to_limbs(a), k.int_to_limbs(b)
        assert k.limbs_to_int(mul(la, lb)) % ref.P == a * b % ref.P
        assert k.limbs_to_int(add(la, lb)) % ref.P == (a + b) % ref.P
        assert k.limbs_to_int(sub(la, lb)) % ref.P == (a - b) % ref.P
    a = rng.randrange(1, ref.P)
    assert k.limbs_to_int(inv(k.int_to_limbs(a))) % ref.P == pow(a, ref.P - 2, ref.P)
    # canonicalization handles values in [p, 2p)
    assert k.limbs_to_int(k.fe_canonical(k.int_to_limbs(ref.P + 5))) == 5


def test_point_ops_match_reference():
    import jax.numpy as jnp

    def to_ext(p):
        return tuple(jnp.asarray(k.int_to_limbs(c)) for c in p)

    def from_ext(e):
        return tuple(k.limbs_to_int(k.fe_canonical(e[i])) for i in range(4))

    p1 = ref.point_mul(987654321, ref.G)
    p2 = ref.point_mul(123456789, ref.G)
    assert ref.point_equal(from_ext(k.pt_add(to_ext(p1), to_ext(p2))), ref.point_add(p1, p2))
    assert ref.point_equal(from_ext(k.pt_double(to_ext(p1))), ref.point_double(p1))
    assert ref.point_equal(from_ext(k.pt_add(to_ext(ref.IDENTITY), to_ext(p1))), p1)
    assert ref.point_equal(from_ext(k.pt_add(to_ext(p1), to_ext(ref.point_neg(p1)))), ref.IDENTITY)


@pytest.fixture(scope="module")
def verifier():
    # One small bucket => one XLA compile for the whole test module (the
    # CPU-backend compile dominates test wall-clock otherwise).
    return TpuVerifier(max_bucket=16)


def test_batch_verify_valid_and_corrupted(verifier):
    rng = random.Random(2)
    keys = [KeyPair.generate() for _ in range(8)]
    items = []
    expected = []
    for i in range(40):
        kp = keys[i % len(keys)]
        msg = bytes([i]) * (1 + i % 17)
        sig = kp.sign(msg)
        kind = i % 5
        if kind == 0:
            items.append((kp.public, msg, sig))
            expected.append(True)
        elif kind == 1:  # corrupt signature R
            bad = bytearray(sig)
            bad[rng.randrange(32)] ^= 1 << rng.randrange(8)
            items.append((kp.public, msg, bytes(bad)))
            expected.append(False)
        elif kind == 2:  # corrupt signature S
            bad = bytearray(sig)
            bad[32 + rng.randrange(31)] ^= 1 << rng.randrange(8)
            items.append((kp.public, msg, bytes(bad)))
            expected.append(False)
        elif kind == 3:  # wrong message
            items.append((kp.public, msg + b"!", sig))
            expected.append(False)
        else:  # wrong key
            items.append((keys[(i + 1) % len(keys)].public, msg, sig))
            expected.append(False)
    got = verifier(items)
    assert got == expected
    assert got == [host_verify(pk, m, s) for pk, m, s in items]


def test_batch_verify_malformed_inputs(verifier):
    kp = KeyPair.generate()
    sig = kp.sign(b"x")
    high_s = sig[:32] + (ref.L + 1).to_bytes(32, "little")
    noncanon_r = (ref.P + 3).to_bytes(32, "little") + sig[32:]
    items = [
        (kp.public, b"x", b"short"),
        (b"\x00" * 31, b"x", sig),
        (kp.public, b"x", high_s),
        (kp.public, b"x", noncanon_r),
        (b"\xff" * 32, b"x", sig),  # y >= p: non-canonical pubkey
        (kp.public, b"x", sig),
    ]
    assert verifier(items) == [False, False, False, False, False, True]


def test_batch_verify_odd_sizes(verifier):
    kp = KeyPair.generate()
    for n in (1, 3, 17):
        items = [(kp.public, bytes([j]), kp.sign(bytes([j]))) for j in range(n)]
        assert verifier(items) == [True] * n


def test_async_pool_coalesces():
    import asyncio

    from narwhal_tpu.tpu.verifier import AsyncVerifierPool

    calls = []

    def backend(items):
        calls.append(len(items))
        from narwhal_tpu.crypto import _host_batch_verify

        return _host_batch_verify(items)

    async def scenario():
        pool = AsyncVerifierPool(backend=backend, max_batch=8, max_delay=0.01)
        kp = KeyPair.generate()
        sigs = [(kp.public, bytes([i]), kp.sign(bytes([i]))) for i in range(8)]
        results = await asyncio.gather(*(pool.verify(*item) for item in sigs))
        assert all(results)
        assert not await pool.verify(kp.public, b"other", sigs[0][2])
        await pool.close()

    asyncio.run(scenario())
    assert calls[0] == 8  # first batch flushed by size, not per item
