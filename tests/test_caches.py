"""Process-wide hot-path caches (N=50 profile: message decode ~30%, host
signature verification ~27%, and repeated store decode 48% of later
windows' CPU — overwhelmingly duplicate work across the hosted nodes).
Correctness contracts: identical wire bytes share one decoded object,
results never change, budgets bound memory, eviction is FIFO and
thread-safe (the shared BoundedCache)."""

import threading

import pytest

from narwhal_tpu import crypto, messages
from narwhal_tpu.bounded_cache import BoundedCache
from narwhal_tpu.crypto import KeyPair
from narwhal_tpu.fixtures import CommitteeFixture
from narwhal_tpu.messages import HeaderMsg, decode_message, encode_message


def test_decode_cache_shares_identical_bodies():
    fx = CommitteeFixture(size=4)
    msg = HeaderMsg(fx.header(author=0, round=1))
    tag, body = encode_message(msg)
    a = decode_message(tag, bytes(body))
    b = decode_message(tag, bytes(body))
    assert a is b  # one decode serves every link carrying these bytes
    assert a.header == msg.header


def test_decoded_payloads_are_immutable():
    """The decode cache hands ONE object to every hosted node; a write
    through it would corrupt all of their views (ADVICE r5 medium). The
    payload mapping must therefore refuse mutation outright — for the
    cached copy AND for locally built headers (whose digest is cached)."""
    fx = CommitteeFixture(size=4)
    tag, body = encode_message(HeaderMsg(fx.header(author=0, round=1)))
    a = decode_message(tag, bytes(body))
    b = decode_message(tag, bytes(body))
    assert a is b
    some_digest = next(iter(a.header.payload), b"\0" * 32)
    with pytest.raises(TypeError):
        # Deliberate mutation attempt: proving the runtime guard fires.
        a.header.payload[some_digest] = 99  # lint: allow(no-shared-decode-mutation)
    with pytest.raises(AttributeError):
        a.header.payload.clear()  # lint: allow(no-shared-decode-mutation)
    # Locally built (proposer-path) headers are frozen too: their digest
    # is a cached_property, so post-build payload writes would desync the
    # signed digest from the contents.
    built = fx.header(author=1, round=1)
    with pytest.raises(TypeError):
        built.payload[some_digest] = 99
    # Reads stay dict-shaped for every consumer.
    assert len(list(a.header.payload.items())) == len(a.header.payload)
    assert dict(a.header.payload) == dict(a.header.payload)


def test_decode_cache_budget_and_large_body_bypass(monkeypatch):
    fx = CommitteeFixture(size=4)
    tag, body = encode_message(HeaderMsg(fx.header(author=0, round=2)))
    monkeypatch.setattr(
        messages, "_DECODE_CACHE", BoundedCache(max_bytes=2 * len(body) + 16)
    )
    # A body over the per-entry cap is decoded correctly but never cached.
    monkeypatch.setattr(messages, "_DECODE_MAX_BODY", len(body) - 1)
    a = decode_message(tag, bytes(body))
    b = decode_message(tag, bytes(body))
    assert a is not b and a.header == b.header
    assert len(messages._DECODE_CACHE) == 0
    # Under budget pressure the OLDEST entry is evicted, newest kept.
    monkeypatch.setattr(messages, "_DECODE_MAX_BODY", 1 << 16)
    bodies = []
    for r in range(3, 6):
        t, bd = encode_message(HeaderMsg(fx.header(author=0, round=r)))
        bodies.append((t, bytes(bd)))
        decode_message(t, bodies[-1][1])
    assert (bodies[0][0], bodies[0][1]) not in messages._DECODE_CACHE
    assert (bodies[-1][0], bodies[-1][1]) in messages._DECODE_CACHE
    assert messages._DECODE_CACHE.total_bytes <= 2 * len(body) + 16


def test_verify_cache_correct_for_valid_and_forged(monkeypatch):
    monkeypatch.setattr(crypto, "_VERIFY_CACHE", BoundedCache(max_entries=1024))
    kp = KeyPair.generate()
    msg = b"\x05" * 32
    sig = kp.sign(msg)
    assert crypto.verify(kp.public, msg, sig) is True
    assert crypto.verify(kp.public, msg, sig) is True  # cached hit
    forged = bytes([sig[0] ^ 1]) + sig[1:]
    assert crypto.verify(kp.public, msg, forged) is False
    assert crypto.verify(kp.public, msg, forged) is False  # cached miss
    assert crypto._VERIFY_CACHE.get((kp.public, msg, sig)) is True
    assert crypto._VERIFY_CACHE.get((kp.public, msg, forged)) is False
    # Oversized messages verify but are not pinned.
    big = b"\x07" * 1024
    big_sig = kp.sign(big)
    assert crypto.verify(kp.public, big, big_sig) is True
    assert (kp.public, big, big_sig) not in crypto._VERIFY_CACHE


def test_verify_cache_eviction_keeps_bound(monkeypatch):
    monkeypatch.setattr(crypto, "_VERIFY_CACHE", BoundedCache(max_entries=8))
    kp = KeyPair.generate()
    for i in range(20):
        m = bytes([i]) * 32
        crypto.verify(kp.public, m, kp.sign(m))
    assert len(crypto._VERIFY_CACHE) <= 8


def test_store_decode_cache_content_addressed():
    """CertificateStore/HeaderStore skip re-decoding on repeat reads (48%
    of the N=50 profile), while presence still comes from the engine —
    delete semantics unchanged, re-write after delete reads again."""
    from narwhal_tpu.fixtures import CommitteeFixture, mock_certificate
    from narwhal_tpu.stores import NodeStorage
    from narwhal_tpu.types import Certificate

    fx = CommitteeFixture(size=4)
    genesis = {c.digest for c in Certificate.genesis(fx.committee)}
    cert = mock_certificate(
        fx.committee, fx.committee.authority_keys()[0], 1, genesis
    )
    st = NodeStorage(None)
    st.certificate_store.write(cert)
    a = st.certificate_store.read(cert.digest)
    b = st.certificate_store.read(cert.digest)
    assert a is b and a == cert  # decoded once, shared after
    st.certificate_store.delete(cert.digest)
    assert st.certificate_store.read(cert.digest) is None  # engine decides
    st.certificate_store.write(cert)
    assert st.certificate_store.read(cert.digest) == cert
    # Header store: same contract.
    st.header_store.write(cert.header)
    h1 = st.header_store.read(cert.header.digest)
    h2 = st.header_store.read(cert.header.digest)
    assert h1 is h2 and h1 == cert.header
    st.close()


def test_bounded_cache_rejects_over_budget_entry():
    """A put whose weight exceeds max_bytes outright must be refused, not
    admitted after evicting the entire cache: the budget stays intact and
    the warm working set survives."""
    cache = BoundedCache(max_bytes=100)
    cache.put("a", 1, weight=40)
    cache.put("b", 2, weight=40)
    cache.put("huge", 3, weight=200)  # over the whole budget
    assert "huge" not in cache
    assert cache.get("huge") is None
    assert "a" in cache and "b" in cache  # working set untouched
    assert cache.total_bytes == 80
    # Exactly-at-budget entries still admit (evicting as needed).
    cache.put("full", 4, weight=100)
    assert "full" in cache
    assert cache.total_bytes <= 100


def test_decode_cache_weight_accounts_key_bytes(monkeypatch):
    """The decode cache's key tuple pins the raw body bytes next to the
    decoded object, so an entry must be charged ~2x the body length: a
    budget of 2*len(body)-1 refuses the entry, 2*len(body) admits it."""
    fx = CommitteeFixture(size=4)
    tag, body = encode_message(HeaderMsg(fx.header(author=0, round=7)))
    body = bytes(body)

    monkeypatch.setattr(
        messages, "_DECODE_CACHE", BoundedCache(max_bytes=2 * len(body) - 1)
    )
    a = decode_message(tag, body)
    b = decode_message(tag, body)
    assert a is not b  # over budget even when empty: never admitted
    assert messages._DECODE_CACHE.total_bytes == 0

    monkeypatch.setattr(
        messages, "_DECODE_CACHE", BoundedCache(max_bytes=2 * len(body))
    )
    a = decode_message(tag, body)
    b = decode_message(tag, body)
    assert a is b
    assert messages._DECODE_CACHE.total_bytes == 2 * len(body)


def test_bounded_cache_byte_accounting_stays_exact():
    """total_bytes must equal the sum of live entries' weights through
    admissions, evictions, and rejections — a drifting byte ledger either
    leaks budget (cache shrinks to nothing) or overfills memory."""
    cache = BoundedCache(max_bytes=100)
    weights = {}
    for i in range(50):
        w = (i % 7) * 5 + 5  # 5..35
        cache.put(i, i, weight=w)
        weights[i] = w
    live = {k: w for k, w in weights.items() if k in cache}
    assert cache.total_bytes == sum(live.values())
    assert cache.total_bytes <= 100
    # A rejected over-budget entry must not disturb the ledger.
    before = cache.total_bytes
    cache.put("huge", 0, weight=101)
    assert "huge" not in cache and cache.total_bytes == before


def test_bounded_cache_concurrent_eviction_thread_safety():
    """The r5-review crash scenario: verify() runs on executor threads;
    concurrent evictions over a plain dict double-delete keys. The shared
    BoundedCache must survive hammering from several threads at a tiny
    bound with no KeyError and an intact bound."""
    cache = BoundedCache(max_entries=16)
    errors = []

    def hammer(base: int) -> None:
        try:
            for i in range(3000):
                cache.put((base, i), i)
                cache.get((base, i % 50))
        except Exception as e:  # pragma: no cover - the failure under test
            errors.append(e)

    threads = [threading.Thread(target=hammer, args=(t,)) for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert len(cache) <= 16
