"""Full-system tests over the in-process Cluster, mirroring
/root/reference/node/tests/node_smoke_test.rs,
executor/tests/consensus_integration_tests.rs and the cluster-based
nodes_bootstrapping/restart tests."""

import asyncio

import pytest

from narwhal_tpu.cluster import Cluster
from narwhal_tpu.messages import SubmitTransactionMsg, SubmitTransactionStreamMsg
from narwhal_tpu.network import NetworkClient


def test_cluster_commits_without_load(run):
    """Four nodes, no transactions: empty headers still drive Bullshark
    commits (leader election over empty certificates)."""

    async def scenario():
        cluster = Cluster(size=4, workers=1)
        await cluster.start()
        try:
            rounds = await cluster.assert_progress(commit_threshold=2, timeout=30.0)
            assert all(r >= 2 for r in rounds.values())
        finally:
            await cluster.shutdown()

    run(scenario(), timeout=60.0)


def test_cluster_commits_transactions_e2e(run):
    """Client txs -> worker batches -> DAG -> Bullshark -> executor: the
    executed transactions come out the execution output channel in the same
    order on every node."""

    async def scenario():
        cluster = Cluster(size=4, workers=1)
        await cluster.start()
        client = NetworkClient()
        try:
            target = cluster.authorities[0].worker_transactions_address(0)
            txs = tuple(bytes([1]) * 8 + bytes([i]) for i in range(64))
            await client.request(target, SubmitTransactionStreamMsg(txs))

            async def executed(details, count):
                out = []
                while len(out) < count:
                    _, tx = await asyncio.wait_for(
                        details.primary.tx_execution_output.recv(), 30.0
                    )
                    out.append(tx)
                return out

            # Every node must execute all 64 txs, in an identical order.
            results = await asyncio.gather(
                *(executed(a, 64) for a in cluster.authorities)
            )
            assert all(len(r) == 64 for r in results)
            assert results[0] == results[1] == results[2] == results[3]
            assert set(results[0]) == set(txs)

            # §5.6 observability: every inter-task channel carries a depth
            # gauge wired into the node registry (metered_channel.rs:15-259).
            # Check REGISTRATION (render includes the metric's HELP/TYPE
            # lines), not .value(), which returns 0.0 for unknown names.
            rendered = cluster.authorities[0].primary.registry.render()
            for gauge in (
                "primary_channel_primary_messages_depth",
                "primary_channel_our_digests_depth",
                "node_channel_new_certificates_depth",
                "node_channel_consensus_output_depth",
            ):
                assert gauge in rendered, f"{gauge} not registered"
            # Executor progress counters (executor/src/metrics.rs parity).
            executed = cluster.authorities[0].metric("executor_executed_transactions")
            assert executed >= 64, executed
        finally:
            client.close()
            await cluster.shutdown()

    run(scenario(), timeout=90.0)


def test_cluster_survives_one_fault(run):
    """Stop one of four nodes: the remaining 2f+1 keep committing
    (the benchmark harness's `faults` parameter behavior)."""

    async def scenario():
        cluster = Cluster(size=4, workers=1)
        await cluster.start()
        try:
            await cluster.assert_progress(commit_threshold=2, timeout=30.0)
            await cluster.stop_node(3)
            before = min(
                a.metric("consensus_last_committed_round")
                for a in cluster.authorities
                if a.primary is not None
            )
            await cluster.assert_progress(
                expected_nodes=3, commit_threshold=int(before) + 4, timeout=30.0
            )
        finally:
            await cluster.shutdown()

    run(scenario(), timeout=90.0)


def test_node_restart_recovers_from_store(run, tmp_path):
    """Restart a node with a persistent store: consensus state recovers and
    the node resumes committing (causal_completion_tests.rs restart)."""

    async def scenario():
        cluster = Cluster(size=4, workers=1, store_base=str(tmp_path))
        await cluster.start()
        try:
            await cluster.assert_progress(commit_threshold=2, timeout=30.0)
            await cluster.restart_node(0)
            rounds = await cluster.assert_progress(commit_threshold=4, timeout=30.0)
            assert rounds[cluster.authorities[0].name] >= 4
        finally:
            await cluster.shutdown()

    run(scenario(), timeout=120.0)


def test_cluster_with_tpu_dag_backend(run, tmp_path):
    """--dag-backend tpu: production consensus runs through TpuBullshark's
    adjacency-tensor kernels. All nodes execute client transactions in an
    identical order, and a restarted node rebuilds its device DAG window
    from the store (TpuBullshark.recover) and resumes committing."""

    async def scenario():
        cluster = Cluster(
            size=4, workers=1, store_base=str(tmp_path), dag_backend="tpu"
        )
        await cluster.start()
        client = NetworkClient()
        try:
            from narwhal_tpu.tpu.dag_kernels import TpuBullshark

            assert isinstance(
                cluster.authorities[0].primary.consensus.protocol, TpuBullshark
            )
            target = cluster.authorities[0].worker_transactions_address(0)
            txs = tuple(bytes([7]) * 8 + bytes([i]) for i in range(32))
            await client.request(target, SubmitTransactionStreamMsg(txs))

            async def executed(details, count):
                out = []
                while len(out) < count:
                    _, tx = await asyncio.wait_for(
                        details.primary.tx_execution_output.recv(), 30.0
                    )
                    out.append(tx)
                return out

            results = await asyncio.gather(
                *(executed(a, 32) for a in cluster.authorities)
            )
            assert all(len(r) == 32 for r in results)
            assert results[0] == results[1] == results[2] == results[3]
            assert set(results[0]) == set(txs)

            # Restart: the fresh TpuBullshark must recover its window from
            # the recovered ConsensusState and keep committing.
            await cluster.restart_node(0)
            before = max(
                a.metric("consensus_last_committed_round")
                for a in cluster.authorities
                if a.primary is not None
            )
            rounds = await cluster.assert_progress(
                commit_threshold=int(before) + 2, timeout=30.0
            )
            assert rounds[cluster.authorities[0].name] >= int(before) + 2
        finally:
            client.close()
            await cluster.shutdown()

    run(scenario(), timeout=150.0)


def test_cluster_with_verification_pool(run):
    """crypto_backend="pool": the async pre-verification stage (coalesced
    batch verification off the Core's loop) must preserve liveness and
    ordering; a forged certificate must still be rejected."""

    async def scenario():
        cluster = Cluster(size=4, workers=1, crypto_backend="pool")
        await cluster.start()
        client = NetworkClient()
        try:
            target = cluster.authorities[0].worker_transactions_address(0)
            txs = tuple(bytes([9]) * 16 + bytes([i]) for i in range(32))
            await client.request(target, SubmitTransactionStreamMsg(txs))

            # Forge a certificate with garbage signatures at node 1.
            from dataclasses import replace as dreplace

            from narwhal_tpu.fixtures import mock_certificate
            from narwhal_tpu.messages import CertificateMsg
            from narwhal_tpu.types import Certificate

            genesis = {
                c.digest for c in Certificate.genesis(cluster.committee)
            }
            # Unique payload so the forged digest cannot collide with any
            # legitimately produced certificate.
            forged = mock_certificate(
                cluster.committee,
                cluster.authorities[0].name,
                1,
                genesis,
                payload={b"\xab" * 32: 0},
            )
            forged = dreplace(
                forged,
                signers=(0, 1, 2),
                signatures=(b"\x00" * 64, b"\x01" * 64, b"\x02" * 64),
            )
            # Deliver it as an authenticated committee peer so it passes
            # transport auth and exercises signature verification.
            from narwhal_tpu.network import Credentials, committee_resolver

            peer_client = NetworkClient(
                credentials=Credentials(
                    cluster.fixture.authorities[0].network_keypair,
                    committee_resolver(
                        lambda: cluster.committee, lambda: cluster.worker_cache
                    ),
                )
            )
            await peer_client.unreliable_send(
                cluster.authorities[1].primary.address, CertificateMsg(forged)
            )
            peer_client.close()

            rounds = await cluster.assert_progress(commit_threshold=3, timeout=30.0)
            assert all(r >= 3 for r in rounds.values())
            assert not cluster.authorities[1].primary.storage.certificate_store.contains(
                forged.digest
            )
        finally:
            client.close()
            await cluster.shutdown()

    run(scenario(), timeout=90.0)


def test_cluster_with_sharded_tpu_dag_backend(run, tmp_path):
    """--dag-backend tpu --dag-shards 2: the node wires a mesh into
    TpuBullshark, whose production chain_commit dispatch shards the
    committee axis across two devices. The committee still commits and
    executes transactions identically on every node."""

    async def scenario():
        cluster = Cluster(
            size=4, workers=1, store_base=str(tmp_path),
            dag_backend="tpu", dag_shards=2,
        )
        await cluster.start()
        client = NetworkClient()
        try:
            proto = cluster.authorities[0].primary.consensus.protocol
            assert proto.mesh is not None and proto.mesh.shape["auth"] == 2
            target = cluster.authorities[0].worker_transactions_address(0)
            txs = tuple(bytes([9]) * 8 + bytes([i]) for i in range(16))
            await client.request(target, SubmitTransactionStreamMsg(txs))

            async def executed(details, count):
                out = []
                while len(out) < count:
                    _, tx = await asyncio.wait_for(
                        details.primary.tx_execution_output.recv(), 30.0
                    )
                    out.append(tx)
                return out

            results = await asyncio.gather(
                *(executed(a, 16) for a in cluster.authorities)
            )
            assert all(r == results[0] for r in results)
        finally:
            client.close()
            await cluster.shutdown()

    run(scenario(), timeout=90.0)


@pytest.mark.slow
def test_twenty_node_committee_with_faults(run):
    """Committee scaling (BASELINE configs #4-5 risk): a 20-node in-process
    committee commits, and keeps committing after f=6 nodes die (the
    remaining 14 hold a 2f+1 quorum). Exercises proposer fan-in, certificate
    aggregation and window sizing at a committee size kernels can't see."""

    async def scenario():
        cluster = Cluster(size=20, workers=1)
        await cluster.start()
        try:
            await cluster.assert_progress(commit_threshold=3, timeout=60.0)
            for i in range(14, 20):
                await cluster.stop_node(i)
            before = min(
                a.metric("consensus_last_committed_round")
                for a in cluster.authorities
                if a.primary is not None
            )
            await cluster.assert_progress(
                expected_nodes=14, commit_threshold=int(before) + 4, timeout=60.0
            )
        finally:
            await cluster.shutdown()

    run(scenario(), timeout=150.0)


@pytest.mark.slow
def test_fifty_node_committee_liveness(run):
    """The north-star committee size: a 50-node in-process committee over
    the authenticated mesh reaches lockstep commits (each round is ~7.5k
    signed+sealed control messages on this host's single core, so the
    assertion is liveness, not throughput — see
    benchmark/results/n50_liveness.json)."""
    from narwhal_tpu.config import Parameters

    async def scenario():
        cluster = Cluster(
            size=50, workers=1,
            parameters=Parameters(max_header_delay=1.0, max_batch_delay=0.5),
        )
        await cluster.start()
        try:
            rounds = await cluster.assert_progress(
                commit_threshold=2, timeout=240.0
            )
            assert len(rounds) == 50  # every primary reported progress
        finally:
            await cluster.shutdown()

    run(scenario(), timeout=300.0)


def test_verify_rule_validated_at_startup(tmp_path):
    """parameters.verify_rule is a committee-wide accept-set contract: a
    cpu/pool node (host library = strict/cofactorless rule) must refuse to
    start under verify_rule=cofactored — mixing the two rules in one
    committee is a consensus-split vector on crafted torsion signatures
    (ADVICE r3; narwhal_tpu/tpu/verifier.py msm_epilogue_check)."""
    from dataclasses import replace

    from narwhal_tpu.fixtures import CommitteeFixture
    from narwhal_tpu.node import NodeStorage, PrimaryNode

    fx = CommitteeFixture(size=4)
    auth = fx.authorities[0]
    params = replace(fx.parameters, verify_rule="cofactored")
    for backend in ("cpu", "pool"):
        with pytest.raises(ValueError, match="cofactored"):
            PrimaryNode(
                auth.keypair,
                fx.committee,
                fx.worker_cache,
                params,
                NodeStorage(None),
                crypto_backend=backend,
            )
    with pytest.raises(ValueError, match="verify_rule"):
        PrimaryNode(
            auth.keypair,
            fx.committee,
            fx.worker_cache,
            replace(fx.parameters, verify_rule="bogus"),
            NodeStorage(None),
        )


def test_verify_shards_validated_and_wired(tmp_path):
    """--verify-shards: a node boots with the VerifyService's flushes
    sharded over a 'data' CPU mesh (the §7.8a verifier service at §5.8
    scale), mis-sized shard counts fail AT STARTUP (bucket divisibility,
    like the verify_rule check), and the flag requires the tpu backend.
    Also: parameters.cert_format is validated at startup (advisor r4 — a
    typo must not silently run the 'full' wire form in a 'compact'
    committee)."""
    from dataclasses import replace

    from narwhal_tpu.fixtures import CommitteeFixture
    from narwhal_tpu.node import NodeStorage, PrimaryNode
    from narwhal_tpu.tpu.verifier import VerifyService

    fx = CommitteeFixture(size=4)
    auth = fx.authorities[0]

    def make(**kw):
        return PrimaryNode(
            auth.keypair,
            fx.committee,
            fx.worker_cache,
            kw.pop("parameters", fx.parameters),
            NodeStorage(None),
            **kw,
        )

    with pytest.raises(ValueError, match="verify-shards"):
        make(crypto_backend="cpu", verify_shards=2)
    # 3 does not divide the service's fixed dispatch bucket: the boot must
    # fail, not the first verify — and with ConfigError specifically, the
    # class the node treats as never-fallback-able.
    from narwhal_tpu.config import ConfigError

    with pytest.raises(ConfigError, match="divide"):
        make(crypto_backend="tpu", verify_shards=3)
    with pytest.raises(ValueError, match="cert_format"):
        make(parameters=replace(fx.parameters, cert_format="compat"))

    node = make(crypto_backend="tpu", verify_shards=2)
    try:
        svc = node.crypto_pool
        assert isinstance(svc, VerifyService)
        assert svc.verifier.mesh is not None
        assert svc.verifier.mesh.shape["data"] == 2
        # Catch-up sync shares the same batched lane (advisor r4).
        assert node.block_synchronizer.crypto_pool is svc
    finally:
        if isinstance(node.crypto_pool, VerifyService):
            node.crypto_pool.shutdown()


def test_environmental_valueerror_keeps_host_crypto_fallback(tmp_path, monkeypatch):
    """ADVICE r5 low (node.py:160): a ValueError escaping VerifyService
    device init for NON-config reasons (a jax backend hiccup, not operator
    error) must keep the documented strict-rule host-crypto fallback. Only
    ConfigError skips it; under the cofactored rule ANY failure refuses to
    start (host fallback would run a different accept set)."""
    from dataclasses import replace

    from narwhal_tpu.fixtures import CommitteeFixture
    from narwhal_tpu.node import NodeStorage, PrimaryNode
    from narwhal_tpu.tpu.verifier import AsyncVerifierPool, VerifyService

    fx = CommitteeFixture(size=4)
    auth = fx.authorities[0]

    def boom(mode, shards=1, **kw):
        raise ValueError("XLA backend initialization failed")  # environmental

    monkeypatch.setattr(VerifyService, "shared", boom)

    def make(**kw):
        return PrimaryNode(
            auth.keypair,
            fx.committee,
            fx.worker_cache,
            kw.pop("parameters", fx.parameters),
            NodeStorage(None),
            **kw,
        )

    node = make(crypto_backend="tpu")
    assert isinstance(node.crypto_pool, AsyncVerifierPool)  # degraded, same accept set

    with pytest.raises(RuntimeError, match="refusing to start"):
        make(
            crypto_backend="tpu",
            parameters=replace(fx.parameters, verify_rule="cofactored"),
        )


@pytest.mark.slow  # the device-crypto kernel compiles take minutes on a
# 1-core CPU-backend host (the persistent cache is CPU-disabled); the
# real-chip twin is the round artifact
def test_cluster_with_tpu_crypto_shared_service(run):
    """crypto_backend="tpu": the whole committee shares ONE process-wide
    VerifyService (merged flushes, pipelined submit/collect threads) —
    certificates verify through the device kernel path and commits advance
    (on conftest's CPU devices; the real-chip twin is the round artifact).

    The service is pre-seeded with a small-bucket verifier and warmed: on
    this 1-core CPU host an in-protocol first compile would eat the whole
    progress window (production pays this once at boot, inside the bench's
    warmup_timeout)."""
    from narwhal_tpu.tpu.verifier import TpuVerifier, VerifyService

    svc = VerifyService(
        TpuVerifier(max_bucket=32, msm_min_bucket=16, mode="msm"),
        max_batch=32,
        max_delay=0.002,
    )
    svc.verifier.precompile((16, 32))
    VerifyService._shared["msm:1"] = svc

    async def scenario():
        cluster = Cluster(size=4, workers=1, crypto_backend="tpu")
        assert cluster.parameters.verify_rule == "cofactored"
        await cluster.start()
        try:
            rounds = await cluster.assert_progress(commit_threshold=2, timeout=180.0)
            assert all(r >= 2 for r in rounds.values())
            # Every node's pool is the same process-wide service.
            pools = {id(a.primary.crypto_pool) for a in cluster.authorities}
            assert len(pools) == 1
            assert cluster.authorities[0].primary.crypto_pool is svc
        finally:
            await cluster.shutdown()

    try:
        run(scenario(), timeout=300.0)
    finally:
        svc.shutdown()


@pytest.mark.slow  # same compile bill as the shared-service cluster test
def test_verify_service_merges_and_survives_loops(run):
    """VerifyService is loop-agnostic: requests from sequential event loops
    resolve correctly, bad signatures are rejected, and an msm-mode service
    propagates dispatch failures instead of host-fallback (accept-set
    safety)."""
    import asyncio

    from narwhal_tpu.crypto import KeyPair
    from narwhal_tpu.tpu.verifier import TpuVerifier, VerifyService

    kp = KeyPair.generate()
    good = (kp.public, b"m", kp.sign(b"m"))
    bad = (kp.public, b"x", kp.sign(b"m"))
    svc = VerifyService(
        TpuVerifier(max_bucket=64, msm_min_bucket=16, mode="msm"),
        max_batch=64,
        max_delay=0.002,
    )
    try:
        async def burst():
            return await asyncio.gather(
                *(svc.verify(*good) for _ in range(20)), svc.verify(*bad)
            )

        # Two separate loops back to back — the service must serve both.
        res1 = asyncio.run(burst())
        res2 = asyncio.run(burst())
        for res in (res1, res2):
            assert res[:-1] == [True] * 20 and res[-1] is False

        # Dispatch failure with no safe fallback (msm): error propagates.
        def boom(items):
            raise RuntimeError("device lost")

        svc.verifier.submit = boom  # type: ignore[assignment]
        async def failing():
            with pytest.raises(RuntimeError, match="device lost"):
                await svc.verify(*good)

        asyncio.run(failing())
    finally:
        svc.shutdown()


def test_byzantine_peer_equivocation_and_stale_epoch(run, caplog):
    """A committee member gone byzantine: it equivocates (two validly signed
    round-1 headers with different parent sets) and replays a wrong-epoch
    header, from an authenticated mesh identity. The equivocation guard
    (primary/core.py process_header; core.rs:281-308) must trigger
    observably — the first header's vote digest stays recorded, the second
    is refused with a logged warning — the stale-epoch header is dropped,
    and the honest quorum keeps committing throughout. This exercises
    adversarial-peer behavior the reference's cluster tests never do (they
    are crash-fault only, test_utils/src/cluster.rs:169)."""
    import logging

    from narwhal_tpu.network import Credentials, committee_resolver
    from narwhal_tpu.types import Certificate, Header

    caplog.set_level(logging.DEBUG, logger="narwhal.primary")

    async def scenario():
        cluster = Cluster(size=4, workers=1)
        byz = cluster.fixture.authorities[3]
        await cluster.start(3)  # the byzantine member never runs a node
        try:
            await cluster.assert_progress(commit_threshold=2, timeout=60.0)

            client = NetworkClient(
                credentials=Credentials(
                    byz.network_keypair,
                    committee_resolver(
                        lambda: cluster.committee, lambda: cluster.worker_cache
                    ),
                )
            )
            from narwhal_tpu.messages import HeaderMsg

            genesis = sorted(
                c.digest for c in Certificate.genesis(cluster.committee)
            )
            epoch = cluster.committee.epoch
            # Two quorum-sized but different parent subsets => two distinct,
            # validly signed headers for the same (author, round).
            h1 = Header.build(byz.public, 1, epoch, {}, genesis[:3], byz.keypair)
            h2 = Header.build(byz.public, 1, epoch, {}, genesis[1:], byz.keypair)
            assert h1.digest != h2.digest
            target = cluster.authorities[0].primary.address
            await client.unreliable_send(target, HeaderMsg(h1))
            await asyncio.sleep(1.0)
            await client.unreliable_send(target, HeaderMsg(h2))
            # Wrong-epoch replay: validly signed, stale epoch.
            h3 = Header.build(byz.public, 1, epoch + 7, {}, genesis[:3], byz.keypair)
            await client.unreliable_send(target, HeaderMsg(h3))
            await asyncio.sleep(1.0)
            client.close()

            # The guard recorded the FIRST header's vote and refused the
            # equivocating twin, loudly.
            store = cluster.authorities[0].primary.storage.vote_digest_store
            last = store.read(byz.public)
            assert last is not None and last == (1, h1.digest)
            primary_logs = [
                r.getMessage()
                for r in caplog.records
                if r.name.startswith("narwhal.primary")
            ]
            assert any("equivocated" in m for m in primary_logs), primary_logs[-20:]
            assert any("stale" in m.lower() for m in primary_logs), primary_logs[-20:]

            # Liveness: the honest quorum keeps committing after the attack.
            rounds = await cluster.assert_progress(commit_threshold=4, timeout=60.0)
            assert all(r >= 4 for r in rounds.values())
        finally:
            await cluster.shutdown()

    run(scenario(), timeout=180.0)


def test_cluster_with_compact_certificates(run, tmp_path):
    """Parameters.cert_format="compact": certificates assemble as
    half-aggregated proofs, broadcast by reference (CertificateRefMsg,
    header by digest), peers rebuild them from their header stores, and
    the committee commits transactions with identical order. The pool
    backend exercises the host aggregate-verify path end-to-end."""
    from dataclasses import replace

    from narwhal_tpu.config import Parameters

    async def scenario():
        cluster = Cluster(
            size=4,
            workers=1,
            store_base=str(tmp_path),
            crypto_backend="pool",
            parameters=Parameters(
                max_header_delay=0.1,
                max_batch_delay=0.1,
                cert_format="compact",
            ),
        )
        await cluster.start()
        try:
            rounds = await cluster.assert_progress(commit_threshold=3, timeout=90.0)
            assert all(r >= 3 for r in rounds.values())
            # The stored certificates really are the compact form.
            store = cluster.authorities[0].primary.storage.certificate_store
            compact_seen = 0
            for other in cluster.authorities[1:]:
                for cert in store.after_round(1):
                    if cert.origin == other.name and cert.is_compact:
                        compact_seen += 1
                        break
            assert compact_seen >= 2, "peers' certificates not compact"
        finally:
            await cluster.shutdown()

    run(scenario(), timeout=150.0)
