"""gRPC public plane e2e: a plain grpc.aio client (the shape any language's
generated stubs produce) submits transactions to the worker's Transactions
service and drives Validator/Proposer/Configuration on the primary.

Mirrors the reference's tonic integration tests
(primary/tests/integration_tests_{validator,proposer,configuration}_api.rs)
over narwhal_tpu/proto/narwhal.proto.
"""

import asyncio

import grpc

from narwhal_tpu.cluster import Cluster
from narwhal_tpu.proto import narwhal_pb2 as pb


def _unary(channel, service, method, reply_cls):
    return channel.unary_unary(
        f"/narwhal.{service}/{method}",
        request_serializer=lambda m: m.SerializeToString(),
        response_deserializer=reply_cls.FromString,
    )


async def _probe(call, request, timeout=30.0):
    """Issue a gRPC call whose *definitive* outcome (a response OR a real
    status) is under test, retrying only the transient under-load states —
    UNAVAILABLE (server/loop busy or still starting) — to a deadline, the
    `_wait_rounds` deflake pattern. Returns the response, or raises the
    first non-transient AioRpcError for the caller to assert on."""
    deadline = asyncio.get_event_loop().time() + timeout
    while True:
        try:
            return await call(request)
        except grpc.aio.AioRpcError as e:
            if (
                e.code() != grpc.StatusCode.UNAVAILABLE
                or asyncio.get_event_loop().time() > deadline
            ):
                raise
        await asyncio.sleep(0.2)


async def _wait_rounds(rounds_call, pk, minimum, timeout=30.0):
    """Poll Rounds until `minimum` is reached. NOT_FOUND is the expected
    not-yet state (the Dag serves OutOfCertificates until the first
    certificate for `pk` lands) and UNAVAILABLE covers server startup —
    both retry until the deadline, mirroring the `168849d` deflake of the
    e2e payload poll. Any other status is a real failure and raises
    immediately; on deadline the last gRPC error is part of the report."""
    deadline = asyncio.get_event_loop().time() + timeout
    last_err = None
    while True:
        try:
            resp = await rounds_call(pb.RoundsRequest(public_key=pk))
            if resp.newest_round >= minimum:
                return resp
        except grpc.aio.AioRpcError as e:
            if e.code() not in (
                grpc.StatusCode.NOT_FOUND,
                grpc.StatusCode.UNAVAILABLE,
            ):
                raise
            last_err = e
        if asyncio.get_event_loop().time() > deadline:
            raise AssertionError(
                f"rounds never reached {minimum} (last error: {last_err})"
            )
        await asyncio.sleep(0.2)


def test_grpc_end_to_end(run):
    async def scenario():
        cluster = Cluster(size=4, workers=1, internal_consensus=False)
        await cluster.start()
        channels = []
        try:
            # 1. Submit transactions over gRPC (unary + client stream).
            worker = cluster.authorities[0].workers[0].worker
            tx_chan = grpc.aio.insecure_channel(worker.grpc_transactions_address)
            channels.append(tx_chan)
            submit = _unary(tx_chan, "Transactions", "SubmitTransaction", pb.Empty)
            await submit(pb.Transaction(transaction=bytes([9]) * 64))
            stream = tx_chan.stream_unary(
                "/narwhal.Transactions/SubmitTransactionStream",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=pb.Empty.FromString,
            )
            await stream(
                iter(
                    pb.Transaction(transaction=bytes([9]) * 32 + bytes([i]))
                    for i in range(31)
                )
            )

            # 2+3. Proposer.Rounds / NodeReadCausal / Validator.ReadCausal /
            # GetCollections, retried until the causal history carries our
            # submitted payload. Round 2 can be reached by EMPTY headers
            # before the batch lands in a proposed header — asserting
            # payload presence at the first observed round was a
            # load-sensitive race (the r4 full-suite flake); the payload is
            # guaranteed only eventually, so poll to a deadline.
            api = cluster.authorities[0].primary.grpc_api_address
            chan = grpc.aio.insecure_channel(api)
            channels.append(chan)
            rounds = _unary(chan, "Proposer", "Rounds", pb.RoundsResponse)
            pk = cluster.authorities[0].name
            nrc = _unary(chan, "Proposer", "NodeReadCausal", pb.NodeReadCausalResponse)
            rc = _unary(chan, "Validator", "ReadCausal", pb.ReadCausalResponse)
            gc = _unary(chan, "Validator", "GetCollections", pb.GetCollectionsResponse)

            resp = await _wait_rounds(rounds, pk, 2)
            assert resp.newest_round >= 2
            deadline = asyncio.get_event_loop().time() + 45.0
            fetched_txs = 0
            while True:
                resp = await rounds(pb.RoundsRequest(public_key=pk))
                causal = await nrc(
                    pb.NodeReadCausalRequest(public_key=pk, round=resp.newest_round)
                )
                assert len(causal.collection_ids) >= 1
                start = causal.collection_ids[0]

                walk = await rc(pb.ReadCausalRequest(collection_id=start))
                assert start in list(walk.collection_ids)

                all_ids = list(causal.collection_ids)
                got = await gc(pb.CollectionRequest(collection_ids=all_ids))
                assert len(got.results) == len(all_ids)
                assert got.results[0].collection_id == all_ids[0]
                fetched_txs = sum(
                    len(b.transactions) for r in got.results for b in r.batches
                )
                if fetched_txs >= 1:
                    break
                if asyncio.get_event_loop().time() > deadline:
                    raise AssertionError(f"payload never entered the DAG: {got}")
                await asyncio.sleep(0.5)

            # 4. Configuration: GetPrimaryAddress + NewEpoch is UNIMPLEMENTED.
            gpa = _unary(
                chan, "Configuration", "GetPrimaryAddress", pb.GetPrimaryAddressResponse
            )
            addr = await gpa(pb.Empty())
            assert addr.primary_address == cluster.authorities[0].primary.address

            ne = _unary(chan, "Configuration", "NewEpoch", pb.Empty)
            try:
                await ne(pb.NewEpochRequest(epoch_number=1))
                raise AssertionError("NewEpoch must be UNIMPLEMENTED (parity)")
            except grpc.aio.AioRpcError as e:
                assert e.code() == grpc.StatusCode.UNIMPLEMENTED

            # NewNetworkInfo: full-coverage address update for the current
            # epoch succeeds; a wrong epoch is INVALID_ARGUMENT.
            nni = _unary(chan, "Configuration", "NewNetworkInfo", pb.Empty)
            validators = [
                pb.ValidatorData(
                    public_key=p_,
                    stake_weight=a.stake,
                    primary_address=a.primary_address,
                )
                for p_, a in cluster.committee.authorities.items()
            ]
            await nni(pb.NewNetworkInfoRequest(epoch_number=0, validators=validators))
            try:
                await nni(
                    pb.NewNetworkInfoRequest(epoch_number=9, validators=validators)
                )
                raise AssertionError("wrong epoch must be rejected")
            except grpc.aio.AioRpcError as e:
                assert e.code() == grpc.StatusCode.INVALID_ARGUMENT

            # 5. Validator.RemoveCollections expunges the collection.
            rm = _unary(chan, "Validator", "RemoveCollections", pb.Empty)
            await rm(pb.CollectionRequest(collection_ids=[start]))
            assert not cluster.authorities[
                0
            ].primary.storage.certificate_store.contains(start)
        finally:
            for c in channels:
                await c.close()
            await cluster.shutdown()

    run(scenario(), timeout=90.0)


def test_grpc_error_paths(run):
    """Malformed and unknown inputs over the public gRPC plane: proper
    status codes / per-item errors, never a crash; NewEpoch is
    UNIMPLEMENTED (exact reference parity, configuration.rs:78-81)."""

    async def scenario():
        cluster = Cluster(size=4, workers=1, internal_consensus=False)
        await cluster.start()
        channel = None
        try:
            node = cluster.authorities[0]
            addr = node.primary.grpc_api_address
            channel = grpc.aio.insecure_channel(addr)
            rounds = _unary(channel, "Proposer", "Rounds", pb.RoundsResponse)
            get = _unary(
                channel, "Validator", "GetCollections", pb.GetCollectionsResponse
            )
            new_epoch = _unary(channel, "Configuration", "NewEpoch", pb.Empty)
            await _wait_rounds(rounds, node.name, 2)

            # Every probe below asserts a DEFINITIVE outcome (a payload or
            # a specific status); under full-suite load any of them can
            # transiently see UNAVAILABLE first, so each goes through
            # `_probe` — the same deadline-retry deflake `_wait_rounds`
            # uses (VERDICT r5: this test failed reproducibly in-suite,
            # passing isolated).

            # Unknown digest: per-collection error in the response.
            resp = await _probe(
                get, pb.CollectionRequest(collection_ids=[b"\xee" * 32])
            )
            assert len(resp.results) == 1
            assert resp.results[0].error != ""  # explicit per-item error

            # Malformed (short) digest: clean error, service stays up.
            try:
                resp_short = await _probe(
                    get, pb.CollectionRequest(collection_ids=[b"short"])
                )
                # Non-aborting servers must still flag the item as an error.
                assert resp_short.results[0].error != ""
            except grpc.aio.AioRpcError as e:
                assert e.code() in (
                    grpc.StatusCode.INVALID_ARGUMENT,
                    grpc.StatusCode.INTERNAL,
                )
            # Unknown validator key.
            try:
                await _probe(rounds, pb.RoundsRequest(public_key=b"\x00" * 32))
                raise AssertionError("unknown validator must error")
            except grpc.aio.AioRpcError as e:
                assert e.code() in (
                    grpc.StatusCode.NOT_FOUND,
                    grpc.StatusCode.INVALID_ARGUMENT,
                    grpc.StatusCode.INTERNAL,
                )

            # NewEpoch: reference parity — UNIMPLEMENTED.
            try:
                await _probe(new_epoch, pb.NewEpochRequest(epoch_number=1))
                raise AssertionError("NewEpoch must be unimplemented")
            except grpc.aio.AioRpcError as e:
                assert e.code() == grpc.StatusCode.UNIMPLEMENTED

            # Still alive: rounds must remain servable (NOT_FOUND here
            # would be a post-probe regression, so only UNAVAILABLE — the
            # transient under-load state — retries via _probe).
            resp = await _probe(rounds, pb.RoundsRequest(public_key=node.name))
            assert resp.newest_round >= 2
        finally:
            if channel is not None:
                await channel.close()
            await cluster.shutdown()

    run(scenario(), timeout=90.0)
