"""Tripping fixture for dropped-handle-escape: three escapes —
`Leaky._task` (attr-held, never cancelled, not returned), `Leaky.pending`
(tasks tucked into dict tuples, never cancelled), and `Dropper.boot`
dropping a spawn-like method's returned handle on the floor.
Static fixture: analyzed by tools.analysis, never imported."""

import asyncio


class Leaky:
    def __init__(self):
        self._task = None
        self.pending = {}

    def spawn(self):
        self._task = asyncio.ensure_future(self.run())

    def park(self, key):
        self.pending[key] = (1, asyncio.ensure_future(self.wait()))

    async def run(self):
        while True:
            await asyncio.sleep(1)

    async def wait(self):
        await asyncio.sleep(10)


class Child:
    def spawn(self):
        return asyncio.ensure_future(self.run())

    async def run(self):
        while True:
            await asyncio.sleep(1)


class Dropper:
    def boot(self):
        Child().spawn()
