"""Tripping fixture: the PR-6 standalone-primary wedge, miniaturized.

MiniNode wires an executor whose execution-output channel has NO consumer
anywhere in the program: after ~capacity applied transactions the
executor's output flush blocks forever and the pipeline wedges — exactly
the `tx_execution_output` bug the standalone primary shipped with until
`__main__` grew its drain task. `orphan-producer` must flag it.

Static fixture: analyzed by tools.analysis, never imported or run.
"""

import asyncio

from narwhal_tpu.channels import Channel, metered_channel


class MiniExecutor:
    def __init__(self, rx_consensus: Channel, tx_output: Channel):
        self.rx_consensus = rx_consensus
        self.tx_output = tx_output

    def spawn(self):
        return asyncio.ensure_future(self.run())

    async def run(self):
        while True:
            item = await self.rx_consensus.recv()
            await self.tx_output.send_many([(b"", item)])


class MiniNode:
    def __init__(self, registry):
        def chan(name, capacity):
            return metered_channel(registry, "node", name, capacity)

        self.tx_consensus_output = chan("consensus_output", 10_000)
        self.tx_execution_output = chan("execution_output", 10_000)
        self.executor = MiniExecutor(
            self.tx_consensus_output, self.tx_execution_output
        )
        self._tasks = []

    async def spawn(self):
        self._tasks.append(self.executor.spawn())
        self._tasks.append(asyncio.ensure_future(self._feed()))

    async def _feed(self):
        while True:
            await self.tx_consensus_output.send(b"tx")

    async def shutdown(self):
        for t in self._tasks:
            t.cancel()
