"""Tripping fixture for bounded-channel-cycle: two tasks, each blocking-
sending into the bounded channel the other consumes. If both channels
fill, both tasks block in send and neither ever drains — the deadlock
class PR-6's everything-is-bounded backpressure made load-reachable.
Static fixture: analyzed by tools.analysis, never imported."""

import asyncio

from narwhal_tpu.channels import Channel


class Pinger:
    def __init__(self, rx: Channel, tx: Channel):
        self.rx = rx
        self.tx = tx

    def spawn(self):
        return asyncio.ensure_future(self.run())

    async def run(self):
        while True:
            item = await self.rx.recv()
            await self.tx.send(item)


class Ponger:
    def __init__(self, rx: Channel, tx: Channel):
        self.rx = rx
        self.tx = tx

    def spawn(self):
        return asyncio.ensure_future(self.run())

    async def run(self):
        while True:
            item = await self.rx.recv()
            await self.tx.send(item)


class CycleNode:
    def __init__(self):
        self.tx_ping = Channel(16)
        self.tx_pong = Channel(16)
        self.pinger = Pinger(self.tx_ping, self.tx_pong)
        self.ponger = Ponger(self.tx_pong, self.tx_ping)
        self._tasks = []

    async def spawn(self):
        self._tasks.append(self.pinger.spawn())
        self._tasks.append(self.ponger.spawn())

    async def shutdown(self):
        for t in self._tasks:
            t.cancel()
