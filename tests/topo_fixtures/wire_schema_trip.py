"""Tripping fixture for wire-schema: `BadEcho` reuses tag 7 (collision
with `Echo`), and `Orphan` (tag 9) has no entry in the golden snapshot
(wire_schema_golden.json pins tags 7 and 8 only).
Static fixture: analyzed by tools.analysis, never imported."""

REGISTRY = {}


def message(tag):
    def deco(cls):
        cls.TAG = tag
        REGISTRY[tag] = cls
        return cls

    return deco


@message(7)
class Echo:
    pass


@message(8)
class Ack:
    pass


@message(7)
class BadEcho:  # duplicate tag: finding 1
    pass


@message(9)
class Orphan:  # no golden entry: finding 2
    pass
