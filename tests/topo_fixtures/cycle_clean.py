"""Clean twin of cycle_trip: the back-edge is a non-blocking try_send
(drop on full), so no task can block in send while holding the loop —
the wait-for graph has no cycle of blocking edges."""

import asyncio

from narwhal_tpu.channels import Channel


class Pinger:
    def __init__(self, rx: Channel, tx: Channel):
        self.rx = rx
        self.tx = tx

    def spawn(self):
        return asyncio.ensure_future(self.run())

    async def run(self):
        while True:
            item = await self.rx.recv()
            await self.tx.send(item)


class Ponger:
    def __init__(self, rx: Channel, tx: Channel):
        self.rx = rx
        self.tx = tx

    def spawn(self):
        return asyncio.ensure_future(self.run())

    async def run(self):
        while True:
            item = await self.rx.recv()
            self.tx.try_send(item)  # drop-on-full: cannot block the loop


class CycleNode:
    def __init__(self):
        self.tx_ping = Channel(16)
        self.tx_pong = Channel(16)
        self.pinger = Pinger(self.tx_ping, self.tx_pong)
        self.ponger = Ponger(self.tx_pong, self.tx_ping)
        self._tasks = []

    async def spawn(self):
        self._tasks.append(self.pinger.spawn())
        self._tasks.append(self.ponger.spawn())

    async def shutdown(self):
        for t in self._tasks:
            t.cancel()
