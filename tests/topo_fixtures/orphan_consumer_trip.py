"""Tripping fixture for orphan-consumer: an actor parked forever on a
channel no task anywhere sends into — dead wiring that presents as a
hang. Static fixture: analyzed by tools.analysis, never imported."""

import asyncio

from narwhal_tpu.channels import Channel


class Sink:
    def __init__(self, rx: Channel):
        self.rx = rx

    def spawn(self):
        return asyncio.ensure_future(self.run())

    async def run(self):
        while True:
            await self.rx.recv()


class DeadNode:
    def __init__(self):
        self.tx_ghost = Channel(64)
        self.sink = Sink(self.tx_ghost)
        self._tasks = []

    async def spawn(self):
        self._tasks.append(self.sink.spawn())

    async def shutdown(self):
        for t in self._tasks:
            t.cancel()
