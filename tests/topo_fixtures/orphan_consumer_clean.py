"""Clean twin of orphan_consumer_trip: the ghost channel gains a feeder
task, so every consumer has a reachable producer."""

import asyncio

from narwhal_tpu.channels import Channel


class Sink:
    def __init__(self, rx: Channel):
        self.rx = rx

    def spawn(self):
        return asyncio.ensure_future(self.run())

    async def run(self):
        while True:
            await self.rx.recv()


class DeadNode:
    def __init__(self):
        self.tx_ghost = Channel(64)
        self.sink = Sink(self.tx_ghost)
        self._tasks = []

    async def spawn(self):
        self._tasks.append(self.sink.spawn())
        self._tasks.append(asyncio.ensure_future(self._feed()))

    async def _feed(self):
        while True:
            await self.tx_ghost.send(b"item")

    async def shutdown(self):
        for t in self._tasks:
            t.cancel()
