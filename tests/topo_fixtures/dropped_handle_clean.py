"""Clean twin of dropped_handle_trip: every escape pattern has an owner —
the attr-held task is cancelled on shutdown, the parked dict tasks are
cancelled by iterating values, a swapped-out local is cancelled, and the
spawn-like method's handle is stored in a drained list."""

import asyncio

from narwhal_tpu.channels import drain_cancelled


class Tidy:
    def __init__(self):
        self._task = None
        self.pending = {}
        self._fetches = set()

    def spawn(self):
        self._task = asyncio.ensure_future(self.run())
        return self._task  # ownership also offered to the caller

    def park(self, key):
        self.pending[key] = (1, asyncio.ensure_future(self.wait()))

    def track(self):
        self._fetches.add(asyncio.ensure_future(self.wait()))

    async def run(self):
        while True:
            await asyncio.sleep(1)

    async def wait(self):
        await asyncio.sleep(10)

    async def shutdown(self):
        task, self._task = self._task, None
        if task is not None:
            task.cancel()
        for _, t in self.pending.values():
            t.cancel()
        self.pending.clear()
        await drain_cancelled(self._fetches, who="tidy")


class Child:
    def spawn(self):
        return asyncio.ensure_future(self.run())

    async def run(self):
        while True:
            await asyncio.sleep(1)


class Keeper:
    def __init__(self):
        self._tasks = []

    def boot(self):
        self._tasks.append(Child().spawn())

    async def shutdown(self):
        for t in self._tasks:
            t.cancel()
