"""Cross-module jit-purity clean fixture: the jitted root only reaches
the pure sibling helper — no findings in either module."""

import jax

from .xmod_helper import clean_helper


@jax.jit
def pure_kernel(x):
    return clean_helper(x) + 1
