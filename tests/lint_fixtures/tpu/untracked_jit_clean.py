"""no-untracked-jit clean fixture: every jit entry point routes through
the shared kernel registry."""

from narwhal_tpu.tpu import kernel_registry
from narwhal_tpu.tpu.kernel_registry import tracked_jit


@tracked_jit
def kernel_a(x):
    return x + 1


@kernel_registry.tracked_jit(static_argnames=("n",))
def kernel_b(x, n=2):
    return x * n


def sharded_variant(mesh, spec):
    return kernel_registry.sharded(
        kernel_a, mesh, in_specs=(spec,), out_specs=spec
    )
