"""Tripping fixture: impurity reachable from a jitted root."""

import time
from functools import partial

import jax

CACHE = {}


@partial(jax.jit, static_argnames=("n",))
def kernel(x, n):
    return helper(x) + n


def helper(x):
    print("tracing", x)  # finding: print reachable from jitted `kernel`
    CACHE["t"] = time.time()  # findings: global mutation + time call
    return x * 2


def late_wrapped(x):
    import random

    return x * random.random()  # finding: host RNG under jit


fast = jax.jit(late_wrapped)
