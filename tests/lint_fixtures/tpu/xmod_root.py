"""Cross-module jit-purity tripping fixture: the jitted root is pure in
THIS module, but it calls into xmod_helper — whose impurities the old
same-module BFS could never see. Scanning this file must report the two
unsuppressed impure sites over in xmod_helper.py."""

import jax

from .xmod_helper import clean_helper, helper, warmed


@jax.jit
def kernel(x):
    y = helper(x)
    z = warmed(y)
    return clean_helper(z)
