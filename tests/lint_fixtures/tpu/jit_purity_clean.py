"""Clean fixture: pure jitted code; host-side helpers may be impure."""

import time
from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("n",))
def kernel(x, n):
    key = jax.random.PRNGKey(0)  # fine: jax.random is pure
    return pure_helper(x) + jax.random.uniform(key) + n


def pure_helper(x):
    return jnp.tanh(x) * 2


def host_benchmark(x):
    t0 = time.perf_counter()  # fine: not reachable from a jitted root
    print("host timing", t0)
    return kernel(x, 1)
