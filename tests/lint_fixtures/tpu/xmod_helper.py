"""Sibling-module helper for the cross-module jit-purity fixtures: two
impurities reachable ONLY through xmod_root's jitted kernel, plus one
carrying a justified inline allow (must not be reported)."""

import time


def helper(x):
    print("debug", x)  # impure 1: trace-time print, elided from the kernel
    t = time.time()  # impure 2: trace-time constant baked into the kernel
    return x + t


def warmed(x):
    # compile-time wall-clock log, deliberate: runs once per trace
    # lint: allow(jit-purity)
    t0 = time.perf_counter()
    return x, t0


def clean_helper(x):
    return x * 2
