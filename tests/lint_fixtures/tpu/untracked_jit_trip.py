"""no-untracked-jit tripping fixture: raw jits in tpu/ outside the
registry — a decorator, a partial-decorator, and a wrapping call."""

import functools

import jax


@jax.jit  # finding 1: raw decorator
def kernel_a(x):
    return x + 1


@functools.partial(jax.jit, static_argnames=("n",))  # finding 2: partial form
def kernel_b(x, n=2):
    return x * n


def kernel_c(x):
    return x - 1


kernel_c_jit = jax.jit(kernel_c)  # finding 3: wrapping call
