"""Clean for metric-naming: grammar-conforming names + the sanctioned
computed-name seam (metered_channel's f-string depth gauges)."""


def build(registry, role, name):
    ok_counter = registry.counter("worker_tx_received", "clients' transactions")
    ok_gauge = registry.gauge("node_backpressure_level", "admission level")
    ok_hist = registry.histogram("primary_propose_latency_seconds", "per stage")
    # Computed names are covered by their construction seam, not this rule.
    depth = registry.gauge(f"{role}_channel_{name}_depth", "channel depth")
    # The perf observatory's namespace (tools/perf, benchmark.ab).
    ok_perf = registry.gauge("perf_calibration_ops", "pinned probe capacity")
    ok_perf_hist = registry.histogram("perf_leg_wall_seconds", "A/B leg wall")
    return ok_counter, ok_gauge, ok_hist, depth, ok_perf, ok_perf_hist
