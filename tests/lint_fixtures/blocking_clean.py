"""Clean fixture: the async-safe counterparts."""

import asyncio
import time


def sync_helper_may_sleep():
    time.sleep(0.01)  # fine: not an async def body


async def well_behaved(channel):
    await asyncio.sleep(0.5)
    task = asyncio.ensure_future(channel.recv())
    done, _ = await asyncio.wait({task})
    if task in done:
        return task.result()  # fine: provably an asyncio task spawned here
    proc = await asyncio.create_subprocess_exec("ls")
    await proc.wait()
    return await asyncio.to_thread(sync_helper_may_sleep)
