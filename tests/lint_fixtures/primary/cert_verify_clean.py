"""Clean fixture for no-per-item-cert-verify: batched-API call shapes and
non-certificate receivers that must never match."""

from narwhal_tpu.types import host_batch_verify_aggregates


async def staged(msg, pool, committee, worker_cache):
    # Structural half inline, signatures batched — the verifier-stage shape.
    msg.header.verify(committee, worker_cache, check_signature=False)
    group = msg.aggregate_group(committee)
    return await pool.verify_aggregate(*group)


async def headers_and_votes(header, vote, committee, worker_cache):
    # Per-item header/vote checks are NOT certificate checks.
    header.verify(committee, worker_cache)
    vote.verify(committee)


def batched(groups):
    return host_batch_verify_aggregates(groups)


def structural_only(certificate, committee):
    # Structural/stake checks carry no signature work.
    certificate.structural_verify(committee)
