"""Tripping fixture for no-per-item-cert-verify: three per-certificate
verification shapes the batched verifier API replaces (pinned count 3)."""

from narwhal_tpu.types import host_verify_aggregate


async def handle(certificate, committee, worker_cache):
    # 1: the classic inline per-certificate check.
    certificate.verify(committee, worker_cache)


async def fetch(cert, committee, worker_cache):
    # 2: abbreviated receiver name still a certificate.
    cert.verify(committee, worker_cache)


def check_proof(items, zs, s_agg):
    # 3: raw per-group host walk instead of the batched MSM.
    return host_verify_aggregate(items, zs, s_agg)
