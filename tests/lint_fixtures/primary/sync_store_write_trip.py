"""Tripping fixture for no-sync-store-write-in-async: sync store writes
inside async defs in a primary/-scoped module (4 findings)."""


class Core:
    async def process_header(self, header):
        self.header_store.write(header)  # 1: typed-store write

    async def record_payload(self, digest, worker_id):
        self.payload_store.put(digest, worker_id)  # 2: store put

    async def persist_batch(self, puts):
        self._engine.write_batch(puts)  # 3: raw engine batch

    async def persist_all(self, store, certs):
        store.write_all(certs)  # 4: bare store-named receiver
