"""Clean fixture for no-sync-store-write-in-async: the sanctioned async
variants, non-store writes, and sync contexts never fire."""


class Core:
    async def process_header(self, header):
        # The async group-commit variants are the sanctioned path.
        await self.header_store.write_async(header)
        fut = self.payload_store.write_all_async([(b"d", 0)])
        await fut
        await self._engine.write_batch_async([])

    async def send_frame(self, writer, frame):
        writer.write(frame)  # StreamWriter, not a store
        await writer.drain()

    def replay(self, header):
        # Sync context (recovery/replay tooling): the sync API is fine.
        self.header_store.write(header)
        self._engine.write_batch([])
