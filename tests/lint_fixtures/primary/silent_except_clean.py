"""Clean fixture: every handler logs, re-raises, or forwards the error."""

import logging

logger = logging.getLogger("narwhal.fixture")


async def handles(channel, fut):
    try:
        await channel.recv()
    except ValueError as e:
        logger.warning("recv failed: %s", e)

    try:
        await channel.recv()
    except Exception as e:
        fut.set_exception(e)  # forwarded, not swallowed

    try:
        await channel.recv()
    except OSError:
        logger.exception("transport failure")
        raise
