"""Clean fixture for no-wall-clock-in-actors: elapsed time through the
injected clock only; wall-clock modules may be imported (e.g. for
formatting) as long as nothing reads them for elapsed time."""

from narwhal_tpu.clock import now


async def deadline_loop(channel):
    t0 = now()
    deadline = now() + 5.0
    while now() < deadline:
        await channel.recv()
    return now() - t0
