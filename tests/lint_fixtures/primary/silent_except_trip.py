"""Tripping fixture: swallowed exceptions in a consensus-critical dir."""


async def swallows(channel):
    try:
        await channel.recv()
    except ValueError:
        pass  # finding: silent swallow

    try:
        await channel.recv()
    except Exception:  # finding: broad catch, no logging, no re-raise
        channel.reset()
