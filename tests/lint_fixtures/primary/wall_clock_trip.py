"""Tripping fixture for no-wall-clock-in-actors: five direct wall-clock
reads an actor module must not contain (the injected clock is
narwhal_tpu.clock.now): time.time, time.monotonic, an aliased from-import,
loop.time() through a loop-named variable, and the chained
asyncio.get_event_loop().time() form."""

import asyncio
import time
from time import monotonic as mono


async def deadline_loop(channel):
    t0 = time.time()  # trip 1: wall clock
    last = time.monotonic()  # trip 2: monotonic wall clock
    start = mono()  # trip 3: aliased from-import
    loop = asyncio.get_event_loop()
    deadline = loop.time() + 5.0  # trip 4: loop.time via a loop-named var
    while asyncio.get_event_loop().time() < deadline:  # trip 5: chained form
        await channel.recv()
    return t0, last, start
