"""Clean fixture: every spawn keeps a drainable handle."""

import asyncio


class Owner:
    def __init__(self):
        self._tasks: set[asyncio.Task] = set()

    async def spawn(self, coro_fn):
        task = asyncio.ensure_future(coro_fn())
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)
        return task

    async def shutdown(self):
        for t in list(self._tasks):
            t.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
