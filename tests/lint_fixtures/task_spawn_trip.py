"""Tripping fixture: spawned task handles dropped on the floor."""

import asyncio


async def fire_and_forget(coro_fn):
    asyncio.create_task(coro_fn())  # finding: handle dropped
    asyncio.ensure_future(coro_fn())  # finding: handle dropped
    loop = asyncio.get_running_loop()
    loop.create_task(coro_fn())  # finding: handle dropped
