"""Trips metric-naming: names off the <subsystem>_<name>[_<unit>] grammar."""


def build(registry):
    # Not snake_case: double underscore.
    bad_case = registry.counter("worker__txReceived", "camel/double underscore")
    # Unknown subsystem prefix.
    bad_subsystem = registry.gauge("widget_queue_depth", "no such subsystem")
    # Histogram without a unit suffix.
    bad_unit = registry.histogram("primary_propose_latency", "missing unit")
    # "perf" is a registered subsystem, but the grammar still applies:
    # a perf histogram needs its unit suffix like any other.
    bad_perf = registry.histogram("perf_leg_wall", "missing unit on perf")
    return bad_case, bad_subsystem, bad_unit, bad_perf
