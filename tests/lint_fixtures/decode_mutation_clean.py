"""Clean fixture: copy-before-mutate and read-only access."""

from narwhal_tpu.messages import HeaderMsg, decode_message


def read_only(tag, body):
    msg = decode_message(tag, body)
    return len(msg.header.payload)


def copy_then_mutate(msg: HeaderMsg, digest):
    payload = dict(msg.header.payload)  # private copy
    payload[digest] = 0
    return payload


def unrelated_object(store, digest):
    store.index = {}  # fine: not a decoded message
    store.index[digest] = 1
