"""Tripping fixture: bare asyncio queues as actor edges."""

import asyncio
from asyncio import Queue


def build_edges():
    a = asyncio.Queue(maxsize=100)  # finding
    b = asyncio.LifoQueue()  # finding
    c = Queue()  # finding: from-import form
    return a, b, c
