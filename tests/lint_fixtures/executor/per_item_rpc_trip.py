"""Trips no-per-item-rpc-in-loop: awaited network RPCs inside for-loops —
one round trip per item on the commit-to-execution data plane."""

import asyncio  # noqa: F401


class Fetcher:
    def __init__(self, network, client):
        self.network = network
        self.client = client

    async def fetch_all(self, digests, addr, msg):
        out = []
        for d in digests:  # one RTT per digest: the seed subscriber bug
            out.append(await self.network.request(addr, msg(d)))
        return out

    async def drain(self, stream, addr, msg):
        async for item in stream:
            await self.client.unreliable_send(addr, msg(item))


async def broadcast_each(net, addrs, msg):
    for a in addrs:  # bare-name network receiver
        await net.request(a, msg)
