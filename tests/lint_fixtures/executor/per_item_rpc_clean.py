"""Clean for no-per-item-rpc-in-loop: coalesced fetches, concurrent
fan-out, bounded retry over one batched request, non-network receivers."""

import asyncio


class Fetcher:
    def __init__(self, network, store):
        self.network = network
        self.store = store

    async def fetch_coalesced(self, digests, addr, batch_msg):
        # One RPC carries every digest: the whole point of the rule.
        return await self.network.request(addr, batch_msg(tuple(digests)))

    async def fetch_concurrent(self, groups, msg):
        # Fan-out via gather: concurrent, not one awaited RTT per item.
        return await asyncio.gather(
            *(self.network.request(a, msg(ds)) for a, ds in groups.items())
        )

    async def fetch_with_retry(self, addr, batch_msg, attempts=3):
        # Bounded retry over ONE coalesced request: per-attempt, not
        # per-item — the documented justified case.
        for _ in range(attempts):
            try:
                # lint: allow(no-per-item-rpc-in-loop)
                return await self.network.request(addr, batch_msg)
            except OSError:
                continue
        return None

    async def local_reads(self, digests):
        out = []
        for d in digests:  # non-network receiver named `request`
            out.append(await self.store.request(d))
        return out

    async def helper_in_loop(self, addrs, msg):
        fetchers = []
        for a in addrs:
            async def fetch(a=a):  # defined per item, gathered below
                return await self.network.request(a, msg)

            fetchers.append(fetch())
        return await asyncio.gather(*fetchers)
