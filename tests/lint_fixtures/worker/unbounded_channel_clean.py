"""Clean fixture for no-unbounded-channel: every edge has a deliberate
capacity (positional or keyword), metered or not — and non-Channel calls
never match."""

from narwhal_tpu.channels import Channel, metered_channel


class NotAChannel:
    def Channel(self):  # method named Channel on another receiver
        return None


def build_edges(registry, gauge):
    a = Channel(1_000)  # positional capacity
    b = Channel(capacity=50, gauge=gauge)  # keyword capacity
    c = metered_channel(registry, "worker", "edge", 10_000)  # the wrapper
    d = NotAChannel().Channel()  # not the channels.Channel constructor
    return a, b, c, d
