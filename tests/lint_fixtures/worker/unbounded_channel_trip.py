"""Tripping fixture for no-unbounded-channel: Channel constructed without an
explicit capacity in a scoped dir (3 findings pinned)."""

from narwhal_tpu.channels import Channel
from narwhal_tpu import channels


def build_edges(gauge):
    a = Channel()  # bare default capacity
    b = Channel(gauge=gauge)  # keyword-only, still the default capacity
    c = channels.Channel()  # attribute-form constructor
    return a, b, c
