"""Tripping fixture for no-direct-peer-connection: dedicated sockets opened
outside the LanePool in a scoped dir (4 findings pinned)."""

import asyncio

from narwhal_tpu.network import PeerClient, transport
from narwhal_tpu.network import rpc


async def dial_everything(address, credentials):
    host, port = address.rsplit(":", 1)
    # Direct transport dial (the pool's own privilege, not ours).
    reader, writer = await transport.open_connection(
        host, int(port), limit=1024
    )
    # Raw asyncio dial sidesteps even the transport seam.
    r2, w2 = await asyncio.open_connection(host, int(port))
    # Hand-built legacy clients: direct import and attribute form.
    a = PeerClient(address, credentials)
    b = rpc.PeerClient(address, credentials)
    return reader, writer, r2, w2, a, b
