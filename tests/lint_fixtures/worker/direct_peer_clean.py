"""Clean fixture for no-direct-peer-connection: peers reached through the
pooled client surface; unrelated open()/connection-flavored calls don't
match."""


async def send_all(network, pool, peer_key, address, msg):
    # The sanctioned surfaces: the facade and the pool itself.
    peer = network.peer(address)
    await peer.request(msg)
    link = await pool.link_for(peer_key)
    await link.oneway(msg, 0)
    # Connection-flavored but unrelated: never matches.
    store = open_store(address)
    conn = store.connection()
    return conn


def open_store(path):
    return path
