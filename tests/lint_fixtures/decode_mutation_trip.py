"""Tripping fixture: writes into decoded (cache-shared) messages."""

from narwhal_tpu.messages import HeaderMsg, decode_message


def corrupt_all_nodes(tag, body, digest):
    msg = decode_message(tag, body)
    msg.header = None  # finding: field write on a decoded message
    return msg


def corrupt_payload(msg: HeaderMsg, digest):
    msg.header.payload[digest] = 0  # finding: nested container write
    msg.header.payload.update({digest: 1})  # finding: mutator call


def direct(tag, body):
    decode_message(tag, body).header = None  # finding: direct decode write
