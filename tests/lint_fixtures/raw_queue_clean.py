"""Clean fixture: metered bounded channels only."""

from narwhal_tpu.channels import Channel, metered_channel


def build_edges(registry):
    a = Channel(100)
    b = metered_channel(registry, "primary", "to_core", 1_000)
    return a, b
