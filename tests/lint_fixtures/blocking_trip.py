"""Tripping fixture: blocking primitives inside async def."""

import subprocess
import time
from time import sleep as zzz


async def stalls_the_loop(executor, path):
    time.sleep(0.5)  # finding: time.sleep
    zzz(0.1)  # finding: from-import alias of time.sleep
    data = open(path).read()  # finding: sync file I/O
    subprocess.run(["ls"])  # finding: subprocess in async
    fut = executor.submit(len, data)
    return fut.result()  # finding: unknown-origin future .result()
