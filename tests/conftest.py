import os

# Tests exercise multi-device sharding on a virtual 8-device CPU mesh; the
# real TPU chip is reserved for bench.py. JAX_PLATFORMS alone does not win
# against an already-registered accelerator plugin (the environment presets
# JAX_PLATFORMS=axon), so also pin jax_default_device to CPU below.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import asyncio

import pytest


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running integration test"
    )
    import jax

    if jax.default_backend() != "cpu":
        jax.config.update("jax_default_device", jax.devices("cpu")[0])


@pytest.fixture
def run():
    """Run a coroutine to completion on a fresh event loop."""

    def _run(coro, timeout=30.0):
        return asyncio.run(asyncio.wait_for(coro, timeout))

    return _run
