import os

# Tests exercise multi-device sharding on a virtual 8-device CPU mesh; the
# real TPU chip is reserved for bench.py. JAX_PLATFORMS alone does not win
# against an already-registered accelerator plugin (the environment presets
# JAX_PLATFORMS=axon), so also pin jax_default_device to CPU below.
os.environ["JAX_PLATFORMS"] = "cpu"
# Background prewarm compiles (TpuBullshark._prewarm) contend with
# foreground jit traces for XLA's compiler locks: on this 1-core CI host
# that serializes every later trace behind a minutes-long background
# compile and has deadlocked main-thread traces mid-suite. Tests compile
# whatever they actually dispatch; ahead-of-need warming is a production
# concern.
os.environ.setdefault("NARWHAL_TPU_PREWARM", "0")
# Tests exercise bench entry points; none of their runs are measurements,
# so keep them out of the checked-in perf ledger (tests that cover the
# ledger point NARWHAL_PERF_LEDGER_PATH at a tmp file and re-enable).
os.environ.setdefault("NARWHAL_PERF_LEDGER", "0")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import asyncio
import warnings

import pytest


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running integration test"
    )
    import jax

    if jax.default_backend() != "cpu":
        jax.config.update("jax_default_device", jax.devices("cpu")[0])


# Test files whose failures involve whole clusters (real or simulated):
# those are the ones where a post-mortem needs the per-node flight
# recorders, and the only ones worth the report bloat.
_FLIGHT_DUMP_FILES = (
    "test_lifecycle.py",
    "test_reconfigure.py",
    "test_simnet.py",
    "test_node.py",
    "test_telemetry.py",
)
_FLIGHT_DUMP_MAX_EVENTS = 400


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_makereport(item, call):
    """On cluster/simnet test failure, attach every node's flight-recorder
    dump (live tracers + the archive of already-shutdown nodes) to the
    report, as self-contained JSON the terminal reporter prints under its
    own section. The rings accumulate span edges, backpressure/occupancy
    snapshots, and anomaly markers regardless of NARWHAL_TRACE, so even an
    untraced run leaves a usable post-mortem."""
    outcome = yield
    report = outcome.get_result()
    if report.when != "call" or not report.failed:
        return
    if not any(f in str(item.fspath) for f in _FLIGHT_DUMP_FILES):
        return
    try:
        import json

        from narwhal_tpu import tracing

        dumps = tracing.all_dumps(max_events=_FLIGHT_DUMP_MAX_EVENTS)
        if not dumps:
            return
        payload = json.dumps(dumps, sort_keys=True, indent=1, default=str)
        # Bound the section so one failure can't flood the report.
        if len(payload) > 200_000:
            payload = payload[:200_000] + "\n... [truncated]"
        report.sections.append(
            (f"flight recorder ({len(dumps)} node dumps)", payload)
        )
    except Exception as exc:  # never let diagnostics break reporting
        report.sections.append(("flight recorder", f"dump failed: {exc!r}"))
    try:
        # Host context rides along: on this 1-core host most cluster-test
        # flakes (test_partial_committee_change et al.) are CONTENTION, not
        # code — the calibration probe + loadavg + a concurrent-pytest scan
        # make that diagnosis readable from the artifact alone.
        import json

        from tools.perf import calibrate

        ctx = calibrate.host_context(probe_budget_s=0.05)
        headline = (
            f"capacity {ctx['calibration']['ops_per_s']:.0f} ops/s, "
            f"load {ctx['calibration']['loadavg_1m']:.2f}, "
            f"concurrent pytest: {ctx['concurrent_pytest']}"
        )
        report.sections.append(
            (f"host context ({headline})", json.dumps(ctx, indent=1, sort_keys=True))
        )
    except Exception as exc:
        report.sections.append(("host context", f"capture failed: {exc!r}"))


@pytest.fixture(autouse=True)
def _fresh_flight_archive():
    """Scope flight-recorder post-mortems to the failing test: dumps parked
    by a previous test's teardown must not masquerade as this test's."""
    from narwhal_tpu import tracing

    tracing.clear_archive()
    yield


@pytest.fixture
def run():
    """Run a coroutine to completion on a fresh event loop.

    Not asyncio.run(): its _cancel_all_tasks cleanup waits FOREVER for
    leftover tasks to honor their cancellation, so one task parked on a
    cancel-immune await (e.g. a run_in_executor readback) hangs the whole
    suite — observed in-suite on the 1-core host. Cleanup here is bounded:
    cancel leftovers, give them a grace window, then abandon the stragglers
    with a warning and close the loop."""

    def _run(coro, timeout=30.0, cleanup_grace=15.0):
        loop = asyncio.new_event_loop()
        try:
            return loop.run_until_complete(asyncio.wait_for(coro, timeout))
        finally:
            pending = [t for t in asyncio.all_tasks(loop) if not t.done()]
            for t in pending:
                t.cancel()
            stuck = set()
            if pending:
                # asyncio.wait with a timeout neither cancels again nor
                # blocks on stragglers — it just stops waiting.
                _, stuck = loop.run_until_complete(
                    asyncio.wait(pending, timeout=cleanup_grace)
                )
                if stuck:
                    warnings.warn(
                        f"abandoning {len(stuck)} task(s) that ignored "
                        f"cancellation for {cleanup_grace}s: "
                        + ", ".join(repr(t.get_coro()) for t in stuck),
                        RuntimeWarning,
                        stacklevel=2,
                    )
            with warnings.catch_warnings():
                # Abandoned tasks destroyed with the loop are the point of
                # the bounded cleanup; don't let their teardown chatter
                # drown the test report.
                warnings.simplefilter("ignore")
                loop.run_until_complete(loop.shutdown_asyncgens())
                if not stuck:
                    # Joins executor threads with no timeout (3.10): safe
                    # only when nothing is known to be wedged.
                    loop.run_until_complete(loop.shutdown_default_executor())
                asyncio.set_event_loop(None)
                loop.close()

    return _run
