"""Consensus engine tests, mirroring
/root/reference/consensus/src/tests/{bullshark_tests,tusk_tests}.rs: commit
counts on optimal DAGs, round ordering, lossy DAGs, crash recovery."""

import random

from narwhal_tpu.consensus import Bullshark, ConsensusState, Tusk
from narwhal_tpu.fixtures import CommitteeFixture, make_certificates, make_optimal_certificates
from narwhal_tpu.stores import NodeStorage
from narwhal_tpu.types import Certificate

GC_DEPTH = 50


def fixed_leader(committee, round, dag):
    """The reference pins the leader to the first authority in tests
    (bullshark.rs:150-156) so DAG shapes are predictable."""
    return dag.get(round, {}).get(committee.authority_keys()[0])


def _setup(size=4):
    f = CommitteeFixture(size=size)
    store = NodeStorage(None)
    state = ConsensusState(Certificate.genesis(f.committee))
    return f, store, state


def test_bullshark_commit_one():
    # Feed rounds 1..3: as round-3 certs arrive, leader at round 2 gets
    # support and commits: 4 round-1 certs + the leader itself.
    f, store, state = _setup()
    genesis = {c.digest for c in Certificate.genesis(f.committee)}
    certs, _ = make_optimal_certificates(f.committee, 1, 3, genesis)
    bull = Bullshark(f.committee, store.consensus_store, GC_DEPTH, leader_fn=fixed_leader)

    outputs = []
    idx = 0
    for c in certs:
        seq = bull.process_certificate(state, idx, c)
        idx += len(seq)
        outputs.extend(seq)

    assert len(outputs) == 5
    assert [o.certificate.round for o in outputs] == [1, 1, 1, 1, 2]
    assert outputs[-1].certificate.origin == f.committee.authority_keys()[0]
    assert [o.consensus_index for o in outputs] == list(range(5))


def test_bullshark_commit_chain():
    # 10 rounds: leaders at rounds 2,4,6,8 commit as support arrives.
    f, store, state = _setup()
    genesis = {c.digest for c in Certificate.genesis(f.committee)}
    certs, _ = make_optimal_certificates(f.committee, 1, 10, genesis)
    bull = Bullshark(f.committee, store.consensus_store, GC_DEPTH, leader_fn=fixed_leader)

    outputs = []
    idx = 0
    for c in certs:
        seq = bull.process_certificate(state, idx, c)
        idx += len(seq)
        outputs.extend(seq)

    committed = [o.certificate for o in outputs]
    # no duplicates
    assert len({c.digest for c in committed}) == len(committed)
    # rounds are non-decreasing within each leader commit and overall history
    # is complete below the last committed leader round (8)
    assert state.last_committed_round == 8
    by_round = {}
    for c in committed:
        by_round.setdefault(c.round, 0)
        by_round[c.round] += 1
    for r in range(1, 7):
        assert by_round[r] == 4, f"round {r} fully committed"
    # consensus indices are consecutive
    assert [o.consensus_index for o in outputs] == list(range(len(outputs)))


def test_bullshark_missing_leader_no_commit():
    # Exclude the fixed leader from rounds 1..4: nothing can commit.
    f, store, state = _setup()
    genesis = {c.digest for c in Certificate.genesis(f.committee)}
    keys = f.committee.authority_keys()[1:]
    certs, _ = make_certificates(f.committee, 1, 4, genesis, keys=keys)
    bull = Bullshark(f.committee, store.consensus_store, GC_DEPTH, leader_fn=fixed_leader)
    idx = 0
    for c in certs:
        assert bull.process_certificate(state, idx, c) == []


def test_bullshark_lossy_dag_still_commits():
    f, store, state = _setup()
    genesis = {c.digest for c in Certificate.genesis(f.committee)}
    certs, _ = make_certificates(
        f.committee, 1, 20, genesis, failure_probability=0.3,
        rng=random.Random(7),
    )
    bull = Bullshark(f.committee, store.consensus_store, GC_DEPTH, leader_fn=fixed_leader)
    outputs = []
    idx = 0
    for c in certs:
        seq = bull.process_certificate(state, idx, c)
        idx += len(seq)
        outputs.extend(seq)
    assert len(outputs) > 0
    assert len({o.certificate.digest for o in outputs}) == len(outputs)
    rounds = [o.certificate.round for o in outputs]
    assert state.last_committed_round >= 2


def test_tusk_commit_latency_one_extra_round():
    # Tusk: leader at round 2 commits only once round-5 certificates arrive
    # (r=4 even, leader_round=2, support at round 3).
    f, store, state = _setup()
    genesis = {c.digest for c in Certificate.genesis(f.committee)}
    certs, _ = make_optimal_certificates(f.committee, 1, 5, genesis)
    tusk = Tusk(f.committee, store.consensus_store, GC_DEPTH, leader_fn=fixed_leader)
    outputs = []
    idx = 0
    per_round = {}
    for c in certs:
        seq = tusk.process_certificate(state, idx, c)
        idx += len(seq)
        outputs.extend(seq)
        if seq:
            per_round.setdefault(c.round, []).extend(seq)
    assert outputs, "tusk committed nothing"
    assert min(per_round) == 5  # first commit triggered by a round-5 cert
    assert [o.certificate.round for o in outputs][:5] == [1, 1, 1, 1, 2]


def test_state_crash_recovery():
    f, store, state = _setup()
    genesis = {c.digest for c in Certificate.genesis(f.committee)}
    certs, _ = make_optimal_certificates(f.committee, 1, 10, genesis)
    bull = Bullshark(f.committee, store.consensus_store, GC_DEPTH, leader_fn=fixed_leader)
    store.certificate_store.write_all(certs)
    outputs = []
    idx = 0
    for c in certs:
        seq = bull.process_certificate(state, idx, c)
        idx += len(seq)
        outputs.extend(seq)
    assert outputs

    # "crash": rebuild state from stores; resume processing more rounds.
    recovered = ConsensusState.new_from_store(
        Certificate.genesis(f.committee),
        store.consensus_store.read_last_committed(),
        store.certificate_store,
        GC_DEPTH,
    )
    assert recovered.last_committed_round == state.last_committed_round
    assert recovered.last_committed == state.last_committed

    parents = {c.digest for c in certs if c.round == 10}
    more, _ = make_optimal_certificates(f.committee, 11, 14, parents)
    bull2 = Bullshark(f.committee, store.consensus_store, GC_DEPTH, leader_fn=fixed_leader)
    idx2 = store.consensus_store.last_consensus_index()
    resumed = []
    for c in more:
        seq = bull2.process_certificate(recovered, idx2, c)
        idx2 += len(seq)
        resumed.extend(seq)
    assert resumed, "no progress after recovery"
    committed_digests = {o.certificate.digest for o in outputs}
    assert all(o.certificate.digest not in committed_digests for o in resumed), (
        "recovery must not recommit"
    )


def test_gc_bounds_dag():
    f, store, state = _setup()
    genesis = {c.digest for c in Certificate.genesis(f.committee)}
    gc = 5
    certs, _ = make_optimal_certificates(f.committee, 1, 40, genesis)
    bull = Bullshark(f.committee, store.consensus_store, gc, leader_fn=fixed_leader)
    idx = 0
    for c in certs:
        seq = bull.process_certificate(state, idx, c)
        idx += len(seq)
    assert state.last_committed_round == 38
    assert min(state.dag.keys()) >= state.last_committed_round - gc
    assert state.dag_size() <= (gc + 3) * 4
