"""Generic NodeDag (compression/tombstones) and the external Dag service.

Mirrors /root/reference/dag/src/node_dag.rs proptests (path-compression
invariants) and /root/reference/consensus/src/tests/dag_tests.rs (insert
ordering, causal reads, rounds, remove, notify_read)."""

import asyncio
import random
from dataclasses import dataclass, field

import pytest

from narwhal_tpu.consensus import Dag, ValidatorDagError
from narwhal_tpu.consensus.dag import NoCertificateForCoordinates, OutOfCertificates
from narwhal_tpu.dag import DroppedDigest, NodeDag, UnknownDigests
from narwhal_tpu.channels import Channel
from narwhal_tpu.fixtures import CommitteeFixture, make_optimal_certificates
from narwhal_tpu.types import Certificate


@dataclass
class V:
    digest: str
    _parents: list[str] = field(default_factory=list)
    _compressible: bool = False

    def parents(self):
        return list(self._parents)

    def compressible(self):
        return self._compressible


class TestNodeDag:
    def test_insert_rejects_unknown_parents(self):
        dag = NodeDag()
        with pytest.raises(UnknownDigests) as e:
            dag.try_insert(V("b", ["a"]))
        assert e.value.digests == ["a"]

    def test_insert_idempotent_and_heads(self):
        dag = NodeDag()
        dag.try_insert(V("a"))
        dag.try_insert(V("b", ["a"]))
        dag.try_insert(V("b", ["a"]))
        assert dag.size() == 2
        assert dag.has_head("b") and not dag.has_head("a")
        assert set(dag.head_digests()) == {"b"}

    def test_compression_bypasses_and_sweep_tombstones(self):
        dag = NodeDag()
        dag.try_insert(V("a"))
        dag.try_insert(V("m", ["a"], _compressible=True))
        dag.try_insert(V("b", ["m"]))
        assert dag.parents("b") == ["a"]  # m bypassed
        dropped = dag.sweep()
        assert dropped == 1
        assert dag.contains("m") and not dag.contains_live("m")  # tombstone
        with pytest.raises(DroppedDigest):
            dag.get("m")
        # inserting a child of a dropped parent skips it silently
        dag.try_insert(V("c", ["m", "b"]))
        assert dag.parents("c") == ["b"]

    def test_compressible_head_survives_sweep(self):
        dag = NodeDag()
        dag.try_insert(V("a", _compressible=True))
        assert dag.sweep() == 0
        assert dag.contains_live("a")

    def test_bft_skips_compressed(self):
        dag = NodeDag()
        dag.try_insert(V("a"))
        dag.try_insert(V("m", ["a"], _compressible=True))
        dag.try_insert(V("b", ["m"]))
        assert [v.digest for v in dag.bft("b")] == ["b", "a"]

    def test_random_dags_compression_invariants(self):
        # proptest analog (dag/src/lib.rs:289-377): after compressing, no
        # compressible vertex appears in any live parents list; traversals
        # reach exactly the incompressible causal history.
        rng = random.Random(3)
        for trial in range(5):
            dag = NodeDag()
            layers = [[f"0-{i}" for i in range(4)]]
            for v in layers[0]:
                dag.try_insert(V(v))
            for layer in range(1, 8):
                prev = layers[-1]
                cur = []
                for i in range(4):
                    name = f"{layer}-{i}"
                    parents = [p for p in prev if rng.random() > 0.3] or [prev[0]]
                    dag.try_insert(V(name, parents, _compressible=rng.random() < 0.4))
                    cur.append(name)
                layers.append(cur)
            for head in dag.head_digests():
                for p in dag.parents(head):
                    assert not dag._nodes[p].compressible
            dag.sweep()
            for d, node in dag._nodes.items():
                if node.live:
                    for p in node.parents:
                        assert dag.contains_live(p), (trial, d, p)


def _dag_with_rounds(rounds=4, size=4):
    f = CommitteeFixture(size=size)
    genesis = {c.digest for c in Certificate.genesis(f.committee)}
    certs, _ = make_optimal_certificates(f.committee, 1, rounds, genesis)
    return f, certs


class TestDagService:
    def test_insert_and_causal_read(self, run):
        async def scenario():
            f, certs = _dag_with_rounds(4)
            dag = Dag(f.committee)
            for c in certs:
                await dag.insert(c)
            tip = certs[-1]
            causal = await dag.read_causal(tip.digest)
            # genesis is compressible (empty payload) but the round 1..4
            # mock certificates have no payload either -> all compressible
            # except... mock certs have empty payload, so only the tip
            # (start vertex) is reported.
            assert causal[0] == tip.digest
            rounds = await dag.node_read_causal(tip.origin, tip.round)
            assert rounds == causal

        run(scenario())

    def test_insert_with_payload_reports_history(self, run):
        async def scenario():
            f = CommitteeFixture(size=4)
            genesis = {c.digest for c in Certificate.genesis(f.committee)}
            from narwhal_tpu.fixtures import mock_certificate

            keys = f.committee.authority_keys()
            payload = {b"\x01" * 32: 0}
            r1 = [
                mock_certificate(f.committee, pk, 1, genesis, payload=payload)
                for pk in keys
            ]
            r2 = [
                mock_certificate(
                    f.committee, pk, 2, {c.digest for c in r1}, payload=payload
                )
                for pk in keys
            ]
            dag = Dag(f.committee)
            for c in r1 + r2:
                await dag.insert(c)
            causal = await dag.read_causal(r2[0].digest)
            assert set(causal) == {r2[0].digest} | {c.digest for c in r1}

        run(scenario())

    def test_rounds_and_remove(self, run):
        async def scenario():
            f = CommitteeFixture(size=4)
            genesis = {c.digest for c in Certificate.genesis(f.committee)}
            from narwhal_tpu.fixtures import mock_certificate

            keys = f.committee.authority_keys()
            payload = {b"\x02" * 32: 0}
            rows = []
            parents = genesis
            for r in range(1, 4):
                row = [
                    mock_certificate(f.committee, pk, r, parents, payload=payload)
                    for pk in keys
                ]
                rows.append(row)
                parents = {c.digest for c in row}
            dag = Dag(f.committee)
            for row in rows:
                for c in row:
                    await dag.insert(c)
            lo, hi = await dag.rounds(keys[0])
            assert (lo, hi) == (1, 3)
            # remove round-1 certificates: earliest live round advances
            await dag.remove([c.digest for c in rows[0]])
            lo, hi = await dag.rounds(keys[0])
            assert (lo, hi) == (2, 3)
            with pytest.raises(ValidatorDagError):
                await dag.remove([b"\x00" * 32])
            with pytest.raises(NoCertificateForCoordinates):
                await dag.node_read_causal(keys[0], 9)

        run(scenario())

    def test_rounds_empty_origin_errors(self, run):
        async def scenario():
            f = CommitteeFixture(size=4)
            dag = Dag(f.committee)
            # only genesis (round 0) is present and it's live until swept;
            # genesis certs exist for every key, so rounds() = (0, 0)
            keys = f.committee.authority_keys()
            lo, hi = await dag.rounds(keys[0])
            assert (lo, hi) == (0, 0)

        run(scenario())

    def test_notify_read_resolves_on_insert(self, run):
        async def scenario():
            f, certs = _dag_with_rounds(2)
            dag = Dag(f.committee)
            target = certs[-1]
            waiter = asyncio.ensure_future(dag.notify_read(target.digest))
            await asyncio.sleep(0.01)
            assert not waiter.done()
            for c in certs:
                await dag.insert(c)
            got = await asyncio.wait_for(waiter, 1.0)
            assert got.digest == target.digest

        run(scenario())

    def test_notify_read_fails_on_remove_and_prunes_cancelled(self, run):
        """Removed digests fail their waiters instead of leaving futures
        pending forever, and cancelled waiters are pruned from the
        obligations map (ADVICE r1)."""

        async def scenario():
            f = CommitteeFixture(size=4)
            genesis = {c.digest for c in Certificate.genesis(f.committee)}
            from narwhal_tpu.fixtures import mock_certificate

            keys = f.committee.authority_keys()
            payload = {b"\x03" * 32: 0}
            cert = mock_certificate(f.committee, keys[0], 1, genesis, payload=payload)
            dag = Dag(f.committee)
            await dag.insert(cert)
            waiter = asyncio.ensure_future(dag.notify_read(cert.digest))
            got = await asyncio.wait_for(waiter, 1.0)
            assert got.digest == cert.digest
            # Waiter for a digest that then gets removed -> fails fast.
            other = mock_certificate(f.committee, keys[1], 1, genesis, payload=payload)
            await dag.insert(other)
            pending = asyncio.ensure_future(dag.notify_read(b"\x0f" * 32))
            await asyncio.sleep(0.01)
            # remove() raises on the unknown digest; its waiter stays pending
            # (the feed may still insert it later), while the actually-removed
            # digest's slot is cleared.
            with pytest.raises(ValidatorDagError):
                await dag.remove([b"\x0f" * 32, other.digest])
            await asyncio.sleep(0.01)
            assert not pending.done()
            pending.cancel()
            await asyncio.sleep(0.01)
            assert b"\x0f" * 32 not in dag._obligations
            # White-box: a waiter parked on a digest that IS removed gets
            # failed (in the public flow inserts resolve waiters first, so
            # this guards the defensive path directly).
            victim = mock_certificate(f.committee, keys[2], 1, genesis, payload=payload)
            await dag.insert(victim)
            parked = asyncio.get_running_loop().create_future()
            dag._obligations[victim.digest].append(parked)
            await dag.remove([victim.digest])
            assert isinstance(parked.exception(), ValidatorDagError)
            assert victim.digest not in dag._obligations
            # Cancelled waiters are pruned.
            never = asyncio.ensure_future(dag.notify_read(b"\x0e" * 32))
            await asyncio.sleep(0.01)
            never.cancel()
            await asyncio.sleep(0.01)
            assert b"\x0e" * 32 not in dag._obligations

        run(scenario())

    def test_feed_from_channel(self, run):
        async def scenario():
            f, certs = _dag_with_rounds(3)
            ch = Channel(100)
            dag = Dag(f.committee, ch)
            dag.spawn()
            for c in certs:
                await ch.send(c)
            await asyncio.sleep(0.05)
            assert await dag.contains(certs[-1].digest)
            await dag.shutdown()

        run(scenario())


class TestDeviceDagService:
    def test_device_read_causal_matches_host(self, run):
        """backend="tpu", policy="device": ReadCausal/NodeReadCausal served
        by one reach_mask dispatch must return exactly the host BFS's
        result — same vertices, same canonical order (advisor r4: the
        external API's order must be backend-invariant) — across random
        DAGs with mixed payloads (compressible interiors), removals, and
        window coverage fallbacks."""
        import random

        from narwhal_tpu.fixtures import CommitteeFixture, mock_certificate

        rng = random.Random(7)

        async def scenario():
            for trial in range(4):
                f = CommitteeFixture(size=4)
                genesis = [c.digest for c in Certificate.genesis(f.committee)]
                keys = f.committee.authority_keys()
                host = Dag(f.committee)
                dev = Dag(f.committee, backend="tpu", window=16, policy="device")
                prev = list(genesis)
                all_certs = []
                for r in range(1, 7):
                    cur = []
                    for i, pk in enumerate(keys):
                        payload = (
                            {bytes([r, i]) * 16: 0} if rng.random() < 0.5 else {}
                        )
                        c = mock_certificate(
                            f.committee, pk, r,
                            set(rng.sample(prev, k=max(3, len(prev) - 1))),
                            payload=payload,
                        )
                        cur.append(c)
                        all_certs.append(c)
                    prev = [c.digest for c in cur]
                for c in all_certs:
                    await host.insert(c)
                    await dev.insert(c)
                # Remove a random earlier certificate on both.
                victim = all_certs[rng.randrange(len(all_certs) // 2)]
                await host.remove([victim.digest])
                await dev.remove([victim.digest])
                for c in all_certs[-8:]:
                    h = await host.read_causal(c.digest)
                    d = await dev.read_causal(c.digest)
                    assert h == d, (trial, c.round)  # exact canonical order
                    assert d[0] == c.digest  # start-first shape
                    n_h = await host.node_read_causal(c.origin, c.round)
                    n_d = await dev.node_read_causal(c.origin, c.round)
                    assert n_h == n_d
                assert dev.routing_stats()["dev_calls"] > 0

        run(scenario(), timeout=120.0)

    def test_concurrent_reads_coalesce_into_one_dispatch(self, run):
        """K concurrent ReadCausal requests on the device path must fuse
        into ONE vmapped reach_mask dispatch (the RTT-amortization the
        routing policy's device side is priced on)."""
        from narwhal_tpu.fixtures import CommitteeFixture, mock_certificate

        async def scenario():
            f = CommitteeFixture(size=4)
            genesis = [c.digest for c in Certificate.genesis(f.committee)]
            keys = f.committee.authority_keys()
            dev = Dag(f.committee, backend="tpu", window=16, policy="device")
            host = Dag(f.committee)
            prev = list(genesis)
            tips = []
            for r in range(1, 5):
                cur = [
                    mock_certificate(
                        f.committee, pk, r, set(prev),
                        payload={bytes([r, i]) * 16: 0},
                    )
                    for i, pk in enumerate(keys)
                ]
                for c in cur:
                    await dev.insert(c)
                    await host.insert(c)
                prev = [c.digest for c in cur]
                tips = cur
            dispatches = 0
            real_many = dev._device_causal_many

            def counting(starts):
                nonlocal dispatches
                dispatches += 1
                return real_many(starts)

            dev._device_causal_many = counting
            results = await asyncio.gather(
                *(dev.read_causal(c.digest) for c in tips)
            )
            assert dispatches == 1, "concurrent reads must share one dispatch"
            for c, got in zip(tips, results):
                assert got == await host.read_causal(c.digest)

        run(scenario(), timeout=120.0)

    def test_coalesced_batch_equivalent_to_sequential_host_walks(self, run):
        """The coalescing contract end to end: K concurrent read_causal
        calls with DISTINCT starts spread across rounds, fused into one
        batched reach_mask dispatch over the resident window, must return
        byte-identical causal histories to K sequential host BFS walks."""
        from narwhal_tpu.fixtures import CommitteeFixture, mock_certificate

        async def scenario():
            f = CommitteeFixture(size=4)
            genesis = [c.digest for c in Certificate.genesis(f.committee)]
            keys = f.committee.authority_keys()
            dev = Dag(f.committee, backend="tpu", window=16, policy="device")
            host = Dag(f.committee)
            prev = list(genesis)
            all_certs = []
            for r in range(1, 6):
                cur = [
                    mock_certificate(
                        f.committee, pk, r, set(prev),
                        payload={bytes([r, i]) * 16: 0} if (r + i) % 3 else {},
                    )
                    for i, pk in enumerate(keys)
                ]
                for c in cur:
                    await dev.insert(c)
                    await host.insert(c)
                prev = [c.digest for c in cur]
                all_certs.extend(cur)
            # K starts at different depths: rounds 2..5 across authorities.
            starts = [c for c in all_certs if c.round >= 2][:8]
            dispatches = 0
            real_many = dev._device_causal_many

            def counting(batch):
                nonlocal dispatches
                dispatches += 1
                return real_many(batch)

            dev._device_causal_many = counting
            fused = await asyncio.gather(
                *(dev.read_causal(c.digest) for c in starts)
            )
            assert dispatches == 1, "K concurrent reads must share one dispatch"
            assert dev.routing_stats()["last_coalesced_batch"] == len(starts)
            for c, got in zip(starts, fused):
                want = await host.read_causal(c.digest)
                assert got == want  # byte-identical digests, same order
                assert all(isinstance(d, bytes) for d in got)

        run(scenario(), timeout=120.0)

    def test_read_metrics_and_cost_model(self, run):
        """The per-route latency/EWMA metrics and the coalesced-batch-size
        gauge are recorded (ISSUE acceptance), and the cost model routes by
        amortized prediction: a device dispatch far cheaper than the host's
        per-vertex walk cost pulls adaptive traffic onto the device path."""
        from narwhal_tpu.consensus.metrics import ConsensusMetrics
        from narwhal_tpu.fixtures import CommitteeFixture, mock_certificate
        from narwhal_tpu.metrics import Registry

        async def scenario():
            f = CommitteeFixture(size=4)
            genesis = [c.digest for c in Certificate.genesis(f.committee)]
            keys = f.committee.authority_keys()
            registry = Registry()
            dag = Dag(
                f.committee, backend="tpu", window=16,
                metrics=ConsensusMetrics(registry),
            )
            prev = list(genesis)
            tip = None
            for r in range(1, 5):
                cur = [
                    mock_certificate(
                        f.committee, pk, r, set(prev),
                        payload={bytes([r, i]) * 16: 0},
                    )
                    for i, pk in enumerate(keys)
                ]
                for c in cur:
                    await dag.insert(c)
                prev = [c.digest for c in cur]
                tip = cur[0]
            # First adaptive request goes host, second probes the device.
            await dag.read_causal(tip.digest)
            await dag.read_causal(tip.digest)
            # Warm flag set by the probe's compile dispatch; now force the
            # model coefficients to a regime where the device must win:
            # host pays 10ms/vertex, a fused dispatch costs 1us.
            dag._host_pv = 0.010
            dag._dev_dispatch = 1e-6
            for _ in range(10):
                await dag.read_causal(tip.digest)
            stats = dag.routing_stats()
            assert stats["dev_calls"] >= 10  # cost model prefers the device
            assert stats["host_us_per_vertex"] is not None
            # Histogram counts per route and the EWMA gauges were recorded.
            assert registry.value(
                "consensus_dag_read_causal_latency_seconds", "host"
            ) >= 1
            assert registry.value(
                "consensus_dag_read_causal_latency_seconds", "device"
            ) >= 10
            assert registry.value("consensus_dag_read_route_ewma_ms", "host") > 0
            # Every fused dispatch here served one request; the gauge holds
            # the most recent batch size.
            assert (
                registry.get("consensus_dag_read_coalesced_batch_size").get() == 1
            )
            # And a genuinely concurrent burst moves the gauge to K.
            burst = await asyncio.gather(
                *(dag.read_causal(tip.digest) for _ in range(4))
            )
            assert len(burst) == 4
            assert (
                registry.get("consensus_dag_read_coalesced_batch_size").get() == 4
            )

        run(scenario(), timeout=120.0)

    def test_shutdown_fails_stranded_device_readers(self, run):
        """Shutdown with queued (unflushed) device requests must fail
        their futures — a reader awaiting a coalesced dispatch cannot be
        left hanging forever when the flush task is cancelled."""
        from narwhal_tpu.fixtures import CommitteeFixture

        async def scenario():
            f = CommitteeFixture(size=4)
            dag = Dag(f.committee, backend="tpu", window=16, policy="device")
            fut = asyncio.get_running_loop().create_future()
            dag._dev_queue.append((b"\x00" * 32, fut))
            await dag.shutdown()
            with pytest.raises(ValidatorDagError, match="shut down"):
                await fut

        run(scenario(), timeout=30.0)

    def test_adaptive_policy_routes_to_measured_faster_path(self, run):
        """policy="adaptive" (the default): after both paths have been
        measured, requests go to the faster one — on the virtual-CPU test
        host the BFS wins, so a long request stream must be served
        overwhelmingly by the host path (the measured-crossover fence for
        the r4 'device path 3-30x slower yet preferred' regression)."""
        from narwhal_tpu.fixtures import CommitteeFixture, mock_certificate

        async def scenario():
            f = CommitteeFixture(size=4)
            genesis = [c.digest for c in Certificate.genesis(f.committee)]
            keys = f.committee.authority_keys()
            dag = Dag(f.committee, backend="tpu", window=16)
            prev = list(genesis)
            tip = None
            for r in range(1, 5):
                cur = [
                    mock_certificate(
                        f.committee, pk, r, set(prev),
                        payload={bytes([r, i]) * 16: 0},
                    )
                    for i, pk in enumerate(keys)
                ]
                for c in cur:
                    await dag.insert(c)
                prev = [c.digest for c in cur]
                tip = cur[0]
            # Fake the device measurement as catastrophically slow (the
            # tunneled-chip regime) so the adaptive router must fence it.
            dag._ewma["dev"] = 1.0
            dag._dev_warmed.add(1)
            for _ in range(20):
                await dag.read_causal(tip.digest)
            stats = dag.routing_stats()
            assert stats["host_calls"] >= 19  # probes aside, host serves
            assert stats["ewma_host_ms"] is not None

        run(scenario(), timeout=120.0)
