"""The telemetry plane: causal tracing, the flight recorder, and the
scrape/dump export surface.

Covers the observability contracts end to end:

- the checked-in metrics catalog gate (tools/metrics_catalog.json must match
  what the live registries register — rename/add/drop fails here, in review);
- StageTimer/span equivalence (stage histograms are DERIVED from span
  closes: one close site, two sinks, counts provably equal);
- deterministic digest sampling (every node traces the same certificates);
- scrape golden (render() parses back via parse_exposition, counters are
  monotone, histogram series fold under their base name);
- waterfall stitching across the digest chain (batch -> header -> cert);
- the Telemetry RPC pair over the simnet fabric (typed messages, zero
  sockets) and over a LIVE 4-node cluster (typed RPC + raw-bytes gRPC);
- trace determinism: same simnet seed => bit-identical flight dumps.
"""

import asyncio
import json

import pytest

from narwhal_tpu import tracing
from narwhal_tpu.metrics import Registry, parse_exposition
from narwhal_tpu.pacing import StageTimer
from narwhal_tpu.tracing import Tracer


# ---------------------------------------------------------------------------
# Satellite: the metrics-catalog gate
# ---------------------------------------------------------------------------


def test_metrics_catalog_matches_registries():
    """tools/metrics_catalog.json is the reviewed contract for the scrape
    surface: re-extract the live registries and diff. On drift, regenerate
    with `python -m tools.metrics_catalog --write` and review the diff."""
    from tools.metrics_catalog import extract_catalog, load_catalog

    live = {r["name"]: r for r in extract_catalog()}
    checked = {r["name"]: r for r in load_catalog()}
    undocumented = sorted(set(live) - set(checked))
    stale = sorted(set(checked) - set(live))
    changed = sorted(n for n in set(live) & set(checked) if live[n] != checked[n])
    assert not undocumented, f"undocumented metrics: {undocumented}"
    assert not stale, f"catalog lists dropped metrics: {stale}"
    assert not changed, f"metrics changed shape: {changed}"
    # The catalog is non-trivial and catalog rows carry the full contract.
    assert len(checked) >= 60
    assert all(
        {"name", "type", "labels", "help", "roles"} <= set(r) for r in checked.values()
    )


# ---------------------------------------------------------------------------
# Satellite: StageTimer histograms are derived from span closes
# ---------------------------------------------------------------------------


def _stage_setup(**tracer_kwargs):
    registry = Registry()
    hist = registry.histogram(
        "node_stage_latency_seconds", "per stage", labels=("stage",)
    )
    tracer = Tracer(node="n0", ring=256, **tracer_kwargs)
    t = [0.0]

    def clock():
        t[0] += 0.25
        return t[0]

    timer = StageTimer(hist, "propose", clock=clock, tracer=tracer)
    return registry, tracer, timer


def test_stage_timer_close_is_both_span_and_observation():
    """One close(), two sinks: with tracing enabled every stop() emits
    exactly one span AND one histogram observation — same count, and the
    histogram sum equals the summed span widths."""
    registry, tracer, timer = _stage_setup(enabled=True, sample=1.0)
    keys = [bytes([i]) * 32 for i in range(7)]
    for k in keys:
        timer.start(k)
        timer.stop(k)
    spans = [e for e in tracer.events if e[0] == "span" and e[1] == "propose"]
    assert len(spans) == 7
    assert registry.value("node_stage_latency_seconds", "propose") == 7
    hist_sum = registry.get("node_stage_latency_seconds").labels("propose").sum
    span_sum = sum(t1 - t0 for _, _, _, t0, t1, _ in spans)
    assert hist_sum == pytest.approx(span_sum)
    assert all(e[2] in {k.hex() for k in keys} for e in spans)


def test_stage_timer_disabled_or_unsampled_still_observes():
    """Trace off (or the key sampled out): the histogram keeps recording —
    metrics never degrade when tracing is disabled — and the ring stays
    free of spans."""
    for kwargs in (dict(enabled=False), dict(enabled=True, sample=0.0)):
        registry, tracer, timer = _stage_setup(**kwargs)
        for i in range(5):
            k = bytes([0xF0 + i]) * 32
            timer.start(k)
            timer.stop(k)
        assert registry.value("node_stage_latency_seconds", "propose") == 5
        assert not [e for e in tracer.events if e[0] == "span"]


def test_straggler_restart_cannot_mint_second_span():
    """The certify/commit inversion, pinned at the span layer: after a
    key's stage closes, a straggler re-start + re-stop must not emit a
    second span. With one span per key per stage, waterfall()'s
    earliest-t0 pick can never land on a late re-opened window, even
    after the true span would have been evicted from the ring."""
    registry, tracer, timer = _stage_setup(enabled=True, sample=1.0)
    key = bytes([7]) * 32
    timer.start(key)
    timer.stop(key)  # the true certify window
    timer.start(key)  # straggler vote re-delivers after the close
    assert timer.stop(key) is None
    spans = [e for e in tracer.events if e[0] == "span"]
    assert len(spans) == 1
    # And the surviving span is the FIRST window, not the straggler's.
    _, _, _, t0, t1, _ = spans[0]
    assert (t0, t1) == (0.25, 0.5)


def test_sampling_is_deterministic_and_digest_keyed():
    """sampled() reads only the digest's first 4 bytes: two independent
    tracers (two nodes) always agree, so sampled runs never produce
    partial waterfalls; sample=1.0 admits everything."""
    a = Tracer(node="a", enabled=True, sample=0.5, ring=16)
    b = Tracer(node="b", enabled=True, sample=0.5, ring=16)
    keys = [i.to_bytes(4, "big") + b"\x00" * 28 for i in range(0, 2**32, 2**28)]
    assert [a.sampled(k) for k in keys] == [b.sampled(k) for k in keys]
    assert a.sampled(b"\x00" * 32) and not a.sampled(b"\xff" * 32)
    full = Tracer(node="c", enabled=True, sample=1.0, ring=16)
    assert all(full.sampled(k) for k in keys)


# ---------------------------------------------------------------------------
# Scrape golden: render() -> parse_exposition round trip
# ---------------------------------------------------------------------------


def test_scrape_parses_and_counters_are_monotone():
    registry = Registry()
    c = registry.counter("worker_tx_received", "client transactions")
    g = registry.gauge("node_backpressure_level", "admission level")
    h = registry.histogram(
        "primary_propose_latency_seconds", "propose stage", labels=("stage",)
    )
    c.inc(3)
    g.set(0.25)
    h.labels("propose").observe(0.02)
    first = parse_exposition(registry.render())
    assert first["worker_tx_received"]["type"] == "counter"
    assert first["worker_tx_received"]["help"] == "client transactions"
    assert first["worker_tx_received"]["samples"][""] == 3.0
    assert first["node_backpressure_level"]["samples"][""] == 0.25
    # Histogram series fold under the base name: _bucket/_sum/_count keys.
    hsamples = first["primary_propose_latency_seconds"]["samples"]
    assert any(k.startswith("_bucket") for k in hsamples)
    assert hsamples['_count{stage="propose"}'] == 1.0
    # Monotonicity across scrapes: the counter only moves up.
    c.inc(2)
    h.labels("propose").observe(0.04)
    second = parse_exposition(registry.render())
    assert second["worker_tx_received"]["samples"][""] == 5.0
    assert second["primary_propose_latency_seconds"]["samples"][
        '_count{stage="propose"}'
    ] == 2.0
    for name, entry in first.items():
        if entry["type"] != "counter":
            continue
        for series, value in entry["samples"].items():
            assert second[name]["samples"][series] >= value


# ---------------------------------------------------------------------------
# Waterfall stitching across the digest chain (pure unit)
# ---------------------------------------------------------------------------


def test_waterfall_stitches_batch_header_cert_chain():
    """Spans recorded under three different causal keys (batch digest,
    header digest, certificate digest) merge into ONE waterfall under the
    certificate via the recorded link chain — the zero-wire-bytes trace
    context."""
    batch, header, cert = b"\x01" * 32, b"\x02" * 32, b"\x03" * 32
    t = Tracer(node="n0", enabled=True, sample=1.0, ring=64)
    t.span("seal", batch, 0.0, 0.1)
    t.link("propose", batch, header)
    t.span("propose", header, 0.1, 0.3)
    t.link("certify", header, cert)
    t.span("certify", header, 0.3, 0.5)
    t.span("commit", cert, 0.5, 0.8)
    t.span("execute", cert, 0.8, 0.9)
    falls = tracing.waterfall([t.dump()])
    assert set(falls) == {cert.hex()}
    stages = falls[cert.hex()]["stages"]
    assert set(stages) == {"seal", "propose", "certify", "commit", "execute"}
    assert stages["seal"] == [0.0, 0.1]
    assert stages["execute"] == [0.8, 0.9]
    assert set(falls[cert.hex()]["ancestors"]) == {batch.hex(), header.hex()}
    # The summary table sees every span.
    pct = tracing.stage_percentiles([t.dump()])
    assert set(pct) == {"seal", "propose", "certify", "commit", "execute"}
    assert pct["commit"]["count"] == 1
    assert pct["commit"]["p50_ms"] == pytest.approx(300.0)


def test_anomaly_archives_every_live_ring():
    """on_anomaly snapshots all live tracers into the bounded archive,
    tagged with the reason — what oracles and the commit-stall detector
    call so the pytest hook can attach evidence post-teardown."""
    t1 = Tracer(node="p0", enabled=True, sample=1.0, ring=32)
    t2 = Tracer(node="w0", enabled=True, sample=1.0, ring=32)
    t1.instant("backpressure", level=0.5)
    dumps = tracing.on_anomaly("commit_stall test")
    assert {d["node"] for d in dumps} >= {"p0", "w0"}
    archived = [d for d in tracing.ARCHIVE if d.get("anomaly") == "commit_stall test"]
    assert {d["node"] for d in archived} >= {"p0", "w0"}
    assert "commit_stall test" in t1.anomalies and "commit_stall test" in t2.anomalies
    # all_dumps = archive + live; entries are self-contained JSON.
    json.dumps(tracing.all_dumps(max_events=50), sort_keys=True)
    tracing.clear_archive()
    assert len(tracing.ARCHIVE) == 0


def test_tracer_registry_is_scoped_per_cluster_incarnation():
    """A tracer from a previous cluster incarnation kept alive (a leaked
    ring, a node a test forgot to drop) must not bleed spans into the next
    incarnation's live view: successive in-process clusters reuse node
    labels and — with seeded fixtures — certificate digests, so without
    generation scoping `live_dumps()` merged a prior cluster's spans into
    the next one's waterfalls (the live-cluster waterfall test's flake)."""
    from narwhal_tpu.cluster import Cluster

    stale = Tracer(node="primary-0", enabled=True, sample=1.0, ring=32)
    stale.span("commit", b"\x07" * 32, 0.0, 1.0)
    assert any(
        d["node"] == "primary-0" and d["events"] for d in tracing.live_dumps()
    )

    # Constructing the cluster opens the new incarnation; no boot needed.
    Cluster(size=4, workers=1)
    assert not any(
        d["node"] == "primary-0" and d["events"] for d in tracing.live_dumps()
    )
    # Anomaly snapshots are scoped the same way: the stale ring is neither
    # archived nor tagged.
    tracing.on_anomaly("incarnation test")
    assert "incarnation test" not in stale.anomalies
    tracing.clear_archive()


# ---------------------------------------------------------------------------
# The Telemetry RPC pair over the simnet fabric (zero sockets)
# ---------------------------------------------------------------------------


def test_telemetry_rpc_over_simnet_fabric():
    """Scrape + flight-dump served by ConsensusApi through the in-memory
    fabric: the surface the simnet observability contract requires (grpc
    binds real sockets and is skipped under simnet)."""
    from narwhal_tpu.messages import (
        FlightDumpMsg,
        TelemetryScrapeMsg,
    )
    from narwhal_tpu.network import NetworkClient, transport
    from narwhal_tpu.primary.api_server import ConsensusApi
    from narwhal_tpu.simnet import LinkSpec, SimFabric, SimLoop

    loop = SimLoop()
    asyncio.set_event_loop(loop)
    fabric = SimFabric(seed=1, default_link=LinkSpec(latency=0.005))
    transport.install(fabric)
    fabric.register_node("api-node", ["telemetry-host:1"])

    registry = Registry()
    registry.counter("consensus_commits", "committed certs").inc(4)
    tracer = Tracer(node="primary-test", enabled=True, sample=1.0, ring=64)
    tracer.span("commit", b"\x07" * 32, 1.0, 1.5)
    tracer.instant("backpressure", level=0.1)
    api = ConsensusApi(
        b"\x00" * 32, None, None, None, registry=registry, tracer=tracer
    )

    async def main():
        await api.spawn("telemetry-host:1")
        client = NetworkClient()
        try:
            scrape = await client.request(
                "telemetry-host:1", TelemetryScrapeMsg(), timeout=5.0
            )
            assert scrape.text == registry.render()
            parsed = parse_exposition(scrape.text)
            assert parsed["consensus_commits"]["samples"][""] == 4.0

            resp = await client.request(
                "telemetry-host:1", FlightDumpMsg(), timeout=5.0
            )
            dump = json.loads(resp.payload.decode())
            assert dump["node"] == "primary-test"
            kinds = [e[0] for e in dump["events"]]
            assert "span" in kinds and "instant" in kinds

            # max_events bounds the reply payload from the requester side.
            bounded = await client.request(
                "telemetry-host:1", FlightDumpMsg(max_events=1), timeout=5.0
            )
            assert len(json.loads(bounded.payload.decode())["events"]) == 1
        finally:
            client.close()
            await api.shutdown()

    try:
        loop.run_until_complete(main())
    finally:
        transport.uninstall()
        for t in asyncio.all_tasks(loop):
            t.cancel()
        loop.run_until_complete(asyncio.sleep(0))
        asyncio.set_event_loop(None)
        loop.close()


# ---------------------------------------------------------------------------
# Simnet: same seed => bit-identical traced event log; waterfalls exist
# ---------------------------------------------------------------------------


def test_simnet_trace_determinism_and_waterfall(monkeypatch):
    """With tracing on, a seeded scenario's per-node flight dumps are
    bit-identical across runs (all span timestamps ride the virtual
    clock), and the dumps reconstruct end-to-end commit waterfalls."""
    from narwhal_tpu.config import Parameters
    from narwhal_tpu.simnet import FaultPlan, LinkSpec, run_scenario

    monkeypatch.setenv("NARWHAL_TRACE", "1")
    monkeypatch.setenv("NARWHAL_TRACE_SAMPLE", "1.0")
    params = Parameters(
        max_header_delay=0.1,
        max_batch_delay=0.05,
        header_delay_floor=0.05,
        batch_delay_floor=0.02,
    )

    def go():
        return run_scenario(
            nodes=4,
            duration=2.0,
            load_rate=80,
            parameters=params,
            plan=FaultPlan(seed=11, default_link=LinkSpec(latency=0.002)),
        )

    a = go()
    b = go()
    assert a.flight_dumps, "scenario captured no flight dumps"
    assert all(d["trace_enabled"] for d in a.flight_dumps)
    blob_a = json.dumps(a.flight_dumps, sort_keys=True)
    blob_b = json.dumps(b.flight_dumps, sort_keys=True)
    assert blob_a == blob_b, "same seed must produce a bit-identical trace"

    falls = tracing.waterfall(a.flight_dumps)
    committed = {
        k: v["stages"]
        for k, v in falls.items()
        if {"propose", "certify", "commit"} <= set(v["stages"])
    }
    assert committed, f"no full propose->certify->commit waterfall in {len(falls)}"
    # At least one committed certificate carried payload: its waterfall
    # reaches back through the link chain to a worker's seal span.
    assert any("seal" in stages for stages in committed.values())
    # Stage ordering is causal within every committed waterfall.
    for stages in committed.values():
        assert stages["propose"][0] <= stages["certify"][1] <= stages["commit"][1]
    pct = tracing.stage_percentiles(a.flight_dumps)
    assert {"propose", "certify", "commit"} <= set(pct)
    assert all(v["count"] > 0 for v in pct.values())


# ---------------------------------------------------------------------------
# Live 4-node cluster: the acceptance waterfall + both export surfaces
# ---------------------------------------------------------------------------


def test_live_cluster_scrape_dump_and_waterfall(run, monkeypatch):
    """Boot a real 4-node committee with tracing on, push transactions to
    execution, then reconstruct one certificate's end-to-end waterfall
    purely from the telemetry surface: typed-RPC Telemetry.Scrape (counters
    visible, commit count non-zero) + Telemetry.DumpFlightRecorder from
    every node, and the raw-bytes gRPC mirror of both."""
    import grpc

    from narwhal_tpu.cluster import Cluster
    from narwhal_tpu.messages import (
        FlightDumpMsg,
        SubmitTransactionStreamMsg,
        TelemetryScrapeMsg,
    )
    from narwhal_tpu.network import NetworkClient

    monkeypatch.setenv("NARWHAL_TRACE", "1")
    monkeypatch.setenv("NARWHAL_TRACE_SAMPLE", "1.0")

    async def scenario():
        cluster = Cluster(size=4, workers=1)
        await cluster.start()
        client = NetworkClient()
        channel = None
        try:
            await cluster.assert_progress(commit_threshold=2, timeout=30.0)
            txs = tuple(
                b"\x02" + i.to_bytes(8, "big") + b"\x6b" * 55 for i in range(64)
            )
            await client.request(
                cluster.authorities[0].worker_transactions_address(0),
                SubmitTransactionStreamMsg(txs),
            )
            out = cluster.authorities[0].primary.tx_execution_output
            await asyncio.wait_for(out.recv(), 30.0)

            # -- scrape over the typed RPC plane --------------------------
            a0 = cluster.authorities[0]
            scrape = await client.request(
                a0.primary.api_address, TelemetryScrapeMsg(), timeout=10.0
            )
            parsed = parse_exposition(scrape.text)
            assert parsed["consensus_stage_latency_seconds"]["samples"][
                '_count{stage="commit"}'
            ] > 0

            # -- flight dumps over the typed RPC plane, all four nodes ----
            dumps = []
            for a in cluster.authorities:
                resp = await client.request(
                    a.primary.api_address, FlightDumpMsg(), timeout=10.0
                )
                dumps.append(json.loads(resp.payload.decode()))
            # Worker rings hold the seal spans; workers expose no RPC
            # listener of their own, so take their dumps in-process (the
            # microbench --trace-waterfall path does the same).
            dumps.extend(
                w.tracer.dump() for a in cluster.authorities for w in a.workers.values()
            )

            # The acceptance bar: one certificate's end-to-end waterfall,
            # reconstructed purely from dumped rings. Poll briefly — the
            # execute span closes a beat after the execution output pops.
            deadline = asyncio.get_event_loop().time() + 30.0
            want = {"seal", "propose", "certify", "commit", "execute"}
            while True:
                falls = tracing.waterfall(dumps)
                full = {
                    k: v for k, v in falls.items() if want <= set(v["stages"])
                }
                if full:
                    break
                if asyncio.get_event_loop().time() > deadline:
                    stages = {k: sorted(v["stages"]) for k, v in falls.items()}
                    raise AssertionError(f"no full waterfall yet: {stages}")
                await asyncio.sleep(0.5)
                dumps = tracing.live_dumps()
            cert, entry = next(iter(full.items()))
            s = entry["stages"]
            assert s["seal"][0] <= s["propose"][1] <= s["certify"][1]
            assert s["certify"][0] <= s["commit"][1] <= s["execute"][1]

            # -- the gRPC mirror: raw-bytes unary, any-language clients ---
            addr = a0.primary.grpc_api_address
            if addr:  # grpc plane is mounted outside simnet
                channel = grpc.aio.insecure_channel(addr)
                raw = lambda m: channel.unary_unary(  # noqa: E731
                    f"/narwhal.Telemetry/{m}",
                    request_serializer=lambda b: b,
                    response_deserializer=lambda b: b,
                )
                text = (await raw("Scrape")(b"")).decode()
                gparsed = parse_exposition(text)
                assert gparsed["consensus_stage_latency_seconds"]["samples"][
                    '_count{stage="commit"}'
                ] > 0
                payload = await raw("DumpFlightRecorder")(
                    (50).to_bytes(4, "little")
                )
                gdump = json.loads(payload.decode())
                assert gdump["node"].startswith("primary-")
                assert len(gdump["events"]) <= 50
        finally:
            if channel is not None:
                await channel.close()
            client.close()
            await cluster.shutdown()

    run(scenario(), timeout=120.0)
