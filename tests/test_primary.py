"""Primary protocol tests, mirroring /root/reference/primary/src/tests/
{core,proposer,certificate_waiter,header_waiter}_tests.rs."""

import asyncio
from dataclasses import replace

import pytest

from narwhal_tpu.channels import Channel, Watch
from narwhal_tpu.config import Authority
from narwhal_tpu.fixtures import CommitteeFixture
from narwhal_tpu.primary import NetworkModel, Primary, VotesAggregator
from narwhal_tpu.primary.proposer import Proposer
from narwhal_tpu.stores import NodeStorage
from narwhal_tpu.types import Certificate, ReconfigureNotification, Vote


def test_votes_aggregator_quorum():
    f = CommitteeFixture(size=4)
    header = f.header(author=0, round=1)
    agg = VotesAggregator()
    votes = f.votes(header)  # 3 votes from the other authorities
    cert = None
    # With 4 equal stakes quorum is 3: author's own vote + 2 peers.
    own = Vote.for_header(header, f.authorities[0].public, f.authorities[0].keypair)
    assert agg.append(own, f.committee, header) is None
    assert agg.append(votes[0], f.committee, header) is None
    cert = agg.append(votes[1], f.committee, header)
    assert cert is not None
    cert.verify(f.committee, f.worker_cache)
    # Extra votes after quorum are ignored.
    assert agg.append(votes[2], f.committee, header) is None


def test_votes_aggregator_rejects_duplicate_voter():
    f = CommitteeFixture(size=4)
    header = f.header(author=0, round=1)
    agg = VotesAggregator()
    v = f.votes(header)[0]
    assert agg.append(v, f.committee, header) is None
    assert agg.append(v, f.committee, header) is None
    assert agg.weight == 1


def test_certificates_aggregator_forwards_post_quorum(run):
    """Certificates arriving after the round's quorum (e.g. the leader's) are
    still drained and forwarded so the proposer can extend its parent set
    (aggregators.rs:83-97, required by Bullshark)."""
    from narwhal_tpu.primary.aggregators import CertificatesAggregator

    f = CommitteeFixture(size=4)
    certs = [f.certificate(f.header(author=i, round=1)) for i in range(4)]
    agg = CertificatesAggregator()
    assert agg.append(certs[0], f.committee) is None
    assert agg.append(certs[1], f.committee) is None
    first = agg.append(certs[2], f.committee)
    assert first is not None and len(first) == 3
    late = agg.append(certs[3], f.committee)
    assert late == [certs[3]]
    # Duplicates still dropped after quorum.
    assert agg.append(certs[3], f.committee) is None


def _make_core(f, authority_index=0):
    """A bare Core wired to fresh stores and dummy channels, for direct
    process_header checks (no networking)."""
    from narwhal_tpu.primary.core import Core
    from narwhal_tpu.primary.synchronizer import Synchronizer

    a = f.authorities[authority_index]
    storage = NodeStorage(None)
    genesis = {c.digest: c for c in Certificate.genesis(f.committee)}
    sync = Synchronizer(
        a.public,
        storage.certificate_store,
        storage.payload_store,
        Channel(100),
        genesis,
    )
    return Core(
        a.public,
        f.committee,
        f.worker_cache,
        storage.header_store,
        storage.certificate_store,
        storage.vote_digest_store,
        sync,
        a.signature_service(),
        network=None,
        rx_primaries=Channel(10),
        rx_header_waiter=Channel(10),
        rx_certificate_waiter=Channel(10),
        rx_proposer=Channel(10),
        tx_consensus=Channel(10),
        tx_proposer=Channel(10),
        rx_consensus_round_updates=Watch(0),
        gc_depth=50,
        rx_reconfigure=Watch(ReconfigureNotification("boot")),
    )


def test_core_rejects_empty_parent_header(run):
    """A header with no parents must never be voted for: zero parent stake
    fails the quorum check (ADVICE r1: genesis-subset headers skipped it)."""
    from narwhal_tpu.types import DagError

    f = CommitteeFixture(size=4)

    async def scenario():
        core = _make_core(f)
        header = f.header(author=1, round=1, parents=set())
        with pytest.raises(DagError):
            await core.process_header(header)

    run(scenario())


def test_core_rejects_sub_quorum_genesis_parents(run):
    """Genesis parents count toward the stake quorum like any others; a
    single genesis parent (stake 1 of 4, quorum 3) is rejected."""
    from narwhal_tpu.types import DagError

    f = CommitteeFixture(size=4)
    genesis = Certificate.genesis(f.committee)

    async def scenario():
        core = _make_core(f)
        header = f.header(author=1, round=1, parents={genesis[0].digest})
        with pytest.raises(DagError):
            await core.process_header(header)
        # The full genesis set still passes (round-1 headers are voteable);
        # the author's own round-1 header reaches the vote path.
        ok = f.header(author=0, round=1)
        await core.process_header(ok)

    run(scenario())


def test_proposer_makes_genesis_header(run):
    """The proposer emits a round-1 header on top of genesis
    (proposer_tests.rs propose_empty)."""
    f = CommitteeFixture(size=4)

    async def scenario():
        rx_core, rx_workers, tx_core = Channel(10), Channel(10), Channel(10)
        proposer = Proposer(
            f.authorities[0].public,
            f.committee,
            f.authorities[0].signature_service(),
            header_size=1_000,
            max_header_delay=0.05,
            network_model=NetworkModel.PARTIALLY_SYNCHRONOUS,
            rx_core=rx_core,
            rx_workers=rx_workers,
            tx_core=tx_core,
            rx_reconfigure=Watch(ReconfigureNotification("boot")),
        )
        task = proposer.spawn()
        header = await asyncio.wait_for(tx_core.recv(), 2.0)
        assert header.round == 1
        assert header.author == f.authorities[0].public
        assert header.parents == frozenset(
            c.digest for c in Certificate.genesis(f.committee)
        )
        header.verify(f.committee, f.worker_cache)
        task.cancel()

    run(scenario())


def test_proposer_includes_payload(run):
    """Batch digests reported by workers land in the next header
    (proposer_tests.rs propose_payload)."""
    f = CommitteeFixture(size=4)

    async def scenario():
        rx_core, rx_workers, tx_core = Channel(10), Channel(10), Channel(10)
        proposer = Proposer(
            f.authorities[0].public,
            f.committee,
            f.authorities[0].signature_service(),
            header_size=32,  # one digest seals a header
            max_header_delay=10.0,
            network_model=NetworkModel.PARTIALLY_SYNCHRONOUS,
            rx_core=rx_core,
            rx_workers=rx_workers,
            tx_core=tx_core,
            rx_reconfigure=Watch(ReconfigureNotification("boot")),
        )
        task = proposer.spawn()
        digest = b"\7" * 32
        await rx_workers.send((digest, 3))
        header = await asyncio.wait_for(tx_core.recv(), 2.0)
        assert header.payload == {digest: 3}
        task.cancel()

    run(scenario())


async def _spawn_primaries(f, gc_depth=50):
    """Boot one primary per authority on ephemeral ports, patch the shared
    committee with bound addresses, and return (primaries, consensus channels)."""
    primaries = []
    channels = []
    for a in f.authorities:
        tx_new = Channel(1_000)
        rx_committed = Channel(1_000)
        params = replace_params(f, gc_depth)
        p = Primary(
            a.public,
            a.signature_service(),
            f.committee,
            f.worker_cache,
            params,
            NodeStorage(None),
            tx_new,
            rx_committed,
        )
        await p.spawn()
        auth = f.committee.authorities[a.public]
        f.committee.authorities[a.public] = replace(
            auth, primary_address=p.address
        )
        primaries.append(p)
        channels.append((tx_new, rx_committed))
    return primaries, channels


def replace_params(f, gc_depth):
    from dataclasses import replace as _r

    return _r(f.parameters, gc_depth=gc_depth, max_header_delay=0.05)


def test_primary_committee_builds_dag_e2e(run):
    """Four primaries (no workers, empty payloads) drive the full
    header->vote->certificate loop across rounds; every primary feeds
    certificates to its consensus channel (core_tests.rs + the Cluster
    assert_progress pattern)."""
    f = CommitteeFixture(size=4)

    async def scenario():
        primaries, channels = await _spawn_primaries(f)
        try:
            # Collect certificates from one primary's consensus channel until
            # we see round 3 certified.
            tx_new, _ = channels[0]
            seen_rounds = set()
            while max(seen_rounds, default=0) < 3:
                cert = await asyncio.wait_for(tx_new.recv(), 10.0)
                cert_round = cert.round
                seen_rounds.add(cert_round)
            # A certified DAG: quorum of certificates per round.
            assert max(seen_rounds) >= 3
            # Every primary makes progress, not just one.
            for tx_new_i, _ in channels[1:]:
                cert = await asyncio.wait_for(tx_new_i.recv(), 10.0)
                assert cert.round >= 1
        finally:
            for p in primaries:
                await p.shutdown()

    run(scenario())


def test_primary_catches_up_after_late_start(run):
    """A primary that starts late syncs missing parent certificates from
    peers via the header waiter (header_waiter/certificate_waiter flow)."""
    f = CommitteeFixture(size=4)

    async def scenario():
        # Boot only 3 of 4 primaries: with quorum = 3 they can still advance.
        primaries = []
        channels = []
        for a in f.authorities[:3]:
            tx_new, rx_committed = Channel(1_000), Channel(1_000)
            p = Primary(
                a.public,
                a.signature_service(),
                f.committee,
                f.worker_cache,
                replace_params(f, 50),
                NodeStorage(None),
                tx_new,
                rx_committed,
            )
            await p.spawn()
            auth = f.committee.authorities[a.public]
            f.committee.authorities[a.public] = replace(auth, primary_address=p.address)
            primaries.append(p)
            channels.append((tx_new, rx_committed))
        try:
            # Wait until the DAG reaches round 3.
            tx_new, _ = channels[0]
            round_seen = 0
            while round_seen < 3:
                cert = await asyncio.wait_for(tx_new.recv(), 10.0)
                round_seen = max(round_seen, cert.round)

            # Now boot the 4th; it must catch up via parent sync.
            a = f.authorities[3]
            tx_new4, rx_committed4 = Channel(1_000), Channel(1_000)
            p4 = Primary(
                a.public,
                a.signature_service(),
                f.committee,
                f.worker_cache,
                replace_params(f, 50),
                NodeStorage(None),
                tx_new4,
                rx_committed4,
            )
            await p4.spawn()
            auth = f.committee.authorities[a.public]
            f.committee.authorities[a.public] = replace(auth, primary_address=p4.address)
            primaries.append(p4)

            # The late primary must start emitting certificates (its proposer
            # needs a parent quorum, which requires syncing the DAG suffix).
            cert = await asyncio.wait_for(tx_new4.recv(), 15.0)
            assert cert.round >= 1
        finally:
            for p in primaries:
                await p.shutdown()

    run(scenario())


def test_state_handler_triggers_gc(run):
    """Committed certificates flowing back move the consensus-round watch
    (state_handler.rs:57-98)."""
    f = CommitteeFixture(size=4)

    async def scenario():
        tx_new, rx_committed = Channel(1_000), Channel(1_000)
        a = f.authorities[0]
        p = Primary(
            a.public,
            a.signature_service(),
            f.committee,
            f.worker_cache,
            replace_params(f, 50),
            NodeStorage(None),
            tx_new,
            rx_committed,
        )
        await p.spawn()
        auth = f.committee.authorities[a.public]
        f.committee.authorities[a.public] = replace(auth, primary_address=p.address)
        try:
            header = f.header(author=0, round=7)
            cert = f.certificate(header)
            await rx_committed.send(cert)
            for _ in range(100):
                if p.tx_consensus_round_updates.value == 7:
                    break
                await asyncio.sleep(0.01)
            assert p.tx_consensus_round_updates.value == 7
        finally:
            await p.shutdown()

    run(scenario())
