"""Worker pipeline tests, mirroring /root/reference/worker/src/tests/
{batch_maker,quorum_waiter,processor,synchronizer,worker}_tests.rs."""

import asyncio

from narwhal_tpu.channels import Channel, Watch
from narwhal_tpu.fixtures import CommitteeFixture
from narwhal_tpu.messages import (
    OthersBatchMsg,
    OurBatchMsg,
    RequestBatchMsg,
    SubmitTransactionMsg,
    SubmitTransactionStreamMsg,
    SynchronizeMsg,
    WorkerBatchMsg,
    WorkerBatchRequest,
)
from narwhal_tpu.network import NetworkClient, RpcServer
from narwhal_tpu.stores import NodeStorage
from narwhal_tpu.types import Batch, ReconfigureNotification, serialized_batch_digest
from narwhal_tpu.worker import Worker
from narwhal_tpu.worker.batch_maker import BatchMaker


def _watch():
    return Watch(ReconfigureNotification("boot"))


def _chunk(*txs: bytes) -> tuple[int, bytes]:
    """(count, frames) wire chunk, as the ingest handlers produce."""
    return len(txs), b"".join(len(t).to_bytes(4, "little") + t for t in txs)


def test_batch_maker_seals_on_size(run):
    async def scenario():
        rx, tx_out = Channel(100), Channel(10)
        bm = BatchMaker(100, 10.0, rx, tx_out, _watch())
        task = bm.spawn()
        for i in range(4):
            await rx.send(_chunk(bytes([i]) * 30))  # 120 B total > 100
        batch = await asyncio.wait_for(tx_out.recv(), 2.0)
        assert batch.size_bytes >= 100
        task.cancel()

    run(scenario())


def test_batch_maker_seals_on_timer(run):
    async def scenario():
        rx, tx_out = Channel(100), Channel(10)
        bm = BatchMaker(1_000_000, 0.05, rx, tx_out, _watch())
        task = bm.spawn()
        await rx.send(_chunk(b"lonely-tx"))
        batch = await asyncio.wait_for(tx_out.recv(), 2.0)
        assert batch.transactions == (b"lonely-tx",)
        task.cancel()

    run(scenario())


async def _spawn_committee_workers(f, benchmark=False):
    """Boot one worker per authority on ephemeral ports, patching the shared
    worker cache with the bound addresses (the fixture uses port 0)."""
    workers = []
    for a in f.authorities:
        w = Worker(
            a.public, 0, f.committee, f.worker_cache,
            f.parameters, NodeStorage(None).batch_store, benchmark=benchmark,
        )
        await w.spawn()
        info = f.worker_cache.workers[a.public][0]
        from narwhal_tpu.config import WorkerInfo

        f.worker_cache.workers[a.public][0] = WorkerInfo(
            name=info.name,
            transactions=w.transactions_address,
            worker_address=w.worker_address,
        )
        workers.append(w)
    return workers


def test_worker_batch_dissemination_e2e(run):
    """Submit txs to one worker; every worker ends with the batch in its
    store, and the submitting worker's primary hears OurBatch while peers'
    primaries hear OthersBatch."""

    async def scenario():
        f = CommitteeFixture(size=4, workers=1)
        # Mock primaries: tiny RPC servers collecting digest notifications
        # (the reference's WorkerToPrimaryMockServer, test_utils/src/lib.rs).
        primary_chans = {}
        primary_servers = []
        for i, a in enumerate(f.authorities):
            srv = RpcServer()
            ch = Channel(100)

            def mk(ch_):
                async def on(msg, peer):
                    await ch_.send(msg)

                return on

            srv.route(OurBatchMsg, mk(ch))
            srv.route(OthersBatchMsg, mk(ch))
            port = await srv.start("127.0.0.1", 0)
            # point the committee's primary address at the mock
            from narwhal_tpu.config import Authority

            auth = f.committee.authorities[a.public]
            f.committee.authorities[a.public] = Authority(
                auth.stake, f"127.0.0.1:{port}", auth.network_key
            )
            primary_chans[a.public] = ch
            primary_servers.append(srv)

        f.parameters.batch_size = 60
        f.parameters.max_batch_delay = 0.05
        workers = await _spawn_committee_workers(f)

        # submit enough txs to worker 0 to seal a batch
        client = NetworkClient()
        for i in range(4):
            await client.request(
                workers[0].transactions_address, SubmitTransactionMsg(bytes([1, i]) * 10)
            )

        # worker 0's primary hears OurBatch
        sender = workers[0].name
        our = await asyncio.wait_for(primary_chans[sender].recv(), 5.0)
        assert isinstance(our, OurBatchMsg)
        # peers' primaries hear OthersBatch with the same digest
        for a in f.authorities:
            if a.public == sender:
                continue
            got = await asyncio.wait_for(primary_chans[a.public].recv(), 5.0)
            assert isinstance(got, OthersBatchMsg)
            assert got.digest == our.digest
        # every worker stored the batch
        for w in workers:
            assert w.store.contains(our.digest)

        for w in workers:
            await w.shutdown()
        for s in primary_servers:
            await s.stop()
        client.close()

    run(scenario())


def test_worker_synchronize_fetches_missing(run):
    async def scenario():
        f = CommitteeFixture(size=4, workers=1)
        f.parameters.sync_retry_delay = 0.2
        workers = await _spawn_committee_workers(f)

        # Plant a batch only in worker 1's store.
        batch = Batch((b"planted-tx",))
        serialized = batch.to_bytes()
        workers[1].store.write(batch.digest, serialized)

        # Ask worker 0 to synchronize it from worker 1's authority.
        client = NetworkClient()
        await client.request(
            workers[0].worker_address,
            SynchronizeMsg((batch.digest,), workers[1].name),
        )
        for _ in range(100):
            if workers[0].store.contains(batch.digest):
                break
            await asyncio.sleep(0.05)
        assert workers[0].store.contains(batch.digest)

        # RequestBatch RPC returns the transactions.
        resp = await client.request(
            workers[0].worker_address, RequestBatchMsg(batch.digest)
        )
        assert resp.transactions == (b"planted-tx",)

        for w in workers:
            await w.shutdown()
        client.close()

    run(scenario())


def test_worker_synchronize_retry_via_lucky_broadcast(run):
    """Target authority doesn't have the batch; a retry tick finds it on
    another peer."""

    async def scenario():
        f = CommitteeFixture(size=4, workers=1)
        f.parameters.sync_retry_delay = 0.15
        f.parameters.sync_retry_nodes = 3
        workers = await _spawn_committee_workers(f)

        batch = Batch((b"elsewhere",))
        workers[2].store.write(batch.digest, batch.to_bytes())
        workers[3].store.write(batch.digest, batch.to_bytes())

        client = NetworkClient()
        # ask to sync from authority 1, which does NOT have it
        await client.request(
            workers[0].worker_address, SynchronizeMsg((batch.digest,), workers[1].name)
        )
        for _ in range(100):
            if workers[0].store.contains(batch.digest):
                break
            await asyncio.sleep(0.05)
        assert workers[0].store.contains(batch.digest)

        for w in workers:
            await w.shutdown()
        client.close()

    run(scenario())


def test_synchronizer_never_rerequests_satisfied_digests(run):
    """Retry ticks must trim the want-list: a digest that arrived (via a
    fetch response or a peer broadcast) is never re-requested — before the
    trim, every lucky-broadcast tick re-shipped the WHOLE original
    want-list to sync_retry_nodes peers."""

    async def scenario():
        from narwhal_tpu.worker.synchronizer import WorkerSynchronizer

        f = CommitteeFixture(size=4, workers=1)
        f.parameters.sync_retry_delay = 0.1
        store = NodeStorage(None).batch_store
        requests: list[tuple[bytes, ...]] = []

        class RecordingNetwork:
            async def request(self, address, msg, timeout=None):
                requests.append(tuple(msg.digests))
                from narwhal_tpu.messages import WorkerBatchResponse

                return WorkerBatchResponse(())

        rx_cmd, tx_proc = Channel(16), Channel(16)
        sync = WorkerSynchronizer(
            f.authorities[0].public,
            0,
            f.committee,
            f.worker_cache,
            f.parameters,
            store,
            RecordingNetwork(),
            rx_cmd,
            tx_proc,
            _watch(),
        )
        task = sync.spawn()
        d_satisfied, d_missing = b"\x01" * 32, b"\x02" * 32
        await rx_cmd.send(SynchronizeMsg((d_satisfied, d_missing), f.authorities[1].public))
        for _ in range(100):
            if requests:
                break
            await asyncio.sleep(0.01)
        assert requests and set(requests[0]) == {d_satisfied, d_missing}

        # The batch arrives (peer broadcast path writes the store).
        store.write(d_satisfied, b"whatever")
        baseline = len(requests)
        await asyncio.sleep(0.35)  # several retry ticks
        later = requests[baseline:]
        assert later, "retry ticks should still chase the missing digest"
        for req in later:
            assert d_satisfied not in req, "satisfied digest was re-requested"
            assert d_missing in req

        task.cancel()

    run(scenario())
